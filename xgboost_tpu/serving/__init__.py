"""Production model server over the PR-2 serving fast path.

The per-process primitives (``predictor/serving.py``: zero-copy inplace
predict, bucketed compiled-program cache, native CPU SoA walker) serve ONE
synchronous caller. This package is the traffic-facing layer on top —
the serving-side analog of the reference's bindings/frontends tier
(PAPER.md layer 8):

- :mod:`~xgboost_tpu.serving.batcher` — async micro-batching: concurrent
  small requests coalesce into one bucketed dispatch (the bucket padding
  the fast path already pays gets filled with real traffic);
- :mod:`~xgboost_tpu.serving.tenancy` — multi-model arena: N boosters
  resident by ``name@version`` under an LRU memory budget;
- :mod:`~xgboost_tpu.serving.swap` — zero-downtime hot swap: load → warm
  → atomic pointer flip → drain the old snapshot;
- :mod:`~xgboost_tpu.serving.admission` — SLO-aware admission: deadline /
  queue-depth / per-model-p99 shed decisions, degrade-machine routing to
  the native CPU walker;
- :mod:`~xgboost_tpu.serving.obs` — request-scope observability (ISSUE
  9): per-request ids/traces/access log, the per-dispatch flight ring,
  and the SLO ledger (stage histograms, error-budget burn, exemplars)
  feeding ``python -m xgboost_tpu serve-report``;
- :mod:`~xgboost_tpu.serving.faults` — the self-healing layer (ISSUE
  10): batch fault isolation with bisection re-dispatch (typed
  ``RequestError`` for exactly the poison members), per-model circuit
  breakers, input quarantine, the batcher-worker watchdog, and the
  crash-only manifest/SIGTERM-drain contract (docs/serving.md
  "Failure handling");
- :mod:`~xgboost_tpu.serving.delivery` — continuous train-to-serve
  delivery (ISSUE 12): a controller that watches a training run_dir
  through the verified checkpoint readers, publishes each new snapshot
  as ``name@vN``, canaries it against live traffic (shadow or
  fractional request_id-hash split), gates promotion on the live SLO
  ledger + a held-out AUC parity gate, promotes by the warm hot swap
  and auto-rolls back (+ quarantines) on a post-promotion breaker trip
  (docs/serving.md "Model delivery");
- :mod:`~xgboost_tpu.serving.fleet` — the scale-out tier (ISSUE 11):
  replica supervisor + consistent-hash routing front over N crash-only
  servers sharing one versioned manifest, with weighted-fair multi-
  tenant queuing (``tenancy.TenantFairQueue``) and per-tenant admission
  quotas in every replica (docs/serving.md "Scaling out").

Entry points: :class:`ModelServer` (``xgb.ModelServer``) in Python,
``python -m xgboost_tpu serve`` for the JSONL stdin/socket protocol,
``python -m xgboost_tpu serve-fleet`` for the replicated tier.
Full walkthrough: docs/serving.md ("The model server", "Tracing a
request", "Scaling out").
"""

from .admission import AdmissionController, RequestShed  # noqa: F401
from .batcher import MicroBatcher  # noqa: F401
from .delivery import (  # noqa: F401
    CanaryRouter, CanaryState, DeliveryController,
)
from .faults import (  # noqa: F401
    CircuitBreaker, FaultDomain, Quarantine, RequestError,
)
from .obs import ServingRecorder, SLOLedger  # noqa: F401
from .server import ModelServer, serve_main  # noqa: F401
from .swap import hot_swap, promote_live  # noqa: F401
from .tenancy import (  # noqa: F401
    ModelEntry, ModelRegistry, TenantFairQueue,
)

__all__ = [
    "AdmissionController", "CanaryRouter", "CanaryState", "CircuitBreaker",
    "DeliveryController", "FaultDomain", "MicroBatcher",
    "ModelEntry", "ModelRegistry", "ModelServer", "Quarantine",
    "RequestError", "RequestShed", "SLOLedger", "ServingRecorder",
    "TenantFairQueue", "hot_swap", "promote_live", "serve_main",
]
