"""SLO-aware admission: shed early, degrade gracefully.

A serving frontend that accepts every request fails them all at once when
traffic exceeds capacity — queues grow without bound, every caller times
out, and the process looks wedged (the reference's analog is rabit's "fail
fast, recover from checkpoint" stance: bounded damage beats unbounded
queues). This module is the decision layer in front of the micro-batcher:

- **deadline** — every request may carry one (``deadline_ms``). A request
  whose deadline already passed, or whose *estimated* completion time
  (queue depth x the p99 of ``predict_latency_seconds``, read from the
  process registry) overshoots it, is shed at submit time with a typed
  :class:`RequestShed` instead of being served late. The batcher re-checks
  at dispatch so a request that aged out while queued is shed, not walked.
- **queue bound** — the request queue is bounded
  (``XGBTPU_SERVING_QUEUE``, default 1024); overflow sheds with reason
  ``queue_full`` rather than growing the heap.
- **tenant quota** (ISSUE 11) — each request tenant's *queue occupancy*
  is bounded by ``XGBTPU_TENANT_QUOTA`` (``name=N,*=M`` or a bare int;
  unset = unbounded; parsed once at construction like every other knob). A tenant at its quota sheds with reason
  ``tenant_quota`` while every other tenant keeps admitting — set any
  quota below the global queue bound and one hot tenant can no longer
  cause a single ``queue_full`` shed for anyone else (the fairness
  acceptance pin; the dequeue-side half is
  :class:`~xgboost_tpu.serving.tenancy.TenantFairQueue`).
- **degrade routing** — when the resilience layer marks the device predict
  path unhealthy (the ``pallas_predict`` capability gating the
  ``predict_walk`` op's device impls), the kernel dispatch registry
  resolves dispatches to the native CPU SoA walker
  (``dispatch.resolve("predict_walk", ...)`` inside ``predict_serving``
  — docs/serving.md, "Degrade routing"): the server keeps answering at
  reduced throughput instead of queueing behind a faulting device path.
  State transitions stay owned by the capability machine
  (docs/resilience.md); this layer only *reads* the table's verdict to
  count ``serving_degraded_routes_total``.
- **fault-plane sheds** (ISSUE 10, ``serving/faults.py``) — a request for
  a model whose **circuit breaker** is OPEN sheds with reason
  ``breaker`` (the half-open probe is the one admitted exception); a
  payload whose fingerprint is **quarantined** (a repeat poison
  offender) sheds with reason ``quarantine``; a structurally
  **invalid** payload (wrong width, oversized, non-finite inf values)
  is rejected with reason ``invalid`` before it can throw inside a
  coalesced dispatch; a **draining** server (SIGTERM received) sheds
  new arrivals with reason ``draining`` while queued requests finish.

Every decision is observable: ``requests_shed_total{reason=...}``,
``serving_admitted_total``, ``serving_degraded_routes_total``.
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ..observability.metrics import REGISTRY
from ..resilience import degrade
from .faults import FaultDomain
from .tenancy import tenant_quotas

__all__ = ["RequestShed", "AdmissionController"]

#: shed reasons (the ``reason`` label on ``requests_shed_total``)
QUEUE_FULL = "queue_full"
DEADLINE = "deadline"  # already past due at decision time
SLO = "slo"  # projected completion overshoots the deadline
BREAKER = "breaker"  # the model's circuit breaker is OPEN
QUARANTINE = "quarantine"  # repeat poison offender fingerprint
INVALID = "invalid"  # malformed payload rejected at admission
DRAINING = "draining"  # SIGTERM drain in progress
TENANT_QUOTA = "tenant_quota"  # the tenant's queue-occupancy cap is hit

#: p99 prior (seconds) used before the latency histogram has samples: a
#: generous whole-bucket-walk estimate so a cold server does not shed its
#: warm-up traffic on a fantasy backlog
_COLD_P99_S = 0.050


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


class RequestShed(RuntimeError):
    """A request the server declined to serve (admission or dispatch-time
    shed). ``reason`` is one of ``queue_full`` / ``deadline`` / ``slo``."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"request shed ({reason})"
                         + (f": {detail}" if detail else ""))
        self.reason = reason


class AdmissionController:
    """Stateless-per-request decisions over shared observable state (queue
    depth from the batcher, p99 from the metrics registry, health from the
    degrade machine). One instance per :class:`~xgboost_tpu.serving.ModelServer`."""

    def __init__(self, max_queue: Optional[int] = None,
                 faults: Optional[FaultDomain] = None):
        self.max_queue = max(1, max_queue if max_queue is not None
                             else _env_int("XGBTPU_SERVING_QUEUE", 1024))
        #: the server's fault domain (breakers + quarantine); a bare
        #: controller owns a private one so direct MicroBatcher users
        #: still get isolation/quarantine/breaker behavior
        self.faults = faults if faults is not None else FaultDomain()
        #: SIGTERM drain flag (set via the owning server's begin_drain)
        self.draining = False
        #: XGBTPU_TENANT_QUOTA, parsed ONCE (admit runs per request)
        self.quotas = tenant_quotas()
        # pre-create the families so a healthy server's exposition still
        # documents the shed/admit surface (scrapers see zeros, not gaps)
        self._shed = REGISTRY.counter(
            "requests_shed_total",
            "Requests declined by SLO-aware admission, by reason")
        for reason in (QUEUE_FULL, DEADLINE, SLO, BREAKER, QUARANTINE,
                       INVALID, DRAINING, TENANT_QUOTA):
            self._shed.labels(reason=reason)
        self._admitted = REGISTRY.counter(
            "serving_admitted_total", "Requests admitted into the batcher")
        self._degraded_routes = REGISTRY.counter(
            "serving_degraded_routes_total",
            "Dispatches routed to the native CPU walker because the "
            "device predict path is degraded")
        self._admitted.inc(0)
        self._degraded_routes.inc(0)

    # ------------------------------------------------------------------
    def p99_s(self, model: str = "") -> float:
        """Current p99 of the serving latency series. With a ``model``
        label (``name@vN``), the per-model child of
        ``predict_latency_seconds`` wins whenever it has samples — a slow
        tenant must not be judged by a fast fleet-wide tail (nor the
        reverse); a cold model (no labelled samples yet) falls back to
        the unlabelled process-wide aggregate, and a cold server to the
        prior."""
        if model:
            q = REGISTRY.quantile("predict_latency_seconds", 0.99,
                                  model=model)
            if q is not None:
                return max(q, 1e-6)
        q = REGISTRY.quantile("predict_latency_seconds", 0.99)
        return _COLD_P99_S if q is None else max(q, 1e-6)

    def invalid(self, detail: str) -> RequestShed:
        """Count and build the typed rejection for a structurally
        malformed payload (the batcher raises it BEFORE the request can
        reach the queue — satellite: malformed dense payloads must not
        throw inside a coalesced dispatch)."""
        self._shed.labels(reason=INVALID).inc()
        return RequestShed(INVALID, detail)

    def admit(self, queue_depth: int,
              deadline: Optional[float] = None,
              model: str = "",
              fingerprint: Optional[int] = None,
              tenant: str = "",
              tenant_depth: int = 0) -> None:
        """Raise :class:`RequestShed` if the request should not enter the
        queue; record the admission otherwise. ``deadline`` is an absolute
        ``time.monotonic()`` instant (None = no SLO); ``model`` scopes
        the p99 estimate to the model being requested; ``fingerprint``
        is the payload's quarantine key (None = not fingerprintable);
        ``tenant_depth`` is the requesting tenant's current queue
        occupancy, judged against its ``XGBTPU_TENANT_QUOTA``."""
        if self.draining:
            self._shed.labels(reason=DRAINING).inc()
            raise RequestShed(DRAINING, "server is draining (SIGTERM)")
        quota = self.quotas.get(tenant, self.quotas.get("*"))
        if quota is not None and tenant_depth >= quota:
            self._shed.labels(reason=TENANT_QUOTA).inc()
            raise RequestShed(
                TENANT_QUOTA,
                f"tenant {tenant or 'default'!r} has {tenant_depth} "
                f"queued >= quota {quota}")
        if self.faults.quarantine.quarantined(fingerprint):
            self._shed.labels(reason=QUARANTINE).inc()
            raise RequestShed(
                QUARANTINE,
                f"input fingerprint {fingerprint:08x} is a repeat "
                "poison offender")
        if queue_depth >= self.max_queue:
            self._shed.labels(reason=QUEUE_FULL).inc()
            raise RequestShed(
                QUEUE_FULL, f"queue depth {queue_depth} >= {self.max_queue}")
        if deadline is not None:
            now = time.monotonic()
            if now >= deadline:
                self._shed.labels(reason=DEADLINE).inc()
                raise RequestShed(DEADLINE, "deadline already past at admit")
            # projected completion: everything ahead of us plus our own
            # dispatch, each at the observed tail latency
            p99 = self.p99_s(model)
            eta = (queue_depth + 1) * p99
            if now + eta > deadline:
                self._shed.labels(reason=SLO).inc()
                raise RequestShed(
                    SLO, f"projected wait {eta * 1e3:.1f}ms past deadline "
                         f"(queue depth {queue_depth}, "
                         f"p99 {p99 * 1e3:.2f}ms"
                         + (f" for {model}" if model else "") + ")")
        # breaker LAST: an admitted half-open probe must actually reach
        # dispatch, so it only burns its slot after every cheaper check
        # has passed (a probe shed on queue_full would wedge recovery)
        if model:
            name = model.split("@", 1)[0]
            if not self.faults.breaker(name).allow():
                self._shed.labels(reason=BREAKER).inc()
                raise RequestShed(
                    BREAKER, f"circuit breaker for {name!r} is open")
        self._admitted.inc()

    def shed_at_dispatch(self, reason: str = DEADLINE) -> RequestShed:
        """Count and build the exception for a queued request that aged
        out before its dispatch (the batcher resolves its future with it)."""
        self._shed.labels(reason=reason).inc()
        return RequestShed(reason, "deadline passed while queued")

    # ------------------------------------------------------------------
    def route_native(self) -> bool:
        """Whether the next dispatch will be degrade-routed to the native
        CPU SoA walker — the dispatch registry's verdict for the
        ``predict_walk`` op's capabilities (read-only: no retry countdown
        burns). Counted so the perf cliff is visible in the exposition
        while it lasts; the route itself is resolved inside
        ``predict_serving`` via ``dispatch.resolve``, this method is the
        admission plane's observability hook."""
        from .. import dispatch

        if dispatch.degraded("predict_walk"):
            self._degraded_routes.inc()
            return True
        return False
