"""Multi-model tenancy: a forest-snapshot arena with an LRU memory budget,
plus the request-side half — weighted-fair queuing across request tenants.

One serving process hosts many models (the reference serves this from its
bindings tier — one ``Booster`` handle per model, the host application
doing its own bookkeeping). Here the bookkeeping is first-class:

- models are resident by ``name@version``; the **serving pointer** per
  name is the live version (hot swap flips it atomically — ``swap.py``);
- every resident entry is charged its device/host footprint (stacked
  forest tensors + raw model JSON) against an explicit arena budget
  (``XGBTPU_SERVING_ARENA_MB``, default 512). Loading past the budget
  evicts least-recently-*used* entries — including stale versions left
  behind by swaps — until the new model fits;
- an evicted model is not gone: its **source** (raw model bytes, a model
  file, or a PR-4 checksummed checkpoint directory) is retained, so the
  next request faults it back in (a registry *miss*) instead of erroring.
  ``hits + misses == get() calls`` is a pinned invariant
  (tests/test_model_server.py).

The second kind of tenant is the *caller*: one serving process fronts
many request tenants, and under contention a hot tenant flooding the
micro-batcher queue must not starve the others (ISSUE 11). That half
lives here too:

- :class:`TenantFairQueue` — the micro-batcher's request queue, replaced
  from plain FIFO: per-tenant lanes dequeued by start-time fair queuing
  (virtual time advances by ``rows / weight`` per dequeue, weights from
  ``XGBTPU_TENANT_WEIGHTS``, default equal). While a light tenant has
  anything queued it receives its weight share of dequeued rows no matter
  how deep the hot tenant's backlog is — the fairness pin in
  tests/test_fleet.py.
- :func:`tenant_weights` / :func:`tenant_quota` — the env grammars
  (``name=N,*=M``, same shape as ``XGBTPU_RETRY``). Quotas bound each
  tenant's *queue occupancy* at admission (``admission.py`` sheds with
  reason ``tenant_quota``), so one tenant can never fill the bounded
  queue to the point where another's traffic sheds ``queue_full``.

Registry metrics: ``serving_arena_bytes`` / ``serving_models_resident``
gauges, ``serving_model_loads_total{model=}``,
``serving_model_evictions_total``, ``serving_model_hits_total`` /
``serving_model_misses_total``; per-tenant
``serving_tenant_dequeued_rows_total{tenant=}``.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability.metrics import REGISTRY

__all__ = ["ModelEntry", "ModelRegistry", "resolve_source", "load_booster",
           "TenantFairQueue", "tenant_weights", "tenant_quotas",
           "tenant_quota", "QUEUE_STOP", "OVERFLOW_TENANT",
           "SHADOW_TENANT"]

_ENV_WEIGHTS = "XGBTPU_TENANT_WEIGHTS"
_ENV_QUOTA = "XGBTPU_TENANT_QUOTA"
_ENV_TENANT_MAX = "XGBTPU_TENANT_MAX"

#: the shared lane/label every tenant past the distinct-tenant cap maps
#: to — wire-supplied tenant names must not grow per-tenant server state
#: (metric children, ledger caches, fair-queue lanes) without bound
OVERFLOW_TENANT = "overflow"

#: the tenant lane shadow-canary duplicates ride (serving/delivery.py).
#: The batcher recognizes it to keep shadow traffic OUT of the live
#: fault plane: an all-shadow dispatch group feeds neither the model's
#: NAME-keyed breaker nor the payload quarantine — a bad candidate must
#: fail its canary, never shed live traffic.
SHADOW_TENANT = "_canary"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


# ---------------------------------------------------------------------------
# request tenants: weights, quotas, the weighted-fair queue
# ---------------------------------------------------------------------------


def _parse_tenant_map(raw: Optional[str], conv) -> Dict[str, Any]:
    """``name=N,*=M`` (or a bare number meaning ``*=N``) -> dict. The
    shared grammar of ``XGBTPU_TENANT_WEIGHTS`` / ``XGBTPU_TENANT_QUOTA``
    (mirrors ``XGBTPU_RETRY``); malformed parts are skipped — a bad env
    must never take the server down."""
    out: Dict[str, Any] = {}
    if not raw:
        return out
    for part in raw.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            k, _, v = part.partition("=")
            k, v = k.strip(), v.strip()
        else:
            k, v = "*", part
        try:
            out[k] = conv(v)
        except ValueError:
            continue
    return out


def tenant_weights(env: Optional[str] = None) -> Dict[str, float]:
    """Per-tenant scheduling weights (``XGBTPU_TENANT_WEIGHTS``). Missing
    tenants take the ``*`` entry, default 1.0 — equal shares."""
    raw = env if env is not None else os.environ.get(_ENV_WEIGHTS)
    return {k: max(v, 1e-6)
            for k, v in _parse_tenant_map(raw, float).items() if v > 0}


def tenant_quotas(env: Optional[str] = None) -> Dict[str, int]:
    """The parsed ``XGBTPU_TENANT_QUOTA`` table — parsed ONCE at
    controller construction (the admit path runs per request; same
    read-at-construction contract as every other serving knob)."""
    raw = env if env is not None else os.environ.get(_ENV_QUOTA)
    return {k: max(1, int(v))
            for k, v in _parse_tenant_map(raw, int).items()}


def tenant_quota(tenant: str, env: Optional[str] = None) -> Optional[int]:
    """Max queued requests for ``tenant`` (``XGBTPU_TENANT_QUOTA``), or
    None = unbounded (only the global queue bound applies)."""
    table = tenant_quotas(env)
    return table.get(tenant, table.get("*"))


#: returned by :meth:`TenantFairQueue.get` once the queue is stopped AND
#: drained — the batcher worker's exit marker (never before the last
#: queued request, so ``close(drain=True)`` keeps serving the backlog)
QUEUE_STOP = object()


class TenantFairQueue:
    """Weighted-fair request queue: per-tenant FIFO lanes, dequeued in
    start-time-fair-queuing order.

    Every item enqueues with a *virtual finish tag*
    ``max(vtime, tenant's last tag) + cost / weight`` (cost = request
    rows: the resource a dispatch actually spends); :meth:`get` always
    returns the item with the smallest head tag, and advances the queue's
    virtual time to it. Consequences, both pinned by tests:

    - a backlogged tenant's lane drains at its weight share of dequeued
      rows, independent of how many requests it stuffed into the queue;
    - a tenant with a shallow lane (the "light" tenant under a hot-tenant
      flood) enqueues near the current virtual time and is dequeued
      within ~one weighted round, so its queue wait is bounded by the
      *active tenant count*, not the hot tenant's backlog.

    FIFO order inside a lane is preserved (tags are monotonic per
    tenant). With a single tenant this degrades to the plain FIFO queue
    it replaced. Thread-safe; ``maxsize`` is advisory only (admission
    owns the bound)."""

    def __init__(self, weights: Optional[Dict[str, float]] = None) -> None:
        self._cv = threading.Condition()
        self._lanes: "Dict[str, deque]" = {}  # tenant -> deque[(tag, item)]
        self._weights = tenant_weights() if weights is None \
            else {k: max(float(v), 1e-6) for k, v in weights.items()}
        self._last_tag: Dict[str, float] = {}
        self._vtime = 0.0
        self._size = 0
        self._stopped = False

    def weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self._weights.get("*", 1.0))

    # ------------------------------------------------------------------
    def put(self, item: Any, tenant: str = "", cost: float = 1.0) -> None:
        with self._cv:
            if self._stopped:
                raise RuntimeError("queue is stopped")
            tag = max(self._vtime, self._last_tag.get(tenant, 0.0)) \
                + max(cost, 1e-9) / self.weight(tenant)
            self._last_tag[tenant] = tag
            self._lanes.setdefault(tenant, deque()).append((tag, item))
            self._size += 1
            self._cv.notify()

    def get(self, timeout: Optional[float] = None) -> Any:
        """Next item in weighted-fair order. Blocks up to ``timeout``
        (None = forever); raises ``queue.Empty`` on timeout, returns
        :data:`QUEUE_STOP` once stopped and drained."""
        import queue as _queue

        with self._cv:
            if not self._cv.wait_for(
                    lambda: self._size > 0 or self._stopped, timeout):
                raise _queue.Empty
            if self._size == 0:
                return QUEUE_STOP
            tenant = min(self._lanes, key=lambda t: self._lanes[t][0][0])
            tag, item = self._lanes[tenant].popleft()
            if not self._lanes[tenant]:
                del self._lanes[tenant]
            self._vtime = max(self._vtime, tag)
            self._size -= 1
            return item

    def get_nowait(self) -> Any:
        return self.get(timeout=0)

    def stop(self) -> None:
        """No further :meth:`put`; :meth:`get` serves the backlog then
        returns :data:`QUEUE_STOP` (the positional-sentinel analog for a
        queue whose order is no longer FIFO)."""
        with self._cv:
            self._stopped = True
            self._cv.notify_all()

    # ------------------------------------------------------------------
    def qsize(self) -> int:
        with self._cv:
            return self._size

    def depth(self, tenant: str) -> int:
        """Queued requests for one tenant — the admission layer's quota
        input."""
        with self._cv:
            lane = self._lanes.get(tenant)
            return len(lane) if lane else 0


# ---------------------------------------------------------------------------
# model sources: everything a model can be (re)loaded from
# ---------------------------------------------------------------------------


def resolve_source(source: Any) -> Tuple[str, Any]:
    """Normalize a user-supplied model source into a (kind, payload) spec
    that survives eviction: a live ``Booster`` becomes its raw JSON bytes,
    paths stay paths. Kinds: ``raw`` (model JSON bytes), ``file`` (model
    JSON path), ``ckpt`` (one checkpoint file), ``ckpt_dir`` (checkpoint
    directory — newest *verified* snapshot wins, docs/resilience.md)."""
    if hasattr(source, "save_raw"):  # live Booster
        return ("raw", source.save_raw())
    if isinstance(source, (bytes, bytearray)):
        return ("raw", bytes(source))
    if isinstance(source, (str, os.PathLike)):
        path = os.fspath(source)
        if os.path.isdir(path):
            return ("ckpt_dir", path)
        if path.endswith(".ckpt"):
            return ("ckpt", path)
        return ("file", path)
    raise TypeError(f"unsupported model source: {type(source).__name__}")


def load_booster(spec: Tuple[str, Any]):
    """A fresh ``Booster`` from a resolved source spec. Checkpoint kinds
    go through the resilience layer's verified readers, so a truncated or
    bit-flipped snapshot is rejected (or fallen through) instead of served.

    Every build runs under the ``serving_model_load`` retry/chaos site:
    a transient read hiccup gets one bounded retry (``XGBTPU_RETRY``
    site ``serving_model_load``), anything persistent is classified and
    re-raised — an LRU fault-back-in that fails permanently surfaces to
    the caller instead of crash-looping the arena."""
    from ..resilience import chaos, policy

    def _build():
        chaos.hit("serving_model_load")
        return _load_booster_from(spec)

    try:
        return policy.RetryPolicy("serving_model_load", retries=1).run(
            _build)
    except Exception as e:
        # RetryPolicy already recorded faults_total{site,kind}; add only
        # the serving-plane slice here (no double count)
        REGISTRY.counter(
            "serving_faults_total",
            "Failures observed on the serving plane, by site and kind",
        ).labels(site="serving_model_load", kind=policy.classify(e)).inc()
        raise


def _load_booster_from(spec: Tuple[str, Any]):
    from ..learner import Booster
    from ..resilience import checkpoint

    kind, payload = spec
    if kind == "raw":
        return Booster(model_file=bytes(payload))
    if kind == "file":
        return Booster(model_file=payload)
    if kind == "ckpt":
        got = checkpoint.read_checkpoint(payload)
        if got is None:
            raise ValueError(f"checkpoint {payload!r} failed verification")
        return Booster(model_file=got[0])
    if kind == "ckpt_dir":
        got = checkpoint.load_latest(payload)
        if got is None:
            raise ValueError(
                f"no verified checkpoint in {payload!r} "
                "(python -m xgboost_tpu checkpoint-inspect)")
        return Booster(model_file=got[0])
    raise ValueError(f"unknown source kind: {kind!r}")


def _forest_footprint_bytes(booster) -> int:
    """Resident footprint estimate: the stacked forest's tensor bytes
    (computed from shapes — no device->host sync) plus the tree store's
    JSON size. The full-model snapshot is built here if absent, which is
    exactly the warm-up a fresh model wants before serving."""
    forest, tw = booster._forest_snapshot()
    total = 0
    for field in ("left", "right", "feature", "cond", "default_left",
                  "split_type", "cat_bits", "tree_group"):
        a = getattr(forest, field)
        total += int(np.prod(a.shape, dtype=np.int64)) * a.dtype.itemsize
    if tw is not None:
        total += int(np.prod(tw.shape, dtype=np.int64)) * tw.dtype.itemsize
    return total


# ---------------------------------------------------------------------------


class ModelEntry:
    """One resident ``name@version``: the Booster, its footprint charge,
    and an in-flight count so hot swap can drain requests pinned to the
    old snapshot before releasing it."""

    def __init__(self, name: str, version: int, booster, spec,
                 nbytes: int) -> None:
        self.name = name
        self.version = version
        self.label = f"{name}@v{version}"
        self.booster = booster
        self.spec = spec
        self.nbytes = nbytes
        #: eviction shield (ISSUE 12): a pinned entry is skipped by the
        #: LRU budget pass — the delivery controller pins the canary AND
        #: the incumbent for the whole canary window, so a hot third
        #: tenant cannot evict the incumbent mid-canary and turn a
        #: rollback into a cold fault-in. Set via ModelRegistry.pin().
        self.pinned = False
        self._cv = threading.Condition()
        self._inflight = 0

    # -- in-flight pinning ------------------------------------------------
    def acquire(self) -> "ModelEntry":
        with self._cv:
            self._inflight += 1
        return self

    def release(self) -> None:
        with self._cv:
            self._inflight = max(0, self._inflight - 1)
            self._cv.notify_all()

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Block until no request holds this entry (True) or the timeout
        expires (False). The swap path calls this on the *old* snapshot
        after flipping the pointer: new traffic can no longer acquire it,
        so the count only falls."""
        with self._cv:
            return self._cv.wait_for(lambda: self._inflight == 0, timeout)

    # -- the dispatch the batcher runs ------------------------------------
    def predict(self, X, *, predict_type: str = "value",
                iteration_range=None, missing=np.nan,
                base_margin=None) -> np.ndarray:
        """One coalesced dispatch through the bucketed serving fast path,
        scoped to this tenant (per-model ``predict_latency_seconds``
        labels). Routing — including the degrade route to the native CPU
        walker — is resolved inside the fast path by the kernel dispatch
        registry (``dispatch.resolve("predict_walk", ...)``), not passed
        down here."""
        from ..predictor.serving import serving_context

        with serving_context(model=self.label):
            return self.booster.inplace_predict(
                X, predict_type=predict_type,
                iteration_range=iteration_range, missing=missing,
                base_margin=base_margin)


class ModelRegistry:
    """The arena: name@version -> :class:`ModelEntry`, LRU-ordered, under
    a byte budget. All mutation is lock-guarded; entries a swap just
    replaced stay alive (and addressable by explicit version) until
    evicted or released."""

    def __init__(self, arena_mb: Optional[float] = None,
                 on_event=None) -> None:
        if arena_mb is None:
            arena_mb = _env_float("XGBTPU_SERVING_ARENA_MB", 512.0)
        self.budget_bytes = max(1, int(arena_mb * 1024 * 1024))
        # serving flight-recorder hook (``obs.ServingRecorder.event``):
        # evictions and fault-back-ins are timeline events an operator
        # reading serve-report needs next to the latency cliff they cause
        self._on_event = on_event or (lambda name, **args: None)
        self._lock = threading.RLock()
        self._entries: "OrderedDict[Tuple[str, int], ModelEntry]" = \
            OrderedDict()
        self._live: Dict[str, int] = {}  # serving pointer per name
        self._sources: Dict[Tuple[str, int], Tuple[str, Any]] = {}
        self._next_version: Dict[str, int] = {}
        self._g_bytes = REGISTRY.gauge(
            "serving_arena_bytes",
            "Resident bytes of stacked-forest snapshots in the model arena")
        self._g_models = REGISTRY.gauge(
            "serving_models_resident", "Models resident in the arena")
        self._hits = REGISTRY.counter(
            "serving_model_hits_total",
            "Model lookups served by a resident arena entry")
        self._misses = REGISTRY.counter(
            "serving_model_misses_total",
            "Model lookups that faulted the model back in from its source")
        self._evictions = REGISTRY.counter(
            "serving_model_evictions_total",
            "Arena entries evicted by the LRU memory budget")
        self._g_bytes.set(0)
        self._g_models.set(0)

    # ------------------------------------------------------------------
    def load(self, name: str, source: Any, *,
             version: Optional[int] = None, make_live: bool = True,
             booster=None) -> ModelEntry:
        """Load (or re-register) a model version. ``source`` is anything
        :func:`resolve_source` accepts; ``booster`` short-circuits the
        load with an already-built instance (the in-process path — the
        resolved source is still retained for fault-back-in). Returns the
        resident entry; with ``make_live`` the serving pointer flips to it
        (the caller sequences draining — see ``swap.py``)."""
        spec = resolve_source(source)
        if booster is None:
            booster = load_booster(spec)
        with self._lock:
            if version is None:
                version = self._next_version.get(name, 0) + 1
            self._next_version[name] = max(
                version, self._next_version.get(name, 0))
        # footprint (builds the forest snapshot == warms the model) runs
        # outside the lock: stacking a big forest must not stall lookups
        nbytes = _forest_footprint_bytes(booster) + _spec_bytes(spec)
        entry = ModelEntry(name, version, booster, spec, nbytes)
        with self._lock:
            key = (name, version)
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self._sources[key] = spec
            if make_live:
                self._live[name] = version
            REGISTRY.counter(
                "serving_model_loads_total",
                "Models (re)loaded into the arena").labels(
                    model=entry.label).inc()
            evicted = self._evict_to_budget_locked(keep=key)
            self._publish_locked()
        for label in evicted:  # file I/O stays off the registry lock
            self._on_event("model_evict", model=label)
        return entry

    def get(self, name: str, version: Optional[int] = None) -> ModelEntry:
        """The resident entry for ``name`` (live version unless pinned).
        A budget-evicted model faults back in from its retained source —
        counted as a miss; resident lookups are hits."""
        with self._lock:
            v = version if version is not None else self._live.get(name)
            if v is None:
                raise KeyError(f"unknown model: {name!r}")
            key = (name, v)
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._hits.inc()
                return entry
            spec = self._sources.get(key)
            if spec is None:
                raise KeyError(f"unknown model version: {name!r} v{v}")
            self._misses.inc()
        self._on_event("model_fault_in", model=f"{name}@v{v}")
        # reload outside the lock (may read disk / restack the forest)
        booster = load_booster(spec)
        nbytes = _forest_footprint_bytes(booster) + _spec_bytes(spec)
        entry = ModelEntry(name, v, booster, spec, nbytes)
        with self._lock:
            raced = self._entries.get(key)
            if raced is not None:  # another thread faulted it in first
                self._entries.move_to_end(key)
                return raced
            self._entries[key] = entry
            evicted = self._evict_to_budget_locked(keep=key)
            self._publish_locked()
        for label in evicted:
            self._on_event("model_evict", model=label)
        return entry

    def register_source(self, name: str, version: int,
                        spec: Tuple[str, Any], *,
                        live: bool = False) -> None:
        """Register a model source WITHOUT loading it — the crash-only
        restart path (``docs/serving.md`` "Failure handling"): a server
        restoring its persisted manifest registers every retained source
        lazily, and the first request for each name faults the booster
        back in exactly like an LRU eviction would."""
        if spec[0] not in ("raw", "file", "ckpt", "ckpt_dir"):
            raise ValueError(f"unknown source kind: {spec[0]!r}")
        with self._lock:
            self._sources[(name, int(version))] = (spec[0], spec[1])
            self._next_version[name] = max(
                int(version), self._next_version.get(name, 0))
            if live:
                self._live[name] = int(version)

    def sources_snapshot(self) -> Dict[Tuple[str, int], Tuple[str, Any]]:
        """Every retained (name, version) -> source spec — the manifest
        writer's input."""
        with self._lock:
            return dict(self._sources)

    def reserve_version(self, name: str, version: int) -> None:
        """Make future auto-assigned versions start beyond ``version``.
        The restart path reserves QUARANTINED version numbers: their
        manifest rows are scrubbed (so ``register_source`` never sees
        them), and without the reservation the next published checkpoint
        would be assigned a quarantined number — unpromotable forever."""
        with self._lock:
            self._next_version[name] = max(
                int(version), self._next_version.get(name, 0))

    def pin(self, name: str, version: int, pinned: bool = True) -> None:
        """Shield (or release) one resident version from LRU eviction.
        The delivery controller pins canary + incumbent for the canary
        window (docs/serving.md "Model delivery"); pinning a non-resident
        version is a no-op — the next fault-in loads it unpinned."""
        with self._lock:
            entry = self._entries.get((name, int(version)))
            if entry is not None:
                entry.pinned = bool(pinned)

    def set_live(self, name: str, version: int) -> ModelEntry:
        """Atomically flip the serving pointer (the entry must exist)."""
        with self._lock:
            if (name, version) not in self._entries \
                    and (name, version) not in self._sources:
                raise KeyError(f"unknown model version: {name!r} v{version}")
            self._live[name] = version
        return self.get(name)

    def live_version(self, name: str) -> Optional[int]:
        with self._lock:
            return self._live.get(name)

    def drop(self, name: str, version: Optional[int] = None) -> None:
        """Forget a model (all versions unless one is pinned): entries,
        sources and the serving pointer."""
        with self._lock:
            keys = [k for k in set(self._entries) | set(self._sources)
                    if k[0] == name and (version is None or k[1] == version)]
            for k in keys:
                self._entries.pop(k, None)
                self._sources.pop(k, None)
            if version is None or self._live.get(name) == version:
                self._live.pop(name, None)
            self._publish_locked()

    # ------------------------------------------------------------------
    def total_bytes(self) -> int:
        with self._lock:
            return sum(e.nbytes for e in self._entries.values())

    def resident(self) -> List[str]:
        with self._lock:
            return [e.label for e in self._entries.values()]

    def models(self) -> Dict[str, int]:
        """name -> live version (the serving pointers)."""
        with self._lock:
            return dict(self._live)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": sum(
                    e.nbytes for e in self._entries.values()),
                "resident": [
                    {"model": e.label, "bytes": e.nbytes,
                     "inflight": e.inflight,
                     "live": self._live.get(e.name) == e.version}
                    for e in self._entries.values()
                ],
                "live": {n: f"{n}@v{v}" for n, v in self._live.items()},
            }

    # ------------------------------------------------------------------
    def _evict_to_budget_locked(self, keep: Tuple[str, int]) -> List[str]:
        """Drop least-recently-used entries until under budget. The entry
        being installed is exempt (a model bigger than the whole budget
        still serves — the arena just holds nothing else). In-flight and
        explicitly pinned entries (delivery canaries) are skipped this
        pass: their memory is held by the requests / the canary anyway,
        and dropping the registry's reference would only
        hide the bytes from the gauge. Returns the evicted labels so the
        caller can emit timeline events after releasing the lock."""
        evicted: List[str] = []
        total = sum(e.nbytes for e in self._entries.values())
        if total <= self.budget_bytes:
            return evicted
        for key in list(self._entries):
            if total <= self.budget_bytes:
                break
            if key == keep:
                continue
            entry = self._entries[key]
            if entry.inflight or entry.pinned:
                continue
            del self._entries[key]
            total -= entry.nbytes
            self._evictions.inc()
            evicted.append(entry.label)
        return evicted

    def _publish_locked(self) -> None:
        self._g_bytes.set(sum(e.nbytes for e in self._entries.values()))
        self._g_models.set(len(self._entries))


def _spec_bytes(spec: Tuple[str, Any]) -> int:
    kind, payload = spec
    return len(payload) if kind == "raw" else 0
