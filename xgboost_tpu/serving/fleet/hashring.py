"""Consistent-hash ring: model name -> replica, stable under churn.

The routing front (``router.py``) concentrates each model's traffic on
one replica so arena residency and the bucketed program cache warm in one
place instead of N. The mapping must be:

- **deterministic across processes and restarts** — Python's ``hash()``
  is seeded per interpreter, so points are placed with md5 (stable,
  well-mixed; this is placement, not security). A restarted router
  recomputes exactly the same ring, and two routers over the same replica
  set agree without coordination (pinned by tests/test_fleet.py);
- **minimally disruptive** — each replica owns ``vnodes`` points on the
  ring (default 64: ~1/sqrt(64) ≈ 12% share imbalance between replicas);
  removing a replica frees only *its* points, so only the models that
  hashed to the departed replica remap (to the ring successors), and
  every other model keeps its warm replica. Adding it back restores the
  original mapping exactly.

The ring itself is membership-agnostic: :meth:`walk` yields *all*
replicas in ring order from a key's position, and the router takes the
first healthy one — so an unhealthy replica's models fail over to stable
successors without mutating the ring (and fail back the moment health
returns).
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Dict, Iterator, List, Sequence, Tuple

__all__ = ["HashRing"]


def _point(label: str) -> int:
    """A stable 64-bit ring position for ``label``."""
    return int.from_bytes(
        hashlib.md5(label.encode("utf-8")).digest()[:8], "big")


class HashRing:
    """Sorted list of (point, node) pairs; not thread-safe (the router
    mutates it only under its own lock)."""

    def __init__(self, nodes: Sequence[str] = (), *,
                 vnodes: int = 64) -> None:
        self.vnodes = max(1, int(vnodes))
        self._points: List[Tuple[int, str]] = []
        self._nodes: Dict[str, bool] = {}
        for n in nodes:
            self.add(n)

    def add(self, node: str) -> None:
        if node in self._nodes:
            return
        self._nodes[node] = True
        for i in range(self.vnodes):
            bisect.insort(self._points, (_point(f"{node}#{i}"), node))

    def remove(self, node: str) -> None:
        if self._nodes.pop(node, None) is None:
            return
        self._points = [p for p in self._points if p[1] != node]

    def nodes(self) -> List[str]:
        return sorted(self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    # ------------------------------------------------------------------
    def lookup(self, key: str) -> str:
        """The ring owner of ``key`` (first point at or after its hash,
        wrapping). Raises ``KeyError`` on an empty ring."""
        for node in self.walk(key):
            return node
        raise KeyError("hash ring is empty")

    def walk(self, key: str) -> Iterator[str]:
        """Every node in ring order starting from ``key``'s position —
        the failover order: the owner first, then stable successors.
        Each node is yielded once."""
        if not self._points:
            return
        idx = bisect.bisect_right(self._points, (_point(key), "￿"))
        seen = set()
        n = len(self._points)
        for off in range(n):
            node = self._points[(idx + off) % n][1]
            if node not in seen:
                seen.add(node)
                yield node
