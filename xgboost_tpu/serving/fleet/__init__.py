"""Fleet serving tier: replicated crash-only servers behind a routing
front (ISSUE 11 — the serving-side mirror of PAPER.md layer 8, the
reference's distributed-frontends tier above rabit).

Three pieces compose the single-process server (``serving/server.py``)
into an N-replica fleet:

- :mod:`~xgboost_tpu.serving.fleet.hashring` — deterministic consistent
  hashing (md5 points, virtual nodes): model -> replica, minimally
  disruptive under replica churn;
- :mod:`~xgboost_tpu.serving.fleet.router` — the JSONL routing front on
  one TCP port: consistent-hash placement with least-loaded spill,
  replica health probing (``fleet_replica_healthy{replica=}``), typed
  single-retry re-route on replica loss
  (``resilience.policy.should_reroute``), broadcast ``load``/``swap``;
- :mod:`~xgboost_tpu.serving.fleet.supervisor` — replica lifecycle:
  spawn N ``serve`` children sharing ONE versioned manifest, respawn
  any unplanned exit (the child restores from the manifest alone),
  scale up/down via spawn + SIGTERM drain; ``python -m xgboost_tpu
  serve-fleet`` wires supervisor + router into one command.

The third fleet ingredient — real multi-tenant fairness under
contention — lives in the core serving path where every replica applies
it: :class:`~xgboost_tpu.serving.tenancy.TenantFairQueue` (weighted-fair
dequeue) and the ``tenant_quota`` admission shed (``admission.py``).
docs/serving.md "Scaling out" is the operator walkthrough.
"""

from .hashring import HashRing  # noqa: F401
from .router import ReplicaEndpoint, Router  # noqa: F401
from .supervisor import FleetSupervisor, serve_fleet_main  # noqa: F401

__all__ = ["FleetSupervisor", "HashRing", "ReplicaEndpoint", "Router",
           "serve_fleet_main"]
