"""The fleet routing front: one TCP port, N crash-only replicas behind it.

Speaks exactly the server's JSONL line protocol (``docs/serving.md``,
"The line protocol") so every existing client works unchanged — point it
at the router instead of a replica. Per line:

- ``predict`` routes by **consistent hash of the model name**
  (``hashring.py``) so each model's arena residency and compiled-program
  cache concentrate on one replica; when the hash target is saturated
  (``XGBTPU_ROUTER_SPILL`` outstanding requests, default 16) the request
  **spills to the least-loaded** healthy replica instead of queueing
  behind the hot spot (``fleet_spills_total``).
- a request in flight to a replica that dies mid-dispatch is **re-routed
  exactly once** to a healthy replica
  (``resilience.policy.should_reroute`` — connection loss / EOF /
  timeout verdicts; predict is idempotent, so the retry can duplicate
  work but never corrupt an answer) and the replica is marked unhealthy
  immediately, without waiting for the next probe. A replica answering
  ``shed: draining`` (SIGTERM drain in progress) re-routes the same way.
  ``fleet_reroutes_total`` counts both; a failed re-route surfaces as a
  typed error line carrying the original request id.
- ``load`` / ``swap`` **broadcast** to every healthy replica (any replica
  can then serve any model; the hash only concentrates, never restricts),
  and the shared manifest (``--manifest``) makes the change durable for
  replicas that join later.
- ``metrics`` answers with the *router's* registry exposition (the
  ``fleet_*`` series); ``stats`` with the replica table + routing
  counters; ``shutdown`` stops the fleet.

Replica health: a probe thread pings every replica each
``XGBTPU_ROUTER_HEALTH_S`` (default 0.5s) with a
``XGBTPU_ROUTER_HEALTH_DEADLINE_S`` (default 2s) timeout — a replica is
healthy iff it answers and is not draining. ``fleet_replica_healthy
{replica=}`` is the gauge; transitions land as trace instants. An
unhealthy replica's models fail over to their stable ring successors
(``HashRing.walk``) and fail back automatically when the probe sees it
again — which is how a supervisor restart rejoins within one probe
interval.
"""

from __future__ import annotations

import json
import os
import socket
import socketserver
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ...observability import trace
from ...observability.metrics import REGISTRY
from ...resilience import policy
from ..faults import record_serving_fault
from .hashring import HashRing

__all__ = ["Router", "ReplicaEndpoint"]

ROUTE_SITE = "fleet_route"


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class ReplicaEndpoint:
    """One replica as the router sees it: address, health, a small
    connection pool, and the outstanding-request count the spill
    heuristic reads."""

    def __init__(self, rid: str, host: str, port: int) -> None:
        self.id = rid
        self.host = host
        self.port = port
        self.healthy = True  # the caller registers endpoints it just saw READY
        self.draining = False
        self.outstanding = 0
        self._lock = threading.Lock()
        self._pool: "deque" = deque()

    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- pooled JSONL round trip --------------------------------------
    def _acquire(self, timeout: float):
        with self._lock:
            if self._pool:
                return self._pool.popleft()
        sock = socket.create_connection((self.host, self.port),
                                        timeout=timeout)
        return sock, sock.makefile("rb")

    def _release(self, conn) -> None:
        with self._lock:
            if len(self._pool) < 8:
                self._pool.append(conn)
                return
        self._close(conn)

    @staticmethod
    def _close(conn) -> None:
        sock, rfile = conn
        for c in (rfile, sock):
            try:
                c.close()
            except OSError:
                pass

    def reset(self) -> None:
        """Drop every pooled connection (the endpoint moved or died)."""
        with self._lock:
            conns, self._pool = list(self._pool), deque()
        for conn in conns:
            self._close(conn)

    def rpc(self, msg: Dict[str, Any], timeout: float) -> Dict[str, Any]:
        """One request line -> one response line. Raises ConnectionError
        on EOF (dead replica), OSError/TimeoutError on transport
        failure; never returns None."""
        conn = self._acquire(timeout)
        sock, rfile = conn
        try:
            sock.settimeout(timeout)
            sock.sendall((json.dumps(msg) + "\n").encode())
            line = rfile.readline()
            if not line:
                raise ConnectionError(
                    f"connection closed by peer (replica {self.id})")
            out = json.loads(line)
        except BaseException:
            self._close(conn)
            raise
        self._release(conn)
        return out


class Router:
    """The routing table + forwarding logic. ``serve`` runs the TCP
    front; :meth:`handle` is the per-line entry (also driven directly by
    in-process tests and the bench stage)."""

    def __init__(self, replicas: Optional[List[ReplicaEndpoint]] = None, *,
                 vnodes: int = 64,
                 spill_after: Optional[int] = None,
                 health_interval_s: Optional[float] = None,
                 health_deadline_s: Optional[float] = None,
                 request_timeout_s: Optional[float] = None) -> None:
        self.spill_after = max(1, int(
            spill_after if spill_after is not None
            else _env_float("XGBTPU_ROUTER_SPILL", 16)))
        self.health_interval_s = max(0.05, (
            health_interval_s if health_interval_s is not None
            else _env_float("XGBTPU_ROUTER_HEALTH_S", 0.5)))
        self.health_deadline_s = max(0.1, (
            health_deadline_s if health_deadline_s is not None
            else _env_float("XGBTPU_ROUTER_HEALTH_DEADLINE_S", 2.0)))
        self.request_timeout_s = max(1.0, (
            request_timeout_s if request_timeout_s is not None
            else _env_float("XGBTPU_ROUTER_TIMEOUT_S", 120.0)))
        self._lock = threading.Lock()
        self._ring = HashRing(vnodes=vnodes)
        self._eps: Dict[str, ReplicaEndpoint] = {}
        self._g_healthy = REGISTRY.gauge(
            "fleet_replica_healthy",
            "Routing-front health verdict per replica (1 healthy)")
        self._c_routed = REGISTRY.counter(
            "fleet_routed_requests_total",
            "Requests the router forwarded, by replica")
        self._c_reroutes = REGISTRY.counter(
            "fleet_reroutes_total",
            "In-flight requests retried on a healthy replica after the "
            "hash target was lost or draining")
        self._c_spills = REGISTRY.counter(
            "fleet_spills_total",
            "Requests routed off their hash target to the least-loaded "
            "replica because the target was saturated")
        self._c_reroutes.inc(0)
        self._c_spills.inc(0)
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        for ep in (replicas or []):
            self.set_endpoint(ep.id, ep.host, ep.port)

    # ------------------------------------------------------------------
    # membership (the supervisor's write side)
    # ------------------------------------------------------------------
    def set_endpoint(self, rid: str, host: str, port: int) -> None:
        """Register or move a replica (supervisor spawn/restart). The
        ring position depends only on ``rid``, so a restarted replica
        takes back exactly its old models."""
        with self._lock:
            ep = self._eps.get(rid)
            if ep is None:
                ep = self._eps[rid] = ReplicaEndpoint(rid, host, port)
                self._ring.add(rid)
            else:
                ep.reset()
                ep.host, ep.port = host, port
                ep.healthy, ep.draining = True, False
            self._g_healthy.labels(replica=rid).set(1)

    def remove_endpoint(self, rid: str) -> None:
        """Forget a replica (scale-down): its ring points disappear, so
        only its models remap — everyone else keeps their warm replica."""
        with self._lock:
            ep = self._eps.pop(rid, None)
            self._ring.remove(rid)
            self._g_healthy.labels(replica=rid).set(0)
        if ep is not None:
            ep.reset()

    def endpoints(self) -> List[ReplicaEndpoint]:
        with self._lock:
            return list(self._eps.values())

    def mark_down(self, rid: str, why: str = "") -> None:
        """Out-of-band down verdict (the supervisor saw the process
        exit): stop routing there now instead of waiting out a probe."""
        with self._lock:
            ep = self._eps.get(rid)
        if ep is not None:
            self._mark(ep, False, why=why)

    def _mark(self, ep: ReplicaEndpoint, healthy: bool,
              draining: bool = False, why: str = "") -> None:
        with self._lock:
            changed = ep.healthy != healthy
            ep.healthy = healthy
            ep.draining = draining
            self._g_healthy.labels(replica=ep.id).set(1 if healthy else 0)
        if changed:
            trace.instant("replica_health", replica=ep.id,
                          healthy=healthy, detail=why)
        if not healthy:
            ep.reset()

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    def route(self, model: str,
              exclude: Optional[set] = None) -> Optional[ReplicaEndpoint]:
        """The replica for one request: first healthy node walking the
        ring from the model's position; least-loaded healthy when the
        hash target is saturated. None = no healthy replica at all."""
        exclude = exclude or set()
        with self._lock:
            healthy = [ep for ep in self._eps.values()
                       if ep.healthy and not ep.draining
                       and ep.id not in exclude]
            if not healthy:
                return None
            ok_ids = {ep.id for ep in healthy}
            target = None
            for rid in self._ring.walk(model):
                if rid in ok_ids:
                    target = self._eps[rid]
                    break
            if target is None:
                return None
            if target.outstanding >= self.spill_after:
                spill = min(healthy, key=lambda e: (e.outstanding, e.id))
                if spill is not target \
                        and spill.outstanding < target.outstanding:
                    self._c_spills.inc()
                    return spill
            return target

    def handle(self, msg: Dict[str, Any], shutdown=None) -> Dict[str, Any]:
        """One protocol line. Router-local ops are answered here;
        everything else forwards to a replica."""
        op = msg.get("op", "predict")
        rid = msg.get("id")
        if op == "metrics":
            return self._with_id(rid, {"metrics": REGISTRY.exposition()})
        if op == "stats":
            return self._with_id(rid, {"stats": self.stats()})
        if op == "shutdown":
            if shutdown is not None:
                shutdown()
            return self._with_id(rid, {"ok": True})
        if op in ("load", "swap", "promote", "rollback", "quarantine",
                  "unload"):
            # delivery control plane (ISSUE 12): publish/promote/rollback/
            # quarantine converge every replica — the shared manifest
            # covers any replica a broadcast missed (it restores lazily)
            return self._with_id(rid, self._broadcast(msg))
        # anything else — predict, and a `deliver` op attaching a
        # controller — runs on ONE replica. A controller attached through
        # the router therefore publishes/promotes with broadcast=None:
        # its decisions land in the shared manifest and reach the other
        # replicas at their next restart/fault-in, not live (live fleet
        # convergence needs the broadcast-wired controller the in-process
        # `ModelServer.deliver(broadcast=...)` path sets up).
        return self._forward(msg)

    def _with_id(self, rid, out: Dict[str, Any]) -> Dict[str, Any]:
        if rid is not None:
            out.setdefault("id", rid)
        return out

    def _broadcast(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        """load/swap on every healthy replica. All must succeed; the
        shared manifest then covers replicas that were down (they restore
        lazily on restart)."""
        results, errors = {}, {}
        for ep in self.endpoints():
            if not ep.healthy:
                continue
            try:
                r = ep.rpc(msg, self.request_timeout_s)
            except Exception as e:
                record_serving_fault(ROUTE_SITE, e)
                self._mark(ep, False, why=f"broadcast: {e}")
                errors[ep.id] = f"{type(e).__name__}: {e}"
                continue
            if r.get("error"):
                errors[ep.id] = r["error"]
            else:
                results[ep.id] = r.get("version")
        if errors:
            return {"error": f"{msg.get('op')} failed on "
                             f"{sorted(errors)}: {errors}",
                    "replicas_ok": sorted(results)}
        versions = sorted(set(v for v in results.values() if v))
        return {"ok": True, "version": versions[-1] if versions else None,
                "replicas": sorted(results)}

    def _forward(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        model = str(msg.get("model", "default"))
        rid = msg.get("id")
        tried: set = set()
        ep = self.route(model)
        for attempt in (0, 1):
            if ep is None:
                return self._with_id(rid, {
                    "error": "NoHealthyReplica: fleet has no healthy "
                             "replica for this request"})
            tried.add(ep.id)
            cur = ep  # the endpoint charged for THIS attempt (a re-route
            # reassigns ep before the finally runs)
            with self._lock:
                cur.outstanding += 1
            try:
                resp = cur.rpc(msg, self.request_timeout_s)
            except Exception as e:
                # transport-level loss: classify (faults_total +
                # serving_faults_total, site fleet_route) and decide
                # whether this reads as a dead peer worth one re-route
                record_serving_fault(ROUTE_SITE, e)
                self._mark(cur, False, why=f"{type(e).__name__}: {e}")
                if attempt == 0 and policy.should_reroute(e):
                    self._c_reroutes.inc()
                    trace.instant("fleet_reroute", replica=cur.id,
                                  model=model)
                    ep = self.route(model, exclude=tried)
                    continue
                return self._with_id(rid, {
                    "error": f"ReplicaLost({cur.id}): "
                             f"{type(e).__name__}: {e}"})
            finally:
                with self._lock:
                    cur.outstanding = max(0, cur.outstanding - 1)
            closing = resp.get("shed") == "draining" \
                or "model server is closed" in (resp.get("error") or "")
            if closing and attempt == 0:
                # the replica is exiting cleanly (drain shed, or a request
                # that slipped into the post-drain close window): treat
                # like loss, with the same single-retry bound
                self._mark(ep, False, draining=True, why="draining")
                self._c_reroutes.inc()
                trace.instant("fleet_reroute", replica=ep.id,
                              model=model, draining=True)
                ep = self.route(model, exclude=tried)
                continue
            self._c_routed.labels(replica=ep.id).inc()
            return resp
        raise AssertionError("unreachable")  # pragma: no cover

    # ------------------------------------------------------------------
    # health probing
    # ------------------------------------------------------------------
    def probe(self, ep: ReplicaEndpoint) -> bool:
        try:
            r = ep.rpc({"op": "ping"}, self.health_deadline_s)
        except Exception as e:
            if ep.healthy:  # classify the transition, not every re-probe
                record_serving_fault(ROUTE_SITE, e, kind=policy.TRANSIENT)
            self._mark(ep, False, why=f"probe: {type(e).__name__}")
            return False
        healthy = bool(r.get("ok")) and not r.get("draining")
        self._mark(ep, healthy, draining=bool(r.get("draining")),
                   why="probe")
        return healthy

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            for ep in self.endpoints():
                if self._stop.is_set():
                    return
                self.probe(ep)

    def start(self) -> "Router":
        """Arm the health-probe thread (idempotent)."""
        if self._prober is None or not self._prober.is_alive():
            self._stop.clear()
            self._prober = threading.Thread(
                target=self._probe_loop, name="xgbtpu-fleet-prober",
                daemon=True)
            self._prober.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        for ep in self.endpoints():
            ep.reset()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            reps = [{"replica": ep.id, "address": ep.address(),
                     "healthy": ep.healthy, "draining": ep.draining,
                     "outstanding": ep.outstanding}
                    for ep in sorted(self._eps.values(),
                                     key=lambda e: e.id)]
        return {
            "replicas": reps,
            "reroutes": self._c_reroutes.labels().value,
            "spills": self._c_spills.labels().value,
            "spill_after": self.spill_after,
        }

    # ------------------------------------------------------------------
    # the TCP front
    # ------------------------------------------------------------------
    def serve(self, port: int, host: str = "127.0.0.1", *,
              stdout=None, on_shutdown=None,
              banner: str = "") -> int:
        """Serve the line protocol until a ``shutdown`` op or SIGTERM
        (handled by the caller — ``supervisor.serve_fleet_main`` wires
        fleet-wide drain). Returns 0."""
        import sys

        router = self
        stdout = stdout if stdout is not None else sys.stdout

        class Handler(socketserver.StreamRequestHandler):
            def handle(self) -> None:
                for raw in self.rfile:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line:
                        continue
                    try:
                        msg = json.loads(line)
                    except ValueError as e:
                        out = {"error": f"bad json: {e}"}
                    else:
                        out = router.handle(msg, shutdown)
                    try:
                        self.wfile.write((json.dumps(out) + "\n").encode())
                        self.wfile.flush()
                    except OSError:
                        return  # client went away mid-response

        class Srv(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        tcp = Srv((host, port), Handler)
        self._tcp = tcp

        def shutdown() -> None:
            threading.Thread(target=tcp.shutdown, daemon=True).start()
            if on_shutdown is not None:
                on_shutdown()

        self.start()
        bound_host, bound_port = tcp.server_address[:2]
        print(banner or f"READY fleet router on {bound_host}:{bound_port} "
              f"({len(self.endpoints())} replicas, pid={os.getpid()})",
              file=stdout, flush=True)
        try:
            tcp.serve_forever(poll_interval=0.1)
        except KeyboardInterrupt:
            pass
        finally:
            tcp.server_close()
            self.stop()
        return 0

    def request_shutdown(self) -> None:
        """Stop a live ``serve`` loop from another thread (the SIGTERM
        path)."""
        tcp = getattr(self, "_tcp", None)
        if tcp is not None:
            threading.Thread(target=tcp.shutdown, daemon=True).start()
