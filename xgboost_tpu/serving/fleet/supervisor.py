"""Replica supervisor: spawn, watch, restart and scale crash-only servers.

``python -m xgboost_tpu serve-fleet --replicas N --run-dir D --port P``
is the one-command fleet: N ``serve`` subprocesses (each today's
crash-only server, ``serving/server.py``) sharing ONE manifest
(``D/manifest.json`` — the versioned, merge-on-write, atomic-rename
contract in ``ModelServer._write_manifest``), fronted by the consistent-
hash :class:`~xgboost_tpu.serving.fleet.router.Router` on one TCP port.
Layout under the fleet run_dir::

    D/manifest.json          # shared: every replica's loads/swaps merge here
    D/models/                # raw-source spill (written by replicas)
    D/fleet.json             # supervisor state: replica ids/ports/pids/gen
    D/replica<k>/            # each replica's private run_dir
        obs/server/...       #   its serving flight recorder (serve-report
        serve.log            #   merges every replica<k>/ — ISSUE 11)

Crash-only supervision: a replica process that exits for ANY reason the
supervisor did not initiate (SIGKILL, a crash, an operator's SIGTERM
drain) is respawned with only ``--run-dir``/``--manifest`` — it re-serves
its full model set lazily from the shared manifest, exactly like the
single-server restart contract (docs/serving.md "Failure handling").
``--model name=path`` flags seed the manifest on first boot only;
restarts never re-load (and never burn version numbers). The router is
told about every spawn/restart (``set_endpoint`` — same ring position,
so a restarted replica takes back exactly its models) and scale-down
(``remove_endpoint`` after SIGTERM drain, which loses zero admitted
requests).

Scaling: :meth:`FleetSupervisor.scale` spawns new replicas or
SIGTERM-drains the highest-numbered ones. ``XGBTPU_REPLICAS`` is the
default count. ``fleet_replica_restarts_total`` counts unplanned
respawns; ``fleet_replicas`` is the target gauge.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ...observability import flight as _flight
from ...observability import trace
from ...observability.metrics import REGISTRY
from .router import Router

__all__ = ["FleetSupervisor", "serve_fleet_main"]

FLEET_FORMAT = "xgbtpu-fleet-v1"


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def free_port(host: str = "127.0.0.1") -> int:
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


class _Replica:
    """One supervised child: process handle, endpoint, log plumbing."""

    def __init__(self, rid: int, port: int,
                 proc: "subprocess.Popen") -> None:
        self.rid = rid
        self.port = port
        self.proc = proc
        self.ready = threading.Event()
        self.generation = 0
        self.expected_exit = False

    @property
    def name(self) -> str:
        return f"r{self.rid}"


class FleetSupervisor:
    """Owns the replica processes. ``spawn_cmd(rid, port) -> argv`` is
    injectable so tests can supervise a stdlib stub instead of paying a
    jax interpreter per replica; the default builds the real ``serve``
    command."""

    def __init__(self, run_dir: str, *,
                 replicas: Optional[int] = None,
                 models: Optional[Dict[str, str]] = None,
                 host: str = "127.0.0.1",
                 serve_args: Optional[List[str]] = None,
                 spawn_cmd: Optional[Callable] = None,
                 ready_timeout_s: float = 180.0,
                 router: Optional[Router] = None) -> None:
        self.run_dir = os.path.abspath(run_dir)
        self.manifest = os.path.join(self.run_dir, "manifest.json")
        self.host = host
        self.models = dict(models or {})
        self.serve_args = list(serve_args or [])
        self.spawn_cmd = spawn_cmd
        self.ready_timeout_s = ready_timeout_s
        self.router = router
        self.target = max(1, replicas if replicas is not None
                          else _env_int("XGBTPU_REPLICAS", 2))
        self._lock = threading.Lock()
        self._replicas: Dict[int, _Replica] = {}
        self._stopping = False
        self._monitor: Optional[threading.Thread] = None
        self._g_replicas = REGISTRY.gauge(
            "fleet_replicas", "Supervised replica target count")
        self._c_restarts = REGISTRY.counter(
            "fleet_replica_restarts_total",
            "Replica processes respawned after an unplanned exit")
        self._c_restarts.inc(0)
        os.makedirs(self.run_dir, exist_ok=True)

    # ------------------------------------------------------------------
    def _default_cmd(self, rid: int, port: int) -> List[str]:
        cmd = [sys.executable, "-m", "xgboost_tpu", "serve",
               "--port", str(port), "--host", self.host,
               "--run-dir", os.path.join(self.run_dir, f"replica{rid}"),
               "--manifest", self.manifest] + self.serve_args
        if self.models and not os.path.exists(self.manifest):
            # bootstrap only: afterwards the shared manifest IS the model
            # set, and restarts must prove they can serve from it alone
            for name, path in sorted(self.models.items()):
                cmd += ["--model", f"{name}={path}"]
        return cmd

    def _spawn(self, rid: int, *, restart: bool = False) -> _Replica:
        port = free_port(self.host)
        cmd = (self.spawn_cmd or self._default_cmd)(rid, port)
        rdir = os.path.join(self.run_dir, f"replica{rid}")
        os.makedirs(rdir, exist_ok=True)
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        rep = _Replica(rid, port, proc)
        log_path = os.path.join(rdir, "serve.log")

        def pump() -> None:
            # the replica's stdout -> its log file; the first READY line
            # flips the ready event the spawner blocks on
            try:
                with open(log_path, "a") as log:
                    for line in proc.stdout:
                        log.write(line)
                        log.flush()
                        if line.startswith("READY"):
                            rep.ready.set()
            except (OSError, ValueError):
                pass

        threading.Thread(target=pump, name=f"xgbtpu-fleet-log-{rid}",
                         daemon=True).start()
        if not rep.ready.wait(self.ready_timeout_s):
            proc.kill()
            raise RuntimeError(
                f"replica {rid} not READY within {self.ready_timeout_s}s "
                f"(see {log_path})")
        with self._lock:
            old = self._replicas.get(rid)
            rep.generation = (old.generation + 1) if old else 0
            self._replicas[rid] = rep
        if restart:
            self._c_restarts.inc()
        trace.instant("replica_spawn", replica=rep.name, port=port,
                      pid=proc.pid, restart=restart)
        if self.router is not None:
            self.router.set_endpoint(rep.name, self.host, port)
        self._write_state()
        return rep

    # ------------------------------------------------------------------
    def start(self) -> "FleetSupervisor":
        self._g_replicas.set(self.target)
        for rid in range(self.target):
            self._spawn(rid)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="xgbtpu-fleet-monitor",
            daemon=True)
        self._monitor.start()
        return self

    def _monitor_loop(self) -> None:
        while True:
            time.sleep(0.2)
            with self._lock:
                if self._stopping:
                    return
                dead = [rep for rep in self._replicas.values()
                        if rep.proc.poll() is not None]
            for rep in dead:
                with self._lock:
                    if self._stopping or rep.expected_exit:
                        continue
                    current = self._replicas.get(rep.rid)
                    if current is not rep:
                        continue  # already respawned
                rc = rep.proc.returncode
                trace.instant("replica_exit", replica=rep.name, rc=rc)
                if self.router is not None:
                    # don't wait out a probe interval: the process is gone
                    self.router.mark_down(rep.name,
                                          why=f"process exit rc={rc}")
                try:
                    self._spawn(rep.rid, restart=True)
                except (OSError, RuntimeError) as e:
                    trace.instant("replica_respawn_failed",
                                  replica=rep.name, error=str(e))

    # ------------------------------------------------------------------
    def scale(self, n: int, drain_timeout_s: float = 60.0) -> None:
        """Spawn up / SIGTERM-drain down to ``n`` replicas. Scale-down
        drains the highest-numbered replicas (SIGTERM loses zero admitted
        requests — the server's crash-only drain contract) and removes
        them from the router BEFORE the signal so no new request races
        the drain."""
        n = max(1, int(n))
        with self._lock:
            have = sorted(self._replicas)
            self.target = n
        self._g_replicas.set(n)
        for rid in range(len(have), n):
            self._spawn(rid)
        for rid in have[n:]:
            with self._lock:
                rep = self._replicas.get(rid)
                if rep is None:
                    continue
                rep.expected_exit = True
            if self.router is not None:
                self.router.remove_endpoint(rep.name)
            self._terminate(rep, drain_timeout_s)
            with self._lock:
                self._replicas.pop(rid, None)
        self._write_state()

    @staticmethod
    def _terminate(rep: _Replica, timeout_s: float) -> None:
        if rep.proc.poll() is None:
            try:
                rep.proc.send_signal(signal.SIGTERM)
            except OSError:
                return
        try:
            rep.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            rep.proc.kill()
            rep.proc.wait(timeout=10)

    def stop(self, drain_timeout_s: float = 60.0) -> None:
        with self._lock:
            self._stopping = True
            reps = list(self._replicas.values())
            for rep in reps:
                rep.expected_exit = True
        for rep in reps:
            self._terminate(rep, drain_timeout_s)
        self._write_state()

    # ------------------------------------------------------------------
    def replicas(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [{"replica": rep.name, "port": rep.port,
                     "pid": rep.proc.pid, "generation": rep.generation,
                     "alive": rep.proc.poll() is None}
                    for rep in sorted(self._replicas.values(),
                                      key=lambda r: r.rid)]

    def _write_state(self) -> None:
        """``fleet.json``: the operator's (and CI lane's) view of which
        pids/ports are live — atomic like every shared artifact here."""
        _flight.atomic_write_json(
            os.path.join(self.run_dir, "fleet.json"),
            {"format": FLEET_FORMAT, "unix_ms": time.time() * 1e3,
             "supervisor_pid": os.getpid(), "target": self.target,
             "manifest": self.manifest, "replicas": self.replicas()})


# ---------------------------------------------------------------------------
# CLI: python -m xgboost_tpu serve-fleet
# ---------------------------------------------------------------------------


def _parse_fleet_args(argv: List[str]) -> Dict[str, Any]:
    opts: Dict[str, Any] = {"models": {}, "port": None,
                            "host": "127.0.0.1", "replicas": None,
                            "run_dir": None, "serve_args": []}
    flags = {"--port": ("port", int), "--replicas": ("replicas", int),
             "--host": ("host", str), "--run-dir": ("run_dir", str)}
    passthrough = {"--arena-mb", "--batch-wait-us", "--max-queue"}
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--model":
            i += 1
            name, sep, path = argv[i].partition("=")
            if not sep:
                raise ValueError("--model takes name=path")
            opts["models"][name] = path
        elif a in flags:
            key, conv = flags[a]
            i += 1
            opts[key] = conv(argv[i])
        elif a in passthrough:
            i += 1
            opts["serve_args"] += [a, argv[i]]
        else:
            raise ValueError(f"unknown serve-fleet option: {a!r}")
        i += 1
    if opts["port"] is None or not opts["run_dir"]:
        raise ValueError("serve-fleet needs --port N and --run-dir D")
    return opts


def serve_fleet_main(argv: List[str], stdout=None) -> int:
    """``python -m xgboost_tpu serve-fleet`` entry: supervisor + router
    in one process, replicas as children. SIGTERM drains the whole fleet
    (replicas first — zero admitted requests lost — then the router) and
    exits 0."""
    try:
        opts = _parse_fleet_args(argv)
    except (ValueError, IndexError) as e:
        print(f"serve-fleet: {e}", file=sys.stderr)
        print("usage: python -m xgboost_tpu serve-fleet --port N "
              "--run-dir D [--replicas K] [--model name=path ...] "
              "[--host H] [--arena-mb M] [--batch-wait-us U] "
              "[--max-queue Q]", file=sys.stderr)
        return 1
    stdout = stdout if stdout is not None else sys.stdout
    router = Router()
    sup = FleetSupervisor(
        opts["run_dir"], replicas=opts["replicas"], models=opts["models"],
        host=opts["host"], serve_args=opts["serve_args"], router=router)
    sup.start()

    stopping = threading.Event()

    def shutdown_fleet() -> None:
        if stopping.is_set():
            return
        stopping.set()
        sup.stop()

    prev_term = None
    try:
        def _sigterm(signum, frame):
            threading.Thread(target=shutdown_fleet, daemon=True).start()
            router.request_shutdown()

        prev_term = signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (in-process tests)

    reps = sup.replicas()
    banner = (f"READY fleet on {opts['host']}:{opts['port']} "
              f"({len(reps)} replicas: "
              + " ".join(f"{r['replica']}={r['port']}" for r in reps)
              + f" pid={os.getpid()})")
    try:
        return router.serve(opts["port"], opts["host"], stdout=stdout,
                            on_shutdown=shutdown_fleet, banner=banner)
    finally:
        shutdown_fleet()
        if prev_term is not None:
            try:
                signal.signal(signal.SIGTERM, prev_term)
            except ValueError:
                pass
