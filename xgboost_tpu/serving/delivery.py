"""Continuous train-to-serve delivery: watch, publish, canary, gate,
promote, auto-rollback (ISSUE 12, ROADMAP 3).

The training plane writes checksummed checkpoints (``resilience/
checkpoint.py``); the serving plane hot-swaps versioned models behind
breakers and drains (``swap.py`` / ``faults.py``). This module closes the
loop between them — the online-delivery story the reference never
shipped:

- **watch** — a :class:`DeliveryController` polls a training ``run_dir``
  (``XGBTPU_DELIVERY_POLL_S``) through the PR-4 verified readers. A torn
  or bit-flipped checkpoint is *skipped and counted*
  (``delivery_checkpoints_skipped_total{reason="corrupt"}``) — the old
  version keeps serving; a quarantined round is never picked up again
  (``reason="quarantined"``).
- **publish** — the newest verified new checkpoint becomes ``name@vN``
  via ``ModelRegistry.load(..., make_live=False)`` + a manifest rewrite
  (chaos site ``delivery_publish``), warmed before any traffic can see
  it. With a fleet ``broadcast`` hook, the publish also rides a router
  ``load`` broadcast so every replica holds the version.
- **canary** — two modes (``XGBTPU_CANARY_MODE``):
  *shadow* (default, zero risk): a deterministic ``request_id``-hash
  sample of live requests (``XGBTPU_CANARY_FRACTION``) is duplicated to
  the candidate and the outputs/latency diffed (chaos site
  ``canary_diff``) without affecting responses; *fraction*: the same
  hash split actually serves the sampled requests from the candidate.
  Both canary and incumbent entries are **pinned** against arena LRU
  eviction for the whole window, so a hot third tenant cannot turn a
  rollback into a cold fault-in.
- **gate** — promotion requires, over at least
  ``XGBTPU_CANARY_MIN_REQUESTS`` candidate observations: the candidate's
  live p99 (per-model ``predict_latency_seconds``) within
  ``XGBTPU_PROMOTE_P99_RATIO`` of the incumbent's, the candidate's
  error rate no worse than the incumbent's (the per-version
  error-budget-burn analog: both arms see the same traffic window, so
  comparing miss rates compares burn), AND a quality gate — held-out
  AUC through the bench parity-gate machinery
  (``metric.create_metric("auc")``), candidate no worse than the
  incumbent by more than ``XGBTPU_PROMOTE_DAUC`` (improvements always
  pass).
- **promote** — the existing warm hot-swap (``swap.promote_live``: the
  load already happened at publish; the flip drains the old snapshot);
  fleet promote = router ``promote`` broadcast.
- **auto-rollback** — for ``XGBTPU_DELIVERY_BAKE_S`` after the flip the
  controller watches the model's NAME-keyed circuit breaker (keyed by
  name exactly so a bad swap trips it — ``faults.py``). A trip
  re-swaps to the last-good version (still pinned → warm), **quarantines**
  the bad version in the manifest (the watcher never re-promotes that
  round) and resets the breaker so restored traffic flows immediately.

The second half of the loop is training-side: ``train(resume_from=...,
resume_mode="append")`` trains ``num_boost_round`` MORE rounds on top of
the newest verified checkpoint — on possibly fresh data — so a periodic
re-train + this controller is a real online-learning loop (boosting is
naturally incremental; docs/serving.md "Model delivery").

Every step lands on the serving recorder timeline (checkpoint_seen /
checkpoint_skipped / model_published / canary_start / canary_rejected /
model_promoted / model_rolled_back / model_quarantined) and renders in
``python -m xgboost_tpu serve-report``.
"""

from __future__ import annotations

import os
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability.metrics import REGISTRY
from ..resilience import chaos, checkpoint as _ckpt
from . import faults

__all__ = ["CanaryState", "CanaryRouter", "DeliveryController",
           "attach_shadow", "shadow_diff"]

#: controller fault-classification sites (``faults_total{site=}`` /
#: ``serving_faults_total{site=}``); the first two are chaos-injectable
PUBLISH_SITE = "delivery_publish"
DIFF_SITE = "canary_diff"
WATCH_SITE = "delivery_watch"
SHADOW_SITE = "canary_shadow"

#: the tenant lane shadow traffic rides (kept out of real tenants' fair
#: shares, visibly separate in access logs / per-tenant rollups, and
#: recognized by the batcher to keep shadow failures out of the live
#: breaker/quarantine plane) — defined in tenancy.py next to the other
#: reserved lanes
from .tenancy import SHADOW_TENANT  # noqa: E402  (re-export)

_ENV_FRACTION = "XGBTPU_CANARY_FRACTION"
_ENV_MODE = "XGBTPU_CANARY_MODE"
_ENV_MIN_REQUESTS = "XGBTPU_CANARY_MIN_REQUESTS"
_ENV_CANARY_DEADLINE = "XGBTPU_CANARY_DEADLINE_S"
_ENV_DAUC = "XGBTPU_PROMOTE_DAUC"
_ENV_P99_RATIO = "XGBTPU_PROMOTE_P99_RATIO"
_ENV_POLL = "XGBTPU_DELIVERY_POLL_S"
_ENV_BAKE = "XGBTPU_DELIVERY_BAKE_S"

#: delivery_state{model=} gauge values
IDLE, CANARY, BAKE = 0, 1, 2


#: the serving package's shared env parser (faults.py owns it)
_env_num = faults._env_num


def _hash_unit(request_id: str) -> float:
    """Deterministic [0, 1) from a request id — the canary split is a
    pure function of the id, so the same request replayed lands on the
    same arm (and tests can pick ids per arm)."""
    return (zlib.crc32(str(request_id).encode("utf-8", "replace"))
            % 1_000_000) / 1e6


# ---------------------------------------------------------------------------
# canary state + the server-side router
# ---------------------------------------------------------------------------


class CanaryState:
    """One active canary: candidate vs incumbent accounting for a model
    name. Thread-safe — request threads observe outcomes, the batcher
    worker runs shadow diffs, the controller reads the gate inputs."""

    def __init__(self, name: str, version: int, incumbent_version: int,
                 *, mode: str = "shadow", fraction: float = 0.25) -> None:
        if mode not in ("shadow", "fraction"):
            raise ValueError(f"unknown canary mode: {mode!r}")
        self.name = name
        self.version = int(version)
        self.incumbent_version = int(incumbent_version)
        self.mode = mode
        self.fraction = min(max(float(fraction), 0.0), 1.0)
        self.candidate_label = f"{name}@v{version}"
        self.incumbent_label = f"{name}@v{incumbent_version}"
        self.started_unix = time.time()
        self._lock = threading.Lock()
        self.requests = {"candidate": 0, "incumbent": 0}
        self.errors = {"candidate": 0, "incumbent": 0}
        self.diffs = 0
        self.max_diff = 0.0
        self.sum_diff = 0.0
        self.shadow_dropped = 0
        self._c_requests = REGISTRY.counter(
            "delivery_canary_requests_total",
            "Requests observed by an active canary, by model and arm")
        self._c_diffs = REGISTRY.counter(
            "delivery_canary_diffs_total",
            "Shadow-mode output diffs computed between canary and "
            "incumbent")

    # -- request arms ---------------------------------------------------
    def route_version(self, request_id: str) -> Optional[int]:
        """Fraction mode only: the candidate version when this request's
        hash falls in the canary fraction, else None (incumbent)."""
        if self.mode == "fraction" \
                and _hash_unit(request_id) < self.fraction:
            return self.version
        return None

    def should_shadow(self, request_id: str) -> bool:
        """Shadow mode only: duplicate this request to the candidate?"""
        return self.mode == "shadow" \
            and _hash_unit(request_id) < self.fraction

    def watch_future(self, fut, which: str) -> None:
        """Observe one request's outcome when its future resolves (the
        callback runs on the resolving thread — counter bumps only).
        Latency is NOT tracked per-arm here: the p99 gate reads the
        per-model ``predict_latency_seconds`` histogram instead."""

        def _cb(f) -> None:
            try:
                exc = f.exception()
            except BaseException:  # cancelled — counts as not-ok
                exc = True
            self.observe(which, exc is None)

        fut.add_done_callback(_cb)

    def observe(self, which: str, ok: bool) -> None:
        with self._lock:
            self.requests[which] += 1
            if not ok:
                self.errors[which] += 1
        self._c_requests.labels(model=self.name, arm=which).inc()

    def note_diff(self, diff: float) -> None:
        with self._lock:
            self.diffs += 1
            self.max_diff = max(self.max_diff, diff)
            self.sum_diff += diff
        self._c_diffs.inc()

    def note_shadow_dropped(self) -> None:
        """A shadow duplicate the server declined to enqueue (shed /
        submit failure): not an arm outcome — the candidate never saw
        it — just visibility."""
        with self._lock:
            self.shadow_dropped += 1

    # -- reads ----------------------------------------------------------
    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"candidate": self.requests["candidate"],
                    "incumbent": self.requests["incumbent"],
                    "candidate_errors": self.errors["candidate"],
                    "incumbent_errors": self.errors["incumbent"]}

    def summary(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "model": self.name, "mode": self.mode,
                "fraction": self.fraction,
                "candidate": self.candidate_label,
                "incumbent": self.incumbent_label,
                "requests": dict(self.requests),
                "errors": dict(self.errors),
                "diffs": self.diffs,
                "max_diff": round(self.max_diff, 9),
                "mean_diff": round(self.sum_diff / self.diffs, 9)
                if self.diffs else 0.0,
                "shadow_dropped": self.shadow_dropped,
            }


class CanaryRouter:
    """The server's per-name canary table. ``ModelServer.predict_async``
    consults it on every request whose version the caller did not pin:
    fraction-mode requests may be re-routed to the candidate, shadow-mode
    requests may be duplicated. No active canary = one dict read."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._active: Dict[str, CanaryState] = {}

    def start(self, state: CanaryState) -> None:
        with self._lock:
            if state.name in self._active:
                raise RuntimeError(
                    f"a canary is already active for {state.name!r}")
            self._active[state.name] = state

    def end(self, name: str) -> Optional[CanaryState]:
        with self._lock:
            return self._active.pop(name, None)

    def active(self, name: str) -> Optional[CanaryState]:
        with self._lock:
            return self._active.get(name)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            states = list(self._active.values())
        return [s.summary() for s in states]


def shadow_diff(state: CanaryState, primary_out, shadow_out) -> None:
    """Diff one shadow pair (max |candidate - incumbent| over the
    flattened outputs; shape mismatch records ``inf`` — a candidate that
    changed output arity is maximally different). Runs on the resolving
    thread; chaos site ``canary_diff`` makes the diff path itself
    fault-injectable, and any failure is classified, never raised into
    the batcher worker."""
    try:
        chaos.hit(DIFF_SITE)
        a = np.asarray(primary_out, np.float64).ravel()
        b = np.asarray(shadow_out, np.float64).ravel()
        d = float(np.max(np.abs(a - b))) if a.shape == b.shape \
            else float("inf")
        state.note_diff(d)
    except Exception as e:
        faults.record_serving_fault(DIFF_SITE, e)


def attach_shadow(state: CanaryState, primary_fut, shadow_fut) -> None:
    """Rendezvous two futures (live response + shadow duplicate) and diff
    their outputs once both resolve. Non-blocking: whichever future
    resolves second performs the diff — callbacks must never wait on the
    sibling, both may resolve on the single batcher worker thread. The
    candidate arm's outcome is observed here (the primary's is observed
    by the server's general canary watch)."""
    slots: Dict[str, Any] = {}
    lock = threading.Lock()

    def _arrive(which: str, f) -> None:
        try:
            exc = f.exception()
        except BaseException:
            exc = True
        if which == "shadow":
            state.observe("candidate", exc is None)
        result = None if exc is not None else f.result()
        with lock:
            slots[which] = (exc, result)
            if len(slots) < 2:
                return
            (p_exc, p_out) = slots["primary"]
            (s_exc, s_out) = slots["shadow"]
        if p_exc is None and s_exc is None:
            shadow_diff(state, p_out, s_out)

    primary_fut.add_done_callback(lambda f: _arrive("primary", f))
    shadow_fut.add_done_callback(lambda f: _arrive("shadow", f))


# ---------------------------------------------------------------------------
# the delivery controller
# ---------------------------------------------------------------------------


class DeliveryController:
    """Watch one training checkpoint directory and deliver its verified
    checkpoints to one model name on a :class:`~xgboost_tpu.serving.ModelServer`
    — publish → canary → gate → promote → bake → (auto-rollback +
    quarantine). One controller per (server, model name); start with
    :meth:`start` (daemon thread) or drive one cycle with :meth:`poll`
    from a test. ``eval_data=(X, y)`` arms the AUC quality gate
    (without it only the SLO gates apply — documented operator choice).
    ``broadcast(msg) -> resp`` mirrors publish/promote/rollback/
    quarantine to a fleet router (docs/serving.md "Model delivery")."""

    def __init__(self, server, name: str, watch_dir: str, *,
                 eval_data: Optional[Tuple[Any, Any]] = None,
                 mode: Optional[str] = None,
                 fraction: Optional[float] = None,
                 min_requests: Optional[int] = None,
                 canary_deadline_s: Optional[float] = None,
                 dauc_tol: Optional[float] = None,
                 p99_ratio: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 bake_s: Optional[float] = None,
                 from_rounds: Optional[int] = None,
                 broadcast: Optional[Callable[[Dict[str, Any]],
                                              Dict[str, Any]]] = None
                 ) -> None:
        self.server = server
        self.name = name
        self.watch_dir = watch_dir
        self.eval_data = eval_data
        self.mode = mode if mode is not None \
            else os.environ.get(_ENV_MODE, "shadow")
        if self.mode not in ("shadow", "fraction"):
            raise ValueError(f"unknown canary mode: {self.mode!r}")
        self.fraction = fraction if fraction is not None \
            else _env_num(_ENV_FRACTION, 0.25)
        self.min_requests = max(1, min_requests if min_requests is not None
                                else _env_num(_ENV_MIN_REQUESTS, 32, int))
        self.canary_deadline_s = canary_deadline_s \
            if canary_deadline_s is not None \
            else _env_num(_ENV_CANARY_DEADLINE, 600.0)
        self.dauc_tol = dauc_tol if dauc_tol is not None \
            else _env_num(_ENV_DAUC, 0.002)
        self.p99_ratio = max(1.0, p99_ratio if p99_ratio is not None
                             else _env_num(_ENV_P99_RATIO, 1.25))
        self.poll_s = max(0.01, poll_s if poll_s is not None
                          else _env_num(_ENV_POLL, 1.0))
        self.bake_s = max(0.0, bake_s if bake_s is not None
                          else _env_num(_ENV_BAKE, 30.0))
        self.broadcast = broadcast
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._state = IDLE
        self._published: Dict[int, int] = {}  # rounds -> version
        self._skipped_once: set = set()  # (path, size) already counted
        self._history: List[Dict[str, Any]] = []
        # restart resilience: rounds quarantined by a PREVIOUS controller
        # live in the manifest the server restored — never re-promote them
        self._quarantined_rounds: set = {
            int(info.get("rounds", -1))
            for info in server.quarantined_versions(name).values()
            if info.get("rounds") is not None}
        if from_rounds is not None:
            self._processed = int(from_rounds)
        else:
            # default baseline: when the server already serves this name,
            # assume the operator seeded it from the newest checkpoint
            # present now — only NEW checkpoints are delivered. A server
            # without the model delivers everything from round 0.
            got = _ckpt.load_latest(watch_dir) \
                if server.registry.live_version(name) is not None else None
            self._processed = got[1] if got is not None else 0
        self._c_seen = REGISTRY.counter(
            "delivery_checkpoints_seen_total",
            "New verified checkpoints picked up by the delivery watcher")
        self._c_skipped = REGISTRY.counter(
            "delivery_checkpoints_skipped_total",
            "Checkpoints the delivery watcher refused, by reason "
            "(corrupt = failed verification, quarantined = rolled back "
            "earlier)")
        for reason in ("corrupt", "quarantined"):
            self._c_skipped.labels(reason=reason)
        self._c_published = REGISTRY.counter(
            "delivery_publishes_total",
            "Checkpoint versions published (resident, not yet live)")
        self._c_promoted = REGISTRY.counter(
            "delivery_promotions_total",
            "Canary versions promoted to live")
        self._c_rejected = REGISTRY.counter(
            "delivery_canary_rejected_total",
            "Canary versions rejected by the promotion gates, by reason")
        self._c_rollbacks = REGISTRY.counter(
            "delivery_rollbacks_total",
            "Auto-rollbacks to the last-good version after a "
            "post-promotion breaker trip")
        self._c_quarantined = REGISTRY.counter(
            "delivery_quarantines_total",
            "Versions quarantined in the manifest by auto-rollback")
        self._g_state = REGISTRY.gauge(
            "delivery_state",
            "Delivery controller state per model: 0 idle, 1 canary, "
            "2 bake").labels(model=name)
        self._c_promoted.inc(0)
        self._c_rollbacks.inc(0)
        self._g_state.set(IDLE)

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "DeliveryController":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name=f"xgbtpu-delivery-{self.name}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
        # never leave routing state armed after the controller dies
        state = self.server.canary.end(self.name)
        if state is not None:
            self._unpin(state.version, state.incumbent_version)
            self._set_state(IDLE)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll()
            except Exception as e:
                # the watcher must survive anything a cycle throws (bad
                # disk, publish chaos, a gate read racing a close): the
                # failure is classified and the next poll retries
                faults.record_serving_fault(WATCH_SITE, e)
            self._stop.wait(self.poll_s)

    # ------------------------------------------------------------------
    # one watch cycle
    # ------------------------------------------------------------------
    def poll(self) -> Optional[str]:
        """One watch cycle: scan for a new deliverable checkpoint and, if
        one exists, run the full delivery pipeline on it. Returns the
        cycle outcome (``promoted`` / ``rolled_back`` / ``rejected`` /
        ``bootstrapped`` / None when nothing new)."""
        cand = self._scan()
        if cand is None:
            return None
        path, rounds = cand
        self._event("checkpoint_seen", rounds=rounds, path=path)
        self._c_seen.inc()
        return self._deliver(path, rounds)

    def _scan(self) -> Optional[Tuple[str, int]]:
        """Newest verified checkpoint with rounds beyond the processed
        mark — counting (once) every corrupt or quarantined file it had
        to look past. Multiple new checkpoints collapse to the newest:
        boosting snapshots are strictly cumulative. Steady-state polls
        cost zero file I/O: full verification (a read + sha256 over the
        whole payload) runs only for files NAMED beyond the processed
        mark — a watched multi-hundred-MB model must not be re-hashed
        every ``poll_s`` forever. The filename is only a hint: anything
        it flags as new is fully verified before delivery."""
        for path in reversed(_ckpt.list_checkpoints(self.watch_dir)):
            hint = _ckpt.path_rounds(path)
            if hint is not None and hint <= self._processed:
                return None  # nothing new: settled territory, no reads
            ok, detail, rounds = _ckpt.verify_checkpoint(path)
            if ok and rounds <= self._processed:
                return None  # everything older is already handled
            if not ok:
                try:
                    key = (path, os.path.getsize(path))
                except OSError:
                    key = (path, -1)
                if key not in self._skipped_once:
                    self._skipped_once.add(key)
                    self._c_skipped.labels(reason="corrupt").inc()
                    self._event("checkpoint_skipped", reason="corrupt",
                                detail=detail, path=path)
                continue
            if rounds in self._quarantined_rounds:
                key = (path, "quarantined")
                if key not in self._skipped_once:
                    self._skipped_once.add(key)
                    self._c_skipped.labels(reason="quarantined").inc()
                    self._event("checkpoint_skipped",
                                reason="quarantined", rounds=rounds,
                                path=path)
                continue
            return path, rounds
        return None

    # ------------------------------------------------------------------
    # the delivery pipeline
    # ------------------------------------------------------------------
    def _deliver(self, path: str, rounds: int) -> str:
        version = self._publish(path, rounds)
        incumbent = self.server.registry.live_version(self.name)
        if incumbent is None:
            # bootstrap: no incumbent to canary against — promote
            # directly (first model for this name)
            self.server.promote(self.name, version)
            self._promote_fleet(version)
            self._c_promoted.inc()
            self._finish(rounds, "bootstrapped", version=version)
            return "bootstrapped"
        if incumbent == version:
            self._finish(rounds, "already_live", version=version)
            return "already_live"

        state = CanaryState(self.name, version, incumbent,
                            mode=self.mode, fraction=self.fraction)
        self._pin(version, incumbent)
        self.server.canary.start(state)
        self._set_state(CANARY)
        self._event("canary_start", model=state.candidate_label,
                    incumbent=state.incumbent_label, mode=self.mode,
                    fraction=self.fraction,
                    min_requests=self.min_requests)
        try:
            filled = self._await_canary(state)
            verdict, detail = self._gate(state) if filled \
                else (False, {"reasons": ["canary_timeout"],
                              **state.counts()})
        finally:
            self.server.canary.end(self.name)
        if not verdict:
            self._unpin(version, incumbent)
            self._set_state(IDLE)
            reason = ",".join(detail.get("reasons", [])) or "gate"
            self._c_rejected.labels(reason=reason).inc()
            self._event("canary_rejected", model=state.candidate_label,
                        **detail)
            if "canary_timeout" not in detail.get("reasons", ()):
                # a gate-failed candidate would fail again — settled; a
                # timeout (no traffic) stays pending and retries
                self._finish(rounds, "rejected", version=version,
                             detail=detail)
                # a settled rejection releases everything publish took
                # (arena entry, retained source, manifest row, spilled
                # bytes, fleet copies): an online loop rejecting
                # candidates for weeks must not grow disk or manifest
                with self._lock:
                    self._published.pop(rounds, None)
                self.server.discard_version(self.name, version)
                self._fleet({"op": "unload", "model": self.name,
                             "version": version})
            return "rejected"

        self.server.promote(self.name, version)
        self._promote_fleet(version)
        self._c_promoted.inc()
        outcome = self._bake(version, incumbent, rounds)
        self._unpin(version, incumbent)
        self._set_state(IDLE)
        self._finish(rounds, outcome, version=version)
        return outcome

    def _publish(self, path: str, rounds: int) -> int:
        """Idempotent publish: the resident (not live) version for this
        checkpoint, loading it only once across retried cycles. The
        VERIFIED PAYLOAD is published as raw model bytes — not the
        checkpoint path — so the manifest spills it durably and the
        served version survives training-side retention pruning the
        file it came from (the training dir owns its files; the serving
        plane owns its versions)."""
        got = self._published.get(rounds)
        if got is not None:
            return got
        chaos.hit(PUBLISH_SITE)
        try:
            verified = _ckpt.read_checkpoint(path)
            if verified is None:
                raise ValueError(
                    f"checkpoint {path!r} no longer verifies (pruned or "
                    "corrupted between scan and publish)")
            label = self.server.publish(self.name, bytes(verified[0]))
        except Exception as e:
            faults.record_serving_fault(PUBLISH_SITE, e)
            raise
        version = int(label.rsplit("@v", 1)[1])
        with self._lock:
            self._published[rounds] = version
        self._c_published.inc()
        if self.broadcast is not None:
            # ship the manifest-spilled copy (serving-plane-owned, so it
            # survives training retention pruning), never the training
            # checkpoint path — a replica that faults the version back in
            # after the trainer pruned the .ckpt must still find bytes
            src = self.server.durable_source(self.name, version) or path
            self._fleet({"op": "load", "model": self.name, "path": src,
                         "version": version, "live": False})
        return version

    def _await_canary(self, state: CanaryState) -> bool:
        """Block until the candidate arm saw ``min_requests`` outcomes
        (True) or the canary deadline / a stop passed (False)."""
        deadline = time.monotonic() + self.canary_deadline_s
        while not self._stop.is_set() and time.monotonic() < deadline:
            if state.counts()["candidate"] >= self.min_requests:
                return True
            self._stop.wait(0.02)
        return state.counts()["candidate"] >= self.min_requests

    def _gate(self, state: CanaryState) -> Tuple[bool, Dict[str, Any]]:
        """The promotion verdict: live SLO (p99 ratio + error rate) and
        held-out AUC. Returns (ok, detail-for-the-timeline)."""
        reasons: List[str] = []
        detail: Dict[str, Any] = dict(state.counts())
        cand_p99 = REGISTRY.quantile("predict_latency_seconds", 0.99,
                                     model=state.candidate_label)
        inc_p99 = REGISTRY.quantile("predict_latency_seconds", 0.99,
                                    model=state.incumbent_label)
        if cand_p99 is not None:
            detail["candidate_p99_s"] = round(cand_p99, 9)
        if inc_p99 is not None:
            detail["incumbent_p99_s"] = round(inc_p99, 9)
        if cand_p99 is not None and inc_p99 is not None \
                and cand_p99 > inc_p99 * self.p99_ratio:
            reasons.append("p99")
        c = state.counts()
        cand_err = c["candidate_errors"] / max(c["candidate"], 1)
        inc_err = c["incumbent_errors"] / max(c["incumbent"], 1)
        detail["candidate_error_rate"] = round(cand_err, 6)
        detail["incumbent_error_rate"] = round(inc_err, 6)
        if cand_err > inc_err + 1e-9:
            reasons.append("error_rate")
        if self.eval_data is not None:
            try:
                cand_auc = self._auc(state.version)
                inc_auc = self._auc(state.incumbent_version)
                detail["candidate_auc"] = round(cand_auc, 6)
                detail["incumbent_auc"] = round(inc_auc, 6)
                detail["dauc"] = round(cand_auc - inc_auc, 6)
                if cand_auc - inc_auc < -self.dauc_tol:
                    reasons.append("auc")
            except Exception as e:
                faults.record_serving_fault(WATCH_SITE, e)
                reasons.append("auc_eval_failed")
        detail["reasons"] = reasons
        return not reasons, detail

    def _auc(self, version: int) -> float:
        """Held-out AUC of one resident version — the bench parity-gate
        machinery (``create_metric("auc")``) against the controller's
        eval slice, through the same inplace fast path traffic uses."""
        from ..metric import create_metric

        X, y = self.eval_data
        entry = self.server.registry.get(self.name, version)
        pred = entry.booster.inplace_predict(np.asarray(X, np.float32))
        return float(create_metric("auc").evaluate(
            np.asarray(pred), np.asarray(y)))

    def _bake(self, version: int, incumbent: int, rounds: int) -> str:
        """Post-promotion breaker watch: ``bake_s`` seconds during which
        a NAME-keyed breaker trip triggers rollback + quarantine."""
        self._set_state(BAKE)
        breaker = self.server.faults.breaker(self.name)
        deadline = time.monotonic() + self.bake_s
        while not self._stop.is_set() and time.monotonic() < deadline:
            if breaker.state == faults.OPEN:
                self._rollback(version, incumbent, rounds)
                return "rolled_back"
            self._stop.wait(0.02)
        if breaker.state == faults.OPEN:  # tripped right at the wire
            self._rollback(version, incumbent, rounds)
            return "rolled_back"
        return "promoted"

    def _rollback(self, version: int, incumbent: int, rounds: int) -> None:
        """Re-swap to last-good (still pinned → warm), quarantine the bad
        version in the manifest, reset the breaker the bad version
        tripped so restored traffic flows immediately."""
        self.server.rollback(self.name, incumbent)
        self._fleet({"op": "rollback", "model": self.name,
                     "version": incumbent})
        self._c_rollbacks.inc()
        self.server.quarantine_version(self.name, version, rounds=rounds)
        self._fleet({"op": "quarantine", "model": self.name,
                     "version": version, "rounds": rounds})
        with self._lock:
            self._quarantined_rounds.add(rounds)
        self._c_quarantined.inc()
        self.server.faults.breaker(self.name).reset()

    def _promote_fleet(self, version: int) -> None:
        self._fleet({"op": "promote", "model": self.name,
                     "version": version})

    def _fleet(self, msg: Dict[str, Any]) -> None:
        """Mirror one control op to the fleet router (best effort with
        classification: the shared manifest re-converges any replica a
        broadcast missed on its next restart)."""
        if self.broadcast is None:
            return
        try:
            resp = self.broadcast(msg) or {}
            if resp.get("error"):
                raise RuntimeError(f"fleet {msg.get('op')}: "
                                   f"{resp['error']}")
        except Exception as e:
            faults.record_serving_fault(WATCH_SITE, e)

    # ------------------------------------------------------------------
    # bookkeeping
    # ------------------------------------------------------------------
    def _pin(self, *versions: int) -> None:
        for v in versions:
            self.server.registry.pin(self.name, v, True)

    def _unpin(self, *versions: int) -> None:
        for v in versions:
            self.server.registry.pin(self.name, v, False)

    def _set_state(self, state: int) -> None:
        with self._lock:
            self._state = state
        self._g_state.set(state)

    def _event(self, name: str, **args: Any) -> None:
        self.server.obs.event(name, **args)

    def _finish(self, rounds: int, outcome: str, **extra: Any) -> None:
        with self._lock:
            self._processed = max(self._processed, rounds)
            self._history.append(
                {"rounds": rounds, "outcome": outcome,
                 "unix_ms": time.time() * 1e3, **extra})
            del self._history[:-32]

    def status(self) -> Dict[str, Any]:
        with self._lock:
            state = self._state
            history = list(self._history)
            processed = self._processed
            published = {str(r): v for r, v in self._published.items()}
            quarantined = sorted(self._quarantined_rounds)
        canary = self.server.canary.active(self.name)
        return {
            "model": self.name, "watch_dir": self.watch_dir,
            "state": {IDLE: "idle", CANARY: "canary",
                      BAKE: "bake"}[state],
            "mode": self.mode, "fraction": self.fraction,
            "min_requests": self.min_requests,
            "processed_rounds": processed,
            "published": published,
            "quarantined_rounds": quarantined,
            "canary": canary.summary() if canary is not None else None,
            "history": history,
        }
