"""Async micro-batcher: many small concurrent requests, one bucketed dispatch.

The serving fast path (``predictor/serving.py``) pads every batch up to a
power-of-two bucket, minimum 16 rows — so a 1-row request already pays for
walking 16. This module fills that padding with *real traffic*: callers
submit requests and get ``concurrent.futures.Future``s back; a single
worker thread drains the bounded queue, coalesces compatible requests
(same model snapshot, same predict options) into one concatenated matrix,
runs ONE dispatch through the bucketed program cache, and slices the
result back per caller. 64 concurrent 1-row requests become a handful of
program invocations instead of 64 (pinned by tests/test_model_server.py).

Knobs (env, read at construction):

- ``XGBTPU_BATCH_WAIT_US`` (default 1000) — after the first request of a
  cycle arrives, how long the worker waits for more traffic to coalesce.
  0 = dispatch immediately, coalescing only what is already queued.
- ``XGBTPU_BATCH_MAX_ROWS`` (default 4096) — rows per drain cycle; a full
  cycle dispatches without waiting out the window.
- ``XGBTPU_MAX_REQUEST_ROWS`` (default 65536) — per-request row cap;
  larger payloads are rejected at admission (reason ``invalid``).
- ``XGBTPU_BATCHER_WATCHDOG`` (default 60, seconds; 0 disables) — how
  long one dispatch may block the worker before the watchdog declares it
  wedged, fails its in-flight futures with a typed
  :class:`~xgboost_tpu.serving.faults.RequestError` and respawns the
  worker (crash-only: the queue and every waiting caller survive).

Multi-tenant fairness (ISSUE 11): the queue is a
:class:`~xgboost_tpu.serving.tenancy.TenantFairQueue` — per-tenant lanes
dequeued in weighted-fair order (``XGBTPU_TENANT_WEIGHTS``, default
equal; service cost = rows), so a hot tenant's backlog cannot starve a
light tenant's dispatch share, and each tenant's queue occupancy is
bounded at admission by ``XGBTPU_TENANT_QUOTA`` (shed reason
``tenant_quota``). Requests from different tenants for the same model
still coalesce into one dispatch — fairness decides *order*, not
batching.

Correctness invariants: rows are walked per-row-independently on every
route (XLA program, pallas, native walker), so a coalesced result is
bit-identical to the same request served alone; requests that cannot
coalesce (sparse inputs, explicit base margins) still ride the same queue
but dispatch as their own group. Dispatch-time deadline re-checks shed
requests that aged out while queued (``admission.py``), and futures a
caller cancelled are skipped at dispatch-assembly time and counted as
``serving_requests_total{outcome="abandoned"}`` — an abandoned client
neither keeps its queue slot nor blocks batch completion.

Failure handling (ISSUE 10, ``serving/faults.py``): a failed coalesced
dispatch is classified through ``resilience.policy`` — transients get one
bounded same-batch retry, anything persistent is bisected until the
poison member(s) alone fail with a typed ``RequestError`` while innocent
co-batched requests succeed (docs/serving.md "Failure handling").
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability.metrics import REGISTRY
from ..resilience import chaos, policy
from . import faults
from .admission import AdmissionController, RequestShed
from .obs import RequestRecord, ServingRecorder
from .tenancy import (
    OVERFLOW_TENANT, QUEUE_STOP, SHADOW_TENANT, ModelEntry,
    TenantFairQueue,
)

__all__ = ["MicroBatcher"]

_STOP = QUEUE_STOP


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class _Request:
    __slots__ = ("entry", "X", "n", "group_key", "predict_type",
                 "iteration_range", "missing", "base_margin", "deadline",
                 "future", "rec", "fp", "tenant")

    def __init__(self, entry: ModelEntry, X, n: int, group_key: Tuple,
                 predict_type: str, iteration_range, missing, base_margin,
                 deadline: Optional[float],
                 rec: Optional[RequestRecord],
                 fp: Optional[int] = None, tenant: str = "") -> None:
        self.entry = entry
        self.X = X
        self.n = n
        self.group_key = group_key
        self.predict_type = predict_type
        self.iteration_range = iteration_range
        self.missing = missing
        self.base_margin = base_margin
        self.deadline = deadline
        self.rec = rec
        self.fp = fp
        self.tenant = tenant
        self.future: "Future" = Future()
        if rec is not None:
            # the response side of request tracing: every future carries
            # the id its access-log line and trace track were written under
            self.future.request_id = rec.id


class MicroBatcher:
    """The queue + worker thread. One per :class:`~xgboost_tpu.serving.ModelServer`;
    admission decisions (queue bound, deadline shed, degrade routing,
    breaker/quarantine sheds) are delegated to the attached
    :class:`AdmissionController`, whose fault domain also drives the
    isolation machinery here."""

    def __init__(self, admission: Optional[AdmissionController] = None,
                 *, obs: Optional[ServingRecorder] = None,
                 max_wait_us: Optional[int] = None,
                 max_batch_rows: Optional[int] = None,
                 tenant_weights=None) -> None:
        self.admission = admission or AdmissionController()
        self.obs = obs
        if max_wait_us is None:
            max_wait_us = _env_int("XGBTPU_BATCH_WAIT_US", 1000)
        if max_batch_rows is None:
            max_batch_rows = _env_int("XGBTPU_BATCH_MAX_ROWS", 4096)
        self.max_wait_s = max(0, max_wait_us) / 1e6
        self.max_batch_rows = max(1, max_batch_rows)
        self.max_request_rows = max(
            1, _env_int("XGBTPU_MAX_REQUEST_ROWS", 65536))
        self.watchdog_s = max(0.0, _env_float("XGBTPU_BATCHER_WATCHDOG",
                                              60.0))
        self._q = TenantFairQueue(tenant_weights)
        # wire-supplied tenant names must not grow per-tenant state
        # (labelled metric children, ledger caches, fair-queue lanes)
        # without bound: past XGBTPU_TENANT_MAX distinct tenants, new
        # names share the OVERFLOW_TENANT lane/label
        self._tenant_cap = max(1, _env_int("XGBTPU_TENANT_MAX", 64))
        self._tenants_seen: set = set()
        self._tenant_overflow = REGISTRY.counter(
            "serving_tenant_overflow_total",
            "Requests whose tenant was folded into the shared overflow "
            "lane because the distinct-tenant cap was reached")
        self._tenant_rows = REGISTRY.counter(
            "serving_tenant_dequeued_rows_total",
            "Rows dequeued from the batcher per request tenant — the "
            "weighted-fair dispatch-share ledger")
        self._depth = REGISTRY.gauge(
            "serving_queue_depth", "Requests waiting in the batcher queue")
        self._dispatches = REGISTRY.counter(
            "serving_dispatches_total",
            "Coalesced program dispatches issued by the micro-batcher")
        self._batched = REGISTRY.counter(
            "serving_requests_batched_total",
            "Requests served through the micro-batcher")
        self._rows = REGISTRY.counter(
            "serving_rows_total", "Rows served through the micro-batcher")
        self._respawns = REGISTRY.counter(
            "serving_worker_respawns_total",
            "Batcher worker threads respawned by the wedge watchdog")
        self._fastpath = REGISTRY.counter(
            "serving_batch_fastpath_total",
            "Dispatches that skipped (part of) the coalescing window "
            "because every admitted request was already in the batch "
            "(idle fast-path)")
        # admitted-but-unresolved requests (queued + in the open batch):
        # the idle fast-path's signal. A request leaves the count when its
        # future reaches ANY terminal state (result, typed error, cancel)
        # via the done-callback attached at submit.
        self._outstanding = 0
        self._depth.set(0)
        self._dispatches.inc(0)
        self._batched.inc(0)
        self._respawns.inc(0)
        self._closed = False
        self._lock = threading.Lock()
        # worker generation: the watchdog bumps it when it declares the
        # current worker wedged; a stale worker sees the bump and exits
        # without touching queue or futures (crash-only respawn)
        self._gen = 0
        self._inflight: List[_Request] = []
        self._busy_since = 0.0
        self._worker = threading.Thread(
            target=self._loop, args=(0,),
            name="xgbtpu-serving-batcher", daemon=True)
        self._worker.start()
        if self.watchdog_s > 0:
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="xgbtpu-batcher-watchdog", daemon=True)
            self._watchdog.start()

    # ------------------------------------------------------------------
    def submit(self, entry: ModelEntry, data, *,
               predict_type: str = "value", iteration_range=None,
               missing: float = np.nan, base_margin=None,
               deadline: Optional[float] = None,
               rec: Optional[RequestRecord] = None,
               tenant: str = "") -> "Future":
        """Enqueue one predict request against a pinned model entry.
        Returns a Future resolving to the prediction array (rows in input
        order), or raising :class:`~xgboost_tpu.serving.RequestShed` /
        a typed dispatch error. ``deadline`` is absolute
        ``time.monotonic()``; ``rec`` is the server's request-trace
        record — sealed here on a shed/refusal, by the dispatch path
        otherwise; ``tenant`` picks the fair-queue lane (and quota) the
        request rides."""
        try:
            return self._submit(entry, data, predict_type=predict_type,
                                iteration_range=iteration_range,
                                missing=missing, base_margin=base_margin,
                                deadline=deadline, rec=rec, tenant=tenant)
        except BaseException as e:
            if self.obs is not None and rec is not None:
                if isinstance(e, RequestShed):
                    self.obs.finish(rec, "shed", shed_reason=e.reason)
                else:
                    self.obs.finish(rec, "error",
                                    error=f"{type(e).__name__}: {e}")
                # sheds never produce a future, so the id rides the
                # exception — shed responses still carry their request_id
                e.request_id = rec.id
            raise

    def _intern_tenant(self, tenant: str) -> str:
        """Clamp an untrusted tenant name: length-capped, and folded into
        the shared overflow lane once XGBTPU_TENANT_MAX distinct tenants
        exist — per-tenant state stays bounded no matter what the wire
        sends (the tenant-field analog of PR 10's input validation)."""
        if not tenant:
            return ""
        tenant = str(tenant)[:64]
        with self._lock:
            if tenant in self._tenants_seen:
                return tenant
            if len(self._tenants_seen) < self._tenant_cap:
                self._tenants_seen.add(tenant)
                return tenant
        self._tenant_overflow.inc()
        return OVERFLOW_TENANT

    def _submit(self, entry: ModelEntry, data, *, predict_type,
                iteration_range, missing, base_margin, deadline,
                rec: Optional[RequestRecord], tenant: str = "") -> "Future":
        tenant = self._intern_tenant(tenant)
        if iteration_range is not None \
                and tuple(iteration_range) == (0, 0):
            iteration_range = None
        if hasattr(data, "tocsr") and hasattr(data, "nnz"):
            # scipy sparse: ride the queue un-normalized (the serving
            # entry consumes CSR directly), dispatched as its own group
            X, coalescible = data, False
        else:
            X = entry.booster._inplace_normalize(data, missing)
            if X is None:
                raise TypeError(
                    "micro-batcher inputs must be 2-D arrays or scipy "
                    f"sparse matrices, got {type(data).__name__}")
            missing = np.nan  # sentinel already folded into NaN
            coalescible = base_margin is None
        # structural validation BEFORE the queue (satellite: a malformed
        # dense payload must be rejected with a typed error at admission,
        # not throw inside the coalesced dispatch and poison co-batched
        # callers) — reason "invalid" on requests_shed_total
        n = int(X.shape[0])
        nf = entry.booster.num_features()
        if nf and int(X.shape[1]) != int(nf):
            raise self.admission.invalid(
                f"payload width {X.shape[1]} != model features {nf} "
                f"for {entry.label}")
        if n == 0:
            raise self.admission.invalid("empty payload (0 rows)")
        if n > self.max_request_rows:
            raise self.admission.invalid(
                f"payload rows {n} > XGBTPU_MAX_REQUEST_ROWS="
                f"{self.max_request_rows}")
        vals = X.data if not coalescible and hasattr(X, "data") \
            and not isinstance(X, np.ndarray) else X
        if np.isinf(np.asarray(vals)).any():
            raise self.admission.invalid(
                "non-finite (inf) values in payload (use NaN for "
                "missing)")
        fp = faults.fingerprint(X) if coalescible else None
        if rec is not None:
            rec.rows = int(n)
            rec.tenant = tenant
        rkey = None if iteration_range is None else tuple(iteration_range)
        with self._lock:
            if self._closed:
                raise RuntimeError("model server is closed")
            # qsize is exact under the lock only for submitters; the
            # worker draining concurrently just makes admission lenient
            self.admission.admit(self._q.qsize(), deadline,
                                 model=entry.label, fingerprint=fp,
                                 tenant=tenant,
                                 tenant_depth=self._q.depth(tenant))
            req = _Request(
                entry, X, n,
                # sparse / base-margin requests get an identity key: they
                # ride the drain cycle but dispatch as their own group
                (id(entry), predict_type, rkey, X.shape[1])
                if coalescible else (object(),),
                predict_type, iteration_range, missing, base_margin,
                deadline, rec, fp, tenant)
            entry.acquire()
            self._outstanding += 1
            self._q.put(req, tenant=tenant, cost=float(n))
            self._depth.set(self._q.qsize())
        # attached OUTSIDE the lock: done-callbacks run synchronously on
        # whichever thread resolves (or cancels) the future, and must
        # never fire while this thread holds the batcher lock
        req.future.add_done_callback(self._on_request_done)
        return req.future

    def _on_request_done(self, _fut) -> None:
        with self._lock:
            if self._outstanding > 0:
                self._outstanding -= 1

    # ------------------------------------------------------------------
    def _note_dequeue(self, req: "_Request") -> None:
        if req.rec is not None:
            req.rec.mark_dequeued()
        if req.tenant:
            self._tenant_rows.labels(tenant=req.tenant).inc(req.n)

    def _loop(self, gen: int) -> None:
        while True:
            with self._lock:
                if self._gen != gen \
                        or self._closed and self._q.qsize() == 0:
                    return
            item = self._q.get()
            if item is _STOP:
                break
            self._note_dequeue(item)
            batch = [item]
            rows = item.n
            # idle fast-path (ISSUE 15 satellite): the coalescing window
            # exists to gather requests that are IN FLIGHT toward the
            # queue — but when every admitted request is already in this
            # batch (queue empty and outstanding == len(batch)), nothing
            # can arrive until these futures resolve: closed-loop clients
            # are all blocked on THIS batch. Holding the window open then
            # is a pure stall per dispatch — measured as the concurrent
            # served stream falling BELOW the same stream run
            # sequentially (87.2k vs 96.1k rows/s). Dispatch the moment
            # the live request set is fully assembled; a genuine flood
            # (more outstanding than batched — e.g. async submitters)
            # keeps the window exactly as before.
            window_end = time.monotonic() + self.max_wait_s
            while rows < self.max_batch_rows:
                with self._lock:
                    drained = (self._q.qsize() == 0
                               and self._outstanding <= len(batch))
                if drained:
                    self._fastpath.inc()
                    break
                remaining = window_end - time.monotonic()
                try:
                    nxt = self._q.get(timeout=max(0.0, remaining)) \
                        if remaining > 0 else self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    break  # the stop flag is sticky: exit after this batch
                self._note_dequeue(nxt)
                batch.append(nxt)
                rows += nxt.n
            self._depth.set(self._q.qsize())
            with self._lock:
                if self._gen != gen:
                    # replaced while assembling: hand the batch to the
                    # error path (we must not race the live worker)
                    stale_batch = batch
                else:
                    stale_batch = None
                    self._inflight = batch
                    self._busy_since = time.monotonic()
            if stale_batch is not None:
                for req in stale_batch:
                    self._resolve_err(req, faults.RequestError(
                        "batcher_wedge", policy.TRANSIENT,
                        "batcher worker replaced mid-assembly"))
                return
            try:
                self._run_batch(batch, gen)
            finally:
                with self._lock:
                    if self._gen == gen:
                        self._inflight = []
                        self._busy_since = 0.0

    def _watchdog_loop(self) -> None:
        """Detect a wedged worker: a dispatch that has blocked the worker
        thread past ``XGBTPU_BATCHER_WATCHDOG`` seconds gets its in-flight
        futures failed (typed, site ``batcher_wedge``) and a fresh worker
        spawned — queued requests behind the wedge keep being served.
        The wedged thread itself is abandoned (its generation is stale;
        anything it eventually returns is discarded)."""
        interval = max(0.02, min(1.0, self.watchdog_s / 4))
        while True:
            time.sleep(interval)
            with self._lock:
                if self._closed:
                    return
                busy = self._busy_since
                if not busy or (time.monotonic() - busy) < self.watchdog_s:
                    continue
                batch = self._inflight
                self._inflight = []
                self._busy_since = 0.0
                self._gen += 1
                gen = self._gen
                self._worker = threading.Thread(
                    target=self._loop, args=(gen,),
                    name=f"xgbtpu-serving-batcher-{gen}", daemon=True)
                self._worker.start()
            faults.record_serving_fault(
                "batcher_wedge", kind=policy.TRANSIENT)
            self._respawns.inc()
            if self.obs is not None:
                self.obs.event("batcher_respawn", inflight=len(batch),
                               deadline_s=self.watchdog_s)
            for req in batch:
                rid = req.rec.id if req.rec is not None else None
                self._resolve_err(req, faults.RequestError(
                    "batcher_wedge", policy.TRANSIENT,
                    f"batcher worker wedged > {self.watchdog_s}s; "
                    "in-flight futures failed, worker respawned",
                    request_id=rid))

    def _run_batch(self, batch: List[_Request], gen: int) -> None:
        try:
            chaos.hit("batcher_wedge")
        except chaos.ChaosError:
            # scripted wedge: park (GIL-friendly) until the watchdog
            # replaces this worker or the batcher closes — the testable
            # analog of a dispatch stuck in native code
            while True:
                with self._lock:
                    if self._gen != gen or self._closed:
                        return
                time.sleep(0.02)
        groups: "Dict[Tuple, List[_Request]]" = {}
        now = time.monotonic()
        for req in batch:
            if not self._claim(req):
                self._abandon(req)
                continue
            if req.deadline is not None and now >= req.deadline:
                self._resolve_err(req, self.admission.shed_at_dispatch())
                continue
            groups.setdefault(req.group_key, []).append(req)
        if groups:
            # observability only: counts serving_degraded_routes_total
            # while the device predict path is unhealthy — the route
            # itself is the predict_walk dispatch table's verdict, read
            # inside predict_serving, not a flag threaded through here
            self.admission.route_native()
        for grp in groups.values():
            self._dispatch_group(grp, gen)

    def _dispatch_group(self, grp: List[_Request], gen: int) -> None:
        from ..predictor.serving import bucket_rows, last_route

        first = grp[0]
        domain = self.admission.faults
        # shadow-canary isolation (serving/delivery.py): an all-shadow
        # group must not feed the live fault plane — its failures belong
        # to the CANARY verdict (attach_shadow observes them), not to the
        # model's NAME-keyed breaker or the payload quarantine, or a bad
        # candidate in shadow mode ("zero user impact") could shed live
        # traffic / quarantine a live request's fingerprint. Shadow
        # requests target the candidate entry, so they never coalesce
        # with incumbent-bound live traffic.
        shadow = all(r.tenant == SHADOW_TENANT for r in grp)
        rows = sum(r.n for r in grp)
        h0, m0 = self._cache_counts()
        t0 = time.perf_counter_ns()

        def dispatch(sub: List[_Request]):
            chaos.hit("serving_dispatch")
            X = sub[0].X if len(sub) == 1 else \
                np.concatenate([r.X for r in sub], axis=0)
            faults.check_poison(X)
            faults.check_model_poison(first.entry.label)
            return first.entry.predict(
                X, predict_type=first.predict_type,
                iteration_range=first.iteration_range,
                missing=first.missing, base_margin=first.base_margin)

        # the isolation ladder (faults.py): clean traffic costs exactly
        # one dispatch() call; classification/retry/bisection only run
        # once a failure has already happened (the ≤2% overhead pin)
        ok, failed = faults.isolate_dispatch(
            grp, dispatch, domain=None if shadow else domain,
            model=first.entry.name)
        t1 = time.perf_counter_ns()
        if not shadow:
            domain.breaker(first.entry.name).record(
                ok=not failed, latency_s=(t1 - t0) / 1e9)
        with self._lock:
            if self._gen != gen:
                return  # watchdog already failed this batch's futures
        if ok:
            self._dispatches.inc()
            self._batched.inc(len(ok))
            self._rows.inc(sum(r.n for r, _ in ok))
        route = last_route()  # this thread ran the dispatch: exact
        bucket = bucket_rows(rows)
        h1, m1 = self._cache_counts()
        ok_reqs = [r for r, _ in ok]
        recs = [r.rec for r in ok_reqs if r.rec is not None]
        for req in ok_reqs:
            if req.rec is not None:
                req.rec.t_dispatch0 = t0
                req.rec.t_dispatch1 = t1
                req.rec.route = route
                req.rec.bucket = bucket
                req.rec.coalesced = len(grp)
        if self.obs is not None and ok:
            self.obs.dispatch(
                recs, model=first.entry.label,
                rows=sum(r.n for r, _ in ok), bucket=bucket,
                route=route, cache_hits=h1 - h0, cache_misses=m1 - m0,
                queue_depth=self._q.qsize(), t0_ns=t0, t1_ns=t1)
            for rec in recs:
                self.obs.finish(rec, "ok")
        for req, out in ok:
            req.entry.release()
            self._set_result(req.future, out)
        for req, exc in failed:
            rid = req.rec.id if req.rec is not None else None
            self._resolve_err(req, faults.RequestError(
                faults.DISPATCH_SITE, policy.classify(exc),
                f"{type(exc).__name__}: {exc}", request_id=rid))

    @staticmethod
    def _cache_counts() -> Tuple[float, float]:
        """Bucketed-program-cache hit/miss totals; the single worker
        thread reads deltas around its own dispatch, so concurrent
        non-serving predicts can only over-count, never corrupt."""
        out = []
        for name in ("predict_bucket_cache_hits_total",
                     "predict_bucket_cache_misses_total"):
            fam = REGISTRY.get(name)
            out.append(0.0 if fam is None else fam.labels().value)
        return out[0], out[1]

    # ------------------------------------------------------------------
    @staticmethod
    def _claim(req: _Request) -> bool:
        """Move the future to RUNNING; False = the caller cancelled it
        (the request is abandoned and must be skipped, not dispatched)."""
        try:
            return req.future.set_running_or_notify_cancel()
        except InvalidStateError:
            return True  # already claimed (close() racing the worker)

    def _abandon(self, req: _Request) -> None:
        """A cancelled future skipped at dispatch-assembly time: release
        its model pin and count it — the caller went away, so nothing
        else will."""
        req.entry.release()
        if self.obs is not None and req.rec is not None:
            self.obs.finish(req.rec, "abandoned")
        else:
            REGISTRY.counter(
                "serving_requests_total",
                "Requests completed, by outcome",
            ).labels(outcome="abandoned").inc()

    @staticmethod
    def _set_result(fut: "Future", value) -> None:
        try:
            fut.set_result(value)
        except InvalidStateError:
            pass  # cancelled/failed concurrently: result has no taker

    def _resolve_err(self, req: _Request, exc: BaseException) -> None:
        req.entry.release()
        if self.obs is not None and req.rec is not None:
            if isinstance(exc, RequestShed):
                self.obs.finish(req.rec, "shed", shed_reason=exc.reason)
            else:
                self.obs.finish(req.rec, "error",
                                error=f"{type(exc).__name__}: {exc}")
        try:
            req.future.set_exception(exc)
        except InvalidStateError:
            pass  # cancelled/resolved concurrently (watchdog vs worker)

    # ------------------------------------------------------------------
    def queue_depth(self) -> int:
        return self._q.qsize()

    def close(self, drain: bool = True,
              deadline_s: Optional[float] = None) -> None:
        """Stop the worker. ``drain=True`` serves everything already
        queued first (bounded by ``deadline_s``, default 60 /
        ``XGBTPU_DRAIN_DEADLINE_S``); either way, requests that slip in
        after the stop marker fail with a closed-server error instead of
        hanging."""
        if deadline_s is None:
            deadline_s = _env_float("XGBTPU_DRAIN_DEADLINE_S", 60.0)
        with self._lock:
            if self._closed:
                return
            self._closed = True
            worker = self._worker
            self._q.stop()  # sticky: get() drains the backlog, then STOP
        worker.join(timeout=max(0.1, deadline_s))
        leftovers = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                break
            leftovers.append(item)
        for req in leftovers:
            if not self._claim(req):
                self._abandon(req)
            elif drain:
                # close() raced the worker's exit: serve rather than drop
                self._dispatch_group([req], False, self._gen)
            else:
                self._resolve_err(
                    req, RuntimeError("model server closed before dispatch"))
