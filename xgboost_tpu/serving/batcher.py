"""Async micro-batcher: many small concurrent requests, one bucketed dispatch.

The serving fast path (``predictor/serving.py``) pads every batch up to a
power-of-two bucket, minimum 16 rows — so a 1-row request already pays for
walking 16. This module fills that padding with *real traffic*: callers
submit requests and get ``concurrent.futures.Future``s back; a single
worker thread drains the bounded queue, coalesces compatible requests
(same model snapshot, same predict options) into one concatenated matrix,
runs ONE dispatch through the bucketed program cache, and slices the
result back per caller. 64 concurrent 1-row requests become a handful of
program invocations instead of 64 (pinned by tests/test_model_server.py).

Knobs (env, read at construction):

- ``XGBTPU_BATCH_WAIT_US`` (default 1000) — after the first request of a
  cycle arrives, how long the worker waits for more traffic to coalesce.
  0 = dispatch immediately, coalescing only what is already queued.
- ``XGBTPU_BATCH_MAX_ROWS`` (default 4096) — rows per drain cycle; a full
  cycle dispatches without waiting out the window.

Correctness invariants: rows are walked per-row-independently on every
route (XLA program, pallas, native walker), so a coalesced result is
bit-identical to the same request served alone; requests that cannot
coalesce (sparse inputs, explicit base margins) still ride the same queue
but dispatch as their own group. Dispatch-time deadline re-checks shed
requests that aged out while queued (``admission.py``).
"""

from __future__ import annotations

import os
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability.metrics import REGISTRY
from .admission import AdmissionController, RequestShed
from .obs import RequestRecord, ServingRecorder
from .tenancy import ModelEntry

__all__ = ["MicroBatcher"]

_STOP = object()


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


class _Request:
    __slots__ = ("entry", "X", "n", "group_key", "predict_type",
                 "iteration_range", "missing", "base_margin", "deadline",
                 "future", "rec")

    def __init__(self, entry: ModelEntry, X, n: int, group_key: Tuple,
                 predict_type: str, iteration_range, missing, base_margin,
                 deadline: Optional[float],
                 rec: Optional[RequestRecord]) -> None:
        self.entry = entry
        self.X = X
        self.n = n
        self.group_key = group_key
        self.predict_type = predict_type
        self.iteration_range = iteration_range
        self.missing = missing
        self.base_margin = base_margin
        self.deadline = deadline
        self.rec = rec
        self.future: "Future" = Future()
        if rec is not None:
            # the response side of request tracing: every future carries
            # the id its access-log line and trace track were written under
            self.future.request_id = rec.id


class MicroBatcher:
    """The queue + worker thread. One per :class:`~xgboost_tpu.serving.ModelServer`;
    admission decisions (queue bound, deadline shed, degrade routing) are
    delegated to the attached :class:`AdmissionController`."""

    def __init__(self, admission: Optional[AdmissionController] = None,
                 *, obs: Optional[ServingRecorder] = None,
                 max_wait_us: Optional[int] = None,
                 max_batch_rows: Optional[int] = None) -> None:
        self.admission = admission or AdmissionController()
        self.obs = obs
        if max_wait_us is None:
            max_wait_us = _env_int("XGBTPU_BATCH_WAIT_US", 1000)
        if max_batch_rows is None:
            max_batch_rows = _env_int("XGBTPU_BATCH_MAX_ROWS", 4096)
        self.max_wait_s = max(0, max_wait_us) / 1e6
        self.max_batch_rows = max(1, max_batch_rows)
        self._q: "queue.Queue" = queue.Queue()
        self._depth = REGISTRY.gauge(
            "serving_queue_depth", "Requests waiting in the batcher queue")
        self._dispatches = REGISTRY.counter(
            "serving_dispatches_total",
            "Coalesced program dispatches issued by the micro-batcher")
        self._batched = REGISTRY.counter(
            "serving_requests_batched_total",
            "Requests served through the micro-batcher")
        self._rows = REGISTRY.counter(
            "serving_rows_total", "Rows served through the micro-batcher")
        self._depth.set(0)
        self._dispatches.inc(0)
        self._batched.inc(0)
        self._closed = False
        self._lock = threading.Lock()
        self._worker = threading.Thread(
            target=self._loop, name="xgbtpu-serving-batcher", daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    def submit(self, entry: ModelEntry, data, *,
               predict_type: str = "value", iteration_range=None,
               missing: float = np.nan, base_margin=None,
               deadline: Optional[float] = None,
               rec: Optional[RequestRecord] = None) -> "Future":
        """Enqueue one predict request against a pinned model entry.
        Returns a Future resolving to the prediction array (rows in input
        order), or raising :class:`~xgboost_tpu.serving.RequestShed` /
        the dispatch error. ``deadline`` is absolute ``time.monotonic()``;
        ``rec`` is the server's request-trace record — sealed here on a
        shed/refusal, by the dispatch path otherwise."""
        try:
            return self._submit(entry, data, predict_type=predict_type,
                                iteration_range=iteration_range,
                                missing=missing, base_margin=base_margin,
                                deadline=deadline, rec=rec)
        except BaseException as e:
            if self.obs is not None and rec is not None:
                if isinstance(e, RequestShed):
                    self.obs.finish(rec, "shed", shed_reason=e.reason)
                else:
                    self.obs.finish(rec, "error",
                                    error=f"{type(e).__name__}: {e}")
                # sheds never produce a future, so the id rides the
                # exception — shed responses still carry their request_id
                e.request_id = rec.id
            raise

    def _submit(self, entry: ModelEntry, data, *, predict_type,
                iteration_range, missing, base_margin, deadline,
                rec: Optional[RequestRecord]) -> "Future":
        if iteration_range is not None \
                and tuple(iteration_range) == (0, 0):
            iteration_range = None
        if hasattr(data, "tocsr") and hasattr(data, "nnz"):
            # scipy sparse: ride the queue un-normalized (the serving
            # entry consumes CSR directly), dispatched as its own group
            X, coalescible = data, False
        else:
            X = entry.booster._inplace_normalize(data, missing)
            if X is None:
                raise TypeError(
                    "micro-batcher inputs must be 2-D arrays or scipy "
                    f"sparse matrices, got {type(data).__name__}")
            missing = np.nan  # sentinel already folded into NaN
            coalescible = base_margin is None
        n = X.shape[0]
        if rec is not None:
            rec.rows = int(n)
        rkey = None if iteration_range is None else tuple(iteration_range)
        with self._lock:
            if self._closed:
                raise RuntimeError("model server is closed")
            # qsize is exact under the lock only for submitters; the
            # worker draining concurrently just makes admission lenient
            self.admission.admit(self._q.qsize(), deadline,
                                 model=entry.label)
            req = _Request(
                entry, X, n,
                # sparse / base-margin requests get an identity key: they
                # ride the drain cycle but dispatch as their own group
                (id(entry), predict_type, rkey, X.shape[1])
                if coalescible else (object(),),
                predict_type, iteration_range, missing, base_margin,
                deadline, rec)
            entry.acquire()
            self._q.put(req)
            self._depth.set(self._q.qsize())
        return req.future

    # ------------------------------------------------------------------
    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                break
            if item.rec is not None:
                item.rec.mark_dequeued()
            batch = [item]
            rows = item.n
            window_end = time.monotonic() + self.max_wait_s
            while rows < self.max_batch_rows:
                remaining = window_end - time.monotonic()
                try:
                    nxt = self._q.get(timeout=max(0.0, remaining)) \
                        if remaining > 0 else self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _STOP:
                    self._q.put(_STOP)  # re-arm: exit after this batch
                    break
                if nxt.rec is not None:
                    nxt.rec.mark_dequeued()
                batch.append(nxt)
                rows += nxt.n
            self._depth.set(self._q.qsize())
            self._run_batch(batch)

    def _run_batch(self, batch: List[_Request]) -> None:
        groups: "Dict[Tuple, List[_Request]]" = {}
        now = time.monotonic()
        for req in batch:
            if req.deadline is not None and now >= req.deadline:
                self._resolve_err(req, self.admission.shed_at_dispatch())
                continue
            groups.setdefault(req.group_key, []).append(req)
        force_native = self.admission.route_native() if groups else False
        for grp in groups.values():
            self._dispatch_group(grp, force_native)

    def _dispatch_group(self, grp: List[_Request],
                        force_native: bool) -> None:
        from ..predictor.serving import bucket_rows, last_route

        first = grp[0]
        rows = sum(r.n for r in grp)
        h0, m0 = self._cache_counts()
        t0 = time.perf_counter_ns()
        try:
            if len(grp) == 1:
                X = first.X
            else:
                X = np.concatenate([r.X for r in grp], axis=0)
            out = first.entry.predict(
                X, predict_type=first.predict_type,
                iteration_range=first.iteration_range,
                missing=first.missing, base_margin=first.base_margin,
                force_native=force_native)
            self._dispatches.inc()
            self._batched.inc(len(grp))
            self._rows.inc(rows)
        except BaseException as e:  # noqa: BLE001 — worker must survive
            for req in grp:
                self._resolve_err(req, e)
            return
        t1 = time.perf_counter_ns()
        route = last_route()  # this thread ran the dispatch: exact
        bucket = bucket_rows(rows)
        h1, m1 = self._cache_counts()
        recs = [r.rec for r in grp if r.rec is not None]
        for req in grp:
            if req.rec is not None:
                req.rec.t_dispatch0 = t0
                req.rec.t_dispatch1 = t1
                req.rec.route = route
                req.rec.bucket = bucket
                req.rec.coalesced = len(grp)
        if self.obs is not None:
            self.obs.dispatch(
                recs, model=first.entry.label, rows=rows, bucket=bucket,
                route=route, cache_hits=h1 - h0, cache_misses=m1 - m0,
                queue_depth=self._q.qsize(), t0_ns=t0, t1_ns=t1)
            for rec in recs:
                self.obs.finish(rec, "ok")
        off = 0
        for req in grp:
            req.entry.release()
            req.future.set_result(np.asarray(out[off: off + req.n]))
            off += req.n

    @staticmethod
    def _cache_counts() -> Tuple[float, float]:
        """Bucketed-program-cache hit/miss totals; the single worker
        thread reads deltas around its own dispatch, so concurrent
        non-serving predicts can only over-count, never corrupt."""
        out = []
        for name in ("predict_bucket_cache_hits_total",
                     "predict_bucket_cache_misses_total"):
            fam = REGISTRY.get(name)
            out.append(0.0 if fam is None else fam.labels().value)
        return out[0], out[1]

    def _resolve_err(self, req: _Request, exc: BaseException) -> None:
        req.entry.release()
        if self.obs is not None and req.rec is not None:
            if isinstance(exc, RequestShed):
                self.obs.finish(req.rec, "shed", shed_reason=exc.reason)
            else:
                self.obs.finish(req.rec, "error",
                                error=f"{type(exc).__name__}: {exc}")
        req.future.set_exception(exc)

    # ------------------------------------------------------------------
    def queue_depth(self) -> int:
        return self._q.qsize()

    def close(self, drain: bool = True) -> None:
        """Stop the worker. ``drain=True`` serves everything already
        queued first; either way, requests that slip in after the stop
        marker fail with a closed-server error instead of hanging."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_STOP)
        self._worker.join(timeout=60)
        leftovers = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _STOP:
                leftovers.append(item)
        for req in leftovers:
            if drain:
                # close() raced the worker's exit: serve rather than drop
                self._dispatch_group([req], False)
            else:
                self._resolve_err(
                    req, RuntimeError("model server closed before dispatch"))
