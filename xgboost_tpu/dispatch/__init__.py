"""Backend-neutral kernel dispatch: one registry routing every op.

Public surface (see ``core.py`` for the design notes):

- :func:`resolve` / :class:`Ctx` / :class:`Decision` — the lookup.
- :func:`register` — add an impl (a GPU backend is a table entry).
- :func:`pinned_off` / :func:`degraded` — compat/admission reads.
- :func:`invoke` / :func:`set_invoke_hook` — the invocation seam the
  kernel profiler brackets (``observability/kernelprof.py``).
- :func:`explain` / :func:`last_decisions` / :func:`table_snapshot` —
  the report CLI, BENCH sidecar and flight-black-box surfaces.
"""

from .core import (  # noqa: F401
    Ctx,
    Decision,
    DispatchError,
    KernelImpl,
    LEGACY_ENVS,
    degraded,
    explain,
    invoke,
    last_decisions,
    op_names,
    pinned_off,
    register,
    reset,
    resolve,
    set_invoke_hook,
    set_report_ctx,
    table_snapshot,
)

__all__ = [
    "Ctx", "Decision", "DispatchError", "KernelImpl", "LEGACY_ENVS",
    "degraded", "explain", "invoke", "last_decisions", "op_names",
    "pinned_off", "register", "reset", "resolve", "set_invoke_hook",
    "set_report_ctx", "table_snapshot",
]
