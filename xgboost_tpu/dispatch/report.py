"""``python -m xgboost_tpu dispatch-report`` — the resolved kernel table.

Prints op × impl × status (chosen/pinned-off/degraded/unavailable/
inapplicable/fallback) for the CURRENT platform, plus the pins in effect
(explicit ``XGBTPU_DISPATCH`` grammar and any legacy kill-switch envs
mapped onto it). Exit status 0 when every op resolves, 1 when any op has
no usable implementation — the CI tier-0.5 gate runs this on CPU so a
broken table fails before a single test does."""

from __future__ import annotations

import os
import sys
from typing import List, Optional

from .core import LEGACY_ENVS, DispatchError, explain, op_names, resolve


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(argv or [])
    if argv and argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    import jax

    platform = jax.default_backend()
    print(f"kernel dispatch table (platform={platform})")
    spec = os.environ.get("XGBTPU_DISPATCH")
    legacy = [f"{name}={os.environ.get(name)}"
              for name, trigger, _ in LEGACY_ENVS
              if os.environ.get(name) == trigger]
    if spec:
        print(f"pins: XGBTPU_DISPATCH={spec!r}")
    if legacy:
        print(f"legacy pins (deprecated, see docs/perf.md): "
              f"{', '.join(legacy)}")
    if not spec and not legacy:
        print("pins: none (auto preference order)")
    print()
    failures = 0
    width = max(len(op) for op in op_names())
    for op in op_names():
        try:
            dec = resolve(op)
            head = f"{op:<{width}}  -> {dec.impl} ({dec.reason})"
        except DispatchError as e:
            failures += 1
            head = f"{op:<{width}}  -> UNRESOLVED: {e}"
        print(head)
        for row in explain(op):
            print(f"{'':<{width}}     {row['impl']:<8} "
                  f"{row['status']:<12} {row['note']}")
    if failures:
        print(f"\n{failures} op(s) do not resolve on {platform}",
              file=sys.stderr)
        return 1
    print(f"\nall {len(op_names())} ops resolve on {platform}")
    return 0
