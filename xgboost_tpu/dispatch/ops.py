"""The default kernel-op table: every backend a registry entry.

Each op's impls, applicability predicates and per-platform preference
live HERE — a new backend (a GPU tier, a second native kernel) is a
``register`` call, not a rewrite of the call sites. Predicates read only
the :class:`~xgboost_tpu.dispatch.core.Ctx` the call site passed (shape,
dtype, platform flags) plus the owning module's probe helpers; they are
imported lazily so importing the dispatch layer never drags in jax or
builds a native library.

Op reference (see docs/perf.md, "Choosing a kernel"):

====================  =========================================  =============
op                    implementations (preference order)         capability
====================  =========================================  =============
``tree_grow``         native (CPU, whole-round kernel) > level   native_tree
``sibling_sub``       on > off (histogram subtraction trick)     —
``hist_acc``          CPU: quant > float (integer histogram      —
                      accumulation inside the whole-tree kernel)
``level_hist``        pallas > native (CPU) > xla                native_hist
``level_partition``   native (CPU) > xla                         native_hist
``level_update``      xla (single impl: shared split eval)       —
``depth_scan``        scanned > unrolled                         —
``onehot_build``      pallas > xla                               —
``leaf_delta``        pallas > xla                               —
``predict_walk``      TPU: pallas > xla > native;                pallas_predict
                      CPU: native > xla                          / native_serving
``sketch_cuts``       CPU: native > xla; TPU: xla                native_sketch
``bin_matrix``        CPU: native > xla; TPU: xla                native_sketch
====================  =========================================  =============
"""

from __future__ import annotations

from .core import Ctx, register, set_report_ctx

_NARROW_BINS = ("uint8", "uint16")


def _platform() -> str:
    import jax

    return jax.default_backend()


def _native_level_applicable(ctx: Ctx) -> bool:
    """The FFI level kernel's trace-time envelope: CPU backend, in-process
    (no mesh axis), numerical 4-wide decision tables, narrow-int bins,
    and not the interpret-mode kernel tests."""
    return (ctx.get("platform") == "cpu"
            and not ctx.get("interpret", False)
            and not ctx.get("sharded", False)
            and ctx.get("table_width", 4) == 4
            and ctx.get("bins_dtype") in _NARROW_BINS)


def _native_level_available(ctx: Ctx) -> bool:
    from ..tree import hist_kernel

    return hist_kernel._ensure_ffi()


def _tree_grow_native_applicable(ctx: Ctx) -> bool:
    """The whole-tree kernel's trace-time envelope (ISSUE 17 tentpole):
    everything the per-level native kernel needs, PLUS the features whose
    eval the C++ port replicates bitwise. Per-level colsample draws
    (bylevel/bynode < 1) stay on the per-level path — their PRNG folds
    cannot be mirrored in C++ — as does max_delta_step > 0, whose gain
    expression XLA:CPU contracts into an FMA the kernel must not emit
    (see tree_build.cpp). Monotone/interaction constraints and
    categorical tables keep the XLA evaluator."""
    return (ctx.get("platform") == "cpu"
            and not ctx.get("interpret", False)
            and not ctx.get("sharded", False)
            and not ctx.get("pallas", False)
            and not ctx.get("has_cats", False)
            and ctx.get("bins_dtype") in _NARROW_BINS
            and int(ctx.get("depth", 0)) >= 1
            and not ctx.get("monotone", False)
            and not ctx.get("interaction", False)
            and float(ctx.get("colsample_level", 1.0)) >= 1.0
            and float(ctx.get("colsample_node", 1.0)) >= 1.0
            and float(ctx.get("max_delta_step", 0.0)) == 0.0)


def _tree_grow_native_available(ctx: Ctx) -> bool:
    from ..tree import tree_kernel

    return tree_kernel.tree_ffi_ready()


# The whole-round grow kernel (native/tree_build.cpp): ONE custom call per
# boosting round on CPU; the ``level`` impl is the existing per-level path
# (depth scan / unrolled / pallas / mesh), which every other platform and
# every out-of-envelope config keeps.
register("tree_grow", "native", pref=(("cpu", 0), ("*", 2)),
         applicable=_tree_grow_native_applicable,
         available=_tree_grow_native_available,
         capability="native_tree")
register("tree_grow", "level", pref=(("*", 1),))
set_report_ctx("tree_grow", lambda: Ctx(
    platform=_platform(), pallas=_platform() == "tpu", interpret=False,
    sharded=False, has_cats=False, bins_dtype="uint8", depth=6,
    monotone=False, interaction=False, colsample_level=1.0,
    colsample_node=1.0, max_delta_step=0.0))


# Sibling subtraction inside the whole-tree kernel: build only the smaller
# child's histogram, derive the other as parent - child. ``off`` pins the
# kernel bit-identical to the per-level native path (the legacy
# ``XGBTPU_SIBLING_SUB=0`` kill switch maps here).
register("sibling_sub", "on", pref=(("*", 0),))
register("sibling_sub", "off", pref=(("*", 1),))
set_report_ctx("sibling_sub", lambda: Ctx(platform=_platform()))


# Histogram accumulation inside the whole-tree kernel (ISSUE 19): the
# fixed-point integer engine (per-node row lists, packed int32 gradient
# lanes, int64 merge — thread-count invariant by construction) leads on
# CPU; ``float`` is the r17 f32 core and the bit-identity kill switch —
# pinning BOTH ``hist_acc=float`` and ``sibling_sub=off`` makes the
# whole-tree kernel byte-identical to the per-level native path.
register("hist_acc", "quant", pref=(("cpu", 0), ("*", 2)))
register("hist_acc", "float", pref=(("*", 1),))
set_report_ctx("hist_acc", lambda: Ctx(platform=_platform()))


def _pallas_level_applicable(ctx: Ctx) -> bool:
    from ..tree import hist_kernel

    return bool(ctx.get("pallas")) and hist_kernel.pallas_level_fits(
        int(ctx.get("rows", 0)), int(ctx.get("features", 0)),
        int(ctx.get("nodes", 1)), int(ctx.get("bins", 0)),
        int(ctx.get("onehot_width", 0)))


register("level_hist", "pallas", pref=(("*", 0),),
         applicable=_pallas_level_applicable)
register("level_hist", "native", pref=(("*", 1),),
         applicable=_native_level_applicable,
         available=_native_level_available,
         capability="native_hist")
register("level_hist", "xla", pref=(("*", 2),))
set_report_ctx("level_hist", lambda: Ctx(
    platform=_platform(), pallas=_platform() == "tpu", interpret=False,
    rows=8192, features=50, nodes=32, bins=64, table_width=4,
    bins_dtype="uint8", sharded=False, onehot_width=0))


register("level_partition", "native", pref=(("*", 0),),
         applicable=_native_level_applicable,
         available=_native_level_available,
         capability="native_hist")
register("level_partition", "xla", pref=(("*", 1),))
set_report_ctx("level_partition", lambda: Ctx(
    platform=_platform(), interpret=False, table_width=4,
    bins_dtype="uint8", sharded=False))


# split evaluation / heap writes are one shared pure-XLA body on every
# backend (tree/grow_fused.py:_level_update) — registered so the table is
# complete and a future backend-specific evaluator is a row, not a branch
register("level_update", "xla", pref=(("*", 0),))
set_report_ctx("level_update", lambda: Ctx(platform=_platform()))


def _scanned_applicable(ctx: Ctx) -> bool:
    """The fused depth scan runs where its fixed-width trick is sound:
    off the pallas path (Mosaic kernels specialize per level width by
    design), no categorical tables (level-shaped widening), in-process
    (the unrolled loop is the proven shard_map path), depth >= 1."""
    return (not ctx.get("pallas", False)
            and not ctx.get("has_cats", False)
            and not ctx.get("sharded", False)
            and int(ctx.get("depth", 0)) >= 1)


register("depth_scan", "scanned", pref=(("*", 0),),
         applicable=_scanned_applicable)
register("depth_scan", "unrolled", pref=(("*", 1),))
set_report_ctx("depth_scan", lambda: Ctx(
    platform=_platform(), pallas=_platform() == "tpu", has_cats=False,
    sharded=False, depth=6))


def _onehot_pallas_applicable(ctx: Ctx) -> bool:
    from ..tree import hist_kernel

    return (bool(ctx.get("pallas"))
            and int(ctx.get("features", 0)) > 0
            and hist_kernel._build_tr(int(ctx.get("rows", 0)),
                                      int(ctx.get("features", 0)),
                                      int(ctx.get("bins", 0))) != 0)


register("onehot_build", "pallas", pref=(("*", 0),),
         applicable=_onehot_pallas_applicable)
register("onehot_build", "xla", pref=(("*", 1),))
set_report_ctx("onehot_build", lambda: Ctx(
    platform=_platform(), pallas=_platform() == "tpu", rows=8192,
    features=50, bins=64))


register("leaf_delta", "pallas", pref=(("*", 0),),
         applicable=lambda ctx: bool(ctx.get("pallas")))
register("leaf_delta", "xla", pref=(("*", 1),))
set_report_ctx("leaf_delta", lambda: Ctx(
    platform=_platform(), pallas=_platform() == "tpu"))


def _walk_native_applicable(ctx: Ctx) -> bool:
    return not ctx.get("has_cats", False)


def _walk_native_available(ctx: Ctx) -> bool:
    from ..native import serving_lib_available

    return serving_lib_available()


def _walk_pallas_applicable(ctx: Ctx) -> bool:
    return (ctx.get("platform") == "tpu"
            and bool(ctx.get("heap_layout", False))
            and not ctx.get("has_cats", False))


# Preference: on TPU the device walk (pallas, else the bucketed XLA
# program) owns the route and the native walker is the degrade fallback;
# on CPU the native walker leads and XLA backstops categorical forests /
# missing toolchains. Both device impls carry the ``pallas_predict``
# capability ON DEVICE PLATFORMS ONLY, so a degraded device path routes
# to native with reason="degraded" — the lookup that replaced the
# serving_context(force_native=) thread-local.
register("predict_walk", "pallas", pref=(("*", 0),),
         applicable=_walk_pallas_applicable,
         capability="pallas_predict", cap_platforms=("tpu",))
register("predict_walk", "xla", pref=(("*", 1),),
         capability="pallas_predict", cap_platforms=("tpu",))
register("predict_walk", "native", pref=(("cpu", 0), ("*", 2)),
         applicable=_walk_native_applicable,
         available=_walk_native_available,
         capability="native_serving")
set_report_ctx("predict_walk", lambda: Ctx(
    platform=_platform(), has_cats=False, heap_layout=True))


# The data-plane ops (ISSUE 15): DMatrix-construction sketch + binning.
# The native impls are XLA FFI custom calls (native/sketch_bin.cpp) doing
# the same float ops in the same order as the XLA kernels — bit-identical
# cuts/bins, ~an order of magnitude faster on XLA:CPU. On device backends
# the XLA route leads (the sort/searchsorted pipeline parallelizes there
# and the data is already device-resident).


def _native_sketch_applicable(ctx: Ctx) -> bool:
    return ctx.get("platform") == "cpu" and int(ctx.get("rows", 0)) >= 1


def _native_sketch_available(ctx: Ctx) -> bool:
    from ..data import quantile

    return quantile._ensure_sketch_ffi()


def _native_bin_applicable(ctx: Ctx) -> bool:
    """The native binning kernel writes the narrow storage dtype directly;
    int32-wide tables (max_bin >= 65535) stay on the XLA route."""
    return (ctx.get("platform") == "cpu"
            and int(ctx.get("rows", 0)) >= 1
            and ctx.get("bins_dtype") in _NARROW_BINS)


register("sketch_cuts", "native", pref=(("cpu", 0), ("*", 2)),
         applicable=_native_sketch_applicable,
         available=_native_sketch_available,
         capability="native_sketch")
register("sketch_cuts", "xla", pref=(("*", 1),))
set_report_ctx("sketch_cuts", lambda: Ctx(
    platform=_platform(), rows=8192, features=50, bins=64))


register("bin_matrix", "native", pref=(("cpu", 0), ("*", 2)),
         applicable=_native_bin_applicable,
         available=_native_sketch_available,
         capability="native_sketch")
register("bin_matrix", "xla", pref=(("*", 1),))
set_report_ctx("bin_matrix", lambda: Ctx(
    platform=_platform(), rows=8192, features=50, bins=64,
    bins_dtype="uint8"))
