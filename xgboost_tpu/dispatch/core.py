"""Kernel-op dispatch registry: ONE table that routes every op.

The reference builds every pluggable tier on ``dmlc::Registry`` factory
glue (PAPER.md §1); ``registry.py`` already applies that at the framework
level (objectives / metrics / updaters / boosters). This module extends
the pattern DOWN to the kernel layer, replacing the ad-hoc per-call-site
backend branches (pallas-vs-XLA ``if``s in ``tree/hist_kernel.py`` and
``tree/grow_fused.py``, ``XGBTPU_NATIVE_*`` env kill switches, the
serving thread-local ``force_native`` route) with a single lookup:

    dispatch.resolve("level_hist", Ctx(platform=..., features=F, ...))

Each op (``level_hist``, ``level_partition``, ``depth_scan``,
``onehot_build``, ``predict_walk``, ``leaf_delta``, ``level_update``)
registers its implementations (``pallas`` / ``xla`` / ``native`` / ...)
with applicability predicates and a per-platform preference order
(``dispatch/ops.py``). ``resolve`` integrates, in order:

- **pins** — ``XGBTPU_DISPATCH="level_hist=native,depth_scan=unrolled,
  predict_walk=!native,*=auto"``: ``op=impl`` forces an impl, ``op=!impl``
  bans one, ``op=auto`` clears. The legacy kill switches
  (``XGBTPU_NATIVE_HIST=0``, ``XGBTPU_DEPTH_SCAN=0``,
  ``XGBTPU_NATIVE_SERVING=0``) are translated to pins HERE — one compat
  shim, deprecation-warned once — so they keep flipping their routes.
- **capability state** — an impl carrying a ``resilience.degrade``
  capability is skipped (read-only ``degrade.worst``: no retry countdown
  is burned) while that capability is non-HEALTHY; the fallback decision
  carries ``reason="degraded"``. This replaces the serving-side
  ``serving_context(force_native=)`` TLS hack: degrade routing is now a
  property of the table, not of the calling thread.
- **preference** — deterministic per-platform rank; first applicable +
  available impl wins with ``reason="preferred"`` (or ``"unavailable"``
  when a preferred impl's build/runtime probe failed).

Observability: every resolution counts into
``dispatch_decisions_total{op,impl,reason}``; a route *change* for a
given (op, ctx) emits a trace instant and a flight-recorder event; the
flight black box embeds the resolved table (``table_snapshot()``); and
``python -m xgboost_tpu dispatch-report`` prints the fully-resolved
op × impl × reason table for the current platform.

Resolution is cached per (op, ctx-key, pins, capability-state) — the env
tuple and capability states ARE the cache key, so a pin or degrade
change re-resolves naturally and everything else is a dict hit. Training
ops resolve at trace time (once per compile); the serving op resolves
per request at ~µs cost.
"""

from __future__ import annotations

import os
import threading
from typing import (Any, Callable, Dict, Hashable, List, NamedTuple,
                    Optional, Sequence, Tuple)

__all__ = [
    "Ctx", "Decision", "DispatchError", "KernelImpl",
    "register", "set_report_ctx", "resolve", "explain", "op_names",
    "pinned_off", "degraded", "last_decisions", "table_snapshot",
    "invoke", "set_invoke_hook",
    "reset", "LEGACY_ENVS",
]

#: legacy kill-switch env vars -> the pin each one translates to
#: (the ONE place the old grammar is still understood)
LEGACY_ENVS: Tuple[Tuple[str, str, Tuple[Tuple[str, str], ...]], ...] = (
    ("XGBTPU_NATIVE_HIST", "0", (("level_hist", "!native"),
                                 ("level_partition", "!native"))),
    ("XGBTPU_DEPTH_SCAN", "0", (("depth_scan", "unrolled"),)),
    ("XGBTPU_NATIVE_SERVING", "0", (("predict_walk", "!native"),)),
    ("XGBTPU_SIBLING_SUB", "0", (("sibling_sub", "off"),)),
)

_DISPATCH_ENV = "XGBTPU_DISPATCH"

_CACHE_MAX = 512  # resolved decisions (keys include forest/level shapes)


class Ctx:
    """Immutable, hashable bag of the STATIC routing inputs a call site
    knows (platform, shape/bin widths, dtypes, flags). Everything
    volatile that predicates need must be passed in here by the call
    site — resolution is a pure function of (ctx, pins, capability
    state), which is exactly what makes it cacheable."""

    __slots__ = ("_items",)

    def __init__(self, **kw: Any) -> None:
        object.__setattr__(self, "_items", tuple(sorted(kw.items())))

    def get(self, key: str, default: Any = None) -> Any:
        for k, v in self._items:
            if k == key:
                return v
        return default

    @property
    def key(self) -> Tuple:
        return self._items

    def __setattr__(self, *a: Any) -> None:  # pragma: no cover
        raise AttributeError("Ctx is immutable")

    def __repr__(self) -> str:
        inner = ", ".join(f"{k}={v!r}" for k, v in self._items)
        return f"Ctx({inner})"


class KernelImpl(NamedTuple):
    """One registered implementation of an op."""

    op: str
    name: str
    pref: Tuple[Tuple[str, int], ...]  # platform -> rank ("*" = default)
    applicable: Callable[[Ctx], bool]
    available: Callable[[Ctx], bool]
    capability: Optional[str]  # resilience.degrade capability gating it
    cap_platforms: Optional[Tuple[str, ...]]  # None = every platform

    def rank(self, platform: str) -> int:
        d = dict(self.pref)
        return d.get(platform, d.get("*", 50))

    def cap_for(self, platform: str) -> Optional[str]:
        if self.capability is None:
            return None
        if self.cap_platforms is not None \
                and platform not in self.cap_platforms:
            return None
        return self.capability


class Decision(NamedTuple):
    """The resolved route for one (op, ctx)."""

    op: str
    impl: str
    reason: str  # preferred | pinned | degraded | unavailable
    detail: str = ""


class DispatchError(RuntimeError):
    """No implementation of an op resolves for the given context."""


class _State:
    """All mutable module state, lock-guarded behind one object (keeps
    traced callers from ever closing over a module-level dict — the
    RH202 hazard the lint gate fences)."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        # separate lock for the one-time ops import: register() takes
        # self.lock during that import, so the import must not hold it
        self.ops_lock = threading.Lock()
        self.impls: Dict[str, List[KernelImpl]] = {}
        self.report_ctx: Dict[str, Callable[[], Ctx]] = {}
        self.cache: Dict[Hashable, Decision] = {}
        self.routes: Dict[Hashable, str] = {}  # (op, ctx, excl) -> impl
        self.last: Dict[str, Decision] = {}  # op -> most recent decision
        self.pins_memo: Dict[Tuple, Tuple[Dict[str, str],
                                          Dict[str, Tuple[str, ...]]]] = {}
        self.warned: Dict[str, bool] = {}
        self.ops_loaded = False


_STATE = _State()


# ---------------------------------------------------------------------------
# registration
# ---------------------------------------------------------------------------


def register(op: str, name: str, *,
             pref: Sequence[Tuple[str, int]] = (("*", 50),),
             applicable: Optional[Callable[[Ctx], bool]] = None,
             available: Optional[Callable[[Ctx], bool]] = None,
             capability: Optional[str] = None,
             cap_platforms: Optional[Sequence[str]] = None) -> KernelImpl:
    """Register implementation ``name`` of ``op``. ``applicable`` gates
    on ctx facts (platform, shapes, dtypes) and skipping it is silent;
    ``available`` gates on build/runtime probes (toolchain, FFI load) and
    skipping it surfaces as ``reason="unavailable"``; ``capability``
    names the ``resilience.degrade`` capability that sheds this impl
    while non-HEALTHY (optionally only on ``cap_platforms``).

    Re-registering an (op, name) pair REPLACES the entry (last writer
    wins): a partially-failed ops import that re-runs must not wedge on
    its own survivors, and tests/plugins can override a row."""
    impl = KernelImpl(
        op=op, name=name, pref=tuple(pref),
        applicable=applicable or (lambda ctx: True),
        available=available or (lambda ctx: True),
        capability=capability,
        cap_platforms=tuple(cap_platforms) if cap_platforms else None)
    with _STATE.lock:
        row = _STATE.impls.setdefault(op, [])
        row[:] = [i for i in row if i.name != name]
        row.append(impl)
        _STATE.cache.clear()
    return impl


def set_report_ctx(op: str, factory: Callable[[], Ctx]) -> None:
    """Representative ctx for ``op`` on the current platform — what
    ``dispatch-report`` (and ``resolve(op)`` with no ctx) resolves."""
    with _STATE.lock:
        _STATE.report_ctx[op] = factory


def _ensure_ops() -> None:
    """Import the default op table exactly once. The loaded flag is set
    only AFTER the import succeeds (under its own lock), so a concurrent
    first resolver waits for the full table instead of racing a partial
    one, and a failed import is retried on the next resolve rather than
    latching the process broken."""
    if _STATE.ops_loaded:
        return
    with _STATE.ops_lock:
        if _STATE.ops_loaded:
            return
        from . import ops as _ops  # noqa: F401  (registers the table)

        with _STATE.lock:
            _STATE.ops_loaded = True


def op_names() -> List[str]:
    _ensure_ops()
    with _STATE.lock:
        return sorted(_STATE.impls)


# ---------------------------------------------------------------------------
# pins (XGBTPU_DISPATCH grammar + the legacy kill-switch shim)
# ---------------------------------------------------------------------------


def _warn_once(key: str, msg: str) -> None:
    with _STATE.lock:
        if _STATE.warned.get(key):
            return
        _STATE.warned[key] = True
    from ..utils import console_logger

    console_logger.warning(msg)


def _env_key() -> Tuple:
    return tuple(os.environ.get(name) for name, _, _ in LEGACY_ENVS) + (
        os.environ.get(_DISPATCH_ENV),)


def _parse_pins(env_key: Tuple) -> Tuple[Dict[str, str],
                                         Dict[str, Tuple[str, ...]]]:
    """(pins, bans) for the current env. Memoized on the raw env tuple so
    monkeypatched/updated env vars re-parse, unchanged ones hit a dict.
    Legacy envs are translated first; explicit ``XGBTPU_DISPATCH``
    entries override them (``op=auto`` clears both)."""
    with _STATE.lock:
        hit = _STATE.pins_memo.get(env_key)
        if hit is not None:
            return hit
    pins: Dict[str, str] = {}
    bans: Dict[str, List[str]] = {}

    def apply(op: str, val: str) -> None:
        if val == "auto":
            pins.pop(op, None)
            bans.pop(op, None)
        elif val.startswith("!"):
            bans.setdefault(op, []).append(val[1:])
        else:
            pins[op] = val

    for (name, trigger, mapped), raw in zip(LEGACY_ENVS, env_key):
        if raw == trigger:
            for op, val in mapped:
                apply(op, val)
            pin_text = ",".join(f"{op}={val}" for op, val in mapped)
            _warn_once(
                f"legacy:{name}",
                f"{name}={trigger} is deprecated: it now maps to the "
                f"dispatch pin XGBTPU_DISPATCH=\"{pin_text}\" "
                f"(docs/perf.md, 'Choosing a kernel')")
    spec = env_key[-1]
    if spec:
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            op, sep, val = part.partition("=")
            op, val = op.strip(), val.strip()
            if not sep or not val:
                _warn_once(f"badpin:{part}",
                           f"ignoring malformed {_DISPATCH_ENV} entry "
                           f"{part!r} (grammar: op=impl, op=!impl, op=auto)")
                continue
            if op == "*":
                continue  # *=auto is the documented explicit default
            apply(op, val)
    out = (pins, {op: tuple(v) for op, v in bans.items()})
    with _STATE.lock:
        if len(_STATE.pins_memo) > 64:
            _STATE.pins_memo.clear()
        _STATE.pins_memo[env_key] = out
    return out


def pinned_off(op: str, impl: str) -> bool:
    """Whether pins (legacy or explicit) route ``op`` away from ``impl``
    — banned outright, or positively pinned to a different impl. The
    compat read the old kill-switch helpers (``use_native_hist``)
    delegate to."""
    pins, bans = _parse_pins(_env_key())
    if impl in bans.get(op, ()):
        return True
    pin = pins.get(op)
    return pin is not None and pin != impl


# ---------------------------------------------------------------------------
# resolution
# ---------------------------------------------------------------------------


def _worst(cap: str) -> int:
    from ..resilience import degrade

    return degrade.worst(cap)


def _healthy() -> int:
    from ..resilience import degrade

    return degrade.HEALTHY


def _cap_states(op: str) -> Tuple:
    """(capability, worst-state) for every capability any impl of ``op``
    references — read-only (``degrade.worst``), so polling it per resolve
    never burns a DEGRADED entry's retry countdown."""
    with _STATE.lock:
        caps = sorted({i.capability for i in _STATE.impls.get(op, ())
                       if i.capability is not None})
    return tuple((c, _worst(c)) for c in caps)


def degraded(op: str) -> bool:
    """Whether any capability gating one of ``op``'s impls is currently
    non-HEALTHY (the serving admission controller's per-dispatch poll)."""
    _ensure_ops()
    healthy = _healthy()
    return any(state != healthy for _, state in _cap_states(op))


def _report_ctx(op: str) -> Ctx:
    with _STATE.lock:
        factory = _STATE.report_ctx.get(op)
    return factory() if factory is not None else Ctx(platform="cpu")


def _resolve_uncached(op: str, ctx: Ctx, exclude: Tuple[str, ...],
                      pins: Dict[str, str],
                      bans: Dict[str, Tuple[str, ...]]) -> Decision:
    with _STATE.lock:
        impls = [i for i in _STATE.impls.get(op, ())
                 if i.name not in exclude]
    if not impls:
        raise DispatchError(f"no implementations registered for op {op!r}"
                            + (f" outside {exclude}" if exclude else ""))
    platform = str(ctx.get("platform", ""))
    impls.sort(key=lambda i: (i.rank(platform), i.name))
    healthy = _healthy()
    op_bans = bans.get(op, ())
    pin = pins.get(op)
    blocker: Optional[str] = None
    if pin is not None and pin not in exclude:
        pinned = next((i for i in impls if i.name == pin), None)
        if pinned is None:
            _warn_once(f"unknownpin:{op}:{pin}",
                       f"dispatch pin {op}={pin} names no registered impl "
                       f"of {op!r}; auto-resolving")
        elif pinned.applicable(ctx) and pinned.available(ctx):
            return Decision(op, pin, "pinned", "pinned by env")
        else:
            blocker = "unavailable"
            _warn_once(f"deadpin:{op}:{pin}:{platform}",
                       f"dispatch pin {op}={pin} is not usable on "
                       f"{platform or 'this platform'}; auto-resolving")
    skipped: List[str] = []
    degraded_fallback: Optional[KernelImpl] = None
    for impl in impls:
        if impl.name in op_bans:
            blocker = blocker or "pinned"
            skipped.append(f"{impl.name}: banned by pin")
            continue
        if not impl.applicable(ctx):
            skipped.append(f"{impl.name}: inapplicable")
            continue
        cap = impl.cap_for(platform)
        if cap is not None and _worst(cap) != healthy:
            blocker = blocker or "degraded"
            skipped.append(f"{impl.name}: capability {cap!r} degraded")
            if degraded_fallback is None and impl.available(ctx):
                degraded_fallback = impl
            continue
        if not impl.available(ctx):
            blocker = blocker or "unavailable"
            skipped.append(f"{impl.name}: unavailable")
            continue
        detail = "; ".join(skipped) if skipped else ""
        return Decision(op, impl.name, blocker or "preferred", detail)
    if degraded_fallback is not None:
        # every healthy alternative is exhausted: serving on the degraded
        # impl beats failing the request outright (the pre-registry
        # behavior — e.g. a categorical forest on a degraded device still
        # predicted through the device path)
        return Decision(op, degraded_fallback.name, "degraded",
                        "no healthy alternative; serving on degraded impl: "
                        + "; ".join(skipped))
    raise DispatchError(
        f"op {op!r} resolves to nothing on {platform or 'this platform'}: "
        + "; ".join(skipped))


def resolve(op: str, ctx: Optional[Ctx] = None,
            exclude: Sequence[str] = ()) -> Decision:
    """Resolve ``op`` for ``ctx`` (default: the op's representative
    report ctx). ``exclude`` drops named impls from consideration — the
    call-site escape when a chosen impl's runtime envelope rejects the
    actual input (e.g. the native walker returning None) and the next
    candidate must be picked without re-fighting the whole table."""
    _ensure_ops()
    if ctx is None:
        ctx = _report_ctx(op)
    exclude = tuple(exclude)
    env_key = _env_key()
    cap_key = _cap_states(op)
    cache_key = (op, ctx.key, exclude, env_key, cap_key)
    with _STATE.lock:
        dec = _STATE.cache.get(cache_key)
    if dec is None:
        pins, bans = _parse_pins(env_key)
        dec = _resolve_uncached(op, ctx, exclude, pins, bans)
        with _STATE.lock:
            if len(_STATE.cache) > _CACHE_MAX:
                _STATE.cache.clear()
            _STATE.cache[cache_key] = dec
    # route-change tracking runs on hits AND misses: a recovery flip
    # (degrade clears -> the original healthy cache entry hits again)
    # must announce just like the first degrade did
    route_key = (op, ctx.key, exclude)
    with _STATE.lock:
        prev = _STATE.routes.get(route_key)
        _STATE.routes[route_key] = dec.impl
        _STATE.last[op] = dec
    if prev is not None and prev != dec.impl:
        _announce_route_change(op, prev, dec)
    _count(dec)
    return dec


def _count(dec: Decision) -> None:
    from ..observability.metrics import REGISTRY

    REGISTRY.counter(
        "dispatch_decisions_total",
        "Kernel dispatch resolutions by op, chosen impl and reason",
    ).labels(op=dec.op, impl=dec.impl, reason=dec.reason).inc()


def _announce_route_change(op: str, frm: str, dec: Decision) -> None:
    from ..observability import flight, trace

    trace.instant("dispatch_route_change", op=op, frm=frm, to=dec.impl,
                  reason=dec.reason)
    flight.RECORDER.event("dispatch_route_change", op=op, frm=frm,
                          to=dec.impl, reason=dec.reason)


# ---------------------------------------------------------------------------
# invocation seam (kernel profiler)
# ---------------------------------------------------------------------------

#: per-thread invocation hook — thread-local so an armed profiler on the
#: training thread never observes a serving thread's dispatches (and
#: vice versa), and clearing is just restoring the previous value
_INVOKE_TLS = threading.local()


def set_invoke_hook(
        hook: Optional[Callable[[str, Callable[..., Any], tuple, dict],
                                Any]]) -> Optional[Callable]:
    """Install THIS THREAD's invocation hook (``None`` clears) and return
    the previous one, so callers can restore it in a ``finally``. The
    hook receives ``(op, fn, args, kwargs)`` and must call
    ``fn(*args, **kwargs)`` itself — it owns the bracket around the
    dispatch, which is exactly what the kernel profiler needs to time
    host-blocked vs in-flight work and count deliberate completion syncs
    (``host_syncs_total{site=op}``) at ONE seam for every impl
    (pallas / XLA / native) instead of per call site."""
    prev = getattr(_INVOKE_TLS, "hook", None)
    _INVOKE_TLS.hook = hook
    return prev


def invoke(op: str, fn: Callable[..., Any], *args: Any, **kwargs: Any) -> Any:
    """Run ``fn(*args, **kwargs)`` — the resolved implementation of
    ``op`` — through the invocation seam. With no hook installed this is
    a plain call (one thread-local read of overhead); with a hook (a
    kernel-profiled round) the hook brackets the call. The sync points a
    hook may add live HERE, outside the round-loop files the RH204 lint
    statically walks — the lint stays sound for production rounds
    because unprofiled rounds never reach a hook."""
    hook = getattr(_INVOKE_TLS, "hook", None)
    if hook is None:
        return fn(*args, **kwargs)
    return hook(op, fn, args, kwargs)


# ---------------------------------------------------------------------------
# introspection (report CLI, flight black box, BENCH sidecar)
# ---------------------------------------------------------------------------


def explain(op: str, ctx: Optional[Ctx] = None) -> List[Dict[str, str]]:
    """Per-impl verdicts for ``op`` under ``ctx`` — the report's rows.
    Status: chosen | pinned-off | degraded | unavailable | inapplicable |
    fallback (usable, outranked)."""
    _ensure_ops()
    if ctx is None:
        ctx = _report_ctx(op)
    env_key = _env_key()
    pins, bans = _parse_pins(env_key)
    try:
        dec: Optional[Decision] = resolve(op, ctx)
    except DispatchError:
        dec = None
    platform = str(ctx.get("platform", ""))
    healthy = _healthy()
    with _STATE.lock:
        impls = list(_STATE.impls.get(op, ()))
    impls.sort(key=lambda i: (i.rank(platform), i.name))
    rows: List[Dict[str, str]] = []
    for impl in impls:
        if dec is not None and impl.name == dec.impl:
            status, note = "chosen", dec.reason
        elif impl.name in bans.get(op, ()) or (
                pins.get(op) is not None and pins.get(op) != impl.name):
            status, note = "pinned-off", "pins route elsewhere"
        elif not impl.applicable(ctx):
            status, note = "inapplicable", f"not applicable on {platform}"
        else:
            cap = impl.cap_for(platform)
            if cap is not None and _worst(cap) != healthy:
                status, note = "degraded", f"capability {cap!r} unhealthy"
            elif not impl.available(ctx):
                status, note = "unavailable", "build/runtime probe failed"
            else:
                status, note = "fallback", "usable, outranked by preference"
        rows.append({"impl": impl.name, "status": status, "note": note})
    return rows


def last_decisions() -> Dict[str, str]:
    """op -> most recently chosen impl (this process). The BENCH JSONL
    line embeds this so perf deltas are attributable to routing."""
    with _STATE.lock:
        return {op: dec.impl for op, dec in sorted(_STATE.last.items())}


def table_snapshot() -> Dict[str, Dict[str, str]]:
    """JSON-able resolved table for the flight black box: every op that
    resolved this process, with impl + reason."""
    with _STATE.lock:
        return {op: {"impl": dec.impl, "reason": dec.reason}
                for op, dec in sorted(_STATE.last.items())}


def reset() -> None:
    """Drop cached decisions/route history (tests). Registered ops and
    report ctxs survive — they are code, not state."""
    with _STATE.lock:
        _STATE.cache.clear()
        _STATE.routes.clear()
        _STATE.last.clear()
        _STATE.pins_memo.clear()
        _STATE.warned.clear()
