"""Cross-rank observability aggregation: ``python -m xgboost_tpu obs-report``.

Every rank of a fleet run persists its own telemetry under
``run_dir/obs/rank<k>/`` (``observability/flight.py``): ``flight.jsonl``
(per-round records + fleet events), ``trace.jsonl`` (span timeline),
``metrics.json`` (registry snapshot) and ``clock.json`` (the wall-clock
instant at which that rank's trace timestamps are zero). Per-rank files
answer per-rank questions; the fleet's questions — who straggled, when
was the death detected, what did the whole world spend — need the ranks
merged. This module is that merge (the reference's rabit tracker had the
reduce built into the protocol; here it is an offline pass over the
run directory, so it also works on the wreckage of a crashed run):

- **merged trace** — every rank's events on one clock-aligned timeline
  (each rank's ``ts`` is shifted by its recorded clock offset; Chrome
  ``pid`` = base rank), with flight events (worker loss, tombstones,
  quiesce/resize/replay, degrade transitions, watchdog aborts) rendered
  as instant events. Written to ``run_dir/obs/merged.trace.json`` —
  loadable in Perfetto like any single-rank trace.
- **metrics rollup** — counters summed across ranks, gauges maxed,
  histograms merged (sums/counts/buckets added). Written to
  ``run_dir/obs/metrics_rollup.json``.
- **per-round fleet table** — each round's wall time per rank, the
  straggler skew (max-min), and replay accounting (a (gen, round) pair
  recorded twice by one rank is a replayed round).

Partial data is expected input, not an error: a SIGKILLed rank's last
JSONL line may be torn (skipped), a rank that died before its first
round has only a meta line, and a missing ``clock.json`` degrades that
rank to unshifted timestamps.
"""

from __future__ import annotations

import json
import os
import re
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from .trace import load_trace

__all__ = ["collect", "load_obs_dir", "merge_trace", "write_trace",
           "rollup_metrics", "fleet_table", "format_fleet_report", "main"]

_RANK_RE = re.compile(r"^rank(\d+)$")
_REPLICA_RE = re.compile(r"^replica(\d+)$")


class RankObs:
    """One rank's persisted observability files, parsed leniently.
    ``title`` names the merged-trace process lane (defaults to the rank;
    multi-run merges and fleet replicas override it)."""

    def __init__(self, rank: int, path: str, title: Optional[str] = None):
        self.rank = rank
        self.path = path
        self.title = title if title is not None else f"rank {rank}"
        self.clock_unix_ns: Optional[int] = None
        self.trace_events: List[Dict[str, Any]] = []
        self.flight: List[Dict[str, Any]] = []
        self.metrics: Dict[str, Any] = {}
        self.errors: List[str] = []

    def load(self) -> "RankObs":
        clock = self._read_json("clock.json")
        if isinstance(clock, dict) and "unix_ns" in clock:
            self.clock_unix_ns = int(clock["unix_ns"])
        tr = os.path.join(self.path, "trace.jsonl")
        if os.path.exists(tr):
            try:
                self.trace_events = load_trace(tr)
            except (OSError, ValueError) as e:
                self.errors.append(f"trace.jsonl: {e}")
        fl = os.path.join(self.path, "flight.jsonl")
        if os.path.exists(fl):
            self.flight = self._read_jsonl(fl)
        metrics = self._read_json("metrics.json")
        if isinstance(metrics, dict):
            self.metrics = metrics
        # the black box also carries a metrics snapshot — prefer it only
        # when it is the NEWER file: after a completed/quiesced run it
        # postdates the last per-round metrics.json refresh, but a stale
        # blackbox.json left by an earlier abort of a since-resumed run
        # must not mask the live snapshot
        bb = self._read_json("blackbox.json")
        if isinstance(bb, dict) and isinstance(bb.get("metrics"), dict) \
                and bb["metrics"] and (not self.metrics or self._mtime(
                    "blackbox.json") >= self._mtime("metrics.json")):
            self.metrics = bb["metrics"]
        if not self.flight and isinstance(bb, dict):
            self.flight = [r for r in bb.get("records", [])
                           if isinstance(r, dict)]
        return self

    def _mtime(self, name: str) -> float:
        try:
            return os.path.getmtime(os.path.join(self.path, name))
        except OSError:
            return 0.0

    def _read_json(self, name: str) -> Any:
        try:
            with open(os.path.join(self.path, name)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def _read_jsonl(self, path: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError as e:
            self.errors.append(f"{os.path.basename(path)}: {e}")
            return out
        for i, ln in enumerate(lines):
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
                if isinstance(rec, dict):
                    out.append(rec)
            except ValueError:
                if i == len(lines) - 1:
                    continue  # torn final line: the SIGKILL signature
                self.errors.append(
                    f"{os.path.basename(path)}: bad record at line {i + 1}")
        return out


def load_obs_dir(path: str, rank: int = 0,
                 title: Optional[str] = None) -> RankObs:
    """Load ONE observability directory outside the ``rank<k>`` naming —
    the loader is layout-generic (flight/trace/metrics/clock sidecars),
    so the serving plane's ``obs/server/`` directory (``serve-report``,
    ``observability/serve_report.py``) reuses the same lenient parse and
    the same clock-aligned ``merge_trace``/``rollup_metrics`` machinery
    as a training rank. ``rank`` becomes the Chrome ``pid``."""
    return RankObs(rank, path, title).load()


def collect(run_dir: str) -> List[RankObs]:
    """Every ``rank<k>`` directory under ``run_dir/obs``, loaded — plus,
    for a *fleet* run_dir (``serve-fleet``), every
    ``replica<k>/obs/server`` serving sink as a rank-shaped member, so
    ``obs-report`` on a fleet directory rolls N replicas' metrics and
    traces up exactly like N training ranks (ISSUE 11)."""
    ranks: List[RankObs] = []
    obs = os.path.join(run_dir, "obs")
    try:
        names = sorted(os.listdir(obs))
    except OSError:
        names = []
    for name in names:
        m = _RANK_RE.match(name)
        sub = os.path.join(obs, name)
        if m and os.path.isdir(sub):
            ranks.append(RankObs(int(m.group(1)), sub).load())
    try:
        top = sorted(os.listdir(run_dir))
    except OSError:
        top = []
    # replicas slot in after any training ranks so pids never collide
    base = max((r.rank for r in ranks), default=-1) + 1 if ranks else 0
    for name in top:
        m = _REPLICA_RE.match(name)
        sub = os.path.join(run_dir, name, "obs", "server")
        if m and os.path.isdir(sub):
            ranks.append(RankObs(base + int(m.group(1)), sub,
                                 title=name).load())
    return sorted(ranks, key=lambda r: r.rank)


# ---------------------------------------------------------------------------
# merged trace
# ---------------------------------------------------------------------------

def merge_trace(ranks: List[RankObs]) -> List[Dict[str, Any]]:
    """One clock-aligned event list: the earliest recorded clock base is
    t=0's wall-clock anchor; each rank's events shift by its offset from
    that anchor and take the rank as ``pid``. Flight events become
    Chrome instants (phase 'i', process scope) so membership/degrade/
    elastic transitions are visible even for a rank whose trace ring
    never flushed."""
    bases = [r.clock_unix_ns for r in ranks if r.clock_unix_ns is not None]
    anchor_ns = min(bases) if bases else 0
    merged: List[Dict[str, Any]] = []
    for r in ranks:
        merged.append({
            "name": "process_name", "ph": "M", "pid": r.rank, "tid": 0,
            "args": {"name": f"xgboost_tpu {r.title}"},
        })
        shift_us = 0
        if r.clock_unix_ns is not None and anchor_ns:
            shift_us = (r.clock_unix_ns - anchor_ns) // 1000
        for ev in r.trace_events:
            if ev.get("ph") == "M":
                continue  # regenerated above with the base rank as pid
            ev = dict(ev)
            ev["pid"] = r.rank
            if "ts" in ev:
                ev["ts"] = int(ev["ts"]) + shift_us
            merged.append(ev)
        for rec in r.flight:
            if rec.get("t") != "event" or "unix_ms" not in rec:
                continue
            ts = int(rec["unix_ms"] * 1000) - anchor_ns // 1000
            merged.append({
                "name": rec.get("name", "event"), "ph": "i", "s": "p",
                "ts": max(ts, 0), "pid": r.rank, "tid": 0,
                "args": rec.get("args", {}),
            })
    return merged


def write_trace(path: str, events: List[Dict[str, Any]]) -> None:
    """The same trailing-comma array-of-lines form ``trace.flush``
    writes (Perfetto/chrome://tracing-loadable, line-parseable)."""
    with open(path, "w") as f:
        f.write("[\n")
        for ev in events:
            f.write(json.dumps(ev) + ",\n")


# ---------------------------------------------------------------------------
# metrics rollup
# ---------------------------------------------------------------------------

def rollup_metrics(ranks: List[RankObs]) -> Dict[str, Any]:
    """Fleet-wide registry view: counters and histogram sums/counts/
    buckets ADD across ranks (total work done); gauges take the MAX
    (watermarks and state codes — ``degrade_state``'s worst-state
    encoding and memory peaks both want the maximum; a mean would
    describe no rank at all)."""
    out: Dict[str, Any] = {}
    for r in ranks:
        for name, fam in (r.metrics or {}).items():
            if not isinstance(fam, dict) or "series" not in fam:
                continue
            dst = out.setdefault(name, {
                "type": fam.get("type", "gauge"),
                "help": fam.get("help", ""),
                "series": {},
            })
            for s in fam["series"]:
                key = tuple(sorted((s.get("labels") or {}).items()))
                if dst["type"] == "histogram":
                    agg = dst["series"].setdefault(key, {
                        "labels": dict(key), "sum": 0.0, "count": 0,
                        "buckets": defaultdict(int), "ranks": 0,
                    })
                    agg["sum"] += float(s.get("sum", 0.0))
                    agg["count"] += int(s.get("count", 0))
                    for ub, c in (s.get("buckets") or {}).items():
                        agg["buckets"][ub] += int(c)
                    agg["ranks"] += 1
                else:
                    agg = dst["series"].setdefault(key, {
                        "labels": dict(key), "value": 0.0, "ranks": 0,
                    })
                    v = float(s.get("value", 0.0))
                    if dst["type"] == "counter":
                        agg["value"] += v
                    else:
                        agg["value"] = v if agg["ranks"] == 0 \
                            else max(agg["value"], v)
                    agg["ranks"] += 1
    for fam in out.values():
        series = []
        for _, agg in sorted(fam["series"].items()):
            if "buckets" in agg:
                agg["buckets"] = dict(agg["buckets"])
                # per-rank p50/p99 don't merge; recompute from the summed
                # cumulative buckets so every labelled series (e.g. the
                # per-model predict_latency_seconds children the serving
                # layer writes) keeps fleet-wide quantiles
                agg["p50"] = _merged_quantile(agg["buckets"],
                                              agg["count"], 0.50)
                agg["p99"] = _merged_quantile(agg["buckets"],
                                              agg["count"], 0.99)
            series.append(agg)
        fam["series"] = series
    return out


def _merged_quantile(buckets: Dict[str, Any], count: int,
                     q: float) -> Optional[float]:
    """Prometheus-style quantile from summed CUMULATIVE bucket counts
    (``metrics.Histogram.quantile`` semantics; snapshot buckets are
    cumulative and exclude +Inf, so ranks above the top bound clamp to
    the largest finite bound). None on empty/unparsable series."""
    if not count or not buckets:
        return None
    try:
        ladder = sorted((float(ub), int(c)) for ub, c in buckets.items())
    except (TypeError, ValueError):
        return None
    target = max(min(float(q), 1.0), 0.0) * count
    lo, prev_cum = 0.0, 0
    for ub, cum in ladder:
        c = cum - prev_cum
        if c and cum >= target:
            frac = (target - prev_cum) / c
            return lo + (ub - lo) * min(max(frac, 0.0), 1.0)
        prev_cum, lo = cum, ub
    return ladder[-1][0]


# ---------------------------------------------------------------------------
# per-round fleet table
# ---------------------------------------------------------------------------

def fleet_table(ranks: List[RankObs]) -> Dict[str, Any]:
    """Round-by-round wall times across ranks. Keyed (generation, round):
    ``per_round[(g, i)] = {rank: wall_s}``. ``replayed`` counts (rank,
    gen-crossing) repeats of a round index — the rounds elastic recovery
    re-trained. ``skew`` per round is max-min wall seconds across the
    ranks that recorded it (the straggler gap the async executor of
    ROADMAP 3 must close)."""
    per_round: Dict[Tuple[int, int], Dict[int, float]] = defaultdict(dict)
    replayed = 0
    for r in ranks:
        seen: set = set()
        for rec in r.flight:
            if rec.get("t") != "round" or "wall_s" not in rec:
                continue
            base = int(rec.get("round", -1))
            n = max(int(rec.get("rounds", 1)), 1)
            gen = int(rec.get("gen", 0))
            for i in range(base, base + n):
                if i in seen:
                    replayed += 1
                seen.add(i)
                # chunk records spread their wall evenly; per-round
                # records (n == 1) keep it exact
                per_round[(gen, i)][r.rank] = rec["wall_s"] / n
    rows = []
    for (gen, i), by_rank in sorted(per_round.items()):
        walls = list(by_rank.values())
        rows.append({
            "gen": gen, "round": i,
            "ranks": {str(k): round(v, 6) for k, v in sorted(
                by_rank.items())},
            "skew_s": round(max(walls) - min(walls), 6),
        })
    return {"rounds": rows, "replayed_rounds": replayed}


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------

def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in sorted(labels.items())) \
        + "}"


def format_fleet_report(ranks: List[RankObs], rollup: Dict[str, Any],
                        table: Dict[str, Any], top_rounds: int = 10) -> str:
    lines = [f"obs-report: {len(ranks)} rank(s)"]
    for r in ranks:
        n_rounds = sum(1 for rec in r.flight if rec.get("t") == "round")
        n_events = sum(1 for rec in r.flight if rec.get("t") == "event")
        lines.append(
            f"  {r.title}: {n_rounds} round records, {n_events} "
            f"events, {len(r.trace_events)} trace events"
            + (f", {len(r.errors)} parse errors" if r.errors else ""))
        for err in r.errors:
            lines.append(f"    ! {err}")
    events: Dict[str, int] = defaultdict(int)
    for r in ranks:
        for rec in r.flight:
            if rec.get("t") == "event":
                events[rec.get("name", "?")] += 1
    if events:
        lines.append("")
        lines.append("fleet events:")
        for name in sorted(events):
            lines.append(f"  {name}: {events[name]}")
    rows = table["rounds"]
    if rows:
        lines.append("")
        multi = any(len(row["ranks"]) > 1 for row in rows)
        total = sum(sum(row["ranks"].values()) for row in rows)
        lines.append(
            f"per-round fleet table: {len(rows)} (gen, round) entries, "
            f"{table['replayed_rounds']} replayed, "
            f"{total:.3f}s total round wall")
        show = sorted(rows, key=lambda r: -r["skew_s"])[:top_rounds] \
            if multi else rows[:top_rounds]
        lines.append(f"  {'gen':>4} {'round':>6} {'skew':>10}  per-rank s")
        for row in sorted(show, key=lambda r: (r["gen"], r["round"])):
            per = " ".join(f"r{k}={v:.3f}"
                           for k, v in row["ranks"].items())
            lines.append(f"  {row['gen']:>4} {row['round']:>6} "
                         f"{row['skew_s'] * 1e3:>8.2f}ms  {per}")
        if len(rows) > len(show):
            lines.append(f"  ... ({len(rows) - len(show)} more; "
                         "full table in metrics_rollup.json's sidecar)")
    counters = []
    for name, fam in sorted(rollup.items()):
        if fam["type"] != "counter":
            continue
        for s in fam["series"]:
            counters.append((name + _fmt_labels(s["labels"]), s["value"],
                             s["ranks"]))
    if counters:
        lines.append("")
        lines.append("metrics rollup (counters summed across ranks):")
        for name, value, nr in counters:
            lines.append(f"  {name} = {value:g}  [{nr} rank(s)]")
    for name, fam in sorted(rollup.items()):
        if fam["type"] != "histogram":
            continue
        for s in fam["series"]:
            if s["count"]:
                p99 = s.get("p99")
                lines.append(
                    f"  {name}{_fmt_labels(s['labels'])}: count={s['count']} "
                    f"mean={s['sum'] / s['count'] * 1e3:.3f}ms"
                    + (f" p99={p99 * 1e3:.3f}ms" if p99 is not None else ""))
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    usage = ("usage: python -m xgboost_tpu obs-report <run_dir> ... "
             "[--top-rounds N]")
    if not argv or argv[0] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        return 0 if argv else 1
    top_rounds = 10
    if "--top-rounds" in argv:
        i = argv.index("--top-rounds")
        try:
            top_rounds = int(argv[i + 1])
        except (IndexError, ValueError):
            print(usage, file=sys.stderr)
            return 1
        argv = argv[:i] + argv[i + 2:]
    # multiple run_dirs merge into ONE report (ISSUE 11): each dir's
    # ranks keep their own pid block (dir index * 100 + rank) and carry
    # the dir name in their lane title; outputs land under the FIRST dir
    run_dirs = argv
    run_dir = run_dirs[0]
    ranks: List[RankObs] = []
    for i, d in enumerate(run_dirs):
        sub = collect(d)
        for r in sub:
            if len(run_dirs) > 1:
                label = os.path.basename(os.path.normpath(d)) or d
                r.title = f"{label} {r.title}"
                r.rank += i * 100
        ranks.extend(sub)
    if not ranks:
        print(f"{' '.join(run_dirs)}: no obs/rank<k> (or replica<k>/obs/"
              "server) directories found (was the run launched with a "
              "flight-recorder sink? docs/observability.md)",
              file=sys.stderr)
        return 1
    merged = merge_trace(ranks)
    rollup = rollup_metrics(ranks)
    table = fleet_table(ranks)
    obs = os.path.join(run_dir, "obs")
    trace_out = os.path.join(obs, "merged.trace.json")
    rollup_out = os.path.join(obs, "metrics_rollup.json")
    try:
        write_trace(trace_out, merged)
        with open(rollup_out, "w") as f:
            json.dump({"rollup": rollup, "fleet_table": table}, f)
    except OSError as e:
        print(f"obs-report: cannot write outputs: {e}", file=sys.stderr)
        return 1
    print(format_fleet_report(ranks, rollup, table, top_rounds=top_rounds))
    print(f"\nmerged trace -> {trace_out} ({len(merged)} events)")
    print(f"metrics rollup -> {rollup_out}")
    return 0
