"""Unified telemetry: span tracing, metrics registry, comms accounting.

The one observability layer for the training stack (ISSUE 1), replacing
the reference's three disconnected tools (``common::Monitor`` wall-clock
accumulators, NVTX ranges, ``TrainingObserver`` dumps):

- ``trace`` — ``span("hist_build", node=k)`` context managers emitting a
  Chrome trace-event timeline (Perfetto / ``chrome://tracing``), enabled
  by ``XGBTPU_TRACE=<path>`` or ``set_config(trace_path=...)``;
- ``metrics`` — the process-wide ``REGISTRY`` of counters / gauges /
  histograms with Prometheus text exposition and JSON snapshots
  (``utils.timer.Monitor`` feeds it as a thin adapter);
- ``comms`` — collective ops/bytes accounting for ``collective.py`` and
  the mesh psum / all_gather paths;
- ``flight`` — the always-on per-round flight recorder (ring buffer,
  durable ``run_dir/obs/rank<k>/`` sink, black-box dumps, profiling
  window) — ISSUE 7;
- ``report`` — the ``python -m xgboost_tpu trace-report`` summarizer
  (per-span self times, span-category totals: serving vs train vs
  collective);
- ``fleet`` — the ``python -m xgboost_tpu obs-report`` cross-rank
  merger (clock-aligned trace, metrics rollup, per-round fleet table);
- ``serve_report`` — the ``python -m xgboost_tpu serve-report``
  serving-plane report (per-model latency percentiles, shed/degrade
  timeline, coalescing, worst-request exemplars) over a model server's
  ``run_dir/obs/server/`` sink (``serving/obs.py`` — ISSUE 9).

Everything is a no-op costing one branch per call site when disabled, and
never records from inside ``jit``-traced code (host-side only).
"""

from . import comms, metrics, trace  # noqa: F401
from . import flight  # noqa: F401  (after trace/metrics: it builds on both)
from .flight import RECORDER  # noqa: F401
from .metrics import REGISTRY, MetricsRegistry, get_registry  # noqa: F401
from .trace import (  # noqa: F401
    emit,
    enabled,
    flush,
    instant,
    load_trace,
    span,
    trace_path,
)

__all__ = [
    "trace", "metrics", "comms", "flight",
    "span", "instant", "emit", "enabled", "flush", "trace_path",
    "load_trace",
    "REGISTRY", "MetricsRegistry", "get_registry", "RECORDER",
]
