"""Serving-plane report: ``python -m xgboost_tpu serve-report <dir>``.

The sibling of ``obs-report`` for the traffic-facing half of the system
(ISSUE 9). A :class:`~xgboost_tpu.serving.ModelServer` launched with a
``run_dir`` (or ``XGBTPU_SERVE_DIR``) persists its request-scope
observability under ``run_dir/obs/server/`` — ``access.jsonl`` (one line
per request), ``flight.jsonl`` (per-dispatch ring + timeline events),
``trace.jsonl`` (per-request async span tracks), ``metrics.json`` and
``clock.json``. This module merges them into the operator's one-page
answer to "what did traffic look like":

- **latency percentiles per model** — p50/p99/max of request total time
  plus queue-wait and dispatch p99, computed exactly from the access log
  (the registry histograms stay the scrapeable approximation);
- **shed/degrade timeline** — per-second buckets of ok / shed (by
  reason) / error counts and native-routed dispatch counts, with model
  load/swap/evict events inlined where they happened;
- **coalescing** — requests per dispatch, route mix and program-cache
  misses from the dispatch ring;
- **worst-request exemplars** — the slowest requests with their full
  stage breakdown (queue -> batch wait -> dispatch);
- **merged Chrome trace** — ``obs/serve.trace.json``: span events plus
  timeline events as instants, clock-aligned through the same
  ``fleet.merge_trace`` machinery a training rank uses (loadable in
  Perfetto; per-request tracks are nestable-async lanes).

A machine-readable summary lands next to it as
``obs/serve_report.json``. Partial data is expected input (a killed
server's final line may be torn — same contract as ``obs-report``);
a directory with no serving observability at all exits 1.
"""

from __future__ import annotations

import json
import os
import re
import sys
from collections import defaultdict
from typing import Any, Dict, List, Optional, Tuple

from . import fleet

__all__ = ["load_server_obs", "summarize_access", "summarize_tenants",
           "summarize_delivery", "format_serve_report",
           "expand_server_dirs", "main"]

_REPLICA_RE = re.compile(r"^replica(\d+)$")

#: timeline events emitted by the train-to-serve delivery loop
#: (serving/delivery.py + the server's publish/promote/rollback/
#: quarantine methods) — rendered as their own report section
_DELIVERY_EVENTS = (
    "checkpoint_seen", "checkpoint_skipped", "model_published",
    "canary_start", "canary_rejected", "model_promoted",
    "model_rolled_back", "model_quarantined", "model_discarded")


def _resolve_dir(path: str) -> Optional[str]:
    """The ``obs/server`` directory for any of: a server run_dir, its
    ``obs`` directory, or the server directory itself."""
    for cand in (os.path.join(path, "obs", "server"),
                 os.path.join(path, "server"), path):
        if os.path.isfile(os.path.join(cand, "access.jsonl")) \
                or os.path.isfile(os.path.join(cand, "flight.jsonl")):
            return cand
    return None


def expand_server_dirs(paths: List[str]) -> List[Tuple[str, str]]:
    """(label, server-obs dir) for every serving sink named by ``paths``:
    each path may be a single server run_dir (label = its basename) OR a
    fleet run_dir whose ``replica<k>/`` children each hold one
    (labels ``replica<k>``) — the ``serve-fleet`` layout."""
    entries: List[Tuple[str, str]] = []
    for p in paths:
        d = _resolve_dir(p)
        if d is not None:
            entries.append(
                (os.path.basename(os.path.normpath(p)) or p, d))
            continue
        try:
            names = os.listdir(p)
        except OSError:
            continue
        matches = [(int(m.group(1)), name) for name, m in
                   ((n, _REPLICA_RE.match(n)) for n in names) if m]
        for _, name in sorted(matches):  # numeric: replica2 < replica10
            sub = _resolve_dir(os.path.join(p, name))
            if sub is not None:
                entries.append((name, sub))
    return entries


def load_server_obs(path: str) -> Optional[Tuple[Any, List[Dict[str, Any]]]]:
    """(RankObs-view of the server dir, access records) or None when
    ``path`` holds no serving observability."""
    d = _resolve_dir(path)
    if d is None:
        return None
    obs = fleet.load_obs_dir(d, rank=0)
    access = [rec for rec in obs._read_jsonl(
        os.path.join(d, "access.jsonl")) if rec.get("t") == "req"]
    return obs, access


def _pct(sorted_vals: List[float], q: float) -> float:
    """Exact empirical quantile (nearest-rank) of pre-sorted values."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


def summarize_access(access: List[Dict[str, Any]],
                     dispatches: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The machine-readable summary the text report renders."""
    outcomes: Dict[str, int] = defaultdict(int)
    shed_reasons: Dict[str, int] = defaultdict(int)
    per_model: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for rec in access:
        outcomes[rec.get("outcome", "?")] += 1
        if rec.get("shed"):
            shed_reasons[rec["shed"]] += 1
        per_model[rec.get("model", "?")].append(rec)
    models: Dict[str, Any] = {}
    for model, recs in sorted(per_model.items()):
        ok = [r for r in recs if r.get("outcome") == "ok"]
        totals = sorted(r.get("total_s", 0.0) for r in ok)
        queues = sorted(r["queue_wait_s"] for r in ok
                        if "queue_wait_s" in r)
        disp = sorted(r["dispatch_s"] for r in ok if "dispatch_s" in r)
        models[model] = {
            "requests": len(recs), "ok": len(ok),
            "rows": sum(int(r.get("rows", 0)) for r in recs),
            "total_p50_s": _pct(totals, 0.50),
            "total_p99_s": _pct(totals, 0.99),
            "total_max_s": totals[-1] if totals else 0.0,
            "queue_wait_p99_s": _pct(queues, 0.99),
            "dispatch_p99_s": _pct(disp, 0.99),
        }
    routes: Dict[str, int] = defaultdict(int)
    reqs = rows = misses = 0
    for d in dispatches:
        routes[d.get("route") or "?"] += 1
        reqs += int(d.get("reqs", 0))
        rows += int(d.get("rows", 0))
        misses += int(d.get("cache_misses", 0))
    return {
        "requests": len(access),
        "outcomes": dict(outcomes),
        "shed_reasons": dict(shed_reasons),
        "models": models,
        "dispatches": len(dispatches),
        "dispatched_rows": rows,
        "coalesce_ratio": reqs / max(len(dispatches), 1),
        "routes": dict(routes),
        "cache_misses": misses,
    }


def summarize_tenants(access: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Per request-tenant rollup from access lines (requests that carried
    no tenant group under ``-``): counts, shed reasons, exact total-time
    and queue-wait percentiles — the fairness story per tenant, fleet-wide
    when the access set spans replicas (ISSUE 11)."""
    per: Dict[str, List[Dict[str, Any]]] = defaultdict(list)
    for rec in access:
        per[rec.get("tenant") or "-"].append(rec)
    out: Dict[str, Any] = {}
    for tenant, recs in sorted(per.items()):
        ok = [r for r in recs if r.get("outcome") == "ok"]
        totals = sorted(r.get("total_s", 0.0) for r in ok)
        queues = sorted(r["queue_wait_s"] for r in ok
                        if "queue_wait_s" in r)
        sheds: Dict[str, int] = defaultdict(int)
        for r in recs:
            if r.get("shed"):
                sheds[r["shed"]] += 1
        out[tenant] = {
            "requests": len(recs), "ok": len(ok),
            "rows": sum(int(r.get("rows", 0)) for r in ok),
            "total_p50_s": _pct(totals, 0.50),
            "total_p99_s": _pct(totals, 0.99),
            "queue_wait_p99_s": _pct(queues, 0.99),
            "shed_reasons": dict(sheds),
        }
    return out


def summarize_delivery(events: List[Dict[str, Any]]
                       ) -> List[Dict[str, Any]]:
    """The delivery story in order: every checkpoint_seen / skipped /
    published / canary / promote / rollback / quarantine event with its
    args flattened — the machine-readable "Model delivery" section
    (docs/serving.md)."""
    rows: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("name") not in _DELIVERY_EVENTS:
            continue
        args = ev.get("args") or {}
        row: Dict[str, Any] = {"unix_ms": ev.get("unix_ms"),
                               "event": ev["name"]}
        for k in sorted(args):
            row.setdefault(k, args[k])
        rows.append(row)
    rows.sort(key=lambda r: r.get("unix_ms") or 0)
    return rows


def _timeline(access: List[Dict[str, Any]],
              events: List[Dict[str, Any]],
              dispatches: List[Dict[str, Any]],
              bucket_s: float = 1.0) -> List[Dict[str, Any]]:
    """Per-``bucket_s`` activity rows: outcome counts, native-routed
    dispatches, and the events that fell in the bucket — the shed/
    degrade/swap story in order."""
    stamps = [r["unix_ms"] for r in access + events + dispatches
              if "unix_ms" in r]
    if not stamps:
        return []
    base = min(stamps)
    rows: Dict[int, Dict[str, Any]] = {}

    def at(ms: float) -> Dict[str, Any]:
        k = int((ms - base) / (bucket_s * 1e3))
        return rows.setdefault(k, {
            "t_s": k * bucket_s, "ok": 0, "shed": 0, "error": 0,
            "native": 0, "sheds": defaultdict(int), "events": []})

    for rec in access:
        if "unix_ms" not in rec:
            continue
        row = at(rec["unix_ms"])
        outcome = rec.get("outcome", "error")
        row[outcome if outcome in ("ok", "shed", "error") else "error"] += 1
        if rec.get("shed"):
            row["sheds"][rec["shed"]] += 1
    for d in dispatches:
        if d.get("route") == "native" and "unix_ms" in d:
            at(d["unix_ms"])["native"] += 1
    for ev in events:
        if "unix_ms" not in ev:
            continue
        label = ev.get("name", "event")
        model = (ev.get("args") or {}).get("model")
        at(ev["unix_ms"])["events"].append(
            f"{label}({model})" if model else label)
    out = []
    for k in sorted(rows):
        row = rows[k]
        row["sheds"] = dict(row["sheds"])
        out.append(row)
    return out


def format_serve_report(summary: Dict[str, Any],
                        timeline: List[Dict[str, Any]],
                        exemplars: List[Dict[str, Any]],
                        top: int = 8,
                        tenants: Optional[Dict[str, Any]] = None,
                        replicas: Optional[List[Dict[str, Any]]] = None,
                        delivery: Optional[List[Dict[str, Any]]] = None
                        ) -> str:
    o = summary["outcomes"]
    shed_detail = ",".join(f"{k}={v}" for k, v in
                           sorted(summary["shed_reasons"].items()))
    head = "serve-report" if not replicas \
        else f"fleet serve-report ({len(replicas)} replicas)"
    lines = [
        f"{head}: {summary['requests']} request(s) — "
        f"ok={o.get('ok', 0)} shed={o.get('shed', 0)}"
        + (f" ({shed_detail})" if shed_detail else "")
        + f" error={o.get('error', 0)}",
        f"dispatches: {summary['dispatches']} "
        f"({summary['dispatched_rows']} rows, coalescing "
        f"{summary['coalesce_ratio']:.2f} req/dispatch, "
        f"{summary['cache_misses']} program-cache misses); routes: "
        + (" ".join(f"{k}={v}" for k, v in
                    sorted(summary["routes"].items())) or "none"),
    ]
    if replicas:
        lines.append("")
        lines.append("per-replica rollup:")
        lines.append(f"  {'replica':<14} {'n':>6} {'ok':>6} {'shed':>5} "
                     f"{'err':>4} {'p50':>10} {'p99':>10} {'burn':>6}  "
                     "events")
        for r in replicas:
            evs = ",".join(f"{k}={v}" for k, v in
                           sorted(r.get("events", {}).items()))
            lines.append(
                f"  {r['replica']:<14} {r['requests']:>6} {r['ok']:>6} "
                f"{r['shed']:>5} {r['error']:>4} "
                f"{r['total_p50_s'] * 1e3:>8.2f}ms "
                f"{r['total_p99_s'] * 1e3:>8.2f}ms "
                f"{r.get('burn', 0.0):>6.2f}  {evs}")
    if summary["models"]:
        lines.append("")
        lines.append("per-model latency (access log, completed requests):")
        lines.append(f"  {'model':<18} {'n':>6} {'ok':>6} {'p50':>10} "
                     f"{'p99':>10} {'max':>10} {'queue p99':>10} "
                     f"{'disp p99':>10}")
        for model, m in summary["models"].items():
            lines.append(
                f"  {model:<18} {m['requests']:>6} {m['ok']:>6} "
                f"{m['total_p50_s'] * 1e3:>8.2f}ms "
                f"{m['total_p99_s'] * 1e3:>8.2f}ms "
                f"{m['total_max_s'] * 1e3:>8.2f}ms "
                f"{m['queue_wait_p99_s'] * 1e3:>8.2f}ms "
                f"{m['dispatch_p99_s'] * 1e3:>8.2f}ms")
    if tenants and (len(tenants) > 1 or "-" not in tenants):
        lines.append("")
        lines.append("per-tenant rollup (access log):")
        lines.append(f"  {'tenant':<14} {'n':>6} {'ok':>6} {'rows':>7} "
                     f"{'p50':>10} {'p99':>10} {'queue p99':>10}  sheds")
        for tenant, t in tenants.items():
            sheds = ",".join(f"{k}={v}" for k, v in
                             sorted(t["shed_reasons"].items()))
            lines.append(
                f"  {tenant:<14} {t['requests']:>6} {t['ok']:>6} "
                f"{t['rows']:>7} {t['total_p50_s'] * 1e3:>8.2f}ms "
                f"{t['total_p99_s'] * 1e3:>8.2f}ms "
                f"{t['queue_wait_p99_s'] * 1e3:>8.2f}ms  {sheds}")
    if delivery:
        lines.append("")
        lines.append("model delivery (train-to-serve loop):")
        base = next((r["unix_ms"] for r in delivery
                     if r.get("unix_ms") is not None), 0)
        for row in delivery:
            t = ((row.get("unix_ms") or base) - base) / 1e3
            detail = " ".join(
                f"{k}={v}" for k, v in row.items()
                if k not in ("unix_ms", "event") and v is not None)
            lines.append(f"  t+{t:>6.1f}s {row['event']:<20} {detail}")
    if timeline:
        lines.append("")
        lines.append("shed/degrade timeline (1s buckets):")
        for row in timeline:
            sheds = "".join(f" shed[{k}]={v}"
                            for k, v in sorted(row["sheds"].items()))
            evs = ("  | " + ", ".join(row["events"])) if row["events"] \
                else ""
            lines.append(
                f"  t+{row['t_s']:>4.0f}s ok={row['ok']:<5} "
                f"shed={row['shed']:<4} err={row['error']:<4} "
                f"native={row['native']:<4}{sheds}{evs}")
    if exemplars:
        lines.append("")
        lines.append(f"worst-request exemplars (top {min(top, len(exemplars))} "
                     "by total time):")
        lines.append(f"  {'id':<16} {'model':<14} {'rows':>5} {'total':>10} "
                     f"{'queue':>9} {'batch':>9} {'disp':>9}  outcome")
        for rec in exemplars[:top]:
            lines.append(
                f"  {str(rec.get('id', '?')):<16} "
                f"{rec.get('model', '?'):<14} {rec.get('rows', 0):>5} "
                f"{rec.get('total_s', 0) * 1e3:>8.2f}ms "
                f"{rec.get('queue_wait_s', 0) * 1e3:>7.2f}ms "
                f"{rec.get('batch_wait_s', 0) * 1e3:>7.2f}ms "
                f"{rec.get('dispatch_s', 0) * 1e3:>7.2f}ms  "
                f"{rec.get('outcome', '?')}"
                + (f" ({rec['shed']})" if rec.get("shed") else ""))
    return "\n".join(lines)


def _replica_burn(obs: Any) -> float:
    """The replica's last-persisted error-budget burn gauge (0.0 when the
    snapshot never landed)."""
    fam = (obs.metrics or {}).get("serving_error_budget_burn")
    if not isinstance(fam, dict):
        return 0.0
    for s in fam.get("series", []):
        if not s.get("labels"):
            return float(s.get("value", 0.0))
    return 0.0


def main(argv: List[str]) -> int:
    usage = ("usage: python -m xgboost_tpu serve-report <dir> ... "
             "[--top N]  (a dir may be one server run_dir or a fleet "
             "run_dir with replica<k>/ children)")
    if not argv or argv[0] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        return 0 if argv else 1
    top = 8
    if "--top" in argv:
        i = argv.index("--top")
        try:
            top = int(argv[i + 1])
        except (IndexError, ValueError):
            print(usage, file=sys.stderr)
            return 1
        argv = argv[:i] + argv[i + 2:]
    entries = expand_server_dirs(argv)
    if not entries:
        print(f"{' '.join(argv)}: no serving observability found (launch "
              "the server with run_dir= / --run-dir / XGBTPU_SERVE_DIR, "
              "or point at a serve-fleet run_dir — docs/serving.md "
              "\"Tracing a request\", \"Scaling out\")", file=sys.stderr)
        return 1
    fleet_mode = len(entries) > 1
    all_obs, access, replicas = [], [], []
    events: List[Dict[str, Any]] = []
    dispatches: List[Dict[str, Any]] = []
    for k, (label, d) in enumerate(entries):
        obs = fleet.load_obs_dir(d, rank=k, title=label)
        for err in obs.errors:
            print(f"serve-report: {label}: {err}", file=sys.stderr)
        acc = [rec for rec in obs._read_jsonl(
            os.path.join(d, "access.jsonl")) if rec.get("t") == "req"]
        evs = [r for r in obs.flight if r.get("t") == "event"]
        dis = [r for r in obs.flight if r.get("t") == "dispatch"]
        if fleet_mode:
            for rec in acc:
                rec["replica"] = label
            for rec in evs:
                rec.setdefault("args", {})["replica"] = label
            rsum = summarize_access(acc, dis)
            o = rsum["outcomes"]
            totals = sorted(r.get("total_s", 0.0) for r in acc
                            if r.get("outcome") == "ok")
            replicas.append({
                "replica": label, "requests": rsum["requests"],
                "ok": o.get("ok", 0), "shed": o.get("shed", 0),
                "error": o.get("error", 0),
                "total_p50_s": _pct(totals, 0.50),
                "total_p99_s": _pct(totals, 0.99),
                "shed_reasons": rsum["shed_reasons"],
                "burn": _replica_burn(obs),
                "events": {name: sum(1 for e in evs
                                     if e.get("name") == name)
                           for name in sorted({e.get("name", "?")
                                               for e in evs})},
            })
        all_obs.append(obs)
        access.extend(acc)
        events.extend(evs)
        dispatches.extend(dis)
    summary = summarize_access(access, dispatches)
    tenants = summarize_tenants(access)
    timeline = _timeline(access, events, dispatches)
    delivery = summarize_delivery(events)
    exemplars = sorted((r for r in access if "total_s" in r),
                       key=lambda r: -r["total_s"])
    print(format_serve_report(summary, timeline, exemplars, top=top,
                              tenants=tenants,
                              replicas=replicas if fleet_mode else None,
                              delivery=delivery))

    if fleet_mode:
        # one fleet-wide artifact set under the FIRST input's obs/ dir
        obs_dir = os.path.join(argv[0], "obs")
        try:
            os.makedirs(obs_dir, exist_ok=True)
        except OSError:
            obs_dir = os.path.dirname(all_obs[0].path)
        trace_out = os.path.join(obs_dir, "fleet_serve.trace.json")
        report_out = os.path.join(obs_dir, "fleet_serve_report.json")
        doc = {"summary": summary, "replicas": replicas,
               "tenants": tenants, "timeline": timeline,
               "delivery": delivery,
               "exemplars": exemplars[:top],
               "rollup": fleet.rollup_metrics(all_obs)}
    else:
        obs_dir = os.path.dirname(all_obs[0].path)
        trace_out = os.path.join(obs_dir, "serve.trace.json")
        report_out = os.path.join(obs_dir, "serve_report.json")
        doc = {"summary": summary, "tenants": tenants,
               "timeline": timeline, "delivery": delivery,
               "exemplars": exemplars[:top]}
    try:
        fleet.write_trace(trace_out, fleet.merge_trace(all_obs))
        with open(report_out, "w") as f:
            json.dump(doc, f, default=str)
    except OSError as e:
        print(f"serve-report: cannot write outputs: {e}", file=sys.stderr)
        return 1
    n_spans = sum(len(o.trace_events) for o in all_obs)
    print(f"\nmerged trace -> {trace_out} ({n_spans} span events)")
    print(f"summary -> {report_out}")
    return 0
