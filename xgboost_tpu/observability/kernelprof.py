"""Intra-round grow profiler: per-depth × per-op attribution on demand.

The flight recorder (PR 6) can say a round spent 95% of its wall in
``grow`` — and nothing more. This module answers the next question
(ROADMAP item 1: where does the grow dispatch itself go?) without
touching the production path: on **sampled rounds only**
(``XGBTPU_KERNEL_PROF=every=N`` or ``rounds=a,b,c``; off by default),
the in-core grower runs an instrumented mirror of the fused driver that
routes every kernel dispatch through the ``dispatch.invoke`` seam and
brackets it with a completion sync (``jax.block_until_ready``),
producing a per-round ``grow_detail`` record:

- per-depth × per-op wall time (``level_hist`` / ``level_update`` /
  ``level_partition`` / ``finalize`` / ``leaf_delta`` / ``prep``), with
  the resolved impl (pallas / XLA / native) attached from
  ``dispatch.last_decisions()`` — all impls covered uniformly because
  the bracket sits at the seam, not at any call site;
- a **host-blocked vs in-flight** split per bucket: time until the
  dispatch returned to the host (tracing + program launch) vs time until
  the result was actually ready;
- the **inter-dispatch gap** (host time between one op's completion and
  the next op's dispatch — the Python/driver overhead a fused program
  doesn't pay);
- ``host_syncs_total{site=op}`` — every deliberate completion sync,
  counted from the same seam. The RH204 lint statically walks the
  round-loop files and would flag these syncs there; they live HERE (and
  in ``dispatch/core.py``), outside its scope, which is the point: the
  production round loop stays statically sync-free, and profiled rounds
  opt in at one audited seam.

Sampled rounds stay **bit-identical** to unsampled ones: the mirror
reuses the exact shared level machinery (``fused_level`` /
``_level_update_jit`` / ``partition_apply`` / ``_finalize_jit`` /
``leaf_delta``) the fused program is built from — only sync points are
added, math untouched. This leans on the same cross-driver identity the
repo already pins (scanned ≡ unrolled, PR 13; paged ≡ streaming, PR 15)
and is pinned end-to-end by ``tests/test_kernelprof.py`` (model bytes
equal with profiling on vs off).

Single-dispatch rounds (ISSUE 17): when the production round runs the
whole-tree native kernel (``tree_grow`` resolves to ``native``), there
is exactly ONE dispatch to bracket — useless for attribution. The
mirror therefore replays the round per-level, and when sibling
subtraction is on it substitutes ``fused_level_sub_native`` at depth
>= 1 — the FFI entry that shares tree_build.cpp's partition + build +
subtract core loops — retaining the previous level's histogram between
calls, so the replayed histograms (and hence the whole round) match the
fused kernel's output bit-for-bit while every level still lands in its
own ``level_hist`` bucket. The record carries ``route`` and
``sibling_sub`` so a reader knows the numbers describe a per-level
replay of a one-dispatch round.

The record feeds the flight record as ``grow_detail`` (rendered by
``python -m xgboost_tpu grow-report``) and each bracket is emitted as a
``cat="grow"`` Chrome span, so the substages nest under the existing
``round`` span in the merged Perfetto trace and ``trace-report`` grows a
``grow`` category row for free.

Import discipline: this module imports ONLY stdlib at module scope —
``gbm/gbtree.py`` and ``training.py`` import it eagerly, and the tree /
dispatch / jax machinery must not load (or cycle) before first use.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

__all__ = [
    "should_sample", "arm", "active", "disarm",
    "grow_tree_fused_profiled", "format_grow_detail", "format_grow_diff",
    "main",
]

_ENV = "XGBTPU_KERNEL_PROF"

#: instrumented-driver name stamped into every record — a reader can
#: tell these numbers came from the unrolled host-driven mirror, not
#: from inside the production fused program
DRIVER = "instrumented-unrolled"


# ---------------------------------------------------------------------------
# sampling grammar: every=N | rounds=a,b,c
# ---------------------------------------------------------------------------


def _parse(spec: str) -> Tuple[str, Any]:
    kind, sep, val = spec.partition("=")
    if not sep:
        raise ValueError(spec)
    kind = kind.strip()
    if kind == "every":
        n = int(val)
        if n < 1:
            raise ValueError(spec)
        return ("every", n)
    if kind == "rounds":
        rounds = frozenset(int(x) for x in val.split(",") if x.strip())
        if not rounds or min(rounds) < 0:
            raise ValueError(spec)
        return ("rounds", rounds)
    raise ValueError(spec)


# plan memo, lock-guarded: keyed on the RAW env value so a monkeypatched
# spec re-parses and the steady state is one dict hit per round
_PLAN_LOCK = threading.Lock()
_PLAN_MEMO: Dict[str, Optional[Tuple[str, Any]]] = {}


def _plan() -> Optional[Tuple[str, Any]]:
    spec = os.environ.get(_ENV)
    if not spec:
        return None
    with _PLAN_LOCK:
        if spec in _PLAN_MEMO:
            return _PLAN_MEMO[spec]
    try:
        plan: Optional[Tuple[str, Any]] = _parse(spec)
    except (ValueError, TypeError):
        plan = None
        from ..utils import console_logger

        console_logger.warning(
            f"{_ENV}={spec!r} is malformed (grammar: every=N or "
            f"rounds=a,b,c — docs/observability.md); profiler stays off")
    with _PLAN_LOCK:
        if len(_PLAN_MEMO) > 64:
            _PLAN_MEMO.clear()
        _PLAN_MEMO[spec] = plan
    return plan


def should_sample(round_idx: int) -> bool:
    """Whether round ``round_idx`` is a sampled (profiled) round. With
    the env unset this is one ``os.environ`` read — the whole cost an
    unprofiled run pays per round (pinned ≤2% of a round by
    tests/test_kernelprof.py)."""
    plan = _plan()
    if plan is None:
        return False
    kind, val = plan
    if kind == "every":
        return round_idx % val == 0
    return round_idx in val


# ---------------------------------------------------------------------------
# the per-round profile (armed on the training thread)
# ---------------------------------------------------------------------------


class _Profile:
    """Accumulator for ONE sampled round (all trees of the round)."""

    __slots__ = ("round_idx", "buckets", "host_syncs", "trees", "depth",
                 "route", "sibling_sub", "hist_acc", "quant_scales",
                 "_last_done_ns")

    def __init__(self, round_idx: int) -> None:
        self.round_idx = int(round_idx)
        # (op, depth) -> aggregated bucket; depth -1 = pre-level prep
        self.buckets: Dict[Tuple[str, int], Dict[str, Any]] = {}
        self.host_syncs = 0
        self.trees = 0
        self.depth = -1
        # production route the mirror replayed ("tree_grow" = the round
        # would run as ONE native dispatch; "level" = per-level program)
        self.route = "level"
        self.sibling_sub = False
        # resolved hist_acc impl on the tree_grow route ("quant" /
        # "float"); quant_scales carries the round's quantiser grid
        # exponents {"g_exp": Eg, "h_exp": Eh} (dequantize = * 2^-E)
        self.hist_acc = "float"
        self.quant_scales: Optional[Dict[str, int]] = None
        self._last_done_ns = 0

    def record(self, op: str, depth: int, impl: str,
               host_ns: int, inflight_ns: int, gap_ns: int) -> None:
        b = self.buckets.get((op, depth))
        if b is None:
            b = self.buckets[(op, depth)] = {
                "op": op, "depth": depth, "impl": impl, "count": 0,
                "wall_s": 0.0, "host_s": 0.0, "inflight_s": 0.0,
                "gap_s": 0.0}
        b["count"] += 1
        b["impl"] = impl
        b["wall_s"] += (host_ns + inflight_ns) / 1e9
        b["host_s"] += host_ns / 1e9
        b["inflight_s"] += inflight_ns / 1e9
        b["gap_s"] += gap_ns / 1e9
        self.host_syncs += 1

    def to_record(self) -> Dict[str, Any]:
        ops = [dict(b,
                    wall_s=round(b["wall_s"], 6),
                    host_s=round(b["host_s"], 6),
                    inflight_s=round(b["inflight_s"], 6),
                    gap_s=round(b["gap_s"], 6))
               for _, b in sorted(self.buckets.items(),
                                  key=lambda kv: (kv[0][1], kv[0][0]))]
        return {
            "round": self.round_idx,
            "driver": DRIVER,
            "route": self.route,
            "sibling_sub": self.sibling_sub,
            "hist_acc": self.hist_acc,
            "quant_scales": self.quant_scales,
            "trees": self.trees,
            "host_syncs": self.host_syncs,
            "sum_s": round(sum(b["wall_s"] for b in ops), 6),
            "gap_s": round(sum(b["gap_s"] for b in ops), 6),
            "ops": ops,
        }


_TLS = threading.local()


def arm(round_idx: int) -> _Profile:
    """Open a profile for the sampled round on THIS thread; the in-core
    grower (``gbtree._boost_fused``) routes to the instrumented driver
    while one is armed."""
    prof = _Profile(round_idx)
    _TLS.profile = prof
    return prof


def active() -> bool:
    return getattr(_TLS, "profile", None) is not None


def disarm() -> Optional[Dict[str, Any]]:
    """Close the armed profile and return its ``grow_detail`` record —
    or ``None`` when nothing was profiled (not armed, or the round ran a
    path the instrumented driver does not cover: paged / mesh / scan)."""
    prof = getattr(_TLS, "profile", None)
    _TLS.profile = None
    if prof is None or not prof.buckets:
        return None
    return prof.to_record()


# ---------------------------------------------------------------------------
# the bracket hook (installed at the dispatch.invoke seam)
# ---------------------------------------------------------------------------


def _hook(prof: _Profile) -> Callable[[str, Callable, tuple, dict], Any]:
    import jax

    from .. import dispatch
    from . import trace as _trace
    from .metrics import REGISTRY

    counter = REGISTRY.counter(
        "host_syncs_total",
        "Deliberate host round-trips (completion syncs) by site — "
        "nonzero only on kernel-profiled rounds")

    def run(op: str, fn: Callable, args: tuple, kwargs: dict) -> Any:
        t0 = time.perf_counter_ns()
        gap_ns = (t0 - prof._last_done_ns) if prof._last_done_ns else 0
        out = fn(*args, **kwargs)
        t1 = time.perf_counter_ns()  # dispatch returned to the host
        jax.block_until_ready(out)  # the deliberate sync the seam owns
        t2 = time.perf_counter_ns()
        prof._last_done_ns = t2
        counter.labels(site=op).inc()
        impl = dispatch.last_decisions().get(op, "xla")
        prof.record(op, prof.depth, impl, t1 - t0, t2 - t1, gap_ns)
        _trace.emit(f"grow/{op}", t0, t2, cat="grow",
                    depth=prof.depth, impl=impl)
        return out

    return run


# ---------------------------------------------------------------------------
# the instrumented driver (mirror of grow_tree_fused's unrolled loop)
# ---------------------------------------------------------------------------

# lock-guarded lazy init of the jitted prologue (heavy imports deferred
# until the first sampled round)
_PREP_LOCK = threading.Lock()
_PREP_JIT: Optional[Callable] = None


def _prep_fn() -> Callable:
    global _PREP_JIT
    with _PREP_LOCK:
        if _PREP_JIT is None:
            import jax
            import jax.numpy as jnp

            from ..analysis.retrace import guard_jit
            from ..tree.grow import _sample_features_exact, apply_row_sampling
            from ..tree.grow_fused import _init_state

            def _prep(grad, hess, key, feature_weights, cfg, F, B):
                # op-for-op mirror of _grow_tree_fused_impl's prologue
                # (one program, so the f32 reduction order of the root
                # totals matches the fused program's)
                k_sub, k_ctree, k_level = jax.random.split(key, 3)
                grad, hess = apply_row_sampling(cfg, k_sub, grad, hess)
                gh = jnp.stack([grad, hess], axis=-1)
                if cfg.colsample_bytree < 1.0:
                    tree_mask = _sample_features_exact(
                        k_ctree, F, cfg.colsample_bytree, feature_weights)
                else:
                    tree_mask = jnp.ones((F,), bool)
                G0 = grad.sum()
                H0 = hess.sum()
                st = _init_state(cfg, F, G0, H0, B)
                return gh, tree_mask, k_level, st

            _PREP_JIT = guard_jit(_prep, name="kernelprof_prep",
                                  static_argnames=("cfg", "F", "B"))
        return _PREP_JIT


#: fixed-point quantiser width — MUST match kQBits in native/tree_build.cpp
_KQBITS = 18


def _quant_scales(gh) -> Dict[str, int]:
    """The sampled round's quantiser grid exponents, mirroring
    tree_build.cpp's ``compute_qscale``: per-lane max of finite |x|,
    ``E = kQBits − frexp-exponent`` (quantize = ``llrint(x * 2^E)``,
    dequantize = ``* 2^−E``). Recorded in the grow_detail record so a
    reader can see the grid the integer engine ran on."""
    import numpy as np

    a = np.abs(np.asarray(gh, dtype=np.float64))
    a = np.where(np.isfinite(a), a, 0.0)
    out: Dict[str, int] = {}
    for idx, name in ((0, "g_exp"), (1, "h_exp")):
        m = float(a[:, idx].max()) if a.size else 0.0
        out[name] = int(_KQBITS - np.frexp(m)[1]) if m > 0.0 else 0
    return out


def grow_tree_fused_profiled(bins, grad, hess, cut_values, key, eta, gamma,
                             cfg, feature_weights=None, onehot=None):
    """Instrumented mirror of ``grow_tree_fused`` for a sampled round:
    the same unrolled level loop, driven from the host so every kernel
    dispatch can be bracketed at the ``dispatch.invoke`` seam. Falls back
    to the production program when no profile is armed or under a mesh
    (the mirror is single-process by design). Bit-identity with the
    production drivers rests on reusing their exact level machinery —
    see the module docstring."""
    from ..tree import grow_fused as _gf

    prof = getattr(_TLS, "profile", None)
    if prof is None or cfg.axis_name is not None:
        return _gf.grow_tree_fused(bins, grad, hess, cut_values, key,
                                   eta, gamma, cfg, feature_weights, onehot)

    import jax
    import jax.numpy as jnp

    from .. import dispatch
    from ..tree import hist_kernel as _hk
    from . import trace as _trace

    pallas = _gf._pallas_flag(cfg)
    max_depth = cfg.max_depth
    # Which route would the PRODUCTION program take? Resolved with the
    # original bins dtype (the pallas path widens to i32 below). When
    # the answer is the whole-tree kernel, the mirror replays per-level
    # with the sibling-subtraction FFI entry at d >= 1 (bit-identical by
    # shared C++ core loops — see module docstring).
    route = ("tree_grow"
             if _gf._use_tree_grow(cfg, bool(pallas), max_depth,
                                   str(bins.dtype))
             else "level")
    sub_on = False
    quant_on = False
    if route == "tree_grow":
        plat = jax.default_backend()
        sub_on = dispatch.resolve(
            "sibling_sub", dispatch.Ctx(platform=plat)).impl == "on"
        quant_on = dispatch.resolve(
            "hist_acc", dispatch.Ctx(platform=plat)).impl == "quant"
    prof.route = route
    prof.sibling_sub = sub_on
    prof.hist_acc = "quant" if quant_on else "float"
    if pallas:
        bins = bins.astype(jnp.int32)
    n, F = bins.shape
    B = cut_values.shape[1]
    prof.trees += 1
    # start the gap clock at mirror entry so the setup before the first
    # bracket (route resolution, span entry) lands in prep's gap column
    # instead of vanishing from the attribution
    prof._last_done_ns = time.perf_counter_ns()
    prev = dispatch.set_invoke_hook(_hook(prof))
    try:
        with _trace.span("grow_tree", fused=True, instrumented=True,
                         depth=max_depth, features=int(F)):
            prof.depth = -1
            gh, tree_mask, k_level, st = dispatch.invoke(
                "prep", _prep_fn(), grad, hess, key, feature_weights,
                cfg=cfg, F=int(F), B=int(B))
            pos = jnp.zeros((n, 1), jnp.int32)
            prev_hist = None
            # the quant route carries the previous level's int64
            # histogram as packed int32 word pairs — empty at the root
            prev_q = jnp.zeros((F, 0, B, 2), jnp.int32)
            if quant_on:
                prof.quant_scales = _quant_scales(gh)
            for d in range(max_depth):
                prof.depth = d
                K = 1 << d
                if route == "tree_grow" and quant_on:
                    # quant engine for EVERY level (root included): the
                    # sampled round's histograms must match the fused
                    # kernel's integer accumulation bit-for-bit, and the
                    # int64 carry never passes through f32
                    from ..tree import tree_kernel as _tk

                    pos, prev_q, histC = dispatch.invoke(
                        "level_hist", _tk.fused_level_quant_native, bins,
                        pos, gh, st.ptab, prev_q, K=K, Kp=K >> 1, B=B,
                        d=d, sibling_sub=sub_on)
                elif route == "tree_grow" and sub_on and d >= 1:
                    from ..tree import tree_kernel as _tk

                    pos, histC = dispatch.invoke(
                        "level_hist", _tk.fused_level_sub_native, bins,
                        pos, gh, st.ptab, prev_hist, K=K, Kp=K >> 1, B=B,
                        d=d)
                else:
                    pos, histC = dispatch.invoke(
                        "level_hist", _hk.fused_level, bins, pos, gh,
                        st.ptab, K=K, Kp=K >> 1, B=B, d=d, pallas=pallas,
                        onehot=onehot, axis_name=None)
                prev_hist = histC
                st = dispatch.invoke(
                    "level_update", _gf._level_update_jit, st, histC,
                    cut_values, tree_mask, k_level, cfg=cfg, d=d)
            prof.depth = max_depth
            if max_depth > 0:
                pos = dispatch.invoke(
                    "level_partition", _hk.partition_apply, bins, pos,
                    st.ptab, Kp=1 << (max_depth - 1), B=B, d=max_depth)
            keep, leaf_value = dispatch.invoke(
                "finalize", _gf._finalize_jit, st, jnp.float32(eta),
                jnp.float32(gamma), cfg=cfg)
            pad_nodes = max(128, 1 << (cfg.max_nodes - 1).bit_length())
            delta = dispatch.invoke(
                "leaf_delta", _hk.leaf_delta, pos, leaf_value, pad_nodes,
                pallas=pallas)
    finally:
        dispatch.set_invoke_hook(prev)

    return _gf.GrownTree(
        keep=keep, feature=st.feature, split_bin=st.split_bin,
        split_cond=st.split_cond, default_left=st.default_left,
        node_g=st.node_g, node_h=st.node_h, node_weight=st.node_w,
        loss_chg=st.loss_chg, leaf_value=leaf_value, delta=delta,
        cat_set=st.cat_set,
    )


# ---------------------------------------------------------------------------
# grow-report: render grow_detail records from a flight sink
# ---------------------------------------------------------------------------


def format_grow_detail(rec: Dict[str, Any],
                       grow_s: Optional[float] = None) -> str:
    """Render one ``grow_detail`` record as the per-depth × per-op table.
    ``grow_s`` (the round's ``stages.grow``) adds the coverage line —
    the acceptance contract is substages summing to within 10% of it."""
    route = rec.get("route")
    route_note = ""
    if route:
        route_note = f", route={route}"
        if route == "tree_grow":
            # per-level replay of a one-dispatch production round; the
            # resolved hist_acc impl picks the replay flavour, and the
            # quant flavour shows the round's quantiser grid
            if rec.get("hist_acc") == "quant":
                route_note += " (quant replay"
                qs = rec.get("quant_scales") or {}
                if qs:
                    route_note += (f", scales g=2^-{qs.get('g_exp')}"
                                   f" h=2^-{qs.get('h_exp')}")
                route_note += ")"
            elif rec.get("sibling_sub"):
                route_note += " (sibling-sub replay)"
            else:
                route_note += " (per-level replay)"
    lines = [
        f"round {rec.get('round')}: grow detail "
        f"({rec.get('driver')}, {rec.get('trees')} tree(s){route_note})",
        f"  {'depth':>5} {'op':<16} {'impl':<8} {'count':>5} "
        f"{'wall':>10} {'host':>10} {'inflight':>10} {'gap':>9}",
    ]

    def ms(v: float) -> str:
        return f"{v * 1e3:.3f}ms"

    for b in rec.get("ops", ()):
        depth = b.get("depth", -1)
        lines.append(
            f"  {('prep' if depth < 0 else depth)!s:>5} {b['op']:<16} "
            f"{b.get('impl', '?'):<8} {b.get('count', 0):>5} "
            f"{ms(b['wall_s']):>10} {ms(b.get('host_s', 0.0)):>10} "
            f"{ms(b.get('inflight_s', 0.0)):>10} "
            f"{ms(b.get('gap_s', 0.0)):>9}")
    total = f"  substages {ms(rec.get('sum_s', 0.0))}, " \
            f"dispatch gap {ms(rec.get('gap_s', 0.0))}, " \
            f"host syncs {rec.get('host_syncs', 0)}"
    if grow_s:
        total += (f"; stages.grow {ms(grow_s)} "
                  f"(substages = {100.0 * rec.get('sum_s', 0.0) / grow_s:.1f}%)")
    lines.append(total)
    return "\n".join(lines)


def _aggregate_ops(recs: List[Dict[str, Any]]) -> Tuple[
        Dict[Tuple[int, str], Dict[str, Any]], List[int]]:
    """Sum per-(depth, op) wall seconds across sampled round records —
    the input to the ``--diff`` table. Returns ``(buckets, rounds)``."""
    agg: Dict[Tuple[int, str], Dict[str, Any]] = {}
    rounds: List[int] = []
    for r in recs:
        gd = r.get("grow_detail", {})
        rounds.append(gd.get("round", r.get("round", -1)))
        for b in gd.get("ops", ()):
            key = (b.get("depth", -1), b.get("op", "?"))
            cur = agg.setdefault(key, {"wall_s": 0.0, "count": 0,
                                       "impl": b.get("impl", "?")})
            cur["wall_s"] += b.get("wall_s", 0.0)
            cur["count"] += b.get("count", 0)
            cur["impl"] = b.get("impl", cur["impl"])
    return agg, rounds


def format_grow_diff(agg_a: Dict[Tuple[int, str], Dict[str, Any]],
                     rounds_a: List[int], label_a: str,
                     agg_b: Dict[Tuple[int, str], Dict[str, Any]],
                     rounds_b: List[int], label_b: str) -> str:
    """Render the A-vs-B per-depth × per-op table with a delta column
    (B − A; negative = B faster). Rows missing on one side show '-' —
    e.g. a depth the other run never grew, or an op only one route
    dispatches."""
    lines = [
        f"grow detail diff: A = {label_a} (rounds {sorted(set(rounds_a))}) "
        f"vs B = {label_b} (rounds {sorted(set(rounds_b))})",
        f"  {'depth':>5} {'op':<16} {'impl':<16} {'A wall':>10} "
        f"{'B wall':>10} {'delta':>10}",
    ]

    def ms(v: Optional[float]) -> str:
        return "-" if v is None else f"{v * 1e3:.3f}ms"

    tot_a = tot_b = 0.0
    changed = 0
    for depth, op in sorted(set(agg_a) | set(agg_b)):
        a = agg_a.get((depth, op))
        b = agg_b.get((depth, op))
        wa = a["wall_s"] if a else None
        wb = b["wall_s"] if b else None
        tot_a += wa or 0.0
        tot_b += wb or 0.0
        ia = a["impl"] if a else "-"
        ib = b["impl"] if b else "-"
        impl = ia if ia == ib else f"{ia}->{ib}"
        delta = "-" if (wa is None or wb is None) else ms(wb - wa)
        # rows whose resolved impl changed between the runs get a
        # visible marker — a reader scanning a long table should not
        # have to eyeball the impl column to spot a route flip
        mark = ""
        if ia != ib and a is not None and b is not None:
            mark = " *"
            changed += 1
        lines.append(
            f"  {('prep' if depth < 0 else depth)!s:>5} {op:<16} "
            f"{impl:<16} {ms(wa):>10} {ms(wb):>10} {delta:>10}{mark}")
    lines.append(f"  substages A {ms(tot_a)}, B {ms(tot_b)}, "
                 f"delta {ms(tot_b - tot_a)}")
    if changed:
        lines.append(f"  * = resolved impl changed between runs "
                     f"({changed} row(s))")
    return "\n".join(lines)


def _iter_flight_lines(path: str) -> List[Dict[str, Any]]:
    """Parse a flight.jsonl tolerantly: torn/partial lines (SIGKILL
    mid-write) are skipped, not fatal — the PR-6 precedent."""
    out: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _find_flight_files(arg: str) -> List[str]:
    if os.path.isdir(arg):
        import glob as _glob

        hits = sorted(
            _glob.glob(os.path.join(arg, "obs", "rank*", "flight.jsonl"))
            or _glob.glob(os.path.join(arg, "flight.jsonl")))
        return hits
    return [arg]


def main(argv: List[str]) -> int:
    usage = ("usage: python -m xgboost_tpu grow-report "
             "<flight.jsonl|run-dir> [--round N] | "
             "grow-report --diff <A> <B> [--round N]")
    if not argv or argv[0] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        return 0 if argv else 1
    want_round: Optional[int] = None
    if "--round" in argv:
        i = argv.index("--round")
        try:
            want_round = int(argv[i + 1])
        except (IndexError, ValueError):
            print(usage, file=sys.stderr)
            return 1
        argv = argv[:i] + argv[i + 2:]
    if "--diff" in argv:
        rest = [a for a in argv if a != "--diff"]
        if len(rest) != 2:
            print(usage, file=sys.stderr)
            return 1
        sides = []
        for arg in rest:
            recs: List[Dict[str, Any]] = []
            for path in _find_flight_files(arg):
                try:
                    recs.extend(
                        r for r in _iter_flight_lines(path)
                        if r.get("t") == "round" and "grow_detail" in r)
                except OSError as e:
                    print(f"{path}: {e}", file=sys.stderr)
                    return 1
            if want_round is not None:
                recs = [r for r in recs if r.get("round") == want_round]
            if not recs:
                print(f"{arg}: no sampled grow_detail records found "
                      f"(profiler arms via {_ENV}=every=N|rounds=a,b,c)",
                      file=sys.stderr)
                return 1
            sides.append((arg, recs))
        (la, ra), (lb, rb) = sides
        agg_a, rounds_a = _aggregate_ops(ra)
        agg_b, rounds_b = _aggregate_ops(rb)
        print(format_grow_diff(agg_a, rounds_a, la, agg_b, rounds_b, lb))
        return 0
    paths = _find_flight_files(argv[0])
    if not paths:
        print(f"{argv[0]}: no flight.jsonl found", file=sys.stderr)
        return 1
    rc = 0
    shown = 0
    for path in paths:
        try:
            recs = _iter_flight_lines(path)
        except OSError as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 1
            continue
        sampled = [r for r in recs
                   if r.get("t") == "round" and "grow_detail" in r]
        if want_round is not None:
            sampled = [r for r in sampled if r.get("round") == want_round]
        for r in sampled:
            print(format_grow_detail(
                r["grow_detail"], r.get("stages", {}).get("grow")))
            print()
            shown += 1
    if not shown:
        print("no sampled grow_detail records found "
              f"(profiler arms via {_ENV}=every=N|rounds=a,b,c)",
              file=sys.stderr)
        return 1
    return rc
