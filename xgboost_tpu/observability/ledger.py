"""Banked perf ledger: every ``BENCH_r*.json`` as ONE trajectory.

Each growth round that touches the data plane banks its bench line as
``BENCH_rNN.json`` at the repo root. Historically those were hand-copied
subprocess captures (``{"n", "cmd", "rc", "tail", "parsed"}`` with the
predict line buried in ``tail`` text, and r01 banked a failed run as
``parsed: null``); since PR 16, ``python bench.py --bank rNN`` writes
the canonical schema (``{"n", "schema", "cmd", "rc", "lines": [...]}``,
first line = the train record with stages + dispatch table, optional
second line = the predict record). This module reads BOTH formats into
one trajectory keyed by **(metric family, workload shape)** so
``python -m xgboost_tpu perf-report`` can render the whole perf history
— rounds/s, stage splits, vs_baseline, delta vs the banked best — and
tolerate gaps (rounds that banked nothing, e.g. r06–r14) without
guessing.

Metric-name grammar (produced by bench.py)::

    train_time_{rows//1000}kx{cols}_{iters}r_depth{d}[_bin{b}][_markers]
    predict_inplace_100kx50_10r

with markers ``_cpu_fallback`` / ``_extrapolated_from_{n}r`` /
``_quality_failed`` / ``_parity_failed`` parsed OFF the shape key and
kept as annotations — a degraded run lands on the same trajectory row
it degraded from, flagged, instead of forking a phantom workload.
"""

from __future__ import annotations

import glob
import json
import math
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "SCHEMA", "parse_metric", "validate_record", "load_bank_file",
    "load_ledger", "trajectory", "write_bank", "format_report", "main",
]

SCHEMA = "bench-bank-v1"

_BANK_GLOB = "BENCH_r[0-9]*.json"
_BANK_RE = re.compile(r"BENCH_r(\d+)\.json$")

#: boolean degradation markers bench.py appends to the metric name
_MARKERS = ("cpu_fallback", "quality_failed", "parity_failed")

_EXTRAP_RE = re.compile(r"_extrapolated_from_(\d+)r")
_SHAPE_RE = re.compile(
    r"^(?P<family>[a-z][a-z_]*?)_(?P<kr>\d+)kx(?P<cols>\d+)(?P<rest>(?:_.*)?)$")


# ---------------------------------------------------------------------------
# metric-name grammar
# ---------------------------------------------------------------------------


def parse_metric(name: str) -> Optional[Dict[str, Any]]:
    """Parse a bench metric name; ``None`` when it doesn't follow the
    grammar (e.g. ``train_time_failed``)."""
    if not isinstance(name, str):
        return None
    markers: List[str] = []
    stripped = name
    for mk in _MARKERS:
        if f"_{mk}" in stripped:
            markers.append(mk)
            stripped = stripped.replace(f"_{mk}", "")
    m = _EXTRAP_RE.search(stripped)
    measured_rounds = None
    if m:
        measured_rounds = int(m.group(1))
        markers.append(f"extrapolated_from_{measured_rounds}r")
        stripped = stripped[:m.start()] + stripped[m.end():]
    m = _SHAPE_RE.match(stripped)
    if not m:
        return None
    rest = m.group("rest")
    rounds = None
    rm = re.search(r"_(\d+)r(?:_|$)", rest)
    if rm:
        rounds = int(rm.group(1))
    dm = re.search(r"_depth(\d+)", rest)
    bm = re.search(r"_bin(\d+)", rest)
    return {
        "metric": name,
        "family": m.group("family"),
        "shape": f"{m.group('kr')}kx{m.group('cols')}",
        "rows": int(m.group("kr")) * 1000,
        "cols": int(m.group("cols")),
        "rounds": rounds,
        "depth": int(dm.group(1)) if dm else None,
        "bin": int(bm.group(1)) if bm else None,
        "markers": markers,
        "measured_rounds": measured_rounds,
    }


# ---------------------------------------------------------------------------
# record validation (the --bank write path refuses bad records)
# ---------------------------------------------------------------------------


def _num(v: Any) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool) \
        and math.isfinite(v)


def validate_record(rec: Any, require_stages: bool = False) -> List[str]:
    """Schema check for one bench JSON line; returns the (possibly
    empty) list of violations. ``require_stages`` is the contract for
    the PRIMARY train line: stage split + dispatch table must be there,
    or the banked round is useless for attribution."""
    errs: List[str] = []
    if not isinstance(rec, dict):
        return ["record is not an object"]
    metric = rec.get("metric")
    if parse_metric(metric) is None:
        errs.append(f"metric {metric!r} does not follow the bench grammar")
    if not _num(rec.get("value")) or rec.get("value", -1) < 0:
        errs.append(f"value {rec.get('value')!r} is not a finite number >= 0")
    if not isinstance(rec.get("unit"), str) or not rec.get("unit"):
        errs.append(f"unit {rec.get('unit')!r} is not a nonempty string")
    if "vs_baseline" in rec and not _num(rec["vs_baseline"]):
        errs.append(f"vs_baseline {rec['vs_baseline']!r} is not a number")
    if require_stages:
        stages = rec.get("stages")
        if not isinstance(stages, dict) or not stages or not all(
                isinstance(k, str) and _num(v) for k, v in stages.items()):
            errs.append("stages must be a nonempty {stage: seconds} object")
        disp = rec.get("dispatch")
        if not isinstance(disp, dict) or not disp or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in disp.items()):
            errs.append("dispatch must be a nonempty {op: impl} object")
        if "vs_baseline" not in rec:
            errs.append("train line must carry vs_baseline")
    return errs


# ---------------------------------------------------------------------------
# bank IO (old + new formats)
# ---------------------------------------------------------------------------


def load_bank_file(path: str) -> Dict[str, Any]:
    """One banked round -> ``{"n", "rc", "cmd", "records": [...]}``.
    Old-format files recover the predict line from the raw ``tail`` text
    (it was never in ``parsed``); a failed bank (r01: rc=1,
    parsed=null) loads as zero records rather than raising."""
    with open(path) as f:
        doc = json.load(f)
    n = doc.get("n")
    if not isinstance(n, int):
        m = _BANK_RE.search(os.path.basename(path))
        n = int(m.group(1)) if m else -1
    records: List[Dict[str, Any]] = []

    def add(rec: Any) -> None:
        if isinstance(rec, dict) and isinstance(rec.get("metric"), str) \
                and not any(r.get("metric") == rec["metric"]
                            for r in records):
            records.append(rec)

    if isinstance(doc.get("lines"), list):  # canonical (bench --bank)
        for rec in doc["lines"]:
            add(rec)
    else:  # legacy hand-copied capture
        add(doc.get("parsed"))
        for line in str(doc.get("tail") or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    add(json.loads(line))
                except ValueError:
                    continue
    return {"n": n, "rc": doc.get("rc"), "cmd": doc.get("cmd", ""),
            "path": path, "records": records}


def load_ledger(root: str = ".") -> List[Dict[str, Any]]:
    """Every readable ``BENCH_r*.json`` under ``root``, sorted by round
    number. Unreadable files are reported on stderr and skipped — one
    torn bank must not hide the rest of the trajectory."""
    out: List[Dict[str, Any]] = []
    for path in sorted(glob.glob(os.path.join(root, _BANK_GLOB))):
        try:
            out.append(load_bank_file(path))
        except (OSError, ValueError) as e:
            print(f"{path}: unreadable bank: {e}", file=sys.stderr)
    out.sort(key=lambda d: d["n"])
    return out


def write_bank(root: str, n: int, cmd: str, rc: int,
               records: List[Dict[str, Any]]) -> str:
    """Write the canonical ``BENCH_rNN.json`` (atomic replace). The
    primary (train) record is schema-validated WITH stages + dispatch;
    any further lines (predict) get the base check. Raises ValueError
    with every violation listed — a malformed bank is worse than none."""
    if not records:
        raise ValueError("nothing to bank: no bench records")
    errs = [f"line 0: {e}"
            for e in validate_record(records[0], require_stages=True)]
    for i, rec in enumerate(records[1:], start=1):
        errs += [f"line {i}: {e}" for e in validate_record(rec)]
    if errs:
        raise ValueError("; ".join(errs))
    doc = {"n": int(n), "schema": SCHEMA, "cmd": cmd, "rc": int(rc),
           "lines": records, "parsed": records[0]}
    path = os.path.join(root, f"BENCH_r{int(n):02d}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# the trajectory
# ---------------------------------------------------------------------------


def trajectory(banks: List[Dict[str, Any]]) -> Dict[Tuple[str, str],
                                                    List[Dict[str, Any]]]:
    """(family, shape) -> points sorted by round number. Each point
    carries the parsed metric facts plus rounds/s when derivable
    (train-family seconds with a round count)."""
    groups: Dict[Tuple[str, str], List[Dict[str, Any]]] = {}
    for bank in banks:
        for rec in bank["records"]:
            facts = parse_metric(rec.get("metric"))
            if facts is None or not _num(rec.get("value")):
                continue
            pt = dict(facts)
            pt.update({
                "n": bank["n"],
                "value": float(rec["value"]),
                "unit": rec.get("unit", ""),
                "vs_baseline": rec.get("vs_baseline"),
                "stages": rec.get("stages"),
                "dispatch": rec.get("dispatch"),
            })
            if facts["family"] == "train_time" and facts["rounds"] \
                    and rec.get("unit") == "s" and rec["value"] > 0:
                pt["rounds_per_s"] = round(facts["rounds"] / rec["value"], 3)
            groups.setdefault((facts["family"], facts["shape"]),
                              []).append(pt)
    for pts in groups.values():
        pts.sort(key=lambda p: p["n"])
    return groups


def _gaps(banked: List[int]) -> str:
    """Human-readable missing-round ranges between the first and last
    banked round (the r06–r14 gap prints instead of surprising)."""
    if len(banked) < 2:
        return ""
    have = set(banked)
    missing: List[str] = []
    lo = None
    for n in range(min(banked), max(banked) + 1):
        if n in have:
            if lo is not None:
                hi = n - 1
                missing.append(f"r{lo:02d}" if lo == hi
                               else f"r{lo:02d}-r{hi:02d}")
                lo = None
        elif lo is None:
            lo = n
    return ", ".join(missing)


def _best(pts: List[Dict[str, Any]]) -> Optional[Dict[str, Any]]:
    clean = [p for p in pts
             if "rounds_per_s" in p
             and not any(mk in p["markers"]
                         for mk in ("quality_failed", "parity_failed"))]
    return max(clean, key=lambda p: p["rounds_per_s"]) if clean else None


def format_report(banks: List[Dict[str, Any]],
                  published: Optional[Dict[str, Any]] = None) -> str:
    banked = [b["n"] for b in banks]
    failed = [b["n"] for b in banks if not b["records"]]
    lines = [
        f"== perf ledger: {len(banks)} banked rounds "
        f"({', '.join(f'r{n:02d}' for n in banked)}) =="
    ]
    gaps = _gaps(banked)
    if gaps:
        lines.append(f"   unbanked rounds (no BENCH file): {gaps}")
    if failed:
        lines.append("   failed banks (rc!=0, no parsed record): "
                     + ", ".join(f"r{n:02d}" for n in failed))
    for (family, shape), pts in sorted(trajectory(banks).items()):
        lines.append("")
        lines.append(f"{family} @ {shape}:")
        best = _best(pts)
        for p in pts:
            cfg = "_".join(
                s for s in (f"{p['rounds']}r" if p["rounds"] else "",
                            f"depth{p['depth']}" if p["depth"] else "",
                            f"bin{p['bin']}" if p["bin"] else "") if s)
            row = (f"  r{p['n']:02d}  {p['value']:>10.2f}{p['unit']:<7}"
                   f" {cfg:<22}")
            if "rounds_per_s" in p:
                row += f" {p['rounds_per_s']:>8.3f} r/s"
                if best is not None and best["rounds_per_s"] > 0:
                    delta = (p["rounds_per_s"] / best["rounds_per_s"]
                             - 1.0) * 100.0
                    row += ("   best" if p is best
                            else f" {delta:>+6.1f}% vs best r{best['n']:02d}")
            if _num(p.get("vs_baseline")) and p["vs_baseline"] > 0:
                row += f"   vs_baseline {p['vs_baseline']:.3f}x"
            if p["markers"]:
                row += "   [" + ",".join(p["markers"]) + "]"
            lines.append(row)
            stages = p.get("stages")
            if isinstance(stages, dict) and stages:
                split = ", ".join(
                    f"{k} {v:.2f}s" for k, v in sorted(
                        stages.items(), key=lambda kv: -kv[1]))
                lines.append(f"        stages: {split}")
            disp = p.get("dispatch")
            if isinstance(disp, dict) and disp:
                lines.append("        dispatch: " + ",".join(
                    f"{op}={impl}" for op, impl in sorted(disp.items())))
    if published:
        lines.append("")
        lines.append("published reference anchors (BASELINE.json):")
        for key, ref in sorted(published.items()):
            if isinstance(ref, dict):
                desc = ", ".join(f"{k}={v}" for k, v in sorted(ref.items()))
            else:
                desc = str(ref)
            lines.append(f"  {key}: {desc}")
    return "\n".join(lines)


def main(argv: List[str]) -> int:
    usage = "usage: python -m xgboost_tpu perf-report [--root DIR] [--json]"
    root = "."
    as_json = False
    argv = list(argv)
    if "-h" in argv or "--help" in argv:
        print(usage, file=sys.stderr)
        return 0
    if "--json" in argv:
        as_json = True
        argv.remove("--json")
    if "--root" in argv:
        i = argv.index("--root")
        try:
            root = argv[i + 1]
        except IndexError:
            print(usage, file=sys.stderr)
            return 1
        argv = argv[:i] + argv[i + 2:]
    if argv:
        print(usage, file=sys.stderr)
        return 1
    banks = load_ledger(root)
    if not banks:
        print(f"no {_BANK_GLOB} files under {root!r}", file=sys.stderr)
        return 1
    published = None
    try:
        with open(os.path.join(root, "BASELINE.json")) as f:
            published = json.load(f).get("published") or None
    except (OSError, ValueError):
        pass
    if as_json:
        traj = {f"{fam}@{shape}": pts for (fam, shape), pts
                in trajectory(banks).items()}
        print(json.dumps({"banked": [b["n"] for b in banks],
                          "trajectory": traj}, indent=1))
    else:
        print(format_report(banks, published))
    return 0
