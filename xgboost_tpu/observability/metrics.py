"""Metrics registry: named counters / gauges / histograms.

One process-wide registry (``REGISTRY``) holds every telemetry series —
training progress (``rounds_total``, ``round_seconds``), tree shape
(``tree_depth``, ``split_gain``), host-side phase timings
(``hist_build_seconds``, ``monitor_seconds`` via the ``utils.timer.Monitor``
adapter), collective-comms volume (``collective_bytes_total`` — see
``observability.comms``), and the serving fast path's cache health
(``predict_bucket_cache_{hits,misses,evictions}_total`` +
``predict_bucket_cache_entries``, ``predict_forest_snapshot_*``,
``predict_native_rows_total``, ``inplace_predict_rows_total`` — see
``predictor/serving.py`` and docs/serving.md). Two export surfaces:

- ``REGISTRY.exposition()`` — Prometheus text exposition format, ready to
  serve from a ``/metrics`` endpoint or drop into a textfile collector;
- ``REGISTRY.snapshot()`` — a JSON-able dict for BENCH/MULTICHIP result
  files and programmatic assertions.

Family/child creation is lock-guarded; value updates are plain float ops
(a counter bump may race across threads at worst by one sample — the
right trade for instrumentation that sits on training hot paths). Metric
families are created lazily on first use so importing this module costs
nothing.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricFamily", "MetricsRegistry",
    "REGISTRY", "get_registry",
]

# default histogram buckets: exponential seconds ladder, good for host-side
# phase timings from ~100us dispatches to multi-minute fits
DEFAULT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_LabelKey = Tuple[Tuple[str, str], ...]


class Counter:
    """Monotone accumulator."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        self.value += amount


class Gauge:
    """Last-write-wins scalar."""

    __slots__ = ("value",)

    def __init__(self) -> None:
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.value -= amount


class Histogram:
    """Fixed-bucket histogram: cumulative-bucket Prometheus semantics."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS) -> None:
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        self.sum += value
        self.count += 1
        # linear scan: bucket lists are short and observations host-side
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                self.counts[i] += 1
                return
        self.counts[-1] += 1

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0 < q <= 1) from the bucket counts —
        Prometheus ``histogram_quantile`` semantics: linear interpolation
        inside the bucket the target rank falls in, clamped to the
        largest finite bound when the rank lands in the +Inf bucket.
        None when nothing was observed. The estimate's resolution is the
        bucket ladder (choose buckets for the latencies you care about);
        p50/p99 from this are what the serving latency and round-time
        series report (docs/observability.md)."""
        if self.count == 0:
            return None
        target = max(min(float(q), 1.0), 0.0) * self.count
        cum = 0.0
        lo = 0.0
        for ub, c in zip(self.buckets, self.counts):
            if c and cum + c >= target:
                frac = (target - cum) / c
                return lo + (ub - lo) * min(max(frac, 0.0), 1.0)
            cum += c
            lo = ub
        return float(self.buckets[-1])  # +Inf bucket: clamp


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricFamily:
    """A named metric plus its labelled children. The family itself is
    usable directly (the empty-label child): ``fam.inc()``,
    ``fam.observe(x)``; labelled series via ``fam.labels(op="psum")``."""

    def __init__(self, name: str, kind: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None) -> None:
        if kind not in _KINDS:
            raise ValueError(f"unknown metric kind: {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self._buckets = tuple(buckets) if buckets is not None else None
        self._children: Dict[_LabelKey, Any] = {}
        self._lock = threading.Lock()

    def _make(self):
        if self.kind == "histogram":
            return Histogram(self._buckets or DEFAULT_BUCKETS)
        return _KINDS[self.kind]()

    def labels(self, **labelset: Any):
        key: _LabelKey = tuple(sorted(
            (k, str(v)) for k, v in labelset.items()))
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = self._make()
            return child

    # -- empty-label convenience forwarding ---------------------------------
    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    @property
    def value(self) -> float:
        return self.labels().value

    def series(self) -> List[Tuple[Dict[str, str], Any]]:
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._children.items())]


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in labels.items()]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class MetricsRegistry:
    def __init__(self) -> None:
        self._families: Dict[str, MetricFamily] = {}
        self._lock = threading.Lock()

    def _family(self, name: str, kind: str, help: str,
                buckets: Optional[Sequence[float]] = None) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = MetricFamily(
                    name, kind, help, buckets)
            elif fam.kind != kind:
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}, "
                    f"requested {kind}")
            return fam

    def counter(self, name: str, help: str = "") -> MetricFamily:
        return self._family(name, "counter", help)

    def gauge(self, name: str, help: str = "") -> MetricFamily:
        return self._family(name, "gauge", help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None) -> MetricFamily:
        return self._family(name, "histogram", help, buckets)

    def get(self, name: str) -> Optional[MetricFamily]:
        return self._families.get(name)

    def quantile(self, name: str, q: float, **labels: Any
                 ) -> Optional[float]:
        """Estimated q-quantile of a histogram series, or None when the
        family is absent, not a histogram, or the labelled child has no
        observations — the one-call read the serving admission controller
        uses for its p99-based shed estimate (``docs/serving.md``)."""
        fam = self._families.get(name)
        if fam is None or fam.kind != "histogram":
            return None
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with fam._lock:
            child = fam._children.get(key)
        return None if child is None else child.quantile(q)

    def quantiles(self, name: str, qs: Sequence[float] = (0.50, 0.99)
                  ) -> List[Tuple[Dict[str, str], Dict[str, float]]]:
        """Quantile estimates for EVERY series of a histogram family:
        ``[(labels, {"p50": v, "p99": v}), ...]``, skipping series with
        no observations. The one-call read the serving SLO ledger and the
        ``stats`` op use to report per-model stage latencies without
        walking a full ``snapshot()``."""
        fam = self._families.get(name)
        if fam is None or fam.kind != "histogram":
            return []
        out: List[Tuple[Dict[str, str], Dict[str, float]]] = []
        for labels, child in fam.series():
            if not child.count:
                continue
            out.append((labels, {f"p{float(q) * 100:g}":
                                 child.quantile(q) for q in qs}))
        return out

    def reset(self) -> None:
        """Drop every family (tests / between BENCH repetitions)."""
        with self._lock:
            self._families.clear()

    # ------------------------------------------------------------------
    # export surfaces
    # ------------------------------------------------------------------
    def exposition(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines: List[str] = []
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for labels, child in fam.series():
                if fam.kind == "histogram":
                    cum = child.cumulative()
                    for ub, c in zip(child.buckets, cum):
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{_fmt_labels(labels, f'le={json.dumps(_fmt_value(ub))}')}"
                            f" {c}")
                    lines.append(
                        f"{fam.name}_bucket"
                        f"{_fmt_labels(labels, 'le=' + json.dumps('+Inf'))}"
                        f" {cum[-1]}")
                    lines.append(
                        f"{fam.name}_sum{_fmt_labels(labels)}"
                        f" {_fmt_value(child.sum)}")
                    lines.append(
                        f"{fam.name}_count{_fmt_labels(labels)}"
                        f" {child.count}")
                else:
                    lines.append(
                        f"{fam.name}{_fmt_labels(labels)}"
                        f" {_fmt_value(child.value)}")
        return "\n".join(lines) + ("\n" if lines else "")

    def snapshot(self) -> Dict[str, Any]:
        """JSON-able dict of every series' current state."""
        out: Dict[str, Any] = {}
        with self._lock:
            fams = sorted(self._families.values(), key=lambda f: f.name)
        for fam in fams:
            series = []
            for labels, child in fam.series():
                if fam.kind == "histogram":
                    series.append({
                        "labels": labels,
                        "sum": child.sum,
                        "count": child.count,
                        "p50": child.quantile(0.50),
                        "p99": child.quantile(0.99),
                        "buckets": {
                            _fmt_value(ub): c
                            for ub, c in zip(child.buckets,
                                             child.cumulative())
                        },
                    })
                else:
                    series.append({"labels": labels, "value": child.value})
            out[fam.name] = {
                "type": fam.kind, "help": fam.help, "series": series,
            }
        return out


REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY
