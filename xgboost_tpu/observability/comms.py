"""Collective-communication accounting: ops and bytes per reduction.

The reference's rabit layer had a single choke point for every collective;
here comms happen at two very different altitudes, and both report into the
same two counter families:

- **Host-side collectives** (``collective.allreduce``/``broadcast``, the
  ``multihost_utils.process_allgather`` helpers in ``parallel.mesh``):
  instrumented inline — exact payload byte counts, one record per call.
- **Device-side collectives** (the ``psum``/``all_gather`` ops *inside*
  compiled programs: histogram reductions in ``tree.grow_fused``, summary
  gathers in ``parallel.sketch``): an XLA program cannot call back into
  Python per op, so the *dispatch site* records the analytic per-execution
  volume (shapes are static, so the estimate is exact up to compiler
  rewrites). See ``record_grow_collectives`` / callers in
  ``parallel.grow`` and ``parallel.sketch``.

Metric families (in ``observability.metrics.REGISTRY``):

- ``collective_ops_total{op=...}``   — logical collective operations
- ``collective_bytes_total{op=...}`` — payload bytes reduced / gathered

``snapshot()`` returns ``{op: {"ops": n, "bytes": b}}`` for BENCH /
MULTICHIP result files.
"""

from __future__ import annotations

from typing import Dict

from .metrics import REGISTRY

__all__ = ["record", "snapshot", "grow_psum_bytes", "record_grow_collectives"]

_OPS_HELP = "Logical collective operations by kind"
_BYTES_HELP = "Payload bytes moved through collectives by kind"


def record(op: str, nbytes: int, n_ops: int = 1) -> None:
    """Account ``n_ops`` collective operations moving ``nbytes`` total
    payload bytes under the kind ``op`` (e.g. ``allreduce``, ``broadcast``,
    ``psum_hist``, ``all_gather_sketch``, ``process_allgather``). Doubles
    as the ``collective`` chaos-injection site: every accounted collective
    passes this choke point, so ``XGBTPU_CHAOS="collective:..."`` scripts
    a failing reduction without hardware (rabit-mock analog). Lazy import:
    the resilience layer depends on this package, not vice versa."""
    from ..resilience import chaos

    chaos.hit("collective")
    REGISTRY.counter("collective_ops_total", _OPS_HELP).labels(
        op=op).inc(n_ops)
    REGISTRY.counter("collective_bytes_total", _BYTES_HELP).labels(
        op=op).inc(nbytes)


def snapshot() -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for name, key in (("collective_ops_total", "ops"),
                      ("collective_bytes_total", "bytes")):
        fam = REGISTRY.get(name)
        if fam is None:
            continue
        for labels, child in fam.series():
            op = labels.get("op", "")
            out.setdefault(op, {"ops": 0.0, "bytes": 0.0})[key] = child.value
    return out


def grow_psum_bytes(max_depth: int, n_features: int, max_bin: int) -> int:
    """Per-tree histogram-AllReduce volume of the depthwise growers: one
    ``[F, 2K, B]`` float32 psum per level (K doubling each level) plus the
    8-byte root-total psum — the two collective sites of
    ``grow_tree_fused`` (the reference's hist/histogram.h:201 +
    InitRoot)."""
    total = 8  # root (G0, H0)
    for d in range(max_depth):
        total += n_features * (2 << d) * max_bin * 4
    return total


def record_grow_collectives(max_depth: int, n_features: int, max_bin: int,
                            n_trees: int = 1) -> None:
    """Account the device-side psums of ``n_trees`` distributed tree
    builds. Called at the dispatch site (host), since the psums themselves
    execute inside the compiled program."""
    record("psum_hist",
           grow_psum_bytes(max_depth, n_features, max_bin) * n_trees,
           n_ops=(max_depth + 1) * n_trees)
