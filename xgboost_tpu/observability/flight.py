"""Per-round flight recorder: the fleet's always-on black box.

PR 1's telemetry answers "where did the milliseconds go" only when a
trace destination is configured; since the system became an elastic
multi-host fleet (PR 5), its most interesting events — worker death,
quiesce, resize, replay, a watchdog abort — need a record that exists
*by default* and survives the process dying mid-round. This module is
that record (the reference's Timer/Monitor + TrainingObserver tier,
PAPER.md layer 2, scaled to the rabit-style multi-worker setting):

- **Always-on ring buffer** of per-round records: round wall time,
  host-blocked dispatch time, eval/checkpoint/sketch stage times,
  retrace count delta (from ``analysis.retrace``'s guard), collective
  ops/bytes delta (from ``observability.comms``'s counters), host RSS
  and device-memory watermarks. Recording costs a few dict ops plus two
  clock reads per round (pinned ≤ 2% of a small-bench round by
  ``tests/test_flight.py``); ``XGBTPU_FLIGHT=0`` disables it outright.
- **Durable sink** (``configure(run_dir, rank)``): each rank appends
  every completed record as one JSON line to
  ``run_dir/obs/rank<k>/flight.jsonl`` (line-buffered — a SIGKILL loses
  at most the in-flight round), refreshes ``metrics.json`` (the full
  registry snapshot) and keeps the span trace flowing to
  ``trace.jsonl`` with a recorded clock base (``clock.json``) so
  ``python -m xgboost_tpu obs-report`` can merge ranks onto one
  clock-aligned timeline (``observability/fleet.py``).
- **Black-box dump** (``RECORDER.dump(reason)``): the full ring plus
  registry snapshot written atomically to ``blackbox.json`` — fired on
  any training abort (``training.py``), on ``WatchdogTimeout`` expiry
  (``resilience/watchdog.py``) and at elastic quiesce/completion.
- **Profiling window**: ``XGBTPU_PROFILE=<dir>`` captures a
  ``jax.profiler`` device trace for the first ``XGBTPU_PROFILE_ROUNDS``
  (default 5) boosting rounds — the heavyweight device-side complement
  to the always-on host-side records.

Live queries go through :class:`~xgboost_tpu.callback.FlightRecorderMonitor`
(a training callback handing each completed record to user code) or
directly: ``flight.RECORDER.last()`` / ``.records()``.

File formats (all parseable line-wise, ``docs/observability.md``):

- ``flight.jsonl`` — first line ``{"t": "meta", ...}`` (rank, pid,
  clock base), then ``{"t": "round", ...}`` / ``{"t": "event", ...}``
  records, one per line;
- ``blackbox.json`` — one JSON object: meta + ``records`` + ``metrics``.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from . import trace as _trace
from .metrics import REGISTRY

__all__ = [
    "FlightRecorder", "RECORDER", "enabled", "note", "configure",
    "stage_totals", "profile_tick", "profile_stop", "atomic_write_json",
]

_ENV_FLIGHT = "XGBTPU_FLIGHT"
_ENV_BUFFER = "XGBTPU_FLIGHT_BUFFER"
_ENV_PROFILE = "XGBTPU_PROFILE"
_ENV_PROFILE_ROUNDS = "XGBTPU_PROFILE_ROUNDS"

FORMAT = "xgbtpu-flight-v1"

_ROUND_SECONDS_HELP = "Wall time per boosting round (flight recorder)"


def enabled() -> bool:
    """Whether recording is on (``XGBTPU_FLIGHT=0`` turns it off)."""
    return os.environ.get(_ENV_FLIGHT) != "0"


_enabled = enabled


def _rank() -> int:
    """This process's rank, without initializing a backend (same guarded
    read as ``trace._rank_world``)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return 0
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def atomic_write_json(path: str, doc: Dict[str, Any]) -> bool:
    """Replace-write ``doc`` as JSON (tmp + rename; no fsync — black-box
    artifacts tolerate losing the very last dump on power cut). Shared by
    the training black box here and the serving flight recorder
    (``serving/obs.py``). Best effort: returns False instead of raising,
    because a dump must never mask the abort it documents."""
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return True
    except (OSError, ValueError, TypeError):
        return False


def _rss_peak_mb() -> float:
    """Host peak RSS in MB (``ru_maxrss`` is KB on Linux — one cheap
    syscall, no /proc parse)."""
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    except Exception:
        return 0.0


class FlightRecorder:
    """Ring buffer of per-round records plus the durable sink. One
    process-wide instance (``RECORDER``); all methods are thread-safe
    (membership/degrade events arrive from monitor threads)."""

    def __init__(self, maxlen: Optional[int] = None) -> None:
        if maxlen is None:
            try:
                maxlen = int(os.environ.get(_ENV_BUFFER, "4096") or 4096)
            except ValueError:
                maxlen = 4096
        self._lock = threading.RLock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=max(maxlen, 16))
        self._open: Optional[Dict[str, Any]] = None
        self._depth = 0  # nested begin_round (update -> update_many)
        self._generation = 0  # elastic generation (set_generation)
        self._t0 = 0.0
        # cumulative per-stage seconds for the whole process (bench's
        # per-stage breakdown reads deltas of this — includes stage time
        # spent outside any round, e.g. the initial sketch)
        self._stage_totals: Dict[str, float] = {}
        # deltas are computed against the previous round's absolute totals
        self._last_retraces = 0
        self._last_coll = (0.0, 0.0)
        # sink state (configure)
        self._dir: Optional[str] = None
        self._rank: Optional[int] = None
        self._file = None
        self._dev_mem_ok: Optional[bool] = None  # probe once

    # ------------------------------------------------------------------
    # deltas / watermarks
    # ------------------------------------------------------------------
    def _retrace_total(self) -> int:
        from ..analysis.retrace import retrace_counts

        return sum(retrace_counts().values())

    def _coll_totals(self) -> tuple:
        ops = by = 0.0
        for name in ("collective_ops_total", "collective_bytes_total"):
            fam = REGISTRY.get(name)
            if fam is None:
                continue
            total = sum(child.value for _, child in fam.series())
            if name.endswith("ops_total"):
                ops = total
            else:
                by = total
        return ops, by

    def _dev_peak_mb(self) -> Optional[float]:
        if self._dev_mem_ok is False:
            return None
        try:
            jax = sys.modules.get("jax")
            if jax is None:
                raise RuntimeError("jax not imported")
            stats = jax.local_devices()[0].memory_stats()
            peak = (stats or {}).get("peak_bytes_in_use")
            if peak is None:
                raise RuntimeError("no peak_bytes_in_use")
            self._dev_mem_ok = True
            return peak / (1024.0 * 1024.0)
        except Exception:
            self._dev_mem_ok = False
            return None

    # ------------------------------------------------------------------
    # round lifecycle (the training loop's three calls)
    # ------------------------------------------------------------------
    def set_generation(self, generation: int) -> None:
        """The elastic generation stamped on subsequent round records
        (``elastic_train`` bumps it at every resize, so the fleet table
        can key replayed rounds as (gen, round))."""
        with self._lock:
            self._generation = int(generation)

    def begin_round(self, round_idx: int, rounds: int = 1,
                    generation: Optional[int] = None) -> bool:
        """Open a round record. Returns True when THIS call owns the
        record — a nested begin (``update`` routing through
        ``update_many`` under a mesh) returns False, and the nested
        caller must then skip its own stage notes for work the owner
        already times (else ``stages.grow`` double-counts)."""
        if not _enabled():
            return False
        with self._lock:
            if self._open is not None:  # nested (update -> update_many)
                self._depth += 1
                return False
            if self._dir is None:
                env = os.environ.get(_ENV_FLIGHT)
                if env and env not in ("0", "1"):
                    self._configure_locked(env, None)
            self._t0 = time.perf_counter()
            self._open = {
                "t": "round", "round": int(round_idx), "rounds": int(rounds),
                "gen": int(self._generation if generation is None
                           else generation),
                "unix_ms": time.time() * 1e3,
                "stages": {},
            }
            return True

    def note(self, stage: str, seconds: float) -> None:
        """Charge ``seconds`` of wall time to ``stage`` (``grow`` /
        ``eval`` / ``checkpoint`` / ``sketch`` / ...) — accumulated into
        the open round record (if any) AND the process-lifetime stage
        totals (``stage_totals``, the bench breakdown's source)."""
        if not _enabled():
            return
        with self._lock:
            self._stage_totals[stage] = (
                self._stage_totals.get(stage, 0.0) + seconds)
            if self._open is not None:
                st = self._open["stages"]
                st[stage] = st.get(stage, 0.0) + seconds

    def annotate(self, key: str, value: Any) -> None:
        """Attach a structured sub-record to the OPEN round record under
        ``key`` — e.g. the kernel profiler's ``grow_detail`` (per-depth ×
        per-op attribution for a sampled round). ``value`` must be
        JSON-serializable; a repeat annotation of the same key within one
        round overwrites; with no open round the call is dropped (the
        profiler can outlive a round aborted mid-update)."""
        if not _enabled():
            return
        with self._lock:
            if self._open is not None:
                self._open[key] = value

    def end_round(self) -> Optional[Dict[str, Any]]:
        if not _enabled():
            return None
        with self._lock:
            if self._depth:
                self._depth -= 1
                return None
            rec = self._open
            if rec is None:
                return None
            self._open = None
            wall = time.perf_counter() - self._t0
            rec["wall_s"] = round(wall, 6)
            rec["stages"] = {k: round(v, 6)
                             for k, v in rec["stages"].items()}
            try:
                rt = self._retrace_total()
                rec["retraces"] = rt - self._last_retraces
                self._last_retraces = rt
            except Exception:
                rec["retraces"] = -1
            ops, by = self._coll_totals()
            rec["coll_ops"] = ops - self._last_coll[0]
            rec["coll_bytes"] = by - self._last_coll[1]
            self._last_coll = (ops, by)
            rec["rss_peak_mb"] = round(_rss_peak_mb(), 1)
            dev = self._dev_peak_mb()
            if dev is not None:
                rec["dev_peak_mb"] = round(dev, 1)
            self._ring.append(rec)
            self._write_line(rec)
        REGISTRY.histogram(
            "round_seconds", _ROUND_SECONDS_HELP).observe(wall)
        if self._dir is not None:
            self._refresh_sidecars()
        return rec

    def event(self, name: str, **args: Any) -> None:
        """A fleet event (worker death, degrade transition, quiesce,
        watchdog abort): recorded in the ring + sink; ``obs-report``
        renders these as instants on the merged timeline."""
        if not _enabled():
            return
        rec = {"t": "event", "name": name,
               "unix_ms": time.time() * 1e3}
        if args:
            rec["args"] = {k: v for k, v in args.items()}
        with self._lock:
            self._ring.append(rec)
            self._write_line(rec)

    # ------------------------------------------------------------------
    # queries
    # ------------------------------------------------------------------
    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def last(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("t") == "round":
                    return rec
            return None

    def stage_totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._stage_totals)

    @property
    def run_dir(self) -> Optional[str]:
        with self._lock:
            return self._dir

    # ------------------------------------------------------------------
    # sink
    # ------------------------------------------------------------------
    def configure(self, run_dir: str, rank: Optional[int] = None) -> str:
        """Attach the durable sink at ``run_dir/obs/rank<k>/``. First
        caller wins (``elastic_train`` configures before ``train``'s
        ``resume_from`` fallback would); returns the rank directory."""
        with self._lock:
            if self._dir is None:
                self._configure_locked(run_dir, rank)
            return self._dir  # type: ignore[return-value]

    def _configure_locked(self, run_dir: str, rank: Optional[int]) -> None:
        rank = _rank() if rank is None else int(rank)
        d = os.path.join(run_dir, "obs", f"rank{rank}")
        try:
            os.makedirs(d, exist_ok=True)
            self._file = open(os.path.join(d, "flight.jsonl"), "a")
        except OSError:
            self._file = None
            return
        self._dir = d
        self._rank = rank
        meta = {
            "t": "meta", "format": FORMAT, "rank": rank,
            "pid": os.getpid(), "unix_ms": time.time() * 1e3,
            "clock": _trace.clock_base(),
        }
        self._write_line(meta)
        try:
            with open(os.path.join(d, "clock.json"), "w") as f:
                json.dump(_trace.clock_base(), f)
        except OSError:
            pass
        # keep the span trace flowing into the same rank directory (a
        # user-set XGBTPU_TRACE / set_config destination still wins)
        _trace.set_sink(os.path.join(d, "trace.jsonl"))

    def _write_line(self, rec: Dict[str, Any]) -> None:
        if self._file is None:
            return
        try:
            self._file.write(json.dumps(rec) + "\n")
            self._file.flush()
        except (OSError, ValueError):
            pass

    def _refresh_sidecars(self) -> None:
        """Refresh ``metrics.json`` + flush the trace ring so a SIGKILL
        between rounds leaves current sidecars on disk. Plain
        replace-write (no fsync): this runs every round and the previous
        snapshot is an acceptable loss on power cut."""
        d = self._dir
        if d is None:
            return
        try:
            tmp = os.path.join(d, f".metrics.tmp.{os.getpid()}")
            with open(tmp, "w") as f:
                json.dump(REGISTRY.snapshot(), f)
            os.replace(tmp, os.path.join(d, "metrics.json"))
        except (OSError, ValueError):
            pass
        try:
            if _trace.enabled():
                _trace.flush()
        except Exception:
            pass

    # ------------------------------------------------------------------
    # black box
    # ------------------------------------------------------------------
    def dump(self, reason: str, path: Optional[str] = None) -> Optional[str]:
        """Write the full ring + registry snapshot as one atomic JSON
        file (``blackbox.json`` in the rank's obs directory unless
        ``path`` is given). Best effort — a dump must never mask the
        abort it documents. Returns the written path, or None when no
        sink is configured and no path was given."""
        if not _enabled():
            return None
        with self._lock:
            if path is None:
                if self._dir is None:
                    return None
                path = os.path.join(self._dir, "blackbox.json")
            doc = {
                "format": FORMAT, "reason": reason,
                "rank": self._rank if self._rank is not None else _rank(),
                "pid": os.getpid(), "unix_ms": time.time() * 1e3,
                "clock": _trace.clock_base(),
                "stage_totals_s": {k: round(v, 6) for k, v
                                   in self._stage_totals.items()},
                "records": list(self._ring),
            }
        try:
            doc["metrics"] = REGISTRY.snapshot()
        except Exception:
            doc["metrics"] = {}
        try:
            # the resolved kernel routing table: which impl served each
            # op when the box was dumped (attributes a perf/fault record
            # to its route — dispatch/core.py)
            from .. import dispatch

            doc["dispatch"] = dispatch.table_snapshot()
        except Exception:
            doc["dispatch"] = {}
        if not atomic_write_json(path, doc):
            return None
        self._refresh_sidecars()
        return path

    def abort_dump(self, exc: BaseException) -> None:
        """The training loop's abort hook: record the abort as an event,
        then dump the black box — both best effort."""
        try:
            self.event("train_abort", error=type(exc).__name__,
                       detail=str(exc)[:200])
            self.dump(f"abort:{type(exc).__name__}")
        except Exception:
            pass

    # ------------------------------------------------------------------
    def reset(self) -> None:
        """Tests: drop records/totals, detach the sink, release the trace
        sink override."""
        with self._lock:
            self._ring.clear()
            self._open = None
            self._depth = 0
            self._generation = 0
            self._stage_totals.clear()
            self._last_retraces = 0
            self._last_coll = (0.0, 0.0)
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
            self._file = None
            self._dir = None
            self._rank = None
        _trace.set_sink(None)


RECORDER = FlightRecorder()


def note(stage: str, seconds: float) -> None:
    RECORDER.note(stage, seconds)


def configure(run_dir: str, rank: Optional[int] = None) -> str:
    return RECORDER.configure(run_dir, rank)


def stage_totals() -> Dict[str, float]:
    return RECORDER.stage_totals()


# ---------------------------------------------------------------------------
# profiling window: XGBTPU_PROFILE=<dir> captures a jax.profiler device
# trace for the first XGBTPU_PROFILE_ROUNDS rounds of the next train loop
# ---------------------------------------------------------------------------

_prof_lock = threading.RLock()  # reentrant: _stop_locked re-enters
_prof_state = {"active": False, "stop_after": -1, "used": False}


def profile_tick(round_idx: int) -> None:
    """Called at each round boundary by the training loop. Starts the
    profiler window on the first tick (once per process), stops it after
    ``XGBTPU_PROFILE_ROUNDS`` rounds. Never raises into training."""
    directory = os.environ.get(_ENV_PROFILE)
    if not directory:
        return
    with _prof_lock:
        if _prof_state["active"]:
            if round_idx >= _prof_state["stop_after"]:
                _stop_locked()
            return
        if _prof_state["used"]:
            return
        try:
            rounds = max(1, int(os.environ.get(_ENV_PROFILE_ROUNDS, "5")))
        except ValueError:
            rounds = 5
        try:
            import jax

            os.makedirs(directory, exist_ok=True)
            jax.profiler.start_trace(directory)
        except Exception as e:
            from ..utils import console_logger

            console_logger.warning(f"flight: profiler window failed to "
                                   f"start ({e}); continuing unprofiled")
            _prof_state["used"] = True
            return
        _prof_state["active"] = True
        _prof_state["used"] = True
        _prof_state["stop_after"] = round_idx + rounds
        _trace.instant("profile_window_start", dir=directory, rounds=rounds)


def _stop_locked() -> None:
    try:
        import jax

        jax.profiler.stop_trace()
        from ..utils import console_logger

        console_logger.info(
            f"flight: jax.profiler window captured into "
            f"{os.environ.get(_ENV_PROFILE)}")
    except Exception:
        pass
    with _prof_lock:  # re-entrant: callers already hold it
        _prof_state["active"] = False
    _trace.instant("profile_window_stop")


def profile_stop() -> None:
    """Close a still-open window (train-loop ``finally``): a profile of
    fewer rounds beats a corrupt unterminated capture."""
    with _prof_lock:
        if _prof_state["active"]:
            _stop_locked()


def profile_reset() -> None:
    """Tests: allow another window in the same process."""
    with _prof_lock:
        if _prof_state["active"]:
            _stop_locked()
        _prof_state["used"] = False
        _prof_state["stop_after"] = -1
