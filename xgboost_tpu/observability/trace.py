"""Structured span tracing: host-side timeline -> Chrome trace-event JSONL.

The reference ships wall-clock accumulators (``common::Monitor``) and
compile-gated NVTX ranges; neither produces a machine-readable timeline.
This module is the unified replacement: a ``span("hist_build", node=k)``
context manager records Chrome trace-event "X" (complete) events —
viewable in Perfetto / ``chrome://tracing`` — into an in-memory ring
buffer, flushed to the path named by ``XGBTPU_TRACE=<path>`` or
``set_config(trace_path=...)``.

Design constraints (ISSUE 1):

- **Near-zero cost when disabled**: ``span()`` performs one enabled check
  (an env-cached None test plus a thread-local dict get) and returns a
  shared no-op context manager. No allocation, no clock read.
- **Host-side only**: spans measure the Python-side view — argument prep,
  dispatch, and blocking host syncs — never device internals, and a span
  opened while JAX is *tracing* a function (inside ``jit``/``shard_map``
  staging) is suppressed (``jax.core.trace_state_clean``), so wrapped
  growers can be staged into larger programs without emitting bogus
  trace-time events. Device-side profiling remains ``jax.profiler``
  (``utils.timer.profiler_context``).
- **Ring buffered**: the newest ``XGBTPU_TRACE_BUFFER`` (default 65536)
  events are retained; older ones are dropped and counted in the
  ``trace_events_dropped_total`` metric. ``flush()`` drains the buffer to
  disk (appending), and runs automatically at interpreter exit.

File format: a Chrome trace-event JSON array written one event per line
(the spec's trailing-``]``-optional form, which both Perfetto and
``chrome://tracing`` load), so the file doubles as JSONL — each event
line (modulo the trailing comma) is a complete JSON object, and
``load_trace`` parses any prefix of a partially written file. Multi-process
runs write one file per rank (``<path>.rank<r>``), with the rank as the
Chrome ``pid``.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = [
    "span", "instant", "emit", "emit_async", "emit_async_track",
    "enabled", "trace_path", "flush", "reset", "load_trace",
    "clock_base", "set_sink",
]

_ENV_PATH = "XGBTPU_TRACE"
_ENV_BUFFER = "XGBTPU_TRACE_BUFFER"

_lock = threading.RLock()
_buffer: "collections.deque[Dict[str, Any]]" = collections.deque(
    maxlen=max(int(os.environ.get(_ENV_BUFFER, "65536") or 65536), 16))
_dropped = 0
_headers_written: set = set()
_tid_map: Dict[int, int] = {}
_rank_cache: Optional[tuple] = None  # (rank, world)
_sink: Optional[str] = None  # flight-recorder sink (observability/flight.py)
# the two clock reads are adjacent on purpose: _EPOCH_UNIX_NS is the
# wall-clock instant at which event timestamps are 0, the per-rank clock
# base cross-rank merging aligns on (obs-report; skew < 1us)
_EPOCH_NS = time.perf_counter_ns()
_EPOCH_UNIX_NS = time.time_ns()


def clock_base() -> Dict[str, Any]:
    """The mapping from this process's event timestamps to wall-clock
    time: an event's ``ts`` (microseconds) is relative to ``unix_ns``.
    Persisted per rank (``obs/rank<k>/clock.json``) so ``obs-report``
    can merge ranks onto one clock-aligned timeline."""
    return {"unix_ns": _EPOCH_UNIX_NS, "ts_unit": "us"}


def set_sink(path: Optional[str]) -> None:
    """Install (or clear) a process-wide fallback trace destination —
    the flight recorder's per-rank ``trace.jsonl``. Explicit choices
    (``XGBTPU_TRACE``, ``set_config(trace_path=...)``) still win, and a
    sink path is written EXACTLY (no ``.rank<r>`` suffix: the sink is
    already rank-scoped)."""
    global _sink
    with _lock:
        _sink = path


def trace_path() -> Optional[str]:
    """The active trace destination, or None when tracing is off. The
    ``XGBTPU_TRACE`` env var wins; otherwise the (thread-local)
    ``set_config(trace_path=...)`` value."""
    p = os.environ.get(_ENV_PATH)
    if p:
        return p
    from ..config import _state  # direct read: no per-span dict copy

    return _state().get("trace_path") or _sink or None


def enabled() -> bool:
    return trace_path() is not None


def _host_side() -> bool:
    """False while JAX is staging (tracing) a program: a span opened there
    would measure trace-time, not run-time, and would fire once per
    compilation instead of once per execution."""
    jax = sys.modules.get("jax")
    if jax is None:
        return True
    try:
        return jax.core.trace_state_clean()
    except Exception:
        return True


def _rank_world() -> tuple:
    # lock-guarded (lint CC402): resolving the rank can initialize the JAX
    # backend; two flushing threads racing the latch would both pay that
    # (and one could read a half-initialized backend)
    global _rank_cache
    with _lock:
        if _rank_cache is None:
            try:
                jax = sys.modules.get("jax")
                if jax is None:
                    raise RuntimeError("jax not imported")
                _rank_cache = (jax.process_index(), jax.process_count())
            except Exception:
                _rank_cache = (0, 1)
        return _rank_cache


def _tid() -> int:
    ident = threading.get_ident()
    t = _tid_map.get(ident)
    if t is None:
        with _lock:
            t = _tid_map.setdefault(ident, len(_tid_map))
    return t


def _record(ev: Dict[str, Any]) -> None:
    global _dropped
    with _lock:
        if len(_buffer) == _buffer.maxlen:
            _dropped += 1
            from .metrics import REGISTRY

            REGISTRY.counter(
                "trace_events_dropped_total",
                "Trace events evicted from the ring buffer before flush",
            ).inc()
        _buffer.append(ev)


class _Span:
    """An open span; emits one Chrome 'X' (complete) event on exit."""

    __slots__ = ("name", "args", "_t0")

    def __init__(self, name: str, args: Dict[str, Any]):
        self.name = name
        self.args = args

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        t1 = time.perf_counter_ns()
        # NOTE: no rank lookup here — the rank is constant per process and
        # resolving it can initialize the JAX backend (hundreds of ms);
        # ``flush`` stamps every event's ``pid`` once instead.
        ev = {
            "name": self.name,
            "ph": "X",
            "ts": (self._t0 - _EPOCH_NS) // 1000,
            "dur": max((t1 - self._t0) // 1000, 1),
            "tid": _tid(),
        }
        if self.args:
            ev["args"] = self.args
        _record(ev)
        return False


class _NoopSpan:
    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        return False


_NOOP = _NoopSpan()


def span(name: str, **args: Any):
    """Context manager timing a host-side phase. ``args`` become the
    event's Chrome ``args`` payload (keep them JSON-scalar). Disabled or
    staging-time calls return a shared no-op."""
    if not enabled() or not _host_side():
        return _NOOP
    return _Span(name, args)


def emit(name: str, start_ns: int, end_ns: int, cat: Optional[str] = None,
         **args: Any) -> None:
    """Record a complete event from a pre-measured ``perf_counter_ns``
    interval — for instrumentation that already owns its clock reads
    (``utils.timer.Monitor``). ``cat`` becomes the Chrome category
    (``trace-report`` groups span time by it: serving vs train vs
    collective)."""
    if not enabled() or not _host_side():
        return
    ev = {
        "name": name,
        "ph": "X",
        "ts": (start_ns - _EPOCH_NS) // 1000,
        "dur": max((end_ns - start_ns) // 1000, 1),
        "tid": _tid(),
    }
    if cat:
        ev["cat"] = cat
    if args:
        ev["args"] = args
    _record(ev)


def emit_async(name: str, track: str, start_ns: int, end_ns: int,
               cat: str = "serving", **args: Any) -> None:
    """Record one nestable-async span (Chrome phases 'b'/'e') on the
    track keyed ``(cat, track)`` — Perfetto renders every event sharing
    that key as one async lane, so a serving request's whole lifetime
    (queue -> batch wait -> dispatch) reads as a single track regardless
    of which thread touched it. Timestamps are pre-measured
    ``perf_counter_ns`` values (the serving layer stamps stages as they
    happen but emits only at completion, off the hot path)."""
    emit_async_track(track, [(name, start_ns, end_ns, args or None)],
                     cat=cat)


def emit_async_track(track: str,
                     spans: List[tuple],
                     cat: str = "serving") -> None:
    """Batched :func:`emit_async`: every ``(name, start_ns, end_ns,
    args-or-None)`` in ``spans`` lands on the ``(cat, track)`` async lane
    with ONE enabled check and one buffer lock acquisition. The serving
    recorder emits a request's whole track (request + queue_wait +
    batch_wait + dispatch) per completion, so per-event overhead is what
    the ≤2% serving pin actually measures."""
    if not spans or not enabled() or not _host_side():
        return
    tid = _tid()
    sid = str(track)
    epoch = _EPOCH_NS
    events: List[Dict[str, Any]] = []
    push = events.append
    for name, start_ns, end_ns, args in spans:
        ts0 = (start_ns - epoch) // 1000
        ts1 = (end_ns - epoch) // 1000
        begin: Dict[str, Any] = {"name": name, "ph": "b", "cat": cat,
                                 "id": sid, "ts": ts0, "tid": tid}
        if args:
            begin["args"] = args
        push(begin)
        push({"name": name, "ph": "e", "cat": cat, "id": sid,
              "ts": ts1 if ts1 > ts0 else ts0 + 1, "tid": tid})
    global _dropped
    dropped = 0
    with _lock:
        for ev in events:
            if len(_buffer) == _buffer.maxlen:
                dropped += 1
            _buffer.append(ev)
        _dropped += dropped
    if dropped:
        from .metrics import REGISTRY

        REGISTRY.counter(
            "trace_events_dropped_total",
            "Trace events evicted from the ring buffer before flush",
        ).inc(dropped)


def instant(name: str, **args: Any) -> None:
    """A zero-duration marker event (Chrome phase 'i')."""
    if not enabled() or not _host_side():
        return
    ev = {
        "name": name,
        "ph": "i",
        "s": "t",
        "ts": (time.perf_counter_ns() - _EPOCH_NS) // 1000,
        "tid": _tid(),
    }
    if args:
        ev["args"] = args
    _record(ev)


def _out_path(path: str) -> str:
    if path == _sink:
        return path  # the sink is already a rank-scoped destination
    rank, world = _rank_world()
    return f"{path}.rank{rank}" if world > 1 else path


def flush(path: Optional[str] = None) -> Optional[str]:
    """Drain the ring buffer to ``path`` (default: the active trace path),
    appending to earlier flushes. Returns the written path, or None when
    tracing is off and no path was given."""
    path = path or trace_path()
    if path is None:
        return None
    path = _out_path(path)
    with _lock:
        events = list(_buffer)
        _buffer.clear()
        need_header = path not in _headers_written
        _headers_written.add(path)
    if need_header:
        try:
            need_header = os.path.getsize(path) == 0
        except OSError:
            need_header = True
    rank, _ = _rank_world()
    with open(path, "a") as f:
        if need_header:
            f.write("[\n")
            meta = {
                "name": "process_name", "ph": "M", "pid": rank, "tid": 0,
                "args": {"name": f"xgboost_tpu rank {rank}"},
            }
            f.write(json.dumps(meta) + ",\n")
        for ev in events:
            ev.setdefault("pid", rank)
            f.write(json.dumps(ev) + ",\n")
    return path


def reset() -> None:
    """Clear buffered events and per-path header state (tests)."""
    global _dropped, _rank_cache
    with _lock:
        _buffer.clear()
        _headers_written.clear()
        _dropped = 0
        _rank_cache = None


def dropped_count() -> int:
    return _dropped


def load_trace(path: str) -> List[Dict[str, Any]]:
    """Parse a trace file written by ``flush`` (or any Chrome trace-event
    JSON: complete array, trailing-comma/unterminated array, JSONL, or a
    ``{"traceEvents": [...]}`` wrapper) into a list of event dicts."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None
    if doc is None and text.startswith("["):
        # the spec's unterminated-array form: close it
        doc = json.loads(text.rstrip().rstrip(",") + "\n]")
    if isinstance(doc, dict):
        doc = doc.get("traceEvents", [])
    if doc is None:
        # JSONL: one event object per line
        doc = [json.loads(ln.rstrip(",")) for ln in text.splitlines()
               if ln.strip() and ln.strip() not in ("[", "]")]
    if not isinstance(doc, list) or not all(
            isinstance(e, dict) for e in doc):
        raise ValueError(f"{path}: not a Chrome trace event file")
    return doc


import atexit  # noqa: E402

atexit.register(lambda: flush() if enabled() and len(_buffer) else None)
