"""Trace summarization: ``python -m xgboost_tpu trace-report <file>``.

Reads a Chrome trace-event file written by ``observability.trace`` (any of
the accepted forms — see ``load_trace``) and prints:

- per-span-name totals: call count, total (inclusive) time, **self time**
  (inclusive minus time spent in nested spans on the same rank+thread),
  ranked by self time — "where did this round's milliseconds go";
- per-category totals: the Chrome ``cat`` field (the serving plane tags
  its request/dispatch spans ``serving``), with uncategorized spans
  bucketed as ``train`` and the known collective span names as
  ``collective`` — so a mixed train+serve trace summarizes both planes
  in one line;
- per-rank (Chrome ``pid``) totals — "on which host";
- counts of instant events.

Self time is reconstructed per (pid, tid) track with a stack sweep over
the complete ('X') events sorted by start time: an event strictly
contained in the open event above it is a child, and its duration is
subtracted from the parent's self time.

Multiple inputs (and shell-unexpanded globs — ``trace.json.rank*``) are
merged into ONE report: per-rank files from a multi-process run carry
their rank as the Chrome ``pid``, so the per-rank totals stay separable
after the merge and nobody has to concatenate JSONL by hand. Any
unreadable or unparseable input makes the exit status non-zero (the
readable inputs still report).
"""

from __future__ import annotations

import glob as _glob
import sys
from collections import defaultdict
from typing import Any, Dict, Iterable, List, Tuple

from .trace import load_trace

__all__ = ["summarize", "format_report", "main"]


def _self_times(events: List[Dict[str, Any]]) -> Dict[str, float]:
    """name -> self time (us), via a per-track stack sweep."""
    tracks: Dict[Tuple[Any, Any], List[Dict[str, Any]]] = defaultdict(list)
    for ev in events:
        tracks[(ev.get("pid", 0), ev.get("tid", 0))].append(ev)
    self_us: Dict[str, float] = defaultdict(float)

    def close(frame: List[Any]) -> None:
        ts, end, name, child_dur = frame
        self_us[name] += max(end - ts - child_dur, 0.0)

    for evs in tracks.values():
        evs.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack: List[List[Any]] = []  # [ts, end, name, child_dur]
        for ev in evs:
            ts, dur = ev["ts"], ev.get("dur", 0)
            # pop every open frame that closed before this event starts
            while stack and ts >= stack[-1][1]:
                close(stack.pop())
            if stack:  # nested: charge our duration to the parent
                stack[-1][3] += dur
            stack.append([ts, ts + dur, ev["name"], 0.0])
        while stack:
            close(stack.pop())
    return dict(self_us)


#: uncategorized span names that belong to the collective plane (the
#: host-side collective choke points emit these — ``collective.py``)
_COLLECTIVE_NAMES = frozenset(
    {"allreduce", "broadcast", "process_allgather", "psum", "all_gather"})


def _category(ev: Dict[str, Any]) -> str:
    cat = ev.get("cat")
    if cat:
        return str(cat)
    name = str(ev.get("name", ""))
    if name in _COLLECTIVE_NAMES or name.startswith("collective"):
        return "collective"
    return "train"


def summarize(events: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    events = list(events)
    complete = [e for e in events
                if e.get("ph") == "X" and "ts" in e and "dur" in e]
    instants = [e for e in events if e.get("ph") == "i"]
    per_name: Dict[str, Dict[str, float]] = {}
    per_rank: Dict[int, Dict[str, float]] = {}
    per_cat: Dict[str, Dict[str, float]] = {}
    for ev in complete:
        s = per_name.setdefault(ev["name"], {"count": 0, "total_us": 0.0})
        s["count"] += 1
        s["total_us"] += ev["dur"]
        r = per_rank.setdefault(int(ev.get("pid", 0)),
                                {"count": 0, "total_us": 0.0})
        r["count"] += 1
        r["total_us"] += ev["dur"]
        c = per_cat.setdefault(_category(ev),
                               {"count": 0, "total_us": 0.0})
        c["count"] += 1
        c["total_us"] += ev["dur"]
    for name, su in _self_times(complete).items():
        per_name.setdefault(name, {"count": 0, "total_us": 0.0})[
            "self_us"] = su
    for s in per_name.values():
        s.setdefault("self_us", 0.0)
    inst_counts: Dict[str, int] = defaultdict(int)
    for ev in instants:
        inst_counts[ev["name"]] += 1
    # grow breakdown: the kernel profiler's cat="grow" substage spans
    # (observability/kernelprof.py), keyed per op name — the per-category
    # line says how much grow detail exists, this says where it went
    per_grow: Dict[str, Dict[str, float]] = {}
    for ev in complete:
        if _category(ev) != "grow":
            continue
        g = per_grow.setdefault(ev["name"], {"count": 0, "total_us": 0.0})
        g["count"] += 1
        g["total_us"] += ev["dur"]
    return {
        "n_events": len(events),
        "n_spans": len(complete),
        "spans": per_name,
        "ranks": per_rank,
        "categories": per_cat,
        "grow": per_grow,
        "instants": dict(inst_counts),
    }


def _ms(us: float) -> str:
    return f"{us / 1000.0:.3f}ms"


def format_report(summary: Dict[str, Any], top: int = 20) -> str:
    cats = summary.get("categories", {})
    lines = [
        f"trace: {summary['n_events']} events, "
        f"{summary['n_spans']} spans, {len(summary['ranks'])} rank(s)",
    ]
    if cats:
        lines.append(
            "span time by category: " + ", ".join(
                f"{cat} {_ms(c['total_us'])} ({c['count']} spans)"
                for cat, c in sorted(
                    cats.items(), key=lambda kv: -kv[1]["total_us"])))
    grow = summary.get("grow") or {}
    if grow:
        lines.append("grow breakdown (kernel-profiled substages):")
        for name, g in sorted(grow.items(),
                              key=lambda kv: -kv[1]["total_us"]):
            lines.append(f"  {name:<28} {g['count']:>7} "
                         f"{_ms(g['total_us']):>12}")
    lines += [
        "",
        f"top spans by self time (top {top}):",
        f"  {'name':<28} {'count':>7} {'total':>12} {'self':>12} {'avg':>10}",
    ]
    ranked = sorted(summary["spans"].items(),
                    key=lambda kv: -kv[1]["self_us"])[:top]
    for name, s in ranked:
        avg = s["total_us"] / s["count"] if s["count"] else 0.0
        lines.append(
            f"  {name:<28} {s['count']:>7} {_ms(s['total_us']):>12} "
            f"{_ms(s['self_us']):>12} {_ms(avg):>10}")
    lines.append("")
    lines.append("per-rank totals:")
    for rank in sorted(summary["ranks"]):
        r = summary["ranks"][rank]
        lines.append(
            f"  rank {rank}: {r['count']} spans, {_ms(r['total_us'])}")
    if summary["instants"]:
        lines.append("")
        lines.append("instant events:")
        for name in sorted(summary["instants"]):
            lines.append(f"  {name}: {summary['instants'][name]}")
    return "\n".join(lines)


def expand_inputs(args: List[str]) -> List[str]:
    """Glob-expand each argument (sorted); an argument matching nothing
    is kept literally so its load error surfaces instead of silently
    reporting on fewer files than asked for."""
    paths: List[str] = []
    for pat in args:
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else [pat])
    return paths


def main(argv: List[str]) -> int:
    usage = ("usage: python -m xgboost_tpu trace-report <trace-file|glob>"
             " [more files...] [--top N]")
    if not argv or argv[0] in ("-h", "--help"):
        print(usage, file=sys.stderr)
        return 0 if argv else 1
    top = 20
    if "--top" in argv:
        i = argv.index("--top")
        try:
            top = int(argv[i + 1])
        except (IndexError, ValueError):
            print(usage, file=sys.stderr)
            return 1
        argv = argv[:i] + argv[i + 2:]
    rc = 0
    events: List[Dict[str, Any]] = []
    loaded: List[str] = []
    for path in expand_inputs(argv):
        try:
            events.extend(load_trace(path))
        except (OSError, ValueError, KeyError) as e:
            print(f"{path}: unreadable trace: {e}", file=sys.stderr)
            rc = 1
            continue
        loaded.append(path)
    if loaded:
        if len(loaded) > 1:
            print(f"== merged {len(loaded)} trace files ==")
        print(format_report(summarize(events), top=top))
    return rc
