"""Training callbacks.

Reference: ``python-package/xgboost/callback.py`` — ``TrainingCallback`` ABC
(:23), ``CallbackContainer`` (:102), ``LearningRateScheduler`` (:239),
``EarlyStopping`` (:275), ``EvaluationMonitor`` (:434),
``TrainingCheckPoint`` (:501).
"""

from __future__ import annotations

import collections
import os
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

__all__ = [
    "TrainingCallback",
    "CallbackContainer",
    "LearningRateScheduler",
    "EarlyStopping",
    "EvaluationMonitor",
    "TrainingCheckPoint",
    "TrainingTelemetry",
    "FlightRecorderMonitor",
]

_EvalsLog = Dict[str, Dict[str, List[float]]]


class TrainingCallback:
    def before_training(self, model):
        return model

    def after_training(self, model):
        return model

    def before_iteration(self, model, epoch: int, evals_log: _EvalsLog) -> bool:
        return False

    def after_iteration(self, model, epoch: int, evals_log: _EvalsLog) -> bool:
        """Return True to request training stop."""
        return False


class CallbackContainer:
    """Drives callbacks around the train loop; owns the evals history."""

    def __init__(
        self,
        callbacks: Sequence[TrainingCallback],
        metric=None,
        output_margin: bool = True,
        is_cv: bool = False,
    ):
        self.callbacks = list(callbacks)
        self.metric = metric
        self.history: _EvalsLog = collections.OrderedDict()
        self.is_cv = is_cv

    def before_training(self, model):
        for cb in self.callbacks:
            model = cb.before_training(model)
        return model

    def after_training(self, model):
        for cb in self.callbacks:
            model = cb.after_training(model)
        return model

    def before_iteration(self, model, epoch, dtrain, evals) -> bool:
        return any(cb.before_iteration(model, epoch, self.history) for cb in self.callbacks)

    def _update_history(self, score_strs: str) -> None:
        # parse "[i]\tname-metric:val\t..." into history
        for tok in score_strs.split("\t")[1:]:
            name_metric, _, val = tok.rpartition(":")
            dname, _, mname = name_metric.partition("-")
            self.history.setdefault(dname, collections.OrderedDict()).setdefault(
                mname, []
            ).append(float(val))

    def after_iteration(self, model, epoch, dtrain, evals, feval=None) -> bool:
        if evals:
            import time

            from .observability import flight

            t0 = time.perf_counter()
            msg = model.eval_set(evals, epoch, feval)
            flight.note("eval", time.perf_counter() - t0)
            self._update_history(msg)
        return any(cb.after_iteration(model, epoch, self.history) for cb in self.callbacks)


class LearningRateScheduler(TrainingCallback):
    """Per-iteration eta override (reference callback.py:239)."""

    def __init__(self, learning_rates: Union[Callable[[int], float], Sequence[float]]):
        if callable(learning_rates):
            self.fn = learning_rates
        else:
            rates = list(learning_rates)
            self.fn = lambda epoch: rates[epoch]

    def before_iteration(self, model, epoch, evals_log) -> bool:
        model.set_param("learning_rate", self.fn(epoch))
        return False


class EarlyStopping(TrainingCallback):
    """Stop when the watched metric hasn't improved for `rounds`
    (reference callback.py:275)."""

    def __init__(
        self,
        rounds: int,
        metric_name: Optional[str] = None,
        data_name: Optional[str] = None,
        maximize: Optional[bool] = None,
        save_best: bool = False,
        min_delta: float = 0.0,
    ):
        self.rounds = rounds
        self.metric_name = metric_name
        self.data_name = data_name
        self.maximize = maximize
        self.save_best = save_best
        self.min_delta = min_delta
        self.stopping_history: _EvalsLog = {}
        self.current_rounds = 0
        self.best_scores: List[float] = []

    _MAXIMIZE_METRICS = ("auc", "aucpr", "map", "ndcg", "pre", "ams",
                         "interval-regression-accuracy")

    def before_training(self, model):
        self.current_rounds = 0
        self.best_scores = []
        return model

    def _is_maximize(self, metric: str) -> bool:
        if self.maximize is not None:
            return self.maximize
        base = metric.split("@")[0]
        return base in self._MAXIMIZE_METRICS

    def after_iteration(self, model, epoch, evals_log) -> bool:
        if not evals_log:
            return False
        data_name = self.data_name or list(evals_log.keys())[-1]
        metrics = evals_log[data_name]
        metric_name = self.metric_name or list(metrics.keys())[-1]
        score = metrics[metric_name][-1]
        maximize = self._is_maximize(metric_name)
        if not self.best_scores:
            improved = True
        elif maximize:
            improved = score > self.best_scores[-1] + self.min_delta
        else:
            improved = score < self.best_scores[-1] - self.min_delta
        if improved:
            self.best_scores.append(score)
            self.current_rounds = 0
            if hasattr(model, "set_attr"):
                model.set_attr(
                    best_iteration=str(epoch), best_score=f"{score:.9g}"
                )
                model.best_iteration = epoch
                model.best_score = score
        else:
            self.current_rounds += 1
        return self.current_rounds >= self.rounds

    def after_training(self, model):
        if self.save_best and getattr(model, "best_iteration", None) is not None:
            model = model[: model.best_iteration + 1]
        return model


class EvaluationMonitor(TrainingCallback):
    """Print the eval line each period (reference callback.py:434)."""

    def __init__(self, rank: int = 0, period: int = 1, show_stdv: bool = False):
        self.period = period
        self.rank = rank
        self.show_stdv = show_stdv
        self._latest: Optional[str] = None

    def after_iteration(self, model, epoch, evals_log) -> bool:
        if not evals_log:
            return False
        msg = f"[{epoch}]"
        for dname, metrics in evals_log.items():
            for mname, vals in metrics.items():
                if isinstance(vals[-1], tuple):
                    mean, std = vals[-1]
                    msg += f"\t{dname}-{mname}:{mean:.5f}" + (
                        f"+{std:.5f}" if self.show_stdv else ""
                    )
                else:
                    msg += f"\t{dname}-{mname}:{vals[-1]:.5f}"
        if epoch % self.period == 0:
            print(msg, flush=True)
            self._latest = None
        else:
            self._latest = msg
        return False

    def after_training(self, model):
        if self._latest is not None:
            print(self._latest, flush=True)
        return model


class TrainingTelemetry(TrainingCallback):
    """Record per-round training telemetry into the metrics registry
    (``observability.REGISTRY`` unless one is passed) — ISSUE 1 tentpole
    piece 4. Per round:

    - ``round_seconds`` (histogram): wall time of update + eval;
    - ``trees_total`` (gauge): trees committed to the model so far;
    - ``tree_depth`` / ``tree_leaves`` (gauges): shape of the round's last
      tree (materializes it host-side — that is this callback's cost, and
      why the recording is opt-in rather than built into ``train()``);
    - ``split_gain`` (histogram): loss_change of every split in the
      round's last tree;
    - ``eval_score{data=,metric=}`` (gauges): latest eval history values;

    plus a ``round`` instant event on the active trace. Telemetry must
    never break training: model-introspection failures (e.g. gblinear has
    no trees) are swallowed."""

    def __init__(self, registry=None):
        from .observability import REGISTRY

        self.registry = registry if registry is not None else REGISTRY
        self._t0: Optional[float] = None

    def before_iteration(self, model, epoch: int, evals_log) -> bool:
        import time

        self._t0 = time.perf_counter()
        return False

    def _record_tree_stats(self, model) -> None:
        gbm = getattr(model, "_gbm", None)
        trees = getattr(getattr(gbm, "model", None), "trees", None)
        if not trees:
            return
        reg = self.registry
        reg.gauge("trees_total", "Trees committed to the model").set(
            gbm.model.num_trees)
        last = trees[-1]
        reg.gauge("tree_depth", "Depth of the last committed tree").set(
            last.max_depth())
        reg.gauge("tree_leaves", "Leaves of the last committed tree").set(
            last.num_leaves)
        gain = reg.histogram(
            "split_gain", "Loss change of committed splits",
            buckets=(0.1, 0.5, 1.0, 5.0, 10.0, 50.0, 100.0, 500.0, 1000.0,
                     10000.0))
        internal = last.left_children != -1
        for g in np.asarray(last.loss_changes)[internal]:
            gain.observe(float(g))

    def after_iteration(self, model, epoch: int, evals_log) -> bool:
        import time

        from .observability import trace

        reg = self.registry
        if self._t0 is not None:
            reg.histogram(
                "round_seconds", "Wall time per boosting round",
            ).observe(time.perf_counter() - self._t0)
            self._t0 = None
        try:
            self._record_tree_stats(model)
        except Exception:  # introspection must never fail training
            pass
        for dname, metrics in (evals_log or {}).items():
            for mname, vals in metrics.items():
                if vals:
                    v = vals[-1]
                    if isinstance(v, tuple):  # cv: (mean, std)
                        v = v[0]
                    reg.gauge(
                        "eval_score", "Latest eval metric value",
                    ).labels(data=dname, metric=mname).set(float(v))
        trace.instant("round", epoch=epoch)
        return False


class FlightRecorderMonitor(TrainingCallback):
    """Live window onto the flight recorder (ISSUE 7): after every round
    the just-completed record (round wall time, grow/eval/checkpoint
    stage seconds, retrace + collective deltas, memory watermarks —
    ``observability/flight.py``) lands in ``self.latest`` and is handed
    to ``on_record`` if given. The recorder itself is always on; this
    callback only *reads* it, so attaching it costs nothing extra.

    ::

        mon = FlightRecorderMonitor(
            on_record=lambda r: print(r["round"], r["wall_s"]))
        xgb.train(params, dtrain, 100, callbacks=[mon])
        mon.records()   # every record still in the ring
    """

    def __init__(self, on_record: Optional[Callable[[dict], None]] = None):
        self.on_record = on_record
        self.latest: Optional[dict] = None

    def after_iteration(self, model, epoch: int, evals_log) -> bool:
        from .observability import flight

        # the loop's end_round() runs after the callbacks: the freshest
        # COMPLETE record is the previous round's (epoch-1); the final
        # round's record is picked up by after_training below
        rec = flight.RECORDER.last()
        if rec is not None and rec is not self.latest:
            self.latest = rec
            if self.on_record is not None:
                self.on_record(rec)
        return False

    def after_training(self, model):
        self.after_iteration(model, -1, None)
        return model

    def records(self) -> List[dict]:
        from .observability import flight

        return flight.RECORDER.records()


class TrainingCheckPoint(TrainingCallback):
    """Save the model every `interval` iterations (reference callback.py:501)."""

    def __init__(self, directory: str, name: str = "model", as_pickle: bool = False, interval: int = 100):
        self.directory = directory
        self.name = name
        self.as_pickle = as_pickle
        self.interval = max(1, interval)
        self._epoch = 0

    def after_iteration(self, model, epoch, evals_log) -> bool:
        self._epoch += 1
        if self._epoch % self.interval == 0:
            ext = "pkl" if self.as_pickle else "json"
            path = os.path.join(self.directory, f"{self.name}_{epoch}.{ext}")
            if self.as_pickle:
                import pickle

                with open(path, "wb") as f:
                    pickle.dump(model, f)
            else:
                model.save_model(path)
        return False
