from .gbtree import GBTree, Dart, GBTreeModel  # noqa: F401
from .gblinear import GBLinear  # noqa: F401
from ..registry import BOOSTERS


def create_booster(name: str, *args, **kwargs):
    return BOOSTERS.create(name, *args, **kwargs)
