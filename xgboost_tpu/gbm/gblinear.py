"""GBLinear booster: coordinate-descent linear boosting.

Reference: ``src/gbm/gblinear.cc`` + ``src/linear/updater_coordinate.cc``
(coord_descent), ``updater_shotgun.cc`` (shotgun), feature-selector math in
``coordinate_common.h``. The per-feature closed-form update
``dw = -(sum g_i x_if + lambda w_f) / (sum h_i x_if^2 + lambda)`` is a pure
reduction — on TPU one round over all features is a couple of matmul-shaped
contractions, so the 'shotgun' (all features in parallel) variant is the
natural default; 'coord_descent' does the same cyclically.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..params import GBLinearParam
from ..registry import BOOSTERS


def _soft_threshold(raw, hsum, alpha):
    return jnp.sign(raw) * jnp.maximum(
        jnp.abs(raw) - alpha / jnp.maximum(hsum, 1e-10), 0.0
    )


def _candidate_deltas(Xz, mask, grad, hess, w, lam, alpha):
    """Closed-form weight deltas for every feature at the current residuals
    (reference: coordinate_common.h CoordinateDelta, vectorized)."""
    gsum = (grad[:, None] * Xz * mask).sum(0) + lam * w[:-1]
    hsum = (hess[:, None] * Xz * Xz * mask).sum(0) + lam
    raw = w[:-1] - gsum / jnp.maximum(hsum, 1e-10)
    return _soft_threshold(raw, hsum, alpha) - w[:-1]


@partial(jax.jit, static_argnames=("selector", "steps"))
def _linear_round(
    X: jax.Array,  # [n, F] (NaN treated as 0 contribution)
    grad: jax.Array,  # [n]
    hess: jax.Array,
    weights: jax.Array,  # [F + 1] (last = bias)
    lam: float,
    alpha: float,
    eta: float,
    key: jax.Array,
    selector: str,  # shotgun | cyclic | shuffle | random | greedy | thrifty
    steps: int,  # coordinate steps this round (top_k for greedy/thrifty)
) -> jax.Array:
    """One boosting round of coordinate descent. Feature selectors follow
    the reference's ``coordinate_common.h`` (~505 LoC) semantics:
    cyclic/shuffle/random walk all features (in order / permuted / with
    replacement); greedy re-scores every feature each step and descends the
    largest magnitude delta; thrifty pre-sorts features by their candidate
    delta once per round and updates the top_k cyclically."""
    F = X.shape[1]
    Xz = jnp.nan_to_num(X)
    mask = (~jnp.isnan(X)).astype(X.dtype)

    # bias update first (reference: gblinear.cc updates bias via sum g / sum h;
    # residuals advance by the APPLIED delta eta*db, coordinate_common.h
    # UpdateResidualParallel with dbias)
    db = -grad.sum() / jnp.maximum(hess.sum(), 1e-10)
    db_applied = eta * db
    weights = weights.at[-1].add(db_applied)
    grad = grad + hess * db_applied

    if selector == "shotgun":
        # simultaneous updates (reference updater_shotgun.cc)
        dw = _candidate_deltas(Xz, mask, grad, hess, weights, lam, alpha)
        return weights.at[:-1].add(eta * dw)

    def coord_step(f, w, g):
        xf = Xz[:, f] * mask[:, f]
        gsum = (g * xf).sum() + lam * w[f]
        hsum = (hess * xf * xf).sum() + lam
        raw = w[f] - (gsum / jnp.maximum(hsum, 1e-10))
        neww = _soft_threshold(raw, hsum, alpha)
        dw = eta * (neww - w[f])
        w = w.at[f].add(dw)
        g = g + hess * xf * dw
        return w, g

    if selector == "greedy":
        # re-score all features each step, descend the best (top_k steps)
        def body(_, carry):
            w, g = carry
            dws = _candidate_deltas(Xz, mask, g, hess, w, lam, alpha)
            f = jnp.argmax(jnp.abs(dws))
            return coord_step(f, w, g)

        weights, _ = jax.lax.fori_loop(0, steps, body, (weights, grad))
        return weights

    if selector == "thrifty":
        dws = _candidate_deltas(Xz, mask, grad, hess, weights, lam, alpha)
        order = jnp.argsort(-jnp.abs(dws))[:steps]
    elif selector == "shuffle":
        order = jax.random.permutation(key, F)
    elif selector == "random":
        order = jax.random.randint(key, (F,), 0, F)
    else:  # cyclic
        order = jnp.arange(F)

    def body(i, carry):
        w, g = carry
        return coord_step(order[i], w, g)

    weights, _ = jax.lax.fori_loop(0, order.shape[0], body, (weights, grad))
    return weights


@BOOSTERS.register("gblinear")
class GBLinear:
    name = "gblinear"

    def __init__(self, n_groups: int, params: Dict[str, Any]):
        self.n_groups = max(1, n_groups)
        self.param = GBLinearParam()
        self.param.update(dict(params))
        self.weights: Optional[np.ndarray] = None  # [F+1, K]

    def set_param(self, key: str, value: Any) -> None:
        self.param.update({key: value})

    def _ensure(self, F: int) -> None:
        if self.weights is None:
            self.weights = np.zeros((F + 1, self.n_groups), np.float32)

    def boost_one_round(self, dtrain_X, grad, hess, iteration):
        X = jnp.asarray(dtrain_X, jnp.float32)
        F = X.shape[1]
        self._ensure(F)
        if self.param.updater in ("coord_descent", "gpu_coord_descent"):
            selector = self.param.feature_selector
            if selector not in ("cyclic", "shuffle", "random", "greedy",
                                "thrifty"):
                raise ValueError(f"Unknown feature_selector: {selector}")
        else:  # shotgun supports cyclic ordering only (updater_shotgun.cc)
            if self.param.feature_selector not in ("cyclic", "shuffle"):
                raise ValueError(
                    "shotgun supports feature_selector cyclic/shuffle only"
                )
            selector = "shotgun"
        top_k = int(self.param.top_k)
        steps = top_k if (top_k > 0 and selector in ("greedy", "thrifty")) else F
        w = jnp.asarray(self.weights)
        key = jax.random.PRNGKey(iteration * 2654435761 & 0x7FFFFFFF)
        for k in range(self.n_groups):
            g = grad[:, k] if grad.ndim == 2 else grad
            h = hess[:, k] if hess.ndim == 2 else hess
            wk = _linear_round(
                X, g, h, w[:, k],
                self.param.reg_lambda_linear, self.param.reg_alpha_linear,
                self.param.eta_linear, jax.random.fold_in(key, k),
                selector, steps,
            )
            w = w.at[:, k].set(wk)
        self.weights = np.asarray(w)

    def predict(self, X, base_margin: jax.Array) -> jax.Array:
        Xj = jnp.nan_to_num(jnp.asarray(X, jnp.float32))
        w = jnp.asarray(self.weights) if self.weights is not None else jnp.zeros(
            (Xj.shape[1] + 1, self.n_groups), jnp.float32
        )
        out = Xj @ w[:-1] + w[-1]
        return base_margin + out

    def save_json(self) -> dict:
        w = self.weights if self.weights is not None else np.zeros((1, self.n_groups), np.float32)
        return {
            "name": "gblinear",
            "model": {"weights": [float(x) for x in w.reshape(-1)], "shape": list(w.shape)},
        }

    def load_json(self, j: dict) -> None:
        shape = j["model"].get("shape")
        w = np.asarray(j["model"]["weights"], np.float32)
        self.weights = w.reshape(shape) if shape else w.reshape(-1, 1)
