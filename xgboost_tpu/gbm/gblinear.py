"""GBLinear booster: coordinate-descent linear boosting.

Reference: ``src/gbm/gblinear.cc`` + ``src/linear/updater_coordinate.cc``
(coord_descent), ``updater_shotgun.cc`` (shotgun), feature-selector math in
``coordinate_common.h``. The per-feature closed-form update
``dw = -(sum g_i x_if + lambda w_f) / (sum h_i x_if^2 + lambda)`` is a pure
reduction — on TPU one round over all features is a couple of matmul-shaped
contractions, so the 'shotgun' (all features in parallel) variant is the
natural default; 'coord_descent' does the same cyclically.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..params import GBLinearParam
from ..registry import BOOSTERS


@partial(jax.jit, static_argnames=("cyclic",))
def _linear_round(
    X: jax.Array,  # [n, F] (NaN treated as 0 contribution)
    grad: jax.Array,  # [n]
    hess: jax.Array,
    weights: jax.Array,  # [F + 1] (last = bias)
    lam: float,
    alpha: float,
    eta: float,
    cyclic: bool,
) -> jax.Array:
    Xz = jnp.nan_to_num(X)
    mask = (~jnp.isnan(X)).astype(X.dtype)

    # bias update first (reference: gblinear.cc updates bias via sum g / sum h;
    # residuals advance by the APPLIED delta eta*db, coordinate_common.h
    # UpdateResidualParallel with dbias)
    db = -grad.sum() / jnp.maximum(hess.sum(), 1e-10)
    db_applied = eta * db
    weights = weights.at[-1].add(db_applied)
    grad = grad + hess * db_applied

    if cyclic:
        def body(f, carry):
            w, g = carry
            xf = Xz[:, f] * mask[:, f]
            gsum = (g * xf).sum() + lam * w[f]
            hsum = (hess * xf * xf).sum() + lam
            raw = w[f] - (gsum / jnp.maximum(hsum, 1e-10))
            # soft threshold for L1
            neww = jnp.sign(raw) * jnp.maximum(jnp.abs(raw) - alpha / jnp.maximum(hsum, 1e-10), 0.0)
            dw = eta * (neww - w[f])
            w = w.at[f].add(dw)
            g = g + hess * Xz[:, f] * mask[:, f] * dw
            return (w, g)

        weights, _ = jax.lax.fori_loop(0, X.shape[1], body, (weights, grad))
    else:
        # shotgun: simultaneous updates (reference updater_shotgun.cc)
        gsum = (grad[:, None] * Xz * mask).sum(0) + lam * weights[:-1]
        hsum = (hess[:, None] * Xz * Xz * mask).sum(0) + lam
        raw = weights[:-1] - gsum / jnp.maximum(hsum, 1e-10)
        neww = jnp.sign(raw) * jnp.maximum(jnp.abs(raw) - alpha / jnp.maximum(hsum, 1e-10), 0.0)
        weights = weights.at[:-1].add(eta * (neww - weights[:-1]))
    return weights


@BOOSTERS.register("gblinear")
class GBLinear:
    name = "gblinear"

    def __init__(self, n_groups: int, params: Dict[str, Any]):
        self.n_groups = max(1, n_groups)
        self.param = GBLinearParam()
        self.param.update(dict(params))
        self.weights: Optional[np.ndarray] = None  # [F+1, K]

    def set_param(self, key: str, value: Any) -> None:
        self.param.update({key: value})

    def _ensure(self, F: int) -> None:
        if self.weights is None:
            self.weights = np.zeros((F + 1, self.n_groups), np.float32)

    def boost_one_round(self, dtrain_X, grad, hess, iteration):
        X = jnp.asarray(dtrain_X, jnp.float32)
        self._ensure(X.shape[1])
        cyclic = self.param.updater in ("coord_descent", "gpu_coord_descent")
        w = jnp.asarray(self.weights)
        for k in range(self.n_groups):
            g = grad[:, k] if grad.ndim == 2 else grad
            h = hess[:, k] if hess.ndim == 2 else hess
            wk = _linear_round(
                X, g, h, w[:, k],
                self.param.reg_lambda_linear, self.param.reg_alpha_linear,
                self.param.eta_linear, cyclic,
            )
            w = w.at[:, k].set(wk)
        self.weights = np.asarray(w)

    def predict(self, X, base_margin: jax.Array) -> jax.Array:
        Xj = jnp.nan_to_num(jnp.asarray(X, jnp.float32))
        w = jnp.asarray(self.weights) if self.weights is not None else jnp.zeros(
            (Xj.shape[1] + 1, self.n_groups), jnp.float32
        )
        out = Xj @ w[:-1] + w[-1]
        return base_margin + out

    def save_json(self) -> dict:
        w = self.weights if self.weights is not None else np.zeros((1, self.n_groups), np.float32)
        return {
            "name": "gblinear",
            "model": {"weights": [float(x) for x in w.reshape(-1)], "shape": list(w.shape)},
        }

    def load_json(self, j: dict) -> None:
        shape = j["model"].get("shape")
        w = np.asarray(j["model"]["weights"], np.float32)
        self.weights = w.reshape(shape) if shape else w.reshape(-1, 1)
