"""GBTree / DART boosters.

Reference: ``src/gbm/gbtree.{h,cc}`` — ``DoBoost`` (gbtree.cc:219) slices
per-group gradients, ``BoostNewTrees`` (:319) runs the updater chain, and
``CommitModel`` (:364) appends trees + updates the prediction cache; DART
subclass at gbtree.cc:637-1020 (drop/normalize logic mirrored here line by
line from DropTrees:914 / NormalizeTrees:963).
"""

from __future__ import annotations

import dataclasses as _dc
import functools
import time as _time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import REGISTRY as _REGISTRY, trace as _trace
from ..observability import kernelprof as _kernelprof
from ..params import GBTreeParam, TrainParam
from ..predictor import StackedForest, predict_leaf, predict_margin, stack_forest
from ..registry import BOOSTERS
from ..analysis.retrace import guard_jit
from ..tree.grow import GrowParams, grow_tree, leaf_value_map, prune_heap
from ..tree.grow_fused import GrownTree, grow_tree_fused
from ..tree.model import RegTree
from ..tree.param import SplitParams
from ..utils import console_logger


def _hist_seconds():
    return _REGISTRY.histogram(
        "hist_build_seconds",
        "Host-side wall time of one tree build dispatch "
        "(hist + split + partition)")


@functools.partial(guard_jit, name="margin_add", static_argnames=("k",),
                   donate_argnames=("m",))
def _margin_add_jit(m, delta, *, k=None):
    if k is None:
        return m + delta
    return m.at[:, k].add(delta)


def _margin_add(margin_cache, delta, k):
    """Per-round prediction-cache update with the OLD margin donated: the
    round's cache buffer is updated in place instead of allocating a fresh
    [n, K] every round (ISSUE 13 donation tentpole). The caller must treat
    the passed-in cache as dead (every call site rebinds)."""
    if margin_cache.ndim == 2:
        return _margin_add_jit(margin_cache, delta, k=k)
    return _margin_add_jit(margin_cache, delta)


class _PendingTree:
    """A tree still living on device as heap-layout arrays (GrownTree minus
    the per-row delta). RegTree materialization is deferred until model IO
    or host introspection needs it — each device->host sync costs more than
    an entire tree build, so the training loop never pays it."""

    __slots__ = ("keep", "feature", "split_bin", "split_cond", "default_left",
                 "node_weight", "loss_chg", "node_h", "leaf_value", "eta",
                 "max_depth", "cat_set", "cat_mask")

    def __init__(self, g: GrownTree, eta: float, max_depth: int,
                 cat_mask=None):
        self.keep = g.keep
        self.feature = g.feature
        self.split_bin = g.split_bin
        self.split_cond = g.split_cond
        self.default_left = g.default_left
        self.node_weight = g.node_weight
        self.loss_chg = g.loss_chg
        self.node_h = g.node_h
        self.leaf_value = g.leaf_value
        self.eta = eta
        self.max_depth = max_depth
        # categorical metadata ([max_nodes, B] right-going sets + [F] bool
        # feature mask); None for pure-numerical trees
        self.cat_set = g.cat_set if cat_mask is not None else None
        self.cat_mask = cat_mask


class _PendingChunk:
    """A whole scan-chunk of trees held as the scan's native [R, K, N]
    device arrays. Slicing R*K per-tree views out of these on device was
    measured to matter: ~11 arrays x rounds tiny dispatches per chunk and
    thousands of live buffers by round 500 (the prime suspect for the
    round-3 rounds/s decay, VERDICT Weak #4) — so the chunk is stored
    as-is and trees are carved out lazily, on host, one bulk transfer per
    field per chunk."""

    __slots__ = ("fields", "R", "K", "eta", "max_depth", "_host")

    FIELDS = ("keep", "feature", "split_bin", "split_cond", "default_left",
              "node_weight", "loss_chg", "node_h", "leaf_value")

    def __init__(self, stacked: GrownTree, R: int, K: int, eta: float,
                 max_depth: int):
        self.fields = {f: getattr(stacked, f) for f in self.FIELDS}
        self.R, self.K = R, K
        self.eta, self.max_depth = eta, max_depth
        self._host = None

    @property
    def n_nodes(self) -> int:
        return int(self.fields["keep"].shape[2])

    def host(self):
        """One bulk device->host transfer per field, cached."""
        if self._host is None:
            self._host = {f: np.asarray(a) for f, a in self.fields.items()}
        return self._host

    def flat(self, f: str) -> jax.Array:
        """[R*K, N] device view in tree order (r-major, k inner) — a free
        reshape, never a per-tree slice."""
        a = self.fields[f]
        return a.reshape(a.shape[0] * a.shape[1], a.shape[2])


class _ChunkRef:
    """Per-tree placeholder into a _PendingChunk (plain python — creating
    one performs zero device operations)."""

    __slots__ = ("chunk", "r", "k")

    def __init__(self, chunk: _PendingChunk, r: int, k: int):
        self.chunk = chunk
        self.r = r
        self.k = k

    @property
    def flat_index(self) -> int:
        return self.r * self.chunk.K + self.k

    @property
    def max_depth(self) -> int:
        return self.chunk.max_depth

    @property
    def n_nodes(self) -> int:
        return self.chunk.n_nodes


class _PendingAllocChunk:
    """Lossguide twin of _PendingChunk: a scan chunk of allocation-ordered
    trees held as the scan's [R, K, M] device outputs (alloc fields +
    on-device keep/leaf_value); per-tree carving happens on host, one bulk
    transfer per field per chunk."""

    __slots__ = ("fields", "R", "K", "eta", "gamma", "max_depth",
                 "cat_mask", "_host")

    ALLOC_FIELDS = ("left", "right", "feature", "split_bin", "split_cond",
                    "default_left", "node_weight", "loss_chg", "node_h",
                    "cat_set", "n_nodes", "depth")

    def __init__(self, alloc_stacked, keep, leaf_value, R, K, eta, gamma,
                 max_depth, cat_mask):
        self.fields = {f: getattr(alloc_stacked, f)
                       for f in self.ALLOC_FIELDS}
        self.fields["keep"] = keep
        self.fields["leaf_value"] = leaf_value
        self.R, self.K = R, K
        self.eta, self.gamma = eta, gamma
        self.max_depth = max_depth
        self.cat_mask = cat_mask
        self._host = None

    def host(self):
        """Bulk transfer of exactly what RegTree.from_alloc consumes (keep/
        leaf_value/depth serve only the DEVICE stacker; cat_set only when
        categorical)."""
        if self._host is None:
            skip = {"keep", "leaf_value", "depth"}
            if self.cat_mask is None:
                skip.add("cat_set")
            self._host = {f: np.asarray(a)
                          for f, a in self.fields.items() if f not in skip}
        return self._host

    def flat(self, f: str) -> jax.Array:
        """[R*K, M] device view in tree order — a free reshape."""
        a = self.fields[f]
        return a.reshape(a.shape[0] * a.shape[1], *a.shape[2:])


class _AllocChunkRef:
    """Per-tree placeholder into a _PendingAllocChunk."""

    __slots__ = ("chunk", "r", "k")

    def __init__(self, chunk: _PendingAllocChunk, r: int, k: int):
        self.chunk = chunk
        self.r = r
        self.k = k

    @property
    def flat_index(self) -> int:
        return self.r * self.chunk.K + self.k

    @property
    def cat_mask(self):
        return self.chunk.cat_mask


def _pad_stack(arrs, n_cols: int, col_pad: int, row_pad: int, fill, dtype):
    """Stack 1-D per-tree arrays into a [row_pad, col_pad] device matrix:
    per-array pad to ``n_cols`` then to pow2 ``col_pad`` columns and
    ``row_pad`` rows (compile-reuse bucketing). Single home for the padding
    policy used by every device stacker/materializer in this module."""
    arrs = [a if a.shape[0] == n_cols
            else jnp.pad(a, (0, n_cols - a.shape[0]), constant_values=fill)
            for a in arrs]
    s = jnp.stack(arrs)
    if n_cols != col_pad:
        s = jnp.pad(s, ((0, 0), (0, col_pad - n_cols)), constant_values=fill)
    if s.shape[0] != row_pad:
        s = jnp.pad(s, ((0, row_pad - s.shape[0]), (0, 0)),
                    constant_values=fill)
    return s.astype(dtype)


class _PendingAllocTree:
    """A lossguide tree still on device (allocation-ordered arrays +
    on-device prune/leaf results). RegTree materialization via
    ``RegTree.from_alloc`` is deferred like ``_PendingTree``."""

    __slots__ = ("left", "right", "feature", "split_bin", "split_cond",
                 "default_left", "node_weight", "loss_chg", "node_h",
                 "cat_set", "keep", "leaf_value", "n_nodes", "depth",
                 "eta", "gamma", "max_depth", "cat_mask")

    def __init__(self, alloc, keep, leaf_value, eta, gamma, max_depth,
                 cat_mask):
        self.left = alloc.left
        self.right = alloc.right
        self.feature = alloc.feature
        self.split_bin = alloc.split_bin
        self.split_cond = alloc.split_cond
        self.default_left = alloc.default_left
        self.node_weight = alloc.node_weight
        self.loss_chg = alloc.loss_chg
        self.node_h = alloc.node_h
        self.cat_set = alloc.cat_set
        self.n_nodes = alloc.n_nodes
        self.depth = alloc.depth
        self.keep = keep
        self.leaf_value = leaf_value
        self.eta = eta
        self.gamma = gamma
        self.max_depth = max_depth
        self.cat_mask = cat_mask


def _materialize_pending_alloc(pending: List[_PendingAllocTree]) -> List[RegTree]:
    """Bulk host conversion of device lossguide trees (pad to common width,
    stack per field, one transfer per field)."""
    if not pending:
        return []
    fields = ("left", "right", "feature", "split_cond", "default_left",
              "node_weight", "loss_chg", "node_h", "split_bin", "n_nodes")
    sizes = [t.left.shape[0] for t in pending]
    Mmax = max(sizes)

    def stack(f):
        arrs = [getattr(t, f) for t in pending]
        if f == "n_nodes":
            return np.asarray(jnp.stack(arrs))
        arrs = [a if a.shape[0] == Mmax
                else jnp.pad(a, (0, Mmax - a.shape[0]),
                             constant_values=(-1 if f in ("left", "right")
                                              else 0))
                for a in arrs]
        return np.asarray(jnp.stack(arrs))

    st = {f: stack(f) for f in fields}
    cat_sets = None
    if any(t.cat_mask is not None for t in pending):
        cat_sets = [np.asarray(t.cat_set) for t in pending]
    out = []
    for i, t in enumerate(pending):
        m = sizes[i]
        tree, _ = RegTree.from_alloc(
            st["left"][i][:m], st["right"][i][:m], st["feature"][i][:m],
            st["split_cond"][i][:m], st["default_left"][i][:m],
            st["node_weight"][i][:m], st["loss_chg"][i][:m],
            st["node_h"][i][:m], int(st["n_nodes"][i]), eta=t.eta,
            min_split_loss=t.gamma, split_bin=st["split_bin"][i][:m],
            cat_features=t.cat_mask,
            cat_set=cat_sets[i] if cat_sets is not None else None,
        )
        out.append(tree)
    return out


def _pack_cat_bits(cat_set: jax.Array) -> jax.Array:
    """[T, M, B] bool right-going sets -> [T, M, W] uint32 bitfields
    (common/bitfield.h CatBitField layout), W pow2-padded."""
    T, M, B = cat_set.shape
    W = max(1, -(-B // 32))
    W = 1 << (W - 1).bit_length()
    if B != W * 32:
        cat_set = jnp.pad(cat_set, ((0, 0), (0, 0), (0, W * 32 - B)))
    bits = cat_set.reshape(T, M, W, 32).astype(jnp.uint32)
    weights = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return (bits * weights).sum(axis=-1, dtype=jnp.uint32)


def _stack_device_alloc(pending: List[_PendingAllocTree], tree_info,
                        n_groups: int) -> StackedForest:
    """Stacked forest from device lossguide trees — explicit child arrays
    (allocation order), pruned topology applied via ``keep``. Uses the
    XLA walk (not the heap pallas kernel). One scalar readback for the
    walk depth bound."""
    T = len(pending)
    Tp = 1 << (T - 1).bit_length() if T > 1 else 1
    M = max(t.left.shape[0] for t in pending)
    Mp = max(1, 1 << (M - 1).bit_length())

    def stack(get, fill, dtype):
        return _pad_stack([get(t) for t in pending], M, Mp, Tp, fill, dtype)

    keep = stack(lambda t: t.keep, False, bool)
    left = jnp.where(keep, stack(lambda t: t.left, -1, jnp.int32), -1)
    right = jnp.where(keep, stack(lambda t: t.right, -1, jnp.int32), -1)
    cond = jnp.where(keep,
                     stack(lambda t: t.split_cond, 0.0, jnp.float32),
                     stack(lambda t: t.leaf_value, 0.0, jnp.float32))
    feature = stack(lambda t: t.feature, 0, jnp.int32)
    has_cats = any(t.cat_mask is not None for t in pending)
    if has_cats:
        catf = [jnp.asarray(t.cat_mask) if t.cat_mask is not None
                else jnp.zeros(int(t.feature.max()) + 1, bool)
                for t in pending]
        st_rows = [cf[jnp.clip(t.feature, 0, cf.shape[0] - 1)]
                   for cf, t in zip(catf, pending)]
        split_type = jnp.stack(
            [r if r.shape[0] == M else jnp.pad(r, (0, M - r.shape[0]))
             for r in st_rows]
        )
        if M != Mp:
            split_type = jnp.pad(split_type, ((0, 0), (0, Mp - M)))
        if Tp != T:
            split_type = jnp.pad(split_type, ((0, Tp - T), (0, 0)))
        split_type = split_type & keep
        css = [t.cat_set for t in pending]
        B = max(c.shape[1] for c in css)
        css = [jnp.pad(c, ((0, M - c.shape[0]), (0, B - c.shape[1])))
               for c in css]
        cat_all = jnp.stack(css)
        if M != Mp:
            cat_all = jnp.pad(cat_all, ((0, 0), (0, Mp - M), (0, 0)))
        if Tp != T:
            cat_all = jnp.pad(cat_all, ((0, Tp - T), (0, 0), (0, 0)))
        cat_bits = _pack_cat_bits(cat_all)
    else:
        split_type = jnp.zeros((Tp, Mp), bool)
        cat_bits = jnp.zeros((Tp, Mp, 1), jnp.uint32)
    md = int(jnp.max(jnp.stack([jnp.max(t.depth) for t in pending]))) + 1
    group = np.zeros(Tp, np.int32)
    group[:T] = np.asarray(tree_info, np.int32)
    return StackedForest(
        left=left, right=right, feature=feature, cond=cond,
        default_left=stack(lambda t: t.default_left, False, bool),
        split_type=split_type, cat_bits=cat_bits,
        tree_group=jnp.asarray(group), max_depth=max(md, 1),
        n_groups=n_groups, has_cats=has_cats, heap_layout=False,
    )


def _materialize_pending(pending: List[_PendingTree]) -> List[RegTree]:
    """Convert device trees to host RegTrees in a handful of bulk transfers
    (one stacked array per field) instead of per-tree round trips."""
    if not pending:
        return []
    fields = ("keep", "feature", "split_cond", "default_left", "node_weight",
              "loss_chg", "node_h", "split_bin")
    sizes = [t.keep.shape[0] for t in pending]
    Nmax = max(sizes)

    def stack(f):
        # trees can differ in max_nodes if max_depth changed between rounds;
        # pad (zeros => leaves) to the common width before stacking
        arrs = [getattr(t, f) for t in pending]
        arrs = [a if a.shape[0] == Nmax else jnp.pad(a, (0, Nmax - a.shape[0]))
                for a in arrs]
        return np.asarray(jnp.stack(arrs))

    stacked = {f: stack(f) for f in fields}
    cat_ix = [i for i, t in enumerate(pending) if t.cat_mask is not None]
    cat_sets = {}
    if cat_ix:
        # one bulk transfer for every categorical set, like the scalar
        # fields: pad to the common [Nmax, Bmax] then stack
        Bmax = max(pending[i].cat_set.shape[1] for i in cat_ix)
        padded = [
            jnp.pad(pending[i].cat_set,
                    ((0, Nmax - pending[i].cat_set.shape[0]),
                     (0, Bmax - pending[i].cat_set.shape[1])))
            for i in cat_ix
        ]
        host_sets = np.asarray(jnp.stack(padded))
        cat_sets = {i: host_sets[j] for j, i in enumerate(cat_ix)}
    out = []
    for i, t in enumerate(pending):
        m = sizes[i]
        out.append(RegTree.from_heap(
            stacked["keep"][i][:m], stacked["feature"][i][:m],
            stacked["split_cond"][i][:m], stacked["default_left"][i][:m],
            stacked["node_weight"][i][:m], stacked["loss_chg"][i][:m],
            stacked["node_h"][i][:m], eta=t.eta,
            split_bin=stacked["split_bin"][i][:m],
            cat_features=t.cat_mask,
            cat_set=cat_sets.get(i)[:m] if i in cat_sets else None,
        ))
    return out


# (the _PendingTree-only device stacker was subsumed by _stack_device_mixed,
# which handles pure, chunk-backed, and mixed pending lists with one padding
# policy — see below)


class GBTreeModel:
    """Tree collection + group ids (reference: ``src/gbm/gbtree_model.h``).

    Trees grown by the fused TPU path are kept on device (``_PendingTree``)
    and materialized to host ``RegTree`` lazily; host-origin trees (JSON
    load, lossguide path) are stored directly."""

    def __init__(self, n_groups: int = 1, num_parallel_tree: int = 1):
        self.n_groups = n_groups
        self.num_parallel_tree = max(1, num_parallel_tree)
        self._entries: List[Any] = []  # RegTree | _PendingTree
        self.tree_info: List[int] = []
        self._stacked: Optional[StackedForest] = None
        self._stacked_count: int = -1

    def add(self, tree: RegTree, group: int) -> None:
        self._entries.append(tree)
        self.tree_info.append(group)
        self._stacked = None

    def add_device(self, grown: GrownTree, eta: float, group: int,
                   max_depth: int, cat_mask=None) -> None:
        self._entries.append(_PendingTree(grown, eta, max_depth, cat_mask))
        self.tree_info.append(group)
        self._stacked = None

    def add_device_chunk(self, stacked: GrownTree, R: int,
                         groups_per_round, eta: float,
                         max_depth: int) -> None:
        """Append a whole scan-chunk ([R, T, N] stacked heap arrays, T
        trees per round) as R*T trees WITHOUT slicing per-tree device
        arrays (see _PendingChunk). ``groups_per_round`` lists each tree
        slot's output group in the per-round order (group-major, parallel
        trees inner — matching boost_one_round / BoostNewTrees)."""
        T = len(groups_per_round)
        chunk = _PendingChunk(stacked, R, T, eta, max_depth)
        for r in range(R):
            for idx, grp in enumerate(groups_per_round):
                self._entries.append(_ChunkRef(chunk, r, idx))
                self.tree_info.append(int(grp))
        self._stacked = None

    def add_device_alloc_chunk(self, alloc_stacked, keep, leaf_value,
                               R: int, K: int, eta: float, gamma: float,
                               max_depth: int, cat_mask) -> None:
        """Lossguide twin of add_device_chunk: a whole scan chunk appended
        without slicing per-tree device arrays."""
        chunk = _PendingAllocChunk(alloc_stacked, keep, leaf_value, R, K,
                                   eta, gamma, max_depth, cat_mask)
        for r in range(R):
            for k in range(K):
                self._entries.append(_AllocChunkRef(chunk, r, k))
                self.tree_info.append(k)
        self._stacked = None

    def add_device_alloc(self, alloc, keep, leaf_value, eta: float,
                         gamma: float, group: int, max_depth: int,
                         cat_mask) -> None:
        self._entries.append(_PendingAllocTree(
            alloc, keep, leaf_value, eta, gamma, max_depth, cat_mask
        ))
        self.tree_info.append(group)
        self._stacked = None

    @property
    def trees(self) -> List[RegTree]:
        heap_ix = [i for i, e in enumerate(self._entries)
                   if isinstance(e, _PendingTree)]
        alloc_ix = [i for i, e in enumerate(self._entries)
                    if isinstance(e, _PendingAllocTree)]
        ref_any = any(isinstance(e, (_ChunkRef, _AllocChunkRef))
                      for e in self._entries)
        if ref_any:
            _materialize_chunk_refs(self._entries)
            _materialize_alloc_chunk_refs(self._entries)
        if heap_ix:
            converted = _materialize_pending(
                [self._entries[i] for i in heap_ix]
            )
            for i, t in zip(heap_ix, converted):
                self._entries[i] = t
        if alloc_ix:
            converted = _materialize_pending_alloc(
                [self._entries[i] for i in alloc_ix]
            )
            for i, t in zip(alloc_ix, converted):
                self._entries[i] = t
        if heap_ix or alloc_ix or ref_any:
            # a device-stacked forest uses raw device node ids; after
            # materialization node ids are BFS-compacted — rebuild so
            # pred_leaf etc. are consistent with the saved model
            self._stacked = None
        return self._entries

    @property
    def num_trees(self) -> int:
        return len(self._entries)

    def stacked(self) -> StackedForest:
        if self._stacked is not None and self._stacked_count == len(self._entries):
            return self._stacked
        self._stacked = self.stacked_slice(0, len(self._entries))
        self._stacked_count = len(self._entries)
        return self._stacked

    def stacked_slice(self, lo: int, hi: int) -> StackedForest:
        """Stacked forest over trees [lo, hi) WITHOUT materializing pending
        device trees when the slice is uniformly device-resident — neither
        the incremental prediction-cache catch-up nor per-round DART
        repredicts may trigger host syncs mid-training (gbtree.cc:519)."""
        ents = self._entries[lo:hi]
        if ents and all(
            isinstance(e, (_PendingTree, _ChunkRef))
            and getattr(e, "cat_mask", None) is None
            for e in ents
        ):
            # (categorical pending trees fall through to host
            # materialization — their bitset packing lives in RegTree)
            return _stack_device_mixed(ents, self.tree_info[lo:hi],
                                       self.n_groups)
        if ents and all(isinstance(e, _PendingAllocTree) for e in ents):
            return _stack_device_alloc(ents, self.tree_info[lo:hi],
                                       self.n_groups)
        if ents and all(
            isinstance(e, (_PendingAllocTree, _AllocChunkRef))
            and getattr(e, "cat_mask", None) is None
            for e in ents
        ):
            return _stack_device_alloc_mixed(ents, self.tree_info[lo:hi],
                                             self.n_groups)
        trees = self.trees[lo:hi]
        return stack_forest(trees, self.tree_info[lo:hi], self.n_groups)

    def slice(self, begin: int, end: int, step: int = 1) -> "GBTreeModel":
        out = GBTreeModel(self.n_groups, self.num_parallel_tree)
        # layered slicing: rounds -> trees_per_round trees (gbtree slicing
        # semantics operate on boosting rounds; one round appends
        # n_groups * num_parallel_tree trees — gbtree.cc:326)
        trees = self.trees
        per_round = max(1, self.n_groups) * self.num_parallel_tree
        for r in range(begin, end, step):
            for t in range(r * per_round, min((r + 1) * per_round, len(trees))):
                out.add(trees[t], self.tree_info[t])
        return out


def _cat_cfg(cfg: GrowParams, binned, tp) -> Tuple[GrowParams, Any]:
    """Apply the one-hot vs optimal-partition gate (reference UseOneHot,
    evaluate_splits.h: one-hot when n_cats < max_cat_to_onehot) to a grow
    config. Single home for the rule so the fused and lossguide growers
    cannot diverge. Returns (cfg, cat_mask or None)."""
    cats = tuple(getattr(binned, "categorical", ()))
    if not cats:
        return cfg, None
    counts = tuple(getattr(binned, "cat_counts", ())) or (0,) * len(cats)
    onehot_f = tuple(f for f, c in zip(cats, counts)
                     if c < tp.max_cat_to_onehot)
    part_f = tuple(f for f, c in zip(cats, counts)
                   if c >= tp.max_cat_to_onehot)
    cfg = _dc.replace(cfg, categorical=onehot_f, cat_partition=part_f)
    return cfg, cfg.cat_mask_np(binned.n_features)


def round_seed_py(seed: int, iteration: int, k: int = 0,
                  ptree: int = 0) -> int:
    """Per-tree RNG seed (python-int path). The traced twin
    ``round_seed_traced`` MUST stay in lockstep — the scan paths' identity
    with per-round training depends on it."""
    return (seed * 1000003 + iteration * 131 + k * 17 + ptree) & 0x7FFFFFFF


def round_seed_traced(seed_base_u32, i, k: int = 0, ptree: int = 0):
    """Traced twin of ``round_seed_py`` for scan bodies: ``seed_base_u32``
    is uint32((seed * 1000003) & 0xFFFFFFFF); the 31-bit mask reads only
    low bits, which uint32 arithmetic preserves, so the two formulas agree
    bit for bit."""
    return (seed_base_u32 + i.astype(jnp.uint32) * jnp.uint32(131)
            + jnp.uint32(k * 17 + ptree)) & jnp.uint32(0x7FFFFFFF)


def _mesh_active() -> bool:
    from ..parallel.mesh import current_mesh

    mesh = current_mesh()
    return mesh is not None and mesh.devices.size > 1


def _obj_fingerprint(obj) -> tuple:
    """Hashable snapshot of the scalar params an objective can read at
    trace time. Part of the scan's static jit key so mutating params via
    set_param between update_many calls retraces instead of silently
    reusing gradients compiled with the old values."""
    p = getattr(obj, "params", None)
    fields = getattr(p, "FIELDS", None)
    if p is None or not fields:
        return ()
    return tuple(
        (k, v) for k in sorted(fields)
        for v in (getattr(p, k, None),)
        if isinstance(v, (int, float, str, bool, type(None)))
    )


@functools.partial(guard_jit, name="scan_rounds",
                   static_argnames=("obj", "obj_fp", "cfg", "n", "n_pad",
                                    "n_groups", "n_parallel"),
                   donate_argnames=("m_pad",))
def _scan_rounds_impl(binsf, label, weight, m_pad, iters, cut_vals, eta,
                      gamma, fw, seed_base, onehot=None, *, obj, obj_fp,
                      cfg, n, n_pad, n_groups, n_parallel=1):
    """Multi-round boosting as one program: scan body = gradient -> fused
    tree(s) -> margin update (one tree per output group, like DoBoost's
    per-group gradient slicing, gbtree.cc:219). Cache key includes the
    objective INSTANCE (its params are read at trace time) and the static
    grow config; equal-length chunks reuse the compile. The carried margin
    is DONATED (ISSUE 13: async executor + donation): each chunk's margin
    buffer is reused in place instead of re-allocated, so the steady-state
    live-buffer count is flat across a whole training run — the caller's
    input margin is dead after the call (update_many re-points the cache
    at the returned one)."""
    K = n_groups

    def pad0(v):
        if n_pad == n:
            return v
        return jnp.concatenate([v, jnp.zeros((n_pad - n,), jnp.float32)])

    def body(m_pad, i):
        m = m_pad[:n, 0] if K == 1 else m_pad[:n]
        g, h = obj.get_gradient(m, label, weight, i)
        trees = []
        for k in range(K):
            gk = pad0(g[:, k] if g.ndim == 2 else g)
            hk = pad0(h[:, k] if h.ndim == 2 else h)
            for pt in range(n_parallel):
                # bit-identical to boost_one_round's python-int key
                # formula: the 31-bit mask reads only low bits
                seed = round_seed_traced(seed_base, i, k, pt)
                key = jax.random.PRNGKey(seed.astype(jnp.int32))
                t = grow_tree_fused(binsf, gk, hk, cut_vals, key, eta,
                                    gamma, cfg, feature_weights=fw,
                                    onehot=onehot)
                m_pad = m_pad.at[:, k].add(t.delta)
                trees.append(
                    t._replace(delta=jnp.zeros((0,), jnp.float32)))
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)
        return m_pad, stacked

    return jax.lax.scan(body, m_pad, iters)


@functools.partial(guard_jit, name="scan_rounds_lossguide",
                   static_argnames=("obj", "obj_fp", "cfg", "n_groups",
                                    "max_leaves"),
                   donate_argnames=("m_cur",))
def _scan_rounds_lossguide_impl(bins, label, weight, m_cur, iters, cut_vals,
                                eta, gamma, fw, seed_base, *, obj, obj_fp,
                                cfg, n_groups, max_leaves):
    """Lossguide variant of the multi-round scan: body = gradient ->
    allocation-ordered growth (grow_tree_lossguide) -> on-device prune /
    leaf values / delta (finalize_alloc) -> margin update. Per-row
    positions are stripped from the stacked outputs (only the delta uses
    them)."""
    from ..tree.grow_lossguide import finalize_alloc, grow_tree_lossguide

    K = n_groups

    def body(m_cur, i):
        m = m_cur[:, 0] if K == 1 else m_cur
        g, h = obj.get_gradient(m, label, weight, i)
        outs = []
        for k in range(K):
            gk = g[:, k] if g.ndim == 2 else g
            hk = h[:, k] if h.ndim == 2 else h
            seed = round_seed_traced(seed_base, i, k)
            key = jax.random.PRNGKey(seed.astype(jnp.int32))
            alloc = grow_tree_lossguide(bins, gk, hk, cut_vals, key, cfg,
                                        max_leaves, fw)
            keep, lv, delta = finalize_alloc(alloc, eta, gamma)
            m_cur = m_cur.at[:, k].add(delta)
            outs.append((alloc._replace(
                positions=jnp.zeros((0,), jnp.int32)), keep, lv))
        stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *outs)
        return m_cur, stacked

    return jax.lax.scan(body, m_cur, iters)


def _chunked_field2d(entries: List[Any], ref_type, name: str, Np: int,
                     Tp: int, fill, dtype) -> jax.Array:
    """[Tp, Np] device matrix of one per-tree field over a mixed pending
    list: consecutive ``ref_type`` refs into the same chunk contribute ONE
    reshape+slice of the chunk's [R*K, ...] view; plain pending trees
    contribute their own array. Shared by both mixed stackers so the
    run-detection/padding policy has a single home."""
    T = len(entries)
    segs = []
    i = 0
    while i < T:
        e = entries[i]
        if isinstance(e, ref_type):
            c, start = e.chunk, e.flat_index
            j = i + 1
            while (j < T and isinstance(entries[j], ref_type)
                   and entries[j].chunk is c
                   and entries[j].flat_index == start + (j - i)):
                j += 1
            seg = c.flat(name)[start:start + (j - i)]
            i = j
        else:
            seg = getattr(e, name)[None]
            i += 1
        if seg.shape[1] != Np:
            seg = jnp.pad(seg, ((0, 0), (0, Np - seg.shape[1])),
                          constant_values=fill)
        segs.append(seg)
    s = segs[0] if len(segs) == 1 else jnp.concatenate(segs)
    if s.shape[0] != Tp:
        s = jnp.pad(s, ((0, Tp - s.shape[0]), (0, 0)), constant_values=fill)
    return s.astype(dtype)


def _stack_device_alloc_mixed(entries: List[Any], tree_info,
                              n_groups: int) -> StackedForest:
    """Device-stacked forest over a mixture of _PendingAllocTree and
    _AllocChunkRef entries (numerical-only — categorical lossguide never
    reaches the scan path): consecutive refs into one chunk contribute one
    reshape+slice, like _stack_device_mixed for the depthwise twin."""
    T = len(entries)
    Tp = 1 << (T - 1).bit_length() if T > 1 else 1

    def width(e):
        if isinstance(e, _AllocChunkRef):
            return int(e.chunk.fields["left"].shape[2])
        return int(e.left.shape[0])

    M = max(width(e) for e in entries)
    Mp = max(1, 1 << (M - 1).bit_length())

    def field2d(name, fill, dtype):
        return _chunked_field2d(entries, _AllocChunkRef, name, Mp, Tp,
                                fill, dtype)

    keep = field2d("keep", False, bool)
    left = jnp.where(keep, field2d("left", -1, jnp.int32), -1)
    right = jnp.where(keep, field2d("right", -1, jnp.int32), -1)
    cond = jnp.where(keep, field2d("split_cond", 0.0, jnp.float32),
                     field2d("leaf_value", 0.0, jnp.float32))
    # Static depth bound — reading fields["depth"] here would force a
    # device->host sync inside the no-sync catch-up path (ADVICE r4). When
    # cfg max_depth is 0 (unbounded lossguide), a tree over M=2L-1 alloc
    # slots has depth <= L-1 = (M-1)//2; an over-estimate only costs walk
    # iterations, never correctness.
    def depth_bound(e):
        cap = e.chunk.max_depth if isinstance(e, _AllocChunkRef) else e.max_depth
        return cap if cap and cap > 0 else (width(e) - 1) // 2

    md = 1 + max(depth_bound(e) for e in entries)
    group = np.zeros(Tp, np.int32)
    group[:T] = np.asarray(tree_info, np.int32)
    return StackedForest(
        left=left, right=right,
        feature=field2d("feature", 0, jnp.int32), cond=cond,
        default_left=field2d("default_left", False, bool),
        split_type=jnp.zeros((Tp, Mp), bool),
        cat_bits=jnp.zeros((Tp, Mp, 1), jnp.uint32),
        tree_group=jnp.asarray(group), max_depth=max(md, 1),
        n_groups=n_groups, has_cats=False, heap_layout=False,
    )


def _materialize_alloc_chunk_refs(entries: List[Any]) -> None:
    """Replace every _AllocChunkRef (in place) with a host RegTree; one
    bulk transfer per field per chunk, numpy slicing per tree. from_alloc
    re-runs the gamma prune host-side exactly like the per-tree
    materializer (_materialize_pending_alloc)."""
    for i, e in enumerate(entries):
        if not isinstance(e, _AllocChunkRef):
            continue
        h = e.chunk.host()
        c = e.chunk
        r, k = e.r, e.k
        tree, _ = RegTree.from_alloc(
            h["left"][r, k], h["right"][r, k], h["feature"][r, k],
            h["split_cond"][r, k], h["default_left"][r, k],
            h["node_weight"][r, k], h["loss_chg"][r, k], h["node_h"][r, k],
            int(h["n_nodes"][r, k]), eta=c.eta, min_split_loss=c.gamma,
            split_bin=h["split_bin"][r, k], cat_features=c.cat_mask,
            cat_set=(h["cat_set"][r, k] if c.cat_mask is not None else None),
        )
        entries[i] = tree


def _materialize_chunk_refs(entries: List[Any]) -> None:
    """Replace every _ChunkRef in ``entries`` (in place) with a host
    RegTree; each distinct chunk pays one bulk transfer per field and the
    per-tree carving is numpy slicing."""
    for i, e in enumerate(entries):
        if not isinstance(e, _ChunkRef):
            continue
        h = e.chunk.host()
        r, k = e.r, e.k
        entries[i] = RegTree.from_heap(
            h["keep"][r, k], h["feature"][r, k], h["split_cond"][r, k],
            h["default_left"][r, k], h["node_weight"][r, k],
            h["loss_chg"][r, k], h["node_h"][r, k], eta=e.chunk.eta,
            split_bin=h["split_bin"][r, k],
        )


def _stack_device_mixed(entries: List[Any], tree_info, n_groups: int
                        ) -> StackedForest:
    """Stacked forest directly from device heap trees — no host transfer.
    Heap layout is itself a valid node indexing (children of i at
    2i+1/2i+2); leaves carry their governing (pruned) leaf value; the tree
    list is padded to a power of two so the predictor recompiles only
    log2(T) times over a training run. Handles any mixture of _PendingTree
    and _ChunkRef entries: consecutive refs into the same chunk contribute
    ONE reshape+slice of the chunk's [R*K, N] arrays (a handful of device
    ops per chunk) instead of per-tree slices."""
    T = len(entries)
    Tp = 1 << (T - 1).bit_length() if T > 1 else 1
    N = max(e.n_nodes if isinstance(e, _ChunkRef) else e.keep.shape[0]
            for e in entries)
    Np = max(1, 1 << (N - 1).bit_length())
    md = max(e.max_depth for e in entries)

    def field2d(name, fill, dtype):
        return _chunked_field2d(entries, _ChunkRef, name, Np, Tp, fill,
                                dtype)

    keep = field2d("keep", False, bool)
    iota = jnp.arange(Np, dtype=jnp.int32)[None, :]
    cond = jnp.where(keep, field2d("split_cond", 0.0, jnp.float32),
                     field2d("leaf_value", 0.0, jnp.float32))
    group = np.zeros(Tp, np.int32)
    group[:T] = np.asarray(tree_info, np.int32)
    return StackedForest(
        left=jnp.where(keep, 2 * iota + 1, -1),
        right=jnp.where(keep, 2 * iota + 2, -1),
        feature=field2d("feature", 0, jnp.int32),
        cond=cond,
        default_left=field2d("default_left", False, bool),
        split_type=jnp.zeros((Tp, Np), bool),
        cat_bits=jnp.zeros((Tp, Np, 1), jnp.uint32),
        tree_group=jnp.asarray(group),
        max_depth=max(md, 1),
        n_groups=n_groups,
        has_cats=False,
        heap_layout=True,
    )


@BOOSTERS.register("gbtree")
class GBTree:
    """Boosting orchestration over the tpu_hist grower."""

    name = "gbtree"

    def __init__(self, n_groups: int, params: Dict[str, Any]):
        self.n_groups = max(1, n_groups)
        self.gbtree_param = GBTreeParam()
        rest = self.gbtree_param.update(dict(params))
        self.train_param = TrainParam()
        self.train_param.update(rest)
        self.model = GBTreeModel(self.n_groups, self.gbtree_param.num_parallel_tree)
        self._configure_method()

    #: updater registry names the tree path honors (reference:
    #: tree_updater.h registry; every grow_* maps onto the tpu_hist grower
    #: the way the reference maps them onto updater sequences,
    #: gbtree.cc:158-190)
    _KNOWN_UPDATERS = {
        "grow_quantile_histmaker": "grow", "grow_histmaker": "grow",
        "grow_local_histmaker": "grow", "grow_colmaker": "grow",
        "grow_gpu_hist": "grow", "grow_fast_histmaker": "grow",
        "distcol": "grow", "prune": "prune", "refresh": "refresh",
        "sync": "sync",
    }

    def _configure_method(self) -> None:
        tm = self.gbtree_param.tree_method
        # every quantile-hist family method maps onto the tpu_hist grower;
        # exact is realized as exact binning (cuts at every distinct value,
        # compute_exact_cuts) + the same fixed-shape level program — the
        # colmaker candidate set without its data-dependent column scans
        if tm not in ("auto", "exact", "hist", "gpu_hist", "tpu_hist",
                      "approx"):
            raise ValueError(f"Unknown tree_method: {tm}")
        # explicit updater sequence overrides tree_method (gbtree.cc:158):
        # grow_* -> the fused grower; refresh -> the refresh pass; unknown
        # names are an error, not a silent no-op
        self._updater_seq = []
        if self.gbtree_param.updater:
            for name in str(self.gbtree_param.updater).split(","):
                name = name.strip()
                if name and name not in self._KNOWN_UPDATERS:
                    raise ValueError(f"Unknown updater: {name!r}")
                if name:
                    self._updater_seq.append(name)
            roles = {self._KNOWN_UPDATERS[u] for u in self._updater_seq}
            if "prune" in self._updater_seq and "grow" not in roles \
                    and "refresh" not in roles:
                # prune-only sequences (re-prune an existing model without
                # growing) are a distinct reference behavior we don't have;
                # gamma pruning is built into the growers
                raise NotImplementedError(
                    "standalone updater='prune' is not supported; pruning "
                    "runs inside every grower (gamma)"
                )
        if self.train_param.sampling_method not in ("uniform", "gradient_based"):
            raise ValueError(
                f"Unknown sampling_method: {self.train_param.sampling_method}"
            )
        if self.gbtree_param.process_type not in ("default", "update"):
            raise ValueError(
                f"Unknown process_type: {self.gbtree_param.process_type}"
            )
        if not self.train_param.single_precision_histogram:
            console_logger.warning(
                "single_precision_histogram=False (float64 histograms) is "
                "not available on TPU; using deterministic hi/lo bf16 "
                "accumulation (~f32 precision)"
            )
        if self.train_param.is_explicit("sketch_eps"):
            console_logger.warning(
                "sketch_eps is superseded by max_bin on the tpu_hist sketch "
                "(reference hist makes the same substitution)"
            )
        if self.train_param.is_explicit("sparse_threshold"):
            console_logger.warning(
                "sparse_threshold has no effect: the TPU quantized matrix is "
                "dense ELLPACK-style (missing encoded as a null bin)"
            )
        if self.gbtree_param.predictor not in (
            "auto", "cpu_predictor", "gpu_predictor", "tpu_predictor"
        ):
            raise ValueError(f"Unknown predictor: {self.gbtree_param.predictor}")
        if self.gbtree_param.is_explicit("predictor") and (
            self.gbtree_param.predictor in ("cpu_predictor", "gpu_predictor")
        ):
            console_logger.warning(
                "predictor=%s requested; the TPU stacked-forest predictor "
                "is always used" % self.gbtree_param.predictor
            )

    @property
    def _is_update_process(self) -> bool:
        return (
            self.gbtree_param.process_type == "update"
            or "refresh" in getattr(self, "_updater_seq", [])
        )

    @property
    def needs_exact_cuts(self) -> bool:
        """tree_method='exact' / updater='grow_colmaker': train on the
        exact-greedy candidate set (one bin per distinct value,
        ``compute_exact_cuts``) instead of quantile cuts — the TPU
        realization of ``src/tree/updater_colmaker.cc``."""
        return (
            self.gbtree_param.tree_method == "exact"
            or "grow_colmaker" in getattr(self, "_updater_seq", [])
        )

    @property
    def needs_local_sketch(self) -> bool:
        """``updater='grow_local_histmaker'``: per-NODE hessian-weighted
        cut re-proposal every level (``src/tree/updater_histmaker.cc:753``
        CQHistMaker / registration :25) — the grower re-sketches each
        expand node's rows and evaluates it against its OWN cuts
        (``tree/grow_local.py``), unlike the global per-iteration proposal
        of ``approx``."""
        return "grow_local_histmaker" in getattr(self, "_updater_seq", [])

    @property
    def needs_iteration_sketch(self) -> bool:
        """tree_method='approx': the reference's histmaker re-proposes the
        candidate cuts EVERY iteration from hessian-weighted sketches
        (``src/tree/updater_histmaker.cc:639`` SerializeReducer AllReduce of
        per-iteration WXQSketches); hist/tpu_hist sketch once. The learner
        rebuilds the quantized matrix per round with hessian weights when
        this is set."""
        return (
            self.gbtree_param.tree_method == "approx"
            or "grow_histmaker" in getattr(self, "_updater_seq", [])
        )

    def _lossguide_max_leaves(self) -> int:
        """Default leaf budget: bounded by depth when small, else a fixed
        255 cap — the fixed-shape grower sizes its tensors and loop trips
        by this, so it must stay modest (users wanting more set max_leaves
        explicitly, as the reference requires for lossguide)."""
        tp = self.train_param
        if tp.max_leaves:
            return tp.max_leaves
        if 0 < tp.max_depth <= 8:
            return 1 << tp.max_depth
        return 255

    def _grow_params(self, axis_name: Optional[str] = None) -> GrowParams:
        tp = self.train_param
        from ..native import boundary as _boundary

        return GrowParams(
            native_caps=_boundary.cap_snapshot(),
            max_depth=tp.max_depth,
            subsample=tp.subsample,
            sampling_method=tp.sampling_method,
            colsample_bytree=tp.colsample_bytree,
            colsample_bylevel=tp.colsample_bylevel,
            colsample_bynode=tp.colsample_bynode,
            split=SplitParams(
                reg_lambda=tp.reg_lambda,
                reg_alpha=tp.reg_alpha,
                max_delta_step=tp.max_delta_step,
                min_child_weight=tp.min_child_weight,
                min_split_loss=tp.gamma,
            ),
            monotone=tuple(int(c) for c in tp.monotone_constraints),
            interaction=tuple(
                tuple(int(f) for f in grp) for grp in tp.interaction_constraints
            ),
            axis_name=axis_name,
        )

    def set_param(self, key: str, value: Any) -> None:
        rest = self.gbtree_param.update({key: value})
        self.train_param.update(rest)
        if key in ("updater", "process_type", "tree_method",
                   "sampling_method"):
            self._configure_method()  # refresh the updater sequence/flags

    # ------------------------------------------------------------------
    def boost_one_round(
        self,
        binned,
        grad: jax.Array,  # [n, K]
        hess: jax.Array,
        iteration: int,
        margin_cache: Optional[jax.Array],  # [n, K] updated in place-ish
        feature_weights: Optional[jax.Array] = None,
    ) -> Tuple[List[RegTree], Optional[jax.Array]]:
        """One boosting round: K groups x num_parallel_tree new trees.
        Returns (new trees, updated margin cache). The cache update is the
        UpdatePredictionCache fast path — leaf values gathered at each row's
        final grower position, no predictor pass (gbtree.cc:219).

        Under an active mesh (``mesh_context``), rows are sharded over the
        mesh and trees grow via the shard_map'd growers with psum'd
        histograms — the reference's inter-node data-parallel strategy
        (dsplit=row, histogram.h:201) with zero changes above this layer."""
        from ..parallel.mesh import current_mesh

        tp = self.train_param
        cfg = self._grow_params()
        mesh = current_mesh()
        use_mesh = mesh is not None and mesh.devices.size > 1
        if use_mesh and jax.process_count() > 1:
            # covers EVERY per-round branch (fused, lossguide, legacy):
            # per-round margin deltas stay device-sharded across processes
            raise NotImplementedError(
                "multi-process training runs through update_many (scan) "
                "chunks; see docs/distributed.md"
            )
        cats = tuple(getattr(binned, "categorical", ()))
        lossguide_pol = tp.grow_policy == "lossguide"
        # fast path: fused per-level kernels, device-resident trees, zero
        # host syncs per round (depthwise incl. categorical; mesh-aware)
        if not lossguide_pol:
            return self._boost_fused(binned, grad, hess, iteration,
                                     margin_cache, feature_weights)
        if getattr(binned, "is_paged", False):
            raise NotImplementedError(
                "external-memory matrices support depthwise numerical "
                "training only (reference external memory has the same "
                "hist-only restriction)"
            )
        cfg, cat_mask = _cat_cfg(cfg, binned, tp)
        cuts = binned.cuts
        cut_vals = jnp.asarray(cuts.values)
        lossguide = tp.grow_policy == "lossguide"
        if lossguide:
            max_leaves = self._lossguide_max_leaves()
        new_trees: List[RegTree] = []
        if use_mesh:
            from ..parallel.grow import (
                distributed_grow_tree,
                distributed_grow_tree_lossguide,
            )
            from ..parallel.mesh import shard_rows

            bins_sh, n_pad = binned.sharded(mesh)
            n_rows = binned.n_rows

            def _shard_gh(v: jax.Array) -> jax.Array:
                if n_pad != n_rows:
                    v = jnp.concatenate(
                        [v, jnp.zeros((n_pad - n_rows,), v.dtype)]
                    )
                return shard_rows(v, mesh)

        for k in range(self.n_groups):
            g = grad[:, k] if grad.ndim == 2 else grad
            h = hess[:, k] if hess.ndim == 2 else hess
            if use_mesh:
                g, h = _shard_gh(g), _shard_gh(h)
            for ptree in range(self.gbtree_param.num_parallel_tree):
                key = jax.random.PRNGKey(
                    round_seed_py(tp.seed, iteration, k, ptree)
                )
                fw = (
                    jnp.asarray(feature_weights)
                    if feature_weights is not None
                    else None
                )
                if lossguide:
                    from ..tree.grow_lossguide import (
                        finalize_alloc,
                        grow_tree_lossguide,
                    )

                    t0 = _time.perf_counter()
                    with _trace.span("build_tree", iteration=iteration,
                                     group=k, policy="lossguide"):
                        if use_mesh:
                            alloc = distributed_grow_tree_lossguide(
                                mesh, bins_sh, g, h, cut_vals, key, cfg,
                                max_leaves, fw
                            )
                        else:
                            alloc = grow_tree_lossguide(
                                binned.bins, g, h, cut_vals, key, cfg,
                                max_leaves, fw
                            )
                    _hist_seconds().observe(_time.perf_counter() - t0)
                    # on-device prune/leaf-values/delta: the lossguide round
                    # performs zero host syncs, like the fused depthwise path
                    keep, lv, delta_full = finalize_alloc(
                        alloc, jnp.float32(tp.eta), jnp.float32(tp.gamma)
                    )
                    self.model.add_device_alloc(
                        alloc, keep, lv, tp.eta, tp.gamma, k, tp.max_depth,
                        cat_mask,
                    )
                    new_trees.append(alloc)
                    if margin_cache is not None:
                        delta = delta_full
                        if use_mesh and delta.shape[0] != binned.n_rows:
                            delta = delta[: binned.n_rows]
                        margin_cache = _margin_add(margin_cache, delta, k)
                    continue
                else:
                    t0 = _time.perf_counter()
                    with _trace.span("build_tree", iteration=iteration,
                                     group=k):
                        if use_mesh:
                            heap = distributed_grow_tree(
                                mesh, bins_sh, g, h, cut_vals, key, cfg, fw
                            )
                        else:
                            heap = grow_tree(binned.bins, g, h, cut_vals,
                                             key, cfg, fw)
                    _hist_seconds().observe(_time.perf_counter() - t0)
                    is_split = np.asarray(heap.is_split)
                    loss_chg = np.asarray(heap.loss_chg)
                    pruned = prune_heap(is_split, loss_chg, tp.gamma)
                    tree = RegTree.from_heap(
                        pruned,
                        np.asarray(heap.feature),
                        np.asarray(heap.split_cond),
                        np.asarray(heap.default_left),
                        np.asarray(heap.node_weight),
                        loss_chg,
                        np.asarray(heap.node_h),
                        eta=tp.eta,
                        split_bin=np.asarray(heap.split_bin),
                        cat_features=cat_mask,
                        cat_set=(
                            np.asarray(heap.cat_set) if cfg.has_categorical else None
                        ),
                    )
                    lmap_np = leaf_value_map(pruned, np.asarray(heap.node_weight), tp.eta)
                    positions = heap.positions
                self.model.add(tree, k)
                new_trees.append(tree)
                if margin_cache is not None:
                    delta = jnp.asarray(lmap_np)[positions]
                    if use_mesh and delta.shape[0] != binned.n_rows:
                        delta = delta[: binned.n_rows]  # drop inert padding
                    margin_cache = _margin_add(margin_cache, delta, k)
        return new_trees, margin_cache

    # ------------------------------------------------------------------
    def local_boost_one_round(self, X, grad, hess, iteration, margin_cache,
                              feature_weights=None):
        """One boosting round via the LOCAL histmaker
        (``updater='grow_local_histmaker'``): trees grow on RAW values with
        per-node re-sketched cuts (``tree/grow_local.py``) instead of the
        global quantized matrix. Same model/caching contract as the legacy
        ``boost_one_round`` loop."""
        from ..parallel.mesh import current_mesh
        from ..tree.grow_local import grow_tree_local

        tp = self.train_param
        mesh = current_mesh()
        if mesh is not None and mesh.devices.size > 1:
            raise NotImplementedError(
                "grow_local_histmaker is single-process/single-device; "
                "use tree_method='tpu_hist' under a mesh")
        if tp.grow_policy == "lossguide":
            raise NotImplementedError(
                "grow_local_histmaker is depthwise (the reference's "
                "histmaker family has no lossguide variant)")
        cfg = self._grow_params()
        X = jnp.asarray(X, jnp.float32)
        new_trees: List[RegTree] = []
        for k in range(self.n_groups):
            g = grad[:, k] if grad.ndim == 2 else grad
            h = hess[:, k] if hess.ndim == 2 else hess
            for ptree in range(self.gbtree_param.num_parallel_tree):
                key = jax.random.PRNGKey(
                    round_seed_py(tp.seed, iteration, k, ptree))
                fw = (jnp.asarray(feature_weights)
                      if feature_weights is not None else None)
                heap = grow_tree_local(X, g, h, key, cfg, tp.max_bin, fw)
                is_split = np.asarray(heap.is_split)
                loss_chg = np.asarray(heap.loss_chg)
                pruned = prune_heap(is_split, loss_chg, tp.gamma)
                tree = RegTree.from_heap(
                    pruned,
                    np.asarray(heap.feature),
                    np.asarray(heap.split_cond),
                    np.asarray(heap.default_left),
                    np.asarray(heap.node_weight),
                    loss_chg,
                    np.asarray(heap.node_h),
                    eta=tp.eta,
                    split_bin=np.asarray(heap.split_bin),
                )
                lmap_np = leaf_value_map(pruned, np.asarray(heap.node_weight),
                                         tp.eta)
                self.model.add(tree, k)
                new_trees.append(tree)
                if margin_cache is not None:
                    delta = jnp.asarray(lmap_np)[heap.positions]
                    margin_cache = _margin_add(margin_cache, delta, k)
        return new_trees, margin_cache

    # ------------------------------------------------------------------
    def refresh_one_round(self, X, grad, hess, iteration):
        """``process_type=update`` / ``updater=refresh``: recompute node
        statistics — and leaf values when ``refresh_leaf`` — of the existing
        model's trees against the current data/gradients, adding NO new
        trees (reference: ``src/tree/updater_refresh.cc:162``,
        ``TreeProcessType`` ``src/gbm/gbtree.h:42``)."""
        from ..predictor import predict_leaf as _pl
        from ..predictor import stack_forest as _sf
        from ..tree.param import calc_weight

        per_round = max(1, self.n_groups) * self.gbtree_param.num_parallel_tree
        if not hasattr(self, "_update_queue") or self._update_queue is None:
            trees = self.model.trees
            if not trees:
                raise ValueError(
                    "process_type=update requires an existing model "
                    "(pass xgb_model / load_model first)"
                )
            self._update_queue = list(zip(trees, self.model.tree_info))
            self.model = GBTreeModel(self.n_groups,
                                     self.gbtree_param.num_parallel_tree)
        if not self._update_queue:
            raise ValueError(
                "num_boost_round exceeds the number of trees to update "
                "(reference gbtree.cc process_type=update contract)"
            )
        batch = self._update_queue[:per_round]
        self._update_queue = self._update_queue[per_round:]
        tp = self.train_param
        p = self._grow_params().split
        eta = tp.eta
        Xj = jnp.asarray(X, jnp.float32)
        new_trees = []
        for slot, (tree, group) in enumerate(batch):
            g = grad[:, group] if grad.ndim == 2 else grad
            h = hess[:, group] if hess.ndim == 2 else hess
            leaves = np.asarray(
                _pl(_sf([tree], [group], self.n_groups), Xj)
            )[:, 0]
            nn = tree.num_nodes
            G = np.zeros(nn, np.float64)
            H = np.zeros(nn, np.float64)
            np.add.at(G, leaves, np.asarray(g, np.float64))
            np.add.at(H, leaves, np.asarray(h, np.float64))
            # push leaf sums up; BFS ids => parents precede children
            for i in range(nn - 1, 0, -1):
                par = tree.parents[i]
                G[par] += G[i]
                H[par] += H[i]
            tree.sum_hessian = H.astype(np.float32)
            w = np.asarray(
                calc_weight(jnp.asarray(G, jnp.float32),
                            jnp.asarray(H, jnp.float32), p)
            )
            tree.base_weights = (eta * w).astype(np.float32)
            # refresh loss_chg too: gain(L) + gain(R) - gain(self) on the
            # NEW stats for internal nodes, 0 for leaves
            # (updater_refresh.cc:148-151; pinned by the golden fixture —
            # CalcGain's min_child_weight zero rule included)
            from ..tree.param import calc_gain

            gains = np.asarray(calc_gain(jnp.asarray(G, jnp.float32),
                                         jnp.asarray(H, jnp.float32), p))
            internal = tree.left_children != -1
            lc = np.where(internal, tree.left_children, 0)
            rc = np.where(internal, tree.right_children, 0)
            tree.loss_changes = np.where(
                internal, gains[lc] + gains[rc] - gains, 0.0
            ).astype(np.float32)
            if tp.refresh_leaf:
                leaf_mask = tree.left_children == -1
                tree.split_conditions = np.where(
                    leaf_mask, eta * w, tree.split_conditions
                ).astype(np.float32)
            self.model.add(tree, group)
            new_trees.append(tree)
        return new_trees, None

    # ------------------------------------------------------------------
    def _boost_fused(
        self, binned, grad, hess, iteration,
        margin_cache, feature_weights=None,
    ):
        """Fast-path round: ``grow_tree_fused`` builds each tree, its gamma
        pruning / leaf values / prediction-cache delta all on device; the
        tree is stored as device arrays and materialized lazily."""
        from ..parallel.mesh import current_mesh, shard_rows

        tp = self.train_param
        cfg, cat_mask = _cat_cfg(self._grow_params(), binned, tp)
        mesh = current_mesh()
        use_mesh = mesh is not None and mesh.devices.size > 1
        if use_mesh and cfg.has_categorical:
            raise NotImplementedError(
                "categorical training under a mesh is not supported yet "
                "(the distributed sketch's categorical identity-cut path "
                "is untested); train single-device or drop feature_types"
            )
        n = binned.n_rows
        cut_vals = jnp.asarray(binned.cuts.values)
        fw = (jnp.asarray(feature_weights)
              if feature_weights is not None else None)
        paged = getattr(binned, "is_paged", False)
        if paged and use_mesh:
            raise NotImplementedError(
                "external-memory + mesh training is not supported yet; "
                "shard rows across processes instead (docs/distributed.md)"
            )
        if paged and cfg.has_categorical:
            raise NotImplementedError(
                "external-memory matrices support numerical training only "
                "(reference external memory has the same restriction)"
            )
        if paged:
            from ..tree.grow_fused import grow_tree_fused_paged

            def grow_one(g, h, key):
                return grow_tree_fused_paged(
                    binned, g, h, cut_vals, key,
                    float(tp.eta), float(tp.gamma), cfg,
                    feature_weights=fw,
                )
        elif use_mesh:
            from ..parallel.grow import distributed_grow_tree_fused

            binsf, n_pad = binned.fused_bins_mesh(mesh)
            onehot_mesh = (None if cfg.has_categorical
                           else binned.fused_onehot_mesh(mesh, tp.max_depth))

            def grow_one(g, h, key):
                if n_pad != n:
                    pad = jnp.zeros((n_pad - n,), jnp.float32)
                    g = jnp.concatenate([g, pad])
                    h = jnp.concatenate([h, pad])
                g, h = shard_rows(g, mesh), shard_rows(h, mesh)
                return distributed_grow_tree_fused(
                    mesh, binsf, g, h, cut_vals, key,
                    jnp.float32(tp.eta), jnp.float32(tp.gamma), cfg, fw,
                    onehot=onehot_mesh,
                )
        else:
            binsf, n_pad = binned.fused_bins()
            onehot = binned.fused_onehot(tp.max_depth)

            def grow_one(g, h, key):
                if n_pad != n:
                    pad = jnp.zeros((n_pad - n,), jnp.float32)
                    g = jnp.concatenate([g, pad])
                    h = jnp.concatenate([h, pad])
                elif self.gbtree_param.num_parallel_tree > 1:
                    # hess is DONATED into the grow program; parallel trees
                    # re-pass the same slice, so each call needs its own
                    # buffer to give up
                    h = jnp.copy(h)
                if _kernelprof.active():
                    # sampled round: the host-driven instrumented mirror
                    # (bit-identical — pinned by tests/test_kernelprof.py)
                    return _kernelprof.grow_tree_fused_profiled(
                        binsf, g, h, cut_vals, key,
                        float(tp.eta), float(tp.gamma), cfg, fw, onehot,
                    )
                return grow_tree_fused(
                    binsf, g, h, cut_vals, key,
                    float(tp.eta), float(tp.gamma), cfg, fw, onehot,
                )

        new_trees = []
        hist_seconds = _hist_seconds()
        for k in range(self.n_groups):
            g = grad[:, k] if grad.ndim == 2 else grad
            h = hess[:, k] if hess.ndim == 2 else hess
            for ptree in range(self.gbtree_param.num_parallel_tree):
                key = jax.random.PRNGKey(
                    round_seed_py(tp.seed, iteration, k, ptree)
                )
                t0 = _time.perf_counter()
                with _trace.span("build_tree", iteration=iteration, group=k,
                                 ptree=ptree):
                    grown = grow_one(g, h, key)
                hist_seconds.observe(_time.perf_counter() - t0)
                self.model.add_device(grown, tp.eta, k, tp.max_depth,
                                      cat_mask)
                new_trees.append(grown)
                if margin_cache is not None:
                    margin_cache = _margin_add(margin_cache, grown.delta[:n],
                                               k)
        return new_trees, margin_cache

    def scan_rounds_supported(self, binned, obj, n_groups: int) -> bool:
        """Whether ``boost_rounds_scan`` can run: the fused depthwise
        path with a scan-safe (jax-traceable, groupless-state) objective;
        one tree per output group per round."""
        tp = self.train_param
        npt_ok = self.gbtree_param.num_parallel_tree == 1 or (
            tp.grow_policy != "lossguide" and not _mesh_active()
        )
        return (
            self.name == "gbtree"
            and npt_ok
            and not self._is_update_process
            and getattr(obj, "scan_safe", False)
            and not tuple(getattr(binned, "categorical", ()))
            and not getattr(binned, "is_paged", False)
            and (tp.grow_policy != "lossguide" or not _mesh_active())
        )

    def boost_rounds_scan(
        self,
        binned,
        obj,
        label: jax.Array,  # [n]
        weight,  # [n] or None
        margin: jax.Array,  # [n, 1]
        start_iteration: int,
        num_rounds: int,
        feature_weights=None,
    ) -> jax.Array:
        """``num_rounds`` boosting rounds as ONE compiled program: a
        ``lax.scan`` whose body is gradient -> fused tree build -> margin
        update, with per-tree heap arrays stacked as scan outputs. One
        dispatch replaces ~10 x num_rounds host round-trips — the
        whole-training-loop-on-device design point the reference cannot
        reach (its DoBoost crosses Python/C/driver boundaries every round,
        ``gbtree.cc:219``). Per-round RNG keys reproduce ``boost_one_round``
        exactly; results match the per-round path to float-fusion noise.
        Under an active mesh the whole chunk runs inside one shard_map
        (distributed_boost_rounds_scan)."""
        t0 = _time.perf_counter()
        with _trace.span("scan_chunk", start=start_iteration,
                         rounds=num_rounds):
            out = self._boost_rounds_scan_impl(
                binned, obj, label, weight, margin, start_iteration,
                num_rounds, feature_weights)
        _REGISTRY.histogram(
            "scan_chunk_seconds",
            "Host-side wall time of one fused multi-round scan dispatch",
        ).observe(_time.perf_counter() - t0)
        return out

    def _boost_rounds_scan_impl(
        self,
        binned,
        obj,
        label: jax.Array,
        weight,
        margin: jax.Array,
        start_iteration: int,
        num_rounds: int,
        feature_weights=None,
    ) -> jax.Array:
        from ..parallel.mesh import current_mesh, shard_rows

        tp = self.train_param
        cfg = self._grow_params()
        mesh = current_mesh()
        use_mesh = mesh is not None and mesh.devices.size > 1
        n = binned.n_rows
        if tp.grow_policy == "lossguide":
            assert not use_mesh  # eligibility gate keeps mesh off this path
            return self._scan_lossguide(binned, obj, label, weight, margin,
                                        start_iteration, num_rounds,
                                        feature_weights)
        if use_mesh:
            binsf, n_pad = binned.fused_bins_mesh(mesh)
        else:
            binsf, n_pad = binned.fused_bins()
        cut_vals = jnp.asarray(binned.cuts.values)
        fw = (jnp.asarray(feature_weights)
              if feature_weights is not None else None)
        eta = jnp.float32(tp.eta)
        gamma = jnp.float32(tp.gamma)
        label = jnp.asarray(label, jnp.float32)
        weight_j = jnp.asarray(weight, jnp.float32) if weight is not None else None
        seed_base = np.uint32((tp.seed * 1000003) & 0xFFFFFFFF)

        K = self.n_groups
        m_pad = margin
        if n_pad != n:
            m_pad = jnp.concatenate(
                [m_pad, jnp.zeros((n_pad - n, K), jnp.float32)])
        iters = jnp.arange(start_iteration, start_iteration + num_rounds,
                           dtype=jnp.int32)
        if use_mesh:
            from ..parallel.grow import distributed_boost_rounds_scan

            # the mesh path shards label/weight alongside the padded rows
            if n_pad != n:
                label = jnp.concatenate(
                    [label, jnp.zeros((n_pad - n,), jnp.float32)])
                if weight_j is not None:
                    weight_j = jnp.concatenate(
                        [weight_j, jnp.zeros((n_pad - n,), jnp.float32)])
            m_pad, stacked = distributed_boost_rounds_scan(
                mesh, obj, binsf, shard_rows(label, mesh),
                shard_rows(weight_j, mesh) if weight_j is not None else None,
                shard_rows(m_pad, mesh), iters, cut_vals, eta, gamma, fw,
                jnp.uint32(seed_base), n, cfg,
                onehot=binned.fused_onehot_mesh(mesh, tp.max_depth),
                fh_plan=binned.hoist_plan_mesh(mesh, tp.max_depth),
            )
            from ..parallel.mesh import local_rows

            # back to THIS process's rows (identity single-process): the
            # margin cache, evals, and predictions are process-local
            m_pad = local_rows(m_pad)
        else:
            npt = self.gbtree_param.num_parallel_tree
            m_pad, stacked = _scan_rounds_impl(
                binsf, label, weight_j, m_pad, iters, cut_vals, eta, gamma,
                fw, jnp.uint32(seed_base), binned.fused_onehot(tp.max_depth),
                obj=obj,
                obj_fp=_obj_fingerprint(obj), cfg=cfg, n=n, n_pad=n_pad,
                n_groups=K, n_parallel=npt,
            )
            groups = [k for k in range(K) for _ in range(npt)]
            self.model.add_device_chunk(stacked, num_rounds, groups,
                                        tp.eta, tp.max_depth)
            return m_pad[:n]
        self.model.add_device_chunk(stacked, num_rounds, list(range(K)),
                                    tp.eta, tp.max_depth)
        return m_pad[:n]

    def _scan_lossguide(self, binned, obj, label, weight, margin,
                        start_iteration, num_rounds, feature_weights):
        tp = self.train_param
        cfg = self._grow_params()
        max_leaves = self._lossguide_max_leaves()
        K = self.n_groups
        cut_vals = jnp.asarray(binned.cuts.values)
        fw = (jnp.asarray(feature_weights)
              if feature_weights is not None else None)
        label_j = jnp.asarray(label, jnp.float32)
        weight_j = (jnp.asarray(weight, jnp.float32)
                    if weight is not None else None)
        seed_base = np.uint32((tp.seed * 1000003) & 0xFFFFFFFF)
        iters = jnp.arange(start_iteration, start_iteration + num_rounds,
                           dtype=jnp.int32)
        m_cur, stacked = _scan_rounds_lossguide_impl(
            binned.bins, label_j, weight_j, margin, iters, cut_vals,
            jnp.float32(tp.eta), jnp.float32(tp.gamma), fw,
            jnp.uint32(seed_base), obj=obj, obj_fp=_obj_fingerprint(obj),
            cfg=cfg, n_groups=K, max_leaves=max_leaves,
        )
        self.model.add_device_alloc_chunk(
            stacked[0], stacked[1], stacked[2], num_rounds, K,
            tp.eta, tp.gamma, tp.max_depth, cat_mask=None,
        )
        return m_cur

    # ------------------------------------------------------------------
    def training_margin(self, X, base_margin: jax.Array) -> jax.Array:
        """Margin used to compute this round's gradients (DART overrides to
        apply dropout)."""
        return predict_margin(self.model.stacked(), X, base_margin)

    def tree_weights(self) -> Optional[jax.Array]:
        return None

    def predict(self, X, base_margin: jax.Array) -> jax.Array:
        return predict_margin(self.model.stacked(), X, base_margin, self.tree_weights())

    def predict_leaf(self, X) -> jax.Array:
        # leaf ids must match the (BFS-compacted) saved model, not the
        # device heap layout: force materialization before stacking
        _ = self.model.trees
        return predict_leaf(self.model.stacked(), X)

    # ------------------------------------------------------------------
    def save_json(self) -> dict:
        return {
            "name": self.name,
            "model": {
                "gbtree_model_param": {
                    "num_trees": str(self.model.num_trees),
                    # persisted so round-slicing semantics survive a JSON
                    # round trip (reference GBTreeModelParam)
                    "num_parallel_tree": str(self.gbtree_param.num_parallel_tree),
                    "size_leaf_vector": "0",
                },
                "trees": [t.to_json(i) for i, t in enumerate(self.model.trees)],
                "tree_info": list(self.model.tree_info),
            },
        }

    def load_json(self, j: dict) -> None:
        m = j["model"]
        npt = int(m.get("gbtree_model_param", {}).get("num_parallel_tree", 0)) or (
            self.gbtree_param.num_parallel_tree
        )
        self.gbtree_param.num_parallel_tree = npt
        self.model = GBTreeModel(self.n_groups, npt)
        for tj, info in zip(m["trees"], m["tree_info"]):
            self.model.add(RegTree.from_json(tj), int(info))


@BOOSTERS.register("dart")
class Dart(GBTree):
    """DART dropout booster (reference: gbtree.cc:637-1020)."""

    name = "dart"

    def __init__(self, n_groups: int, params: Dict[str, Any]):
        super().__init__(n_groups, params)
        self.weight_drop: List[float] = []
        self._idx_drop: List[int] = []
        self._rng = np.random.RandomState(self.train_param.seed)

    def _drop_trees(self) -> None:
        """reference DropTrees (gbtree.cc:914)."""
        p = self.gbtree_param
        self._idx_drop = []
        if p.skip_drop > 0.0 and self._rng.uniform() < p.skip_drop:
            return
        W = self.weight_drop
        if not W:
            return
        if p.sample_type == "weighted":
            sw = sum(W)
            for i, wi in enumerate(W):
                if self._rng.uniform() < p.rate_drop * len(W) * wi / max(sw, 1e-30):
                    self._idx_drop.append(i)
            if p.one_drop and not self._idx_drop:
                probs = np.asarray(W) / max(sum(W), 1e-30)
                self._idx_drop.append(int(self._rng.choice(len(W), p=probs)))
        else:
            for i in range(len(W)):
                if self._rng.uniform() < p.rate_drop:
                    self._idx_drop.append(i)
            if p.one_drop and not self._idx_drop:
                self._idx_drop.append(int(self._rng.randint(len(W))))

    def _normalize_trees(self, n_new: int) -> None:
        """reference NormalizeTrees (gbtree.cc:963)."""
        lr = self.train_param.eta / max(n_new, 1)
        k = len(self._idx_drop)
        if k == 0:
            self.weight_drop.extend([1.0] * n_new)
        elif self.gbtree_param.normalize_type == "forest":
            factor = 1.0 / (1.0 + lr)
            for i in self._idx_drop:
                self.weight_drop[i] *= factor
            self.weight_drop.extend([factor] * n_new)
        else:  # "tree"
            factor = k / (k + lr)
            for i in self._idx_drop:
                self.weight_drop[i] *= factor
            self.weight_drop.extend([1.0 / (k + lr)] * n_new)

    def tree_weights(self) -> Optional[jax.Array]:
        if not self.weight_drop:
            return None
        return jnp.asarray(np.asarray(self.weight_drop, np.float32))

    def training_margin(self, X, base_margin: jax.Array) -> jax.Array:
        self._drop_trees()
        tw = np.asarray(self.weight_drop, np.float32)
        if len(tw):
            tw = tw.copy()
            tw[self._idx_drop] = 0.0
            return predict_margin(self.model.stacked(), X, base_margin, jnp.asarray(tw))
        return predict_margin(self.model.stacked(), X, base_margin)

    def boost_one_round(self, binned, grad, hess, iteration, margin_cache,
                        feature_weights=None):
        # DART cannot use the incremental cache (dropout changes old trees'
        # weights every round) — reference also disables the cache for DART
        new_trees, _ = super().boost_one_round(
            binned, grad, hess, iteration, None, feature_weights
        )
        self._normalize_trees(len(new_trees))
        return new_trees, None

    def save_json(self) -> dict:
        j = super().save_json()
        j["name"] = "dart"
        j["model"] = {"gbtree": j["model"], "weight_drop": list(self.weight_drop)}
        return j

    def load_json(self, j: dict) -> None:
        inner = j["model"]["gbtree"]
        super().load_json({"model": inner})
        self.weight_drop = [float(x) for x in j["model"]["weight_drop"]]
