"""Metric base (reference: ``include/xgboost/metric.h``; distributed
reduction pattern: every metric's final scalar is AllReduce(sum)/
AllReduce(weight) — e.g. ``elementwise_metric.cu:372``. Here metrics return
(sum, weight) pairs so the caller can psum them across a mesh before the
final divide — the exact same contract)."""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import METRICS


def dist_reduce(s: float, w: float) -> Tuple[float, float]:
    """Sum a metric's (residue, weight) pair over every PROCESS of a
    collective multi-process run — the reference's rabit Allreduce in
    every metric's GetFinal (elementwise_metric.cu:372, auc.cc dist path).
    Without this, each rank finalizes on its local eval shard and early
    stopping diverges across ranks. Identity when training is local:
    single process, OR multi-process without an active mesh (gated by
    ``parallel.mesh.collective_active`` — the same predicate the learner's
    routing uses — so a rank evaluating extra local models can never hang
    the others in a surprise allgather)."""
    from ..parallel.mesh import collective_active

    if not collective_active():
        return s, w
    from .. import collective

    arr = collective.process_allgather(
        np.asarray([s, w], np.float64), site="metric_reduce")
    return float(arr[:, 0].sum()), float(arr[:, 1].sum())


class Metric:
    name: str = ""
    # maximize=True metrics (auc, ndcg, map...) flip early-stopping direction
    maximize: bool = False

    def evaluate(
        self,
        preds: jax.Array,  # transformed predictions
        label: jax.Array,
        weight: Optional[jax.Array] = None,
        group_ptr: Optional[np.ndarray] = None,
        label_lower: Optional[jax.Array] = None,
        label_upper: Optional[jax.Array] = None,
    ) -> float:
        raise NotImplementedError


class ElementwiseMetric(Metric):
    """sum(w * loss(pred, y)) / sum(w), the shape of every metric in
    elementwise_metric.cu."""

    def loss(self, pred: jax.Array, label: jax.Array) -> jax.Array:
        raise NotImplementedError

    def finalize(self, s: float, w: float) -> float:
        # the reference's empty/zero-weight convention: wsum == 0 returns
        # the raw esum, NOT nan (elementwise_metric.cu:7 and every GetFinal)
        return s if w == 0 else s / w

    def evaluate(self, preds, label, weight=None, **kw):
        preds = jnp.asarray(preds)
        label = jnp.asarray(label)
        if preds.ndim == 2 and preds.shape[1] == 1:
            preds = preds[:, 0]
        l = self.loss(preds, label)
        if weight is not None and weight.size:
            w = jnp.asarray(weight)
            s, tw = (l * w).sum(), w.sum()
        else:
            s, tw = l.sum(), jnp.float32(l.shape[0])
        return self.finalize(*dist_reduce(float(s), float(tw)))


def create_metric(name: str) -> Metric:
    from ..registry import create_metric as _create

    m = _create(name)
    if not m.name:
        m.name = name
    return m
