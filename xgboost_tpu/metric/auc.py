"""AUC / AUC-PR (reference: ``src/metric/auc.{cc,cu,h}`` — binary ROC,
multiclass one-vs-rest, ranking group-mean; GPU via segmented scans).

TPU design: exact tie handling without ragged blocks — sort by score, build
tie-block segment ids from score boundaries, and compute
P(s_pos > s_neg) + 0.5 P(=) with weighted block sums via ``segment_sum``.
One fixed-shape program; deterministic.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import METRICS
from .base import Metric, dist_reduce


def _dist_mean(local: float, local_w: float) -> float:
    """Weighted mean of per-process values (the reference's distributed
    AUC: each worker contributes (auc * w, w) to one Allreduce,
    auc.cc:293). NaN-weight-0 locals drop out; identity single-process."""
    if np.isnan(local):
        local, local_w = 0.0, 0.0
    s, w = dist_reduce(local * local_w, local_w)
    return s / w if w > 0 else float("nan")


@jax.jit
def _binary_auc(score: jax.Array, label: jax.Array, weight: jax.Array) -> jax.Array:
    n = score.shape[0]
    order = jnp.argsort(score)
    s = score[order]
    y = label[order]
    w = weight[order]
    wp = w * y
    wn = w * (1.0 - y)
    # tie blocks
    newblk = jnp.concatenate([jnp.ones((1,), bool), s[1:] != s[:-1]])
    seg = jnp.cumsum(newblk) - 1  # [n] block id
    blk_wn = jax.ops.segment_sum(wn, seg, num_segments=n)  # padded with zeros
    cum_blk_wn = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(blk_wn)[:-1]])
    below = cum_blk_wn[seg]  # neg weight strictly below this block
    tied = blk_wn[seg]
    num = (wp * (below + 0.5 * tied)).sum()
    Wp, Wn = wp.sum(), wn.sum()
    return jnp.where((Wp > 0) & (Wn > 0), num / jnp.maximum(Wp * Wn, 1e-30), jnp.nan)


@partial(jax.jit, static_argnames=("n_groups",))
def _grouped_auc(score, label, weight, group_of, n_groups):
    """Per-group binary AUCs, averaged over groups that have both classes —
    segmented version of ``_binary_auc`` (one lexsort + segment_sums; the
    reference's GPU path, auc.cu, structures it the same way)."""
    n = score.shape[0]
    order = jnp.lexsort((score, group_of))
    g = group_of[order]
    s = score[order]
    y = label[order]
    w = weight[order]
    wp = w * y
    wn = w * (1.0 - y)
    newblk = jnp.concatenate(
        [jnp.ones((1,), bool), (s[1:] != s[:-1]) | (g[1:] != g[:-1])]
    )
    seg = jnp.cumsum(newblk) - 1
    blk_wn = jax.ops.segment_sum(wn, seg, num_segments=n)
    cum_blk = jnp.concatenate([jnp.zeros((1,)), jnp.cumsum(blk_wn)[:-1]])[seg]
    Wn_g = jax.ops.segment_sum(wn, g, num_segments=n_groups)
    grp_before = jnp.concatenate(
        [jnp.zeros((1,)), jnp.cumsum(Wn_g)[:-1]]
    )[g]
    below = cum_blk - grp_before  # negative weight strictly below, in-group
    tied = blk_wn[seg]
    num_g = jax.ops.segment_sum(wp * (below + 0.5 * tied), g,
                                num_segments=n_groups)
    Wp_g = jax.ops.segment_sum(wp, g, num_segments=n_groups)
    valid = (Wp_g > 0) & (Wn_g > 0)
    auc_g = num_g / jnp.maximum(Wp_g * Wn_g, 1e-30)
    cnt = valid.sum()
    # (sum over valid groups, valid count): the caller divides — and the
    # distributed reduction must weight by VALID groups, not all groups
    return jnp.where(valid, auc_g, 0.0).sum(), cnt


@METRICS.register("auc")
class AUC(Metric):
    name = "auc"
    maximize = True

    def evaluate(self, preds, label, weight=None, group_ptr=None, **kw):
        preds = jnp.asarray(preds)
        label_j = jnp.asarray(label, dtype=jnp.float32)
        n = label_j.shape[0]
        w = (
            jnp.asarray(weight, jnp.float32)
            if weight is not None and np.size(weight) == n
            else jnp.ones((n,), jnp.float32)
        )
        if preds.ndim == 2 and preds.shape[1] > 1:
            # multiclass: weighted one-vs-rest average (auc.cc:385)
            aucs = []
            for k in range(preds.shape[1]):
                aucs.append(float(_binary_auc(preds[:, k], (label_j == k).astype(jnp.float32), w)))
            return _dist_mean(float(np.mean(aucs)), float(w.sum()))
        if preds.ndim == 2:
            preds = preds[:, 0]
        if group_ptr is not None and len(group_ptr) > 2:
            # ranking: mean of per-group AUCs in ONE segmented program
            # (auc.cc:262-313 / auc.cu segmented scans) — no per-group
            # device calls
            sizes = np.diff(np.asarray(group_ptr)).astype(np.int64)
            group_of = np.repeat(np.arange(len(sizes), dtype=np.int32), sizes)
            auc_sum, cnt = _grouped_auc(
                preds, (label_j > 0).astype(jnp.float32), w,
                jnp.asarray(group_of), len(sizes))
            s, c = dist_reduce(float(auc_sum), float(cnt))
            return s / c if c > 0 else float("nan")
        return _dist_mean(float(_binary_auc(preds, label_j, w)),
                          float(w.sum()))


@METRICS.register("aucpr")
class AUCPR(Metric):
    name = "aucpr"
    maximize = True

    def evaluate(self, preds, label, weight=None, **kw):
        p = np.asarray(preds, dtype=np.float64).reshape(-1)
        y = np.asarray(label, dtype=np.float64)
        n = len(y)
        w = (
            np.asarray(weight, np.float64)
            if weight is not None and np.size(weight) == n
            else np.ones(n)
        )
        local = self._local_aucpr(p, y, w)
        # distributed: weighted mean of per-process local curves, invalid
        # shards contributing (0, 0) — the reference's pair allreduce
        # (auc.cc:115 Allreduce<Sum> over (auc * weight, weight))
        if local != local:
            s, c = dist_reduce(0.0, 0.0)
        else:
            s, c = dist_reduce(local * float(w.sum()), float(w.sum()))
        return s / c if c > 0 else float("nan")

    @staticmethod
    def _local_aucpr(p, y, w) -> float:
        order = np.argsort(-p, kind="stable")
        y, w, p = y[order], w[order], p[order]
        if len(y) == 0:
            return float("nan")
        tp = np.cumsum(w * y)
        fp = np.cumsum(w * (1 - y))
        total_pos = tp[-1]
        if total_pos <= 0:
            return float("nan")
        # evaluate only at tie-block ends
        ends = np.append(p[1:] != p[:-1], True)
        tp_e, fp_e = tp[ends], fp[ends]
        recall = tp_e / total_pos
        precision = tp_e / np.maximum(tp_e + fp_e, 1e-30)
        prev_r = np.concatenate([[0.0], recall[:-1]])
        return float(np.sum((recall - prev_r) * precision))
