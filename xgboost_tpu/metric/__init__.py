from .base import Metric, create_metric  # noqa: F401
from . import elementwise  # noqa: F401  (registers)
from . import multiclass  # noqa: F401
from . import auc  # noqa: F401
from . import rank  # noqa: F401
from . import survival  # noqa: F401
