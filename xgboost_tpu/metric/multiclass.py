"""Multiclass metrics (reference: ``src/metric/multiclass_metric.cu``
merror/mlogloss at :248-252)."""

from __future__ import annotations

import jax.numpy as jnp

from ..registry import METRICS
from .base import Metric, dist_reduce

_EPS = 1e-16


@METRICS.register("merror")
class MultiError(Metric):
    name = "merror"

    def evaluate(self, preds, label, weight=None, **kw):
        preds = jnp.asarray(preds)
        if preds.ndim == 1:  # class-index predictions (multi:softmax output)
            yhat = preds
        else:
            yhat = jnp.argmax(preds, axis=-1)
        wrong = (yhat.astype(jnp.int32) != label.astype(jnp.int32)).astype(jnp.float32)
        if weight is not None and weight.size:
            s, w = float((wrong * weight).sum()), float(weight.sum())
        else:
            s, w = float(wrong.sum()), float(wrong.shape[0])
        s, w = dist_reduce(s, w)
        # zero reduced weight returns the residue (0.0), not NaN — the
        # reference's GetFinal convention (multiclass_metric.cu)
        return s / w if w > 0 else s


@METRICS.register("mlogloss")
class MultiLogLoss(Metric):
    name = "mlogloss"

    def evaluate(self, preds, label, weight=None, **kw):
        p = jnp.asarray(preds)
        idx = label.astype(jnp.int32)
        picked = jnp.take_along_axis(p, idx[:, None], axis=1)[:, 0]
        l = -jnp.log(jnp.clip(picked, _EPS, 1.0))
        if weight is not None and weight.size:
            s, w = float((l * weight).sum()), float(weight.sum())
        else:
            s, w = float(l.sum()), float(l.shape[0])
        s, w = dist_reduce(s, w)
        # zero reduced weight returns the residue (0.0), not NaN — the
        # reference's GetFinal convention (multiclass_metric.cu)
        return s / w if w > 0 else s
