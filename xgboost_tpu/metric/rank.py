"""Ranking metrics (reference: ``src/metric/rank_metric.{cc,cu}`` —
ams@k, pre@n, ndcg@n, map@n registered at rank_metric.cc:390-406)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..registry import METRICS
from .base import Metric


def _groups(n: int, group_ptr: Optional[np.ndarray]):
    if group_ptr is None or len(group_ptr) < 2:
        return np.array([0, n], dtype=np.int64)
    return np.asarray(group_ptr)


class _PerGroupMetric(Metric):
    maximize = True

    def __init__(self, arg: str = "", full_name: str = ""):
        self.topn = int(arg) if arg else 0
        if full_name:
            self.name = full_name

    def group_score(self, order_desc: np.ndarray, label: np.ndarray) -> float:
        raise NotImplementedError

    def evaluate(self, preds, label, weight=None, group_ptr=None, **kw):
        p = np.asarray(preds).reshape(-1)
        y = np.asarray(label)
        ptr = _groups(len(y), group_ptr)
        scores = []
        for g in range(len(ptr) - 1):
            lo, hi = int(ptr[g]), int(ptr[g + 1])
            if hi <= lo:
                continue
            order = np.argsort(-p[lo:hi], kind="stable")
            scores.append(self.group_score(order, y[lo:hi]))
        return float(np.mean(scores)) if scores else float("nan")


@METRICS.register("ndcg@", "ndcg")
class NDCG(_PerGroupMetric):
    name = "ndcg"

    def group_score(self, order, y):
        k = self.topn if self.topn > 0 else len(y)
        ranked = y[order][:k]
        gains = 2.0 ** ranked - 1.0
        discounts = 1.0 / np.log2(np.arange(len(ranked)) + 2.0)
        dcg = float((gains * discounts).sum())
        ideal = np.sort(y)[::-1][:k]
        idcg = float(((2.0 ** ideal - 1.0) * (1.0 / np.log2(np.arange(len(ideal)) + 2.0))).sum())
        return dcg / idcg if idcg > 0 else 1.0


@METRICS.register("map@", "map")
class MAP(_PerGroupMetric):
    name = "map"

    def group_score(self, order, y):
        k = self.topn if self.topn > 0 else len(y)
        rel = (y[order] > 0).astype(np.float64)[:k]
        if rel.sum() == 0:
            return 1.0  # reference counts no-positive groups as 1
        hits = np.cumsum(rel)
        prec = hits / (np.arange(len(rel)) + 1.0)
        return float((prec * rel).sum() / rel.sum())


@METRICS.register("pre@", "pre")
class PrecisionAt(_PerGroupMetric):
    name = "pre"

    def group_score(self, order, y):
        k = self.topn if self.topn > 0 else len(y)
        rel = (y[order] > 0)[:k]
        return float(rel.sum() / max(k, 1))


@METRICS.register("ams@")
class AMS(Metric):
    """Approximate median significance (rank_metric.cc)."""

    maximize = True

    def __init__(self, arg: str = "0.15", full_name: str = ""):
        self.ratio = float(arg)
        self.name = full_name or f"ams@{arg}"

    def evaluate(self, preds, label, weight=None, **kw):
        p = np.asarray(preds).reshape(-1)
        y = np.asarray(label)
        n = len(y)
        w = np.asarray(weight) if weight is not None and np.size(weight) == n else np.ones(n)
        order = np.argsort(-p, kind="stable")
        ntop = int(self.ratio * n)
        br = 10.0
        s = float((w[order][:ntop] * (y[order][:ntop] > 0.5)).sum())
        b = float((w[order][:ntop] * (y[order][:ntop] <= 0.5)).sum())
        if b + br <= 0:
            return 0.0
        import math

        return math.sqrt(max(0.0, 2.0 * ((s + b + br) * math.log(1.0 + s / (b + br)) - s)))
