"""Ranking metrics (reference: ``src/metric/rank_metric.{cc,cu}`` —
ams@k, pre@n, ndcg@n, map@n registered at rank_metric.cc:390-406)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..registry import METRICS
from .base import Metric


def _groups(n: int, group_ptr: Optional[np.ndarray]):
    if group_ptr is None or len(group_ptr) < 2:
        return np.array([0, n], dtype=np.int64)
    return np.asarray(group_ptr)


def _segmented_layout(p: np.ndarray, y: np.ndarray, ptr: np.ndarray):
    """Shared segmented machinery: ONE global lexsort by (group, -score)
    instead of a Python argsort per group (the reference's GPU rank metrics
    use segmented sorts the same way, rank_metric.cu / dh::SegmentSorter).
    Returns (sorted y, group id per sorted row, local rank per sorted row,
    group sizes)."""
    sizes = np.diff(ptr).astype(np.int64)
    G = len(sizes)
    group_of = np.repeat(np.arange(G, dtype=np.int64), sizes)
    order = np.lexsort((-p, group_of))
    starts = np.asarray(ptr[:-1], np.int64)
    local = np.arange(len(y), dtype=np.int64) - starts[group_of]
    return y[order], group_of, local, sizes


class _PerGroupMetric(Metric):
    maximize = True

    def __init__(self, arg: str = "", full_name: str = ""):
        self.topn = int(arg) if arg else 0
        if full_name:
            self.name = full_name

    def group_scores(self, ys, group_of, local, sizes, k) -> np.ndarray:
        raise NotImplementedError

    def evaluate(self, preds, label, weight=None, group_ptr=None, **kw):
        p = np.asarray(preds).reshape(-1)
        y = np.asarray(label, np.float64)
        ptr = _groups(len(y), group_ptr)
        ys, group_of, local, sizes = _segmented_layout(p, y, ptr)
        k = self.topn if self.topn > 0 else int(sizes.max(initial=0))
        scores = self.group_scores(ys, group_of, local, sizes, k)
        scores = scores[sizes > 0]
        # distributed: sum-of-scores / total groups over all processes
        # (rank_metric.cc GetFinal's rabit pattern)
        from .base import dist_reduce

        s, c = dist_reduce(float(scores.sum()), float(len(scores)))
        return s / c if c > 0 else float("nan")


@METRICS.register("ndcg@", "ndcg")
class NDCG(_PerGroupMetric):
    name = "ndcg"

    def group_scores(self, ys, group_of, local, sizes, k):
        G = len(sizes)
        disc = 1.0 / np.log2(local + 2.0)
        top = local < k
        dcg = np.bincount(group_of, weights=(2.0 ** ys - 1.0) * disc * top,
                          minlength=G)
        # ideal order: labels sorted descending within group
        lorder = np.lexsort((-ys, group_of))
        yi = ys[lorder]
        idcg = np.bincount(group_of, weights=(2.0 ** yi - 1.0) * disc * top,
                           minlength=G)
        return np.where(idcg > 0, dcg / np.maximum(idcg, 1e-30),
                        0.0 if getattr(self, "minus", False) else 1.0)


@METRICS.register("map@", "map")
class MAP(_PerGroupMetric):
    name = "map"

    def group_scores(self, ys, group_of, local, sizes, k):
        G = len(sizes)
        rel = (ys > 0).astype(np.float64)
        cs = np.cumsum(rel)
        starts_sorted = local == 0
        base = np.repeat(cs[starts_sorted] - rel[starts_sorted],
                         sizes[sizes > 0])
        hits = cs - base  # positives at-or-above each row, within group
        top = local < k
        prec_terms = np.where(top, hits / (local + 1.0) * rel, 0.0)
        num = np.bincount(group_of, weights=prec_terms, minlength=G)
        # the reference divides by the group's TOTAL hit count, not the
        # hits inside top-n (rank_metric.cc:321-330: nhits accumulates over
        # the whole group, only sumap is top-n-gated)
        den = np.bincount(group_of, weights=rel, minlength=G)
        return np.where(den > 0, num / np.maximum(den, 1e-30),
                        0.0 if getattr(self, "minus", False) else 1.0)


@METRICS.register("pre@", "pre")
class PrecisionAt(_PerGroupMetric):
    name = "pre"

    def group_scores(self, ys, group_of, local, sizes, k):
        G = len(sizes)
        rel = (ys > 0) & (local < k)
        hits = np.bincount(group_of, weights=rel.astype(np.float64),
                           minlength=G)
        if self.topn > 0:  # pre@n divides by the fixed n (rank_metric.cc)
            return hits / max(k, 1)
        # bare "pre": per-group precision over the whole group
        return hits / np.maximum(sizes, 1)


@METRICS.register("ams@")
class AMS(Metric):
    """Approximate median significance (rank_metric.cc)."""

    maximize = True

    def __init__(self, arg: str = "0.15", full_name: str = ""):
        self.ratio = float(arg)
        self.name = full_name or f"ams@{arg}"

    def evaluate(self, preds, label, weight=None, **kw):
        from ..parallel.mesh import collective_active

        if collective_active():
            # the global top-ratio cut cannot be formed from local sorts;
            # the reference refuses too (rank_metric.cc:107)
            raise ValueError(
                "metric AMS does not support distributed evaluation")
        p = np.asarray(preds).reshape(-1)
        y = np.asarray(label)
        n = len(y)
        w = np.asarray(weight) if weight is not None and np.size(weight) == n else np.ones(n)
        order = np.argsort(-p, kind="stable")
        ntop = int(self.ratio * n)
        br = 10.0
        s = float((w[order][:ntop] * (y[order][:ntop] > 0.5)).sum())
        b = float((w[order][:ntop] * (y[order][:ntop] <= 0.5)).sum())
        if b + br <= 0:
            return 0.0
        import math

        return math.sqrt(max(0.0, 2.0 * ((s + b + br) * math.log(1.0 + s / (b + br)) - s)))
