"""Survival metrics (reference: ``src/metric/survival_metric.cu`` —
aft-nloglik / interval-regression-accuracy at :287-293; cox-nloglik in
rank_metric.cc)."""

from __future__ import annotations

import numpy as np

from ..registry import METRICS
from .base import Metric


@METRICS.register("aft-nloglik")
class AFTNLogLik(Metric):
    name = "aft-nloglik"

    def evaluate(self, preds, label, weight=None, label_lower=None, label_upper=None, **kw):
        import jax.numpy as jnp

        from ..objective.survival import AFT

        # configured like the objective: same distribution + scale
        # (reference survival_metric.cu parses the same AFTParam)
        obj = AFT(getattr(self, "lparam", None))
        # preds arrive UNtransformed — log space (AFT.eval_transform is a
        # no-op, like the reference's)
        margin = jnp.asarray(preds).reshape(-1)
        yl = jnp.asarray(label_lower if label_lower is not None else label)
        yu = jnp.asarray(label_upper if label_upper is not None else label)
        ll = obj._loglik(margin, yl, yu)
        n = margin.shape[0]
        if weight is not None and np.size(weight) == n:
            w = jnp.asarray(weight)
            return float(-(ll * w).sum() / w.sum())
        return float(-ll.mean())


@METRICS.register("interval-regression-accuracy")
class IntervalAccuracy(Metric):
    name = "interval-regression-accuracy"
    maximize = True

    def evaluate(self, preds, label, weight=None, label_lower=None, label_upper=None, **kw):
        # preds live in LOG space (the AFT margin); bounds are linear —
        # accuracy counts log(lower) <= pred <= log(upper)
        # (survival_metric.cu IntervalRegressionAccuracy)
        p = np.asarray(preds).reshape(-1)
        yl = np.asarray(label_lower if label_lower is not None else label,
                        np.float64)
        yu = np.asarray(label_upper if label_upper is not None else label,
                        np.float64)
        with np.errstate(divide="ignore"):
            ok = (p >= np.log(np.maximum(yl, 0.0))) & (
                (~np.isfinite(yu)) | (p <= np.log(np.maximum(yu, 0.0))))
        return float(ok.mean())


@METRICS.register("cox-nloglik")
class CoxNLogLik(Metric):
    name = "cox-nloglik"

    def evaluate(self, preds, label, weight=None, **kw):
        from ..parallel.mesh import collective_active

        if collective_active():
            # risk-set sums need the globally time-ordered cohort; the
            # reference refuses too (rank_metric.cc:348)
            raise ValueError(
                "Cox metric does not support distributed evaluation")
        # data sorted by time ascending; preds are exp(margin)
        e = np.asarray(preds, dtype=np.float64).reshape(-1)
        y = np.asarray(label, dtype=np.float64)
        rsum = np.cumsum(e[::-1])[::-1]  # risk-set sums
        events = y > 0
        if events.sum() == 0:
            return float("nan")
        ll = np.log(np.maximum(e[events], 1e-30)) - np.log(np.maximum(rsum[events], 1e-30))
        return float(-ll.sum() / events.sum())
