"""Elementwise metrics (reference: ``src/metric/elementwise_metric.cu``
registrations at :386-426)."""

from __future__ import annotations

import math

import jax.numpy as jnp

from ..registry import METRICS
from .base import ElementwiseMetric

_EPS = 1e-16


@METRICS.register("rmse")
class RMSE(ElementwiseMetric):
    name = "rmse"

    def loss(self, p, y):
        return (p - y) ** 2

    def finalize(self, s, w):
        return math.sqrt(s if w == 0 else s / w)


@METRICS.register("rmsle")
class RMSLE(ElementwiseMetric):
    name = "rmsle"

    def loss(self, p, y):
        return (jnp.log1p(jnp.maximum(p, -1 + 1e-6)) - jnp.log1p(y)) ** 2

    def finalize(self, s, w):
        return math.sqrt(s if w == 0 else s / w)


@METRICS.register("mae")
class MAE(ElementwiseMetric):
    name = "mae"

    def loss(self, p, y):
        return jnp.abs(p - y)


@METRICS.register("mape")
class MAPE(ElementwiseMetric):
    name = "mape"

    def loss(self, p, y):
        return jnp.abs((y - p) / jnp.maximum(jnp.abs(y), _EPS))


@METRICS.register("mphe")
class MPHE(ElementwiseMetric):
    name = "mphe"

    def loss(self, p, y):
        z = p - y
        return jnp.sqrt(1.0 + z * z) - 1.0


@METRICS.register("logloss")
class LogLoss(ElementwiseMetric):
    name = "logloss"

    def loss(self, p, y):
        # the reference's product form (supports fractional labels) with an
        # f32-REPRESENTABLE clamp: the reference's 1e-16 eps rounds
        # 1 - eps to exactly 1.0 in f32 and 0 * log(0) = nan
        eps = 1e-7
        p = jnp.clip(p, eps, 1.0 - eps)
        return -(y * jnp.log(p) + (1.0 - y) * jnp.log(1.0 - p))


@METRICS.register("error")
class BinaryError(ElementwiseMetric):
    name = "error"

    def __init__(self, threshold: float = 0.5):
        self.t = threshold

    def loss(self, p, y):
        return ((p > self.t) != (y > 0.5)).astype(jnp.float32)


@METRICS.register("error@")
class BinaryErrorAt(BinaryError):
    def __init__(self, arg: str, full_name: str = ""):
        super().__init__(float(arg))
        self.name = full_name or f"error@{arg}"


@METRICS.register("poisson-nloglik")
class PoissonNLogLik(ElementwiseMetric):
    name = "poisson-nloglik"

    def loss(self, p, y):
        p = jnp.maximum(p, _EPS)
        return p - y * jnp.log(p) + jnp.asarray(_lgamma_approx(y))


def _lgamma_approx(y):
    import jax.lax as lax

    return lax.lgamma(y + 1.0)


@METRICS.register("gamma-deviance")
class GammaDeviance(ElementwiseMetric):
    name = "gamma-deviance"

    def loss(self, p, y):
        e = _EPS
        return jnp.log(p + e) - jnp.log(y + e) + y / (p + e) - 1.0

    def finalize(self, s, w):
        return 2.0 * (s if w == 0 else s / w)


@METRICS.register("gamma-nloglik")
class GammaNLogLik(ElementwiseMetric):
    name = "gamma-nloglik"

    def loss(self, p, y):
        # fixed shape psi=1 as the reference (elementwise_metric.cu
        # EvalGammaNLogLik): theta = -1/p, b(theta) = -log(-theta) = log p,
        # c(y, psi=1) = log(y)/psi - log(y) - lgamma(1) = 0, so
        # nloglik = -((y*theta - b)/psi + c) = y/p + log(p)
        p = jnp.maximum(p, _EPS)
        return y / p + jnp.log(p)

    def finalize(self, s, w):
        return s if w == 0 else s / w


@METRICS.register("tweedie-nloglik@", "tweedie-nloglik")
class TweedieNLogLik(ElementwiseMetric):
    def __init__(self, arg: str = "1.5", full_name: str = ""):
        self.rho = float(arg)
        self.name = full_name or f"tweedie-nloglik@{arg}"

    def loss(self, p, y):
        rho = self.rho
        p = jnp.maximum(p, _EPS)
        a = y * jnp.power(p, 1.0 - rho) / (1.0 - rho)
        b = jnp.power(p, 2.0 - rho) / (2.0 - rho)
        return -a + b
