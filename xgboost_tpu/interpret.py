"""SHAP values: TreeShap (reference: ``src/tree/tree_model.cc``
``TreeShap/CalculateContributions:552-581``; GPU variant uses the
GPUTreeShap submodule, ``gpu_predictor.cu:852``).

Host implementation of the exact path-dependent TreeShap recursion (the
algorithm is inherently recursive over the tree; the reference also runs it
on host for CPU predictors). ``approx=True`` gives the Saabas attribution
the reference exposes as ``approx_contribs``.
"""

from __future__ import annotations

from typing import List

import numpy as np


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0, pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElem(self.feature_index, self.zero_fraction, self.one_fraction, self.pweight)


def _extend(path: List[_PathElem], pzf: float, pof: float, pi: int) -> None:
    path.append(_PathElem(pi, pzf, pof, 1.0 if len(path) == 0 else 0.0))
    l = len(path)
    for i in range(l - 2, -1, -1):
        path[i + 1].pweight += pof * path[i].pweight * (i + 1) / l
        path[i].pweight = pzf * path[i].pweight * (l - i - 1) / l


def _unwind(path: List[_PathElem], i: int) -> List[_PathElem]:
    l = len(path)
    out = [p.copy() for p in path]
    n = out[l - 1].pweight
    pof = out[i].one_fraction
    pzf = out[i].zero_fraction
    for j in range(l - 2, -1, -1):
        if pof != 0:
            t = out[j].pweight
            out[j].pweight = n * l / ((j + 1) * pof)
            n = t - out[j].pweight * pzf * (l - j - 1) / l
        else:
            out[j].pweight = out[j].pweight * l / (pzf * (l - j - 1))
    for j in range(i, l - 1):
        out[j].feature_index = out[j + 1].feature_index
        out[j].zero_fraction = out[j + 1].zero_fraction
        out[j].one_fraction = out[j + 1].one_fraction
    out.pop()
    return out


def _unwound_sum(path: List[_PathElem], i: int) -> float:
    l = len(path)
    pof = path[i].one_fraction
    pzf = path[i].zero_fraction
    n = path[l - 1].pweight
    total = 0.0
    for j in range(l - 2, -1, -1):
        if pof != 0:
            t = n * l / ((j + 1) * pof)
            total += t
            n = path[j].pweight - t * pzf * (l - j - 1) / l
        else:
            total += path[j].pweight / (pzf * (l - j - 1) / l)
    return total


def _tree_shap(tree, x: np.ndarray, phi: np.ndarray, node: int, path: List[_PathElem],
               pzf: float, pof: float, pi: int) -> None:
    path = [p.copy() for p in path]
    _extend(path, pzf, pof, pi)
    if tree.left_children[node] == -1:  # leaf
        for i in range(1, len(path)):
            w = _unwound_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * tree.split_conditions[node]
        return
    f = int(tree.split_indices[node])
    v = x[f]
    if np.isnan(v):
        hot = tree.left_children[node] if tree.default_left[node] else tree.right_children[node]
    else:
        hot = tree.left_children[node] if tree.goes_left(node, v) else tree.right_children[node]
    cold = (
        tree.right_children[node]
        if hot == tree.left_children[node]
        else tree.left_children[node]
    )
    w_node = max(tree.sum_hessian[node], 1e-30)
    hot_zf = tree.sum_hessian[hot] / w_node
    cold_zf = tree.sum_hessian[cold] / w_node
    incoming_zf, incoming_of = 1.0, 1.0
    path_index = 0
    while path_index < len(path):
        if path[path_index].feature_index == f:
            break
        path_index += 1
    if path_index != len(path):
        incoming_zf = path[path_index].zero_fraction
        incoming_of = path[path_index].one_fraction
        path = _unwind(path, path_index)
    _tree_shap(tree, x, phi, hot, path, incoming_zf * hot_zf, incoming_of, f)
    _tree_shap(tree, x, phi, cold, path, incoming_zf * cold_zf, 0.0, f)


def _expected_value(tree) -> float:
    """Cover-weighted mean leaf value."""
    leaves = tree.left_children == -1
    w = tree.sum_hessian[leaves]
    v = tree.split_conditions[leaves]
    tot = w.sum()
    return float((w * v).sum() / tot) if tot > 0 else float(v.mean() if len(v) else 0.0)


def _saabas(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """approx_contribs: attribute each step's change in node expectation."""

    def node_value(i: int) -> float:
        if tree.left_children[i] == -1:
            return float(tree.split_conditions[i])
        l, r = tree.left_children[i], tree.right_children[i]
        wl, wr = tree.sum_hessian[l], tree.sum_hessian[r]
        tot = max(wl + wr, 1e-30)
        return (node_value(l) * wl + node_value(r) * wr) / tot

    i = 0
    cur = node_value(0)
    phi[-1] += cur
    while tree.left_children[i] != -1:
        f = int(tree.split_indices[i])
        v = x[f]
        if np.isnan(v):
            nxt = tree.left_children[i] if tree.default_left[i] else tree.right_children[i]
        else:
            nxt = tree.left_children[i] if tree.goes_left(i, v) else tree.right_children[i]
        nv = node_value(nxt)
        phi[f] += nv - cur
        cur = nv
        i = nxt


# ---------------------------------------------------------------------------
# Vectorized TreeShap (rows batched).
#
# Leaf-path reformulation of the reference's recursion
# (tree_model.cc:552-581): a row interacts with a leaf's path ONLY through
# the binary vector o = "does the row go the path's way at each (merged)
# path feature". The path's cover ratios z are row-independent. For each
# (leaf, feature k) the Shapley term is therefore a function of the <= 2^D
# bitmask of o — precompute that table once per leaf with an O(D^2)
# polynomial DP, then every row just indexes it. Complexity:
# O(leaves * D^2 * 2^D) per tree once + O(n * leaves * D) per batch,
# instead of O(n * nodes * depth^2) Python recursion per row.
# ---------------------------------------------------------------------------


def _node_go_left(tree, X: np.ndarray) -> np.ndarray:
    """[n, nodes] bool: would row go LEFT at each internal node (missing ->
    default child; categorical: set goes right, categorical.h Decision)."""
    n = X.shape[0]
    nn = tree.num_nodes
    out = np.zeros((n, nn), bool)
    for i in range(nn):
        if tree.left_children[i] == -1:
            continue
        f = int(tree.split_indices[i])
        v = X[:, f]
        miss = np.isnan(v)
        if tree.split_type is not None and tree.split_type[i]:
            cats = (tree.categories[i] if tree.categories is not None
                    and tree.categories[i] is not None else
                    np.asarray([int(tree.split_conditions[i])]))
            in_set = np.isin(v.astype(np.int64, copy=False), cats) & ~miss
            present_left = ~in_set
        else:
            present_left = v < tree.split_conditions[i]
        out[:, i] = np.where(miss, bool(tree.default_left[i]), present_left)
    return out


def _leaf_paths(tree):
    """Yield (leaf_node, [(node, go_left_bool), ...] root->leaf edges)."""
    stack = [(0, [])]
    while stack:
        node, path = stack.pop()
        if tree.left_children[node] == -1:
            yield node, path
            continue
        stack.append((tree.left_children[node], path + [(node, True)]))
        stack.append((tree.right_children[node], path + [(node, False)]))


def _merge_path(tree, path):
    """Merge repeated features along a path (the recursion's unwind/extend
    of duplicates): per unique feature, z = product of cover ratios, and
    the row's o = AND over its edges. Returns (feats, z, edge_groups)."""
    feats, zs, groups = [], [], []
    index = {}
    for node, go_left in path:
        f = int(tree.split_indices[node])
        child = (tree.left_children[node] if go_left
                 else tree.right_children[node])
        ratio = tree.sum_hessian[child] / max(tree.sum_hessian[node], 1e-30)
        if f in index:
            zs[index[f]] *= ratio
            groups[index[f]].append((node, go_left))
        else:
            index[f] = len(feats)
            feats.append(f)
            zs.append(ratio)
            groups.append([(node, go_left)])
    return np.asarray(feats, np.int64), np.asarray(zs, np.float64), groups


def _shap_weight_sum(z: np.ndarray, o: np.ndarray, skip: int) -> float:
    """Sum over subsets S of path-without-skip of |S|!(D-1-|S|)!/D! *
    prod_{j in S} o_j * prod_{j not in S} z_j — via the polynomial DP
    prod_j (o_j x + z_j), reading coefficients against the Shapley kernel."""
    D = len(z)
    coef = np.zeros(D)
    coef[0] = 1.0
    deg = 0
    for j in range(D):
        if j == skip:
            continue
        new = np.zeros(D)
        new[: deg + 1] += coef[: deg + 1] * z[j]
        new[1: deg + 2] += coef[: deg + 1] * o[j]
        coef = new
        deg += 1
    import math

    total = 0.0
    for s in range(deg + 1):
        total += coef[s] * math.factorial(s) * math.factorial(D - 1 - s) / math.factorial(D)
    return total


def _leaf_tables(z: np.ndarray):
    """[2^D, D] per-mask, per-feature Shapley factors for one merged path:
    entry (m, k) = (o_k - z_k) * U_k where o = bits of m."""
    D = len(z)
    tab = np.zeros((1 << D, D))
    for m in range(1 << D):
        o = np.array([(m >> k) & 1 for k in range(D)], np.float64)
        for k in range(D):
            tab[m, k] = (o[k] - z[k]) * _shap_weight_sum(z, o, k)
    return tab


# paths with more unique features than this use the row-vectorized DP
# instead of the 2^D mask table (table memory/precompute is exponential)
_TABLE_MAX_D = 12


def _shap_weight_sum_rows(z: np.ndarray, obits: np.ndarray,
                          skip: int) -> np.ndarray:
    """Row-vectorized version of ``_shap_weight_sum``: obits is [n, D] of
    per-row path-agreement bits; returns [n]. Polynomial DP with [n]-wide
    coefficient columns — O(D^2) numpy passes, no exponential table."""
    import math

    n, D = obits.shape
    coef = np.zeros((n, D))
    coef[:, 0] = 1.0
    deg = 0
    for j in range(D):
        if j == skip:
            continue
        new = np.zeros((n, D))
        new[:, : deg + 1] = coef[:, : deg + 1] * z[j]
        new[:, 1: deg + 2] += coef[:, : deg + 1] * obits[:, j:j + 1]
        coef = new
        deg += 1
    total = np.zeros(n)
    for s in range(deg + 1):
        total += coef[:, s] * (math.factorial(s) * math.factorial(D - 1 - s)
                               / math.factorial(D))
    return total


def _vector_contribs(tree, X: np.ndarray, out: np.ndarray) -> None:
    """Accumulate [n, F+1] SHAP contributions of one tree into ``out``."""
    n, F = X.shape
    go_left = _node_go_left(tree, X)
    out[:, F] += _expected_value(tree)
    for leaf, path in _leaf_paths(tree):
        v = float(tree.split_conditions[leaf])
        if not path or v == 0.0:
            continue
        feats, z, groups = _merge_path(tree, path)
        D = len(feats)
        # per-row o bits: AND over each feature's edges
        obits = np.zeros((n, D))
        for k, grp in enumerate(groups):
            ok = np.ones(n, bool)
            for node, gl in grp:
                ok &= go_left[:, node] == gl
            obits[:, k] = ok
        if D <= _TABLE_MAX_D:
            mask = (obits.astype(np.int64)
                    * (1 << np.arange(D, dtype=np.int64))).sum(axis=1)
            contrib = _leaf_tables(z)[mask]  # [n, D]
            for k in range(D):
                out[:, feats[k]] += contrib[:, k] * v
        else:  # deep path: row-vectorized DP, no exponential table
            for k in range(D):
                U = _shap_weight_sum_rows(z, obits, k)
                out[:, feats[k]] += (obits[:, k] - z[k]) * U * v


def _vector_interactions(tree, X: np.ndarray, out: np.ndarray) -> None:
    """Accumulate [n, F+1, F+1] SHAP interaction values of one tree
    (reference: CalculateContributionsInteractions — phi_i conditioned on
    feature j present minus absent, halved; diagonal fixed so each row sums
    to the feature's plain contribution)."""
    n, F = X.shape
    go_left = _node_go_left(tree, X)
    for leaf, path in _leaf_paths(tree):
        v = float(tree.split_conditions[leaf])
        if not path or v == 0.0:
            continue
        feats, z, groups = _merge_path(tree, path)
        D = len(feats)
        obits = np.zeros((n, D), np.float64)
        for k, grp in enumerate(groups):
            ok = np.ones(n, bool)
            for node, gl in grp:
                ok &= go_left[:, node] == gl
            obits[:, k] = ok
        if D <= _TABLE_MAX_D:
            mask = (obits.astype(np.int64)
                    * (1 << np.arange(D, dtype=np.int64))).sum(axis=1)
            # pair table [2^D, D, D]: (m, i, j) = (o_j - z_j)*(o_i - z_i)*U_i
            # on the path with j removed
            tab = np.zeros((1 << D, D, D))
            for m in range(1 << D):
                o = np.array([(m >> k) & 1 for k in range(D)], np.float64)
                for j in range(D):
                    zr = np.delete(z, j)
                    orr = np.delete(o, j)
                    for i in range(D):
                        if i == j:
                            continue
                        ir = i if i < j else i - 1
                        tab[m, i, j] = ((o[j] - z[j]) * (orr[ir] - zr[ir])
                                        * _shap_weight_sum(zr, orr, ir))
            vals = tab[mask]  # [n, D, D]
        else:  # deep path: row-vectorized conditioned DP
            vals = np.zeros((n, D, D))
            for j in range(D):
                zr = np.delete(z, j)
                obr = np.delete(obits, j, axis=1)
                oz_j = obits[:, j] - z[j]
                for i in range(D):
                    if i == j:
                        continue
                    ir = i if i < j else i - 1
                    U = _shap_weight_sum_rows(zr, obr, ir)
                    vals[:, i, j] = oz_j * (obr[:, ir] - zr[ir]) * U
        half = 0.5 * v
        for i in range(D):
            for j in range(D):
                if i != j:
                    out[:, feats[i], feats[j]] += (
                        vals[:, i, j] + vals[:, j, i]
                    ) * half


def predict_contribs(booster, dmat, approx: bool = False) -> np.ndarray:
    """[n, F+1] (or [n, K, F+1] multiclass) per-feature contributions +
    bias column (reference: pred_contribs, gbtree PredictContribution).
    Exact TreeShap, vectorized over rows; ``approx`` = Saabas."""
    booster._configure()
    X = np.asarray(dmat.data, np.float32)
    n, F = X.shape
    model = booster._gbm.model
    K = booster.n_groups
    out = np.zeros((n, K, F + 1), np.float64)
    tw = booster._gbm.tree_weights()
    tw = np.asarray(tw) if tw is not None else np.ones(len(model.trees))
    for t, g, w in zip(model.trees, model.tree_info, tw):
        if approx:
            for i in range(n):
                phi = np.zeros(F + 1)
                _saabas(t, X[i], phi)
                out[i, g, :] += phi * w
        else:
            phi = np.zeros((n, F + 1))
            _vector_contribs(t, X, phi)
            out[:, g, :] += phi * w
    out[:, :, F] += booster._base_margin_val
    if K == 1:
        return out[:, 0, :]
    return out


def predict_interactions(booster, dmat) -> np.ndarray:
    """[n, F+1, F+1] (or [n, K, F+1, F+1]) SHAP interaction values
    (reference: ``tree_model.cc:552-581`` CalculateContributionsInteractions
    / ``gpu_predictor.cu:911``). Row sums reproduce ``pred_contribs`` by the
    diagonal construction."""
    booster._configure()
    X = np.asarray(dmat.data, np.float32)
    n, F = X.shape
    model = booster._gbm.model
    K = booster.n_groups
    out = np.zeros((n, K, F + 1, F + 1), np.float64)
    tw = booster._gbm.tree_weights()
    tw = np.asarray(tw) if tw is not None else np.ones(len(model.trees))
    for t, g, w in zip(model.trees, model.tree_info, tw):
        inter = np.zeros((n, F + 1, F + 1))
        _vector_interactions(t, X, inter)
        out[:, g, :, :] += inter * w
    base = predict_contribs(booster, dmat)
    if base.ndim == 2:
        base = base[:, None, :]
    # diagonal: plain contribution minus off-diagonal row sum, so every row
    # of the matrix sums to the feature's contribution (reference property,
    # tests/python/test_shap.py)
    offsum = out.sum(axis=-1)
    for fidx in range(F + 1):
        out[:, :, fidx, fidx] = base[:, :, fidx] - (
            offsum[:, :, fidx] - out[:, :, fidx, fidx]
        )
    if K == 1:
        return out[:, 0]
    return out
