"""SHAP values: TreeShap (reference: ``src/tree/tree_model.cc``
``TreeShap/CalculateContributions:552-581``; GPU variant uses the
GPUTreeShap submodule, ``gpu_predictor.cu:852``).

Host implementation of the exact path-dependent TreeShap recursion (the
algorithm is inherently recursive over the tree; the reference also runs it
on host for CPU predictors). ``approx=True`` gives the Saabas attribution
the reference exposes as ``approx_contribs``.
"""

from __future__ import annotations

from typing import List

import numpy as np


class _PathElem:
    __slots__ = ("feature_index", "zero_fraction", "one_fraction", "pweight")

    def __init__(self, feature_index=-1, zero_fraction=0.0, one_fraction=0.0, pweight=0.0):
        self.feature_index = feature_index
        self.zero_fraction = zero_fraction
        self.one_fraction = one_fraction
        self.pweight = pweight

    def copy(self):
        return _PathElem(self.feature_index, self.zero_fraction, self.one_fraction, self.pweight)


def _extend(path: List[_PathElem], pzf: float, pof: float, pi: int) -> None:
    path.append(_PathElem(pi, pzf, pof, 1.0 if len(path) == 0 else 0.0))
    l = len(path)
    for i in range(l - 2, -1, -1):
        path[i + 1].pweight += pof * path[i].pweight * (i + 1) / l
        path[i].pweight = pzf * path[i].pweight * (l - i - 1) / l


def _unwind(path: List[_PathElem], i: int) -> List[_PathElem]:
    l = len(path)
    out = [p.copy() for p in path]
    n = out[l - 1].pweight
    pof = out[i].one_fraction
    pzf = out[i].zero_fraction
    for j in range(l - 2, -1, -1):
        if pof != 0:
            t = out[j].pweight
            out[j].pweight = n * l / ((j + 1) * pof)
            n = t - out[j].pweight * pzf * (l - j - 1) / l
        else:
            out[j].pweight = out[j].pweight * l / (pzf * (l - j - 1))
    for j in range(i, l - 1):
        out[j].feature_index = out[j + 1].feature_index
        out[j].zero_fraction = out[j + 1].zero_fraction
        out[j].one_fraction = out[j + 1].one_fraction
    out.pop()
    return out


def _unwound_sum(path: List[_PathElem], i: int) -> float:
    l = len(path)
    pof = path[i].one_fraction
    pzf = path[i].zero_fraction
    n = path[l - 1].pweight
    total = 0.0
    for j in range(l - 2, -1, -1):
        if pof != 0:
            t = n * l / ((j + 1) * pof)
            total += t
            n = path[j].pweight - t * pzf * (l - j - 1) / l
        else:
            total += path[j].pweight / (pzf * (l - j - 1) / l)
    return total


def _tree_shap(tree, x: np.ndarray, phi: np.ndarray, node: int, path: List[_PathElem],
               pzf: float, pof: float, pi: int) -> None:
    path = [p.copy() for p in path]
    _extend(path, pzf, pof, pi)
    if tree.left_children[node] == -1:  # leaf
        for i in range(1, len(path)):
            w = _unwound_sum(path, i)
            el = path[i]
            phi[el.feature_index] += w * (el.one_fraction - el.zero_fraction) * tree.split_conditions[node]
        return
    f = int(tree.split_indices[node])
    v = x[f]
    if np.isnan(v):
        hot = tree.left_children[node] if tree.default_left[node] else tree.right_children[node]
    else:
        hot = tree.left_children[node] if tree.goes_left(node, v) else tree.right_children[node]
    cold = (
        tree.right_children[node]
        if hot == tree.left_children[node]
        else tree.left_children[node]
    )
    w_node = max(tree.sum_hessian[node], 1e-30)
    hot_zf = tree.sum_hessian[hot] / w_node
    cold_zf = tree.sum_hessian[cold] / w_node
    incoming_zf, incoming_of = 1.0, 1.0
    path_index = 0
    while path_index < len(path):
        if path[path_index].feature_index == f:
            break
        path_index += 1
    if path_index != len(path):
        incoming_zf = path[path_index].zero_fraction
        incoming_of = path[path_index].one_fraction
        path = _unwind(path, path_index)
    _tree_shap(tree, x, phi, hot, path, incoming_zf * hot_zf, incoming_of, f)
    _tree_shap(tree, x, phi, cold, path, incoming_zf * cold_zf, 0.0, f)


def _expected_value(tree) -> float:
    """Cover-weighted mean leaf value."""
    leaves = tree.left_children == -1
    w = tree.sum_hessian[leaves]
    v = tree.split_conditions[leaves]
    tot = w.sum()
    return float((w * v).sum() / tot) if tot > 0 else float(v.mean() if len(v) else 0.0)


def _saabas(tree, x: np.ndarray, phi: np.ndarray) -> None:
    """approx_contribs: attribute each step's change in node expectation."""

    def node_value(i: int) -> float:
        if tree.left_children[i] == -1:
            return float(tree.split_conditions[i])
        l, r = tree.left_children[i], tree.right_children[i]
        wl, wr = tree.sum_hessian[l], tree.sum_hessian[r]
        tot = max(wl + wr, 1e-30)
        return (node_value(l) * wl + node_value(r) * wr) / tot

    i = 0
    cur = node_value(0)
    phi[-1] += cur
    while tree.left_children[i] != -1:
        f = int(tree.split_indices[i])
        v = x[f]
        if np.isnan(v):
            nxt = tree.left_children[i] if tree.default_left[i] else tree.right_children[i]
        else:
            nxt = tree.left_children[i] if tree.goes_left(i, v) else tree.right_children[i]
        nv = node_value(nxt)
        phi[f] += nv - cur
        cur = nv
        i = nxt


def predict_contribs(booster, dmat, approx: bool = False) -> np.ndarray:
    """[n, F+1] per-feature contributions + bias column (reference:
    pred_contribs in gbtree PredictContribution)."""
    booster._configure()
    X = dmat.data
    n, F = X.shape
    model = booster._gbm.model
    K = booster.n_groups
    out = np.zeros((n, K, F + 1), np.float64)
    tw = booster._gbm.tree_weights()
    tw = np.asarray(tw) if tw is not None else np.ones(len(model.trees))
    for t, g, w in zip(model.trees, model.tree_info, tw):
        ev = _expected_value(t) * w
        for i in range(n):
            if approx:
                phi = np.zeros(F + 1)
                _saabas(t, X[i], phi)
                out[i, g, : F] += phi[:F] * w
                out[i, g, F] += phi[F] * w
            else:
                phi = np.zeros(F + 1)
                _tree_shap(t, X[i], phi, 0, [], 1.0, 1.0, -1)
                out[i, g, :] += phi * w
                out[i, g, F] += ev
    out[:, :, F] += booster._base_margin_val
    if K == 1:
        return out[:, 0, :]
    return out


def predict_interactions(booster, dmat) -> np.ndarray:
    """[n, F+1, F+1] SHAP interaction values via conditional TreeShap runs
    (same construction as the reference's PredictInteractionContributions)."""
    booster._configure()
    X = dmat.data
    n, F = X.shape
    # contribs with each feature fixed on/off; interaction_ij =
    # (phi_i | j present) - (phi_i | j absent) halved and symmetrized.
    # For round-1 we provide the diagonal = contribs minus off-diagonal sums
    # using the direct (slow) definition on the shap matrix.
    base = predict_contribs(booster, dmat)
    if base.ndim == 3:
        raise NotImplementedError("interactions for multiclass pending")
    out = np.zeros((n, F + 1, F + 1), np.float64)
    for i in range(n):
        out[i, np.arange(F + 1), np.arange(F + 1)] = base[i]
    return out
