"""scikit-learn style estimator facade.

Reference: ``python-package/xgboost/sklearn.py`` — ``XGBModel`` (:451),
``XGBClassifier/XGBRegressor/XGBRanker/XGBRF*`` (:1231-1621).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .data.dmatrix import DMatrix
from .learner import Booster
from .training import train as _train

__all__ = [
    "XGBModel",
    "XGBRegressor",
    "XGBClassifier",
    "XGBRanker",
    "XGBRFRegressor",
    "XGBRFClassifier",
]


class XGBModel:
    """Base estimator with get_params/set_params/fit/predict."""

    _estimator_type = "regressor"

    def __init__(
        self,
        max_depth: Optional[int] = None,
        learning_rate: Optional[float] = None,
        n_estimators: int = 100,
        objective: Optional[str] = None,
        booster: Optional[str] = None,
        tree_method: Optional[str] = None,
        gamma: Optional[float] = None,
        min_child_weight: Optional[float] = None,
        max_delta_step: Optional[float] = None,
        subsample: Optional[float] = None,
        colsample_bytree: Optional[float] = None,
        colsample_bylevel: Optional[float] = None,
        colsample_bynode: Optional[float] = None,
        reg_alpha: Optional[float] = None,
        reg_lambda: Optional[float] = None,
        scale_pos_weight: Optional[float] = None,
        base_score: Optional[float] = None,
        random_state: Optional[int] = None,
        missing: float = np.nan,
        num_parallel_tree: Optional[int] = None,
        monotone_constraints: Optional[Union[str, Sequence[int]]] = None,
        interaction_constraints: Optional[Union[str, Sequence[Sequence[int]]]] = None,
        importance_type: Optional[str] = None,
        eval_metric: Optional[Union[str, List[str], Callable]] = None,
        early_stopping_rounds: Optional[int] = None,
        max_bin: Optional[int] = None,
        verbosity: Optional[int] = None,
        n_jobs: Optional[int] = None,
        **kwargs: Any,
    ):
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.objective = objective
        self.booster = booster
        self.tree_method = tree_method
        self.gamma = gamma
        self.min_child_weight = min_child_weight
        self.max_delta_step = max_delta_step
        self.subsample = subsample
        self.colsample_bytree = colsample_bytree
        self.colsample_bylevel = colsample_bylevel
        self.colsample_bynode = colsample_bynode
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.scale_pos_weight = scale_pos_weight
        self.base_score = base_score
        self.random_state = random_state
        self.missing = missing
        self.num_parallel_tree = num_parallel_tree
        self.monotone_constraints = monotone_constraints
        self.interaction_constraints = interaction_constraints
        self.importance_type = importance_type
        self.eval_metric = eval_metric
        self.early_stopping_rounds = early_stopping_rounds
        self.max_bin = max_bin
        self.verbosity = verbosity
        self.n_jobs = n_jobs
        self.kwargs = kwargs
        self._Booster: Optional[Booster] = None

    # -- sklearn protocol --
    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        out = {
            k: v
            for k, v in self.__dict__.items()
            if not k.startswith("_") and k != "kwargs"
        }
        out.update(self.kwargs)
        return out

    def set_params(self, **params: Any) -> "XGBModel":
        for k, v in params.items():
            if hasattr(self, k):
                setattr(self, k, v)
            else:
                self.kwargs[k] = v
        return self

    def get_xgb_params(self) -> Dict[str, Any]:
        params: Dict[str, Any] = {}
        skip = {
            "n_estimators", "missing", "importance_type", "kwargs",
            "early_stopping_rounds", "eval_metric", "random_state",
        }
        for k, v in self.get_params().items():
            if k in skip or v is None:
                continue
            params[k] = v
        if self.random_state is not None:
            params["seed"] = self.random_state
        if self.eval_metric is not None and not callable(self.eval_metric):
            params["eval_metric"] = self.eval_metric
        return params

    def _make_dmatrix(self, X, y=None, sample_weight=None, base_margin=None, group=None, qid=None) -> DMatrix:
        return DMatrix(
            X, label=y, weight=sample_weight, base_margin=base_margin,
            missing=self.missing, group=group, qid=qid,
        )

    def fit(
        self,
        X,
        y,
        sample_weight=None,
        base_margin=None,
        eval_set: Optional[Sequence[Tuple]] = None,
        verbose: bool = False,
        xgb_model: Optional[Booster] = None,
        sample_weight_eval_set=None,
        base_margin_eval_set=None,
        callbacks=None,
    ) -> "XGBModel":
        dtrain = self._make_dmatrix(X, y, sample_weight, base_margin)
        evals = []
        if eval_set:
            for i, (ex, ey) in enumerate(eval_set):
                w = sample_weight_eval_set[i] if sample_weight_eval_set else None
                bm = base_margin_eval_set[i] if base_margin_eval_set else None
                evals.append((self._make_dmatrix(ex, ey, w, bm), f"validation_{i}"))
        self.evals_result_: Dict = {}
        feval = self.eval_metric if callable(self.eval_metric) else None
        self._Booster = _train(
            self.get_xgb_params(),
            dtrain,
            num_boost_round=self.n_estimators,
            evals=evals,
            early_stopping_rounds=self.early_stopping_rounds,
            evals_result=self.evals_result_,
            verbose_eval=verbose,
            xgb_model=xgb_model,
            callbacks=callbacks,
            custom_metric=feval,
        )
        return self

    def predict(
        self, X, output_margin: bool = False, validate_features: bool = True,
        base_margin=None, iteration_range: Optional[Tuple[int, int]] = None,
    ) -> np.ndarray:
        b = self.get_booster()
        # serving fast path (reference sklearn.py:can_use_inplace_predict):
        # raw numpy/scipy inputs skip DMatrix construction entirely and go
        # through the bucketed inplace predictor; anything it does not
        # understand falls back to the DMatrix path below
        if (
            getattr(b._gbm, "name", None) in ("gbtree", "dart")
            and (isinstance(X, np.ndarray) or hasattr(X, "tocsr"))
        ):
            try:
                return b.inplace_predict(
                    X, iteration_range=iteration_range,
                    predict_type="margin" if output_margin else "value",
                    missing=self.missing, base_margin=base_margin,
                    validate_features=validate_features,
                )
            except TypeError:
                # exotic array-likes the fast path can't digest fall back;
                # ValueError (e.g. feature-count mismatch) must PROPAGATE —
                # the DMatrix path would silently mispredict instead
                pass
        d = self._make_dmatrix(X, base_margin=base_margin)
        return b.predict(
            d, output_margin=output_margin, iteration_range=iteration_range
        )

    def apply(self, X, iteration_range=None) -> np.ndarray:
        return self.get_booster().predict(self._make_dmatrix(X), pred_leaf=True)

    def get_booster(self) -> Booster:
        if self._Booster is None:
            raise ValueError("need to call fit first")
        return self._Booster

    def evals_result(self) -> Dict:
        """Evaluation history recorded during fit (reference
        sklearn.py:evals_result)."""
        return getattr(self, "evals_result_", {})

    def get_num_boosting_rounds(self) -> int:
        return self.n_estimators

    def _linear_weights(self) -> np.ndarray:
        gbm = self.get_booster()._gbm
        if getattr(gbm, "name", "") != "gblinear" or gbm.weights is None:
            raise AttributeError(
                "coef_/intercept_ are only defined for booster='gblinear' "
                "(reference sklearn.py raises the same way)"
            )
        return np.asarray(gbm.weights)  # [F+1, K], bias last row

    @property
    def coef_(self) -> np.ndarray:
        w = self._linear_weights()[:-1]
        return w[:, 0] if w.shape[1] == 1 else w.T

    @property
    def intercept_(self) -> np.ndarray:
        return self._linear_weights()[-1]

    def save_model(self, fname: str) -> None:
        self.get_booster().save_model(fname)

    def load_model(self, fname: str) -> None:
        self._Booster = Booster(model_file=fname)

    @property
    def feature_importances_(self) -> np.ndarray:
        b = self.get_booster()
        # reference sklearn.py:1142: default importance is 'weight' for
        # gblinear (its only defined type) and 'gain' for tree boosters
        itype = self.importance_type or (
            "weight" if self.booster == "gblinear" else "gain")
        score = b.get_score(importance_type=itype)
        n = b.num_features()
        names = [f"f{i}" for i in range(n)]
        stored = None
        for d in b._cache_refs.values():
            stored = d.feature_names
            break
        if stored:
            names = stored
        arr = np.array([score.get(nm, 0.0) for nm in names], np.float32)
        total = arr.sum()
        return arr / total if total > 0 else arr

    @property
    def best_iteration(self) -> Optional[int]:
        return getattr(self.get_booster(), "best_iteration", None)

    @property
    def best_score(self) -> Optional[float]:
        return getattr(self.get_booster(), "best_score", None)

    def score(self, X, y, sample_weight=None) -> float:
        from numpy import average

        pred = self.predict(X)
        y = np.asarray(y, dtype=np.float64)
        u = ((y - pred) ** 2 * (sample_weight if sample_weight is not None else 1)).sum()
        v = ((y - average(y, weights=sample_weight)) ** 2 * (sample_weight if sample_weight is not None else 1)).sum()
        return 1.0 - u / v if v > 0 else 0.0


class XGBRegressor(XGBModel):
    def __init__(self, *, objective: str = "reg:squarederror", **kwargs: Any):
        super().__init__(objective=objective, **kwargs)


class XGBClassifier(XGBModel):
    _estimator_type = "classifier"

    def __init__(self, *, objective: str = "binary:logistic", **kwargs: Any):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, **kwargs) -> "XGBClassifier":
        y = np.asarray(y)
        self.classes_ = np.unique(y)
        self.n_classes_ = len(self.classes_)
        y_enc = np.searchsorted(self.classes_, y).astype(np.float32)
        if self.n_classes_ > 2:
            self.objective = (
                self.objective
                if str(self.objective).startswith("multi:")
                else "multi:softprob"
            )
            self.kwargs["num_class"] = self.n_classes_
        super().fit(X, y_enc, **kwargs)
        return self

    def predict(self, X, output_margin=False, **kwargs) -> np.ndarray:
        raw = super().predict(X, output_margin=output_margin, **kwargs)
        if output_margin:
            return raw
        if raw.ndim == 2:  # softprob
            return self.classes_[np.argmax(raw, axis=1)]
        if self.objective == "multi:softmax":
            return self.classes_[raw.astype(int)]
        return self.classes_[(raw > 0.5).astype(int)]

    def predict_proba(self, X, **kwargs) -> np.ndarray:
        raw = super().predict(X, **kwargs)
        if raw.ndim == 2:
            return raw
        return np.stack([1.0 - raw, raw], axis=1)

    def score(self, X, y, sample_weight=None) -> float:
        pred = self.predict(X)
        ok = (pred == np.asarray(y)).astype(np.float64)
        if sample_weight is not None:
            return float((ok * sample_weight).sum() / np.sum(sample_weight))
        return float(ok.mean())


class XGBRanker(XGBModel):
    _estimator_type = "ranker"

    def __init__(self, *, objective: str = "rank:ndcg", **kwargs: Any):
        super().__init__(objective=objective, **kwargs)

    def fit(self, X, y, *, group=None, qid=None, sample_weight=None, eval_set=None,
            eval_group=None, eval_qid=None, verbose=False, **kwargs) -> "XGBRanker":
        if group is None and qid is None:
            raise ValueError("XGBRanker requires group or qid")
        dtrain = DMatrix(X, label=y, weight=sample_weight, missing=self.missing,
                         group=group, qid=qid)
        evals = []
        if eval_set:
            for i, (ex, ey) in enumerate(eval_set):
                g = eval_group[i] if eval_group else None
                q = eval_qid[i] if eval_qid else None
                evals.append((DMatrix(ex, ey, missing=self.missing, group=g, qid=q), f"validation_{i}"))
        self.evals_result_: Dict = {}
        self._Booster = _train(
            self.get_xgb_params(), dtrain, num_boost_round=self.n_estimators,
            evals=evals, early_stopping_rounds=self.early_stopping_rounds,
            evals_result=self.evals_result_, verbose_eval=verbose,
        )
        return self


class XGBRFRegressor(XGBRegressor):
    """Random-forest-style: one round of many parallel trees
    (reference sklearn.py XGBRFRegressor defaults)."""

    def __init__(self, *, learning_rate: float = 1.0, subsample: float = 0.8,
                 colsample_bynode: float = 0.8, reg_lambda: float = 1e-5, **kwargs: Any):
        super().__init__(learning_rate=learning_rate, subsample=subsample,
                         colsample_bynode=colsample_bynode, reg_lambda=reg_lambda, **kwargs)

    def get_xgb_params(self) -> Dict[str, Any]:
        p = super().get_xgb_params()
        p["num_parallel_tree"] = self.n_estimators
        return p

    def fit(self, X, y, **kwargs):
        n = self.n_estimators
        self.n_estimators = 1
        try:
            self.kwargs["num_parallel_tree"] = n
            super().fit(X, y, **kwargs)
        finally:
            self.n_estimators = n
        return self


class XGBRFClassifier(XGBClassifier):
    def __init__(self, *, learning_rate: float = 1.0, subsample: float = 0.8,
                 colsample_bynode: float = 0.8, reg_lambda: float = 1e-5, **kwargs: Any):
        super().__init__(learning_rate=learning_rate, subsample=subsample,
                         colsample_bynode=colsample_bynode, reg_lambda=reg_lambda, **kwargs)

    def fit(self, X, y, **kwargs):
        n = self.n_estimators
        self.n_estimators = 1
        try:
            self.kwargs["num_parallel_tree"] = n
            super().fit(X, y, **kwargs)
        finally:
            self.n_estimators = n
        return self
