"""Declarative parameter structs.

TPU-native analog of ``DMLC_DECLARE_PARAMETER`` (reference:
``dmlc/parameter.h`` usage in ``src/tree/param.h``,
``src/gbm/gbtree.h:61``, ``include/xgboost/generic_parameters.h:15``):
each component owns a parameter struct with defaults, bounds, aliases, and
unknown-key collection, so ``validate_parameters`` can flag typos the same
way ``learner.cc:351`` does.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union


@dataclasses.dataclass
class Field:
    default: Any
    aliases: Tuple[str, ...] = ()
    lower: Optional[float] = None
    upper: Optional[float] = None
    doc: str = ""
    # parse: str -> value coercion (params often arrive as strings, as in the
    # reference's key=value config files, src/common/config.h)
    parse: Optional[Callable[[Any], Any]] = None


def _coerce(value: Any, default: Any, parse: Optional[Callable]) -> Any:
    if parse is not None:
        return parse(value)
    if default is None:
        return value
    t = type(default)
    if t is bool:
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes")
        return bool(value)
    if t is int:
        # tolerate "5", 5.0
        return int(float(value))
    if t is float:
        return float(value)
    if t is str:
        return str(value)
    return value


class ParamSet:
    """Base for parameter structs. Subclasses define FIELDS."""

    FIELDS: Dict[str, Field] = {}

    def __init__(self, **kwargs: Any):
        self._explicit: set = set()
        for name, f in self.FIELDS.items():
            setattr(self, name, f.default)
        self.update(kwargs)

    @classmethod
    def _alias_map(cls) -> Dict[str, str]:
        m = {}
        for name, f in cls.FIELDS.items():
            for a in f.aliases:
                m[a] = name
        return m

    def update(self, kwargs: Dict[str, Any]) -> Dict[str, Any]:
        """Apply known keys; return dict of unknown keys (for chaining into
        other ParamSets / validate_parameters)."""
        unknown: Dict[str, Any] = {}
        amap = self._alias_map()
        for key, value in kwargs.items():
            name = amap.get(key, key)
            f = self.FIELDS.get(name)
            if f is None:
                unknown[key] = value
                continue
            v = _coerce(value, f.default, f.parse)
            if f.lower is not None and isinstance(v, (int, float)) and v < f.lower:
                raise ValueError(f"{name}={v} below lower bound {f.lower}")
            if f.upper is not None and isinstance(v, (int, float)) and v > f.upper:
                raise ValueError(f"{name}={v} above upper bound {f.upper}")
            setattr(self, name, v)
            self._explicit.add(name)
        return unknown

    def is_explicit(self, name: str) -> bool:
        return name in self._explicit

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in self.FIELDS}

    def __repr__(self) -> str:  # pragma: no cover
        return f"{type(self).__name__}({self.to_dict()})"


def _parse_constraint_list(v: Any) -> Any:
    """Parse "(1,-1,0)" style monotone constraint strings (reference:
    src/tree/param.h ParseInteractionConstraint)."""
    if isinstance(v, str):
        s = v.strip().strip("()")
        if not s:
            return []
        return [int(x) for x in s.replace(" ", "").split(",")]
    return list(v)


def _parse_interaction(v: Any) -> Any:
    if isinstance(v, str):
        import json as _json

        s = v.replace("(", "[").replace(")", "]")
        return _json.loads(s) if s.strip() else []
    return [list(g) for g in v]


class TrainParam(ParamSet):
    """Tree training hyper-parameters (reference: ``src/tree/param.h``)."""

    FIELDS = {
        "eta": Field(0.3, aliases=("learning_rate",), lower=0.0),
        "gamma": Field(0.0, aliases=("min_split_loss",), lower=0.0),
        "max_depth": Field(6, lower=0),
        "max_leaves": Field(0, lower=0),
        "max_bin": Field(256, lower=2),
        "grow_policy": Field("depthwise"),
        "min_child_weight": Field(1.0, lower=0.0),
        "reg_lambda": Field(1.0, aliases=("lambda",), lower=0.0),
        "reg_alpha": Field(0.0, aliases=("alpha",), lower=0.0),
        "max_delta_step": Field(0.0, lower=0.0),
        "subsample": Field(1.0, lower=0.0, upper=1.0),
        "sampling_method": Field("uniform"),
        "colsample_bytree": Field(1.0, lower=0.0, upper=1.0),
        "colsample_bylevel": Field(1.0, lower=0.0, upper=1.0),
        "colsample_bynode": Field(1.0, lower=0.0, upper=1.0),
        "monotone_constraints": Field([], parse=_parse_constraint_list),
        "interaction_constraints": Field([], parse=_parse_interaction),
        "max_cat_to_onehot": Field(4, lower=1),
        "sparse_threshold": Field(0.2),
        "sketch_eps": Field(0.03),
        "single_precision_histogram": Field(True),
        "seed": Field(0),
        # refresh/process_type support (reference: TreeProcessType gbtree.h:42)
        "refresh_leaf": Field(True),
    }


class GBTreeParam(ParamSet):
    """Booster-level params (reference: ``src/gbm/gbtree.h:61`` GBTreeTrainParam
    + DartTrainParam ``gbtree.cc``)."""

    FIELDS = {
        "tree_method": Field("auto"),
        "updater": Field(""),
        "num_parallel_tree": Field(1, lower=1),
        "process_type": Field("default"),
        "predictor": Field("auto"),
        # DART
        "sample_type": Field("uniform"),
        "normalize_type": Field("tree"),
        "rate_drop": Field(0.0, lower=0.0, upper=1.0),
        "one_drop": Field(False),
        "skip_drop": Field(0.0, lower=0.0, upper=1.0),
    }


class GBLinearParam(ParamSet):
    """Linear booster params (reference: ``src/gbm/gblinear.cc``,
    ``src/linear/coordinate_common.h``)."""

    FIELDS = {
        "updater": Field("coord_descent"),
        "feature_selector": Field("cyclic"),
        "top_k": Field(0, lower=0),
        "reg_lambda_linear": Field(0.0, aliases=("lambda", "reg_lambda"), lower=0.0),
        "reg_alpha_linear": Field(0.0, aliases=("alpha", "reg_alpha"), lower=0.0),
        "eta_linear": Field(0.5, aliases=("eta", "learning_rate"), lower=0.0),
    }


class LearnerParam(ParamSet):
    """Learner-level params (reference: ``src/learner.cc`` LearnerModelParam /
    LearnerTrainParam)."""

    FIELDS = {
        "objective": Field("reg:squarederror"),
        "booster": Field("gbtree"),
        "base_score": Field(None),
        "num_class": Field(0, lower=0),
        "eval_metric": Field([], parse=lambda v: [v] if isinstance(v, str) else list(v)),
        "disable_default_eval_metric": Field(False),
        "seed": Field(0),
        "nthread": Field(0, aliases=("n_jobs",)),
        "verbosity": Field(1, lower=0, upper=3),
        "validate_parameters": Field(False),
        "multi_strategy": Field("one_output_per_tree"),
        # scale_pos_weight lives with the objective in the reference
        # (regression_obj.cu) but is commonly passed at top level.
        "scale_pos_weight": Field(1.0),
        "tweedie_variance_power": Field(1.5, lower=1.0, upper=2.0),
        "huber_slope": Field(1.0),
        "aft_loss_distribution": Field("normal"),
        "aft_loss_distribution_scale": Field(1.0),
        "max_pairs": Field(100),  # ranking pair sampling cap per group
        "lambdarank_num_pair_per_sample": Field(1, lower=1),
        "device": Field(""),
        # read by BOTH layers: the tree updater's TrainParam AND the
        # Poisson objective (reference keeps two params fed from one key:
        # tree/param.h max_delta_step and regression_obj.cu:197
        # PoissonRegressionParam, whose own default is 0.7). The learner
        # forwards it onward to the gbm (learner.py:_apply_params).
        "max_delta_step": Field(0.0, lower=0.0),
    }
