"""Pre-0.5 jax compatibility shims, installed at package import.

This package is written against the modern surface — ``jax.shard_map``
with the vma replication checker (``check_vma``) and ``jax.lax.pcast``
varying-axis annotations. Older jax (< 0.5) ships shard_map under
``jax.experimental`` with the pre-vma ``check_rep`` checker, which cannot
type this package's level loops (scan carries whose replication the
histogram psum restores each level), and has no ``pcast`` at all.

The shims patch the ``jax`` namespace so the ~10 call sites across
``parallel/`` and ``tree/`` stay written in the one modern dialect:

- ``jax.shard_map`` -> the experimental shard_map with replication
  checking OFF (the compiled program is identical; only the static
  verifier differs),
- ``jax.lax.pcast`` -> identity (pcast only adjusts a value's
  varying-manual-axes TYPE; the pre-vma checker needs no annotation).

Imported from ``xgboost_tpu/__init__`` (and defensively from
``parallel.mesh``) so the patch is in place before any grower can run —
no import-ordering dependency on which submodule loads first. On modern
jax this module is a no-op. The namespace patch is process-global by
design: this repo is the application, and the alternative (threading a
local wrapper through every grower) would fork the call sites into two
dialects.
"""

from __future__ import annotations

import jax

if not hasattr(jax, "shard_map"):  # pragma: no cover - version-dependent
    from jax.experimental.shard_map import shard_map as _shard_map_legacy

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=None,
                          **kw):
        kw.setdefault("check_rep", False)
        return _shard_map_legacy(f, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, **kw)

    jax.shard_map = _shard_map_compat

if not hasattr(jax.lax, "pcast"):  # pragma: no cover - version-dependent
    jax.lax.pcast = lambda x, axis_name, to=None: x
