"""Learner / Booster: the API core.

Reference: ``src/learner.cc`` — ``LearnerConfiguration::Configure``
(:250-357, lazy one-time objective/GBM/metric creation),
``LearnerImpl::UpdateOneIter`` (:1060 — PredictRaw -> GetGradient ->
DoBoost), ``BoostOneIter`` (:1088 custom objective), ``EvalOneIter``
(:1105), LearnerIO JSON model save/load (:659-994), plus the Python
``Booster`` facade (python-package/xgboost/core.py). Here the two layers
collapse into one class: there is no C API boundary to cross — the Python
object IS the learner, and device state (prediction caches) lives in JAX
arrays.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .data.dmatrix import DMatrix
from .gbm import create_booster
from .metric import create_metric
from .objective import create_objective
from .observability import REGISTRY as _REGISTRY, trace as _trace
from .params import LearnerParam
from .registry import BOOSTERS, OBJECTIVES
from .utils import Monitor, console_logger, fault

__all__ = ["Booster"]

_VERSION = [2, 0, 0]  # this framework's model version triplet


def _multiprocess_mesh_active() -> bool:
    """True only when training would run the COLLECTIVE multi-process path:
    several processes AND an active ``mesh_context``. A program that merely
    initialized jax.distributed (e.g. for its own IO) but trains mesh-less
    per-process boosters takes the normal local paths. Shares the metric
    layer's predicate so routing and reductions cannot disagree."""
    from .parallel.mesh import collective_active

    return collective_active()


class _PredCache:
    """Versioned prediction cache (reference: PredictionContainer,
    include/xgboost/predictor.h:242 — tracks how many trees are already
    folded into the cached margin)."""

    def __init__(self) -> None:
        self.margin: Optional[jax.Array] = None  # [n, K]
        self.num_trees: int = 0
        # whether the cached margin may have come from the predict_walk
        # dispatch route's NATIVE walker (double accumulation — off by
        # ~1 ulp from the device path): the TRAINING margin read
        # (_cached_margin) must never consume such an entry, or resumed
        # runs would stop being bit-identical to uninterrupted ones
        self.native: bool = False


class Booster:
    """A trained (or training) gradient-boosted model."""

    def __init__(
        self,
        params: Optional[Dict[str, Any]] = None,
        cache: Optional[Sequence[DMatrix]] = None,
        model_file: Optional[Union[str, bytes, os.PathLike]] = None,
    ):
        self.lparam = LearnerParam()
        self._extra_params: Dict[str, Any] = {}
        self._gbm = None
        self._obj = None
        self._metrics: List = []
        self._base_margin_val: float = 0.0
        self._caches: Dict[int, _PredCache] = {}
        self._cache_refs: Dict[int, DMatrix] = {}
        # stacked-forest snapshots keyed by (num_trees, resolved
        # iteration_range): repeated predicts — the serving pattern — must
        # not re-stack/re-pad trees per request (see _forest_snapshot).
        # Lock-guarded: a multi-threaded serving frontend hits this from
        # concurrent inplace_predict calls (lock recreated on unpickle via
        # __setstate__ -> __init__)
        self._forest_snapshots: "OrderedDict" = OrderedDict()
        self._forest_snapshots_lock = threading.Lock()
        self.attributes_: Dict[str, str] = {}
        self.best_iteration: Optional[int] = None
        self.best_score: Optional[float] = None
        # bounded in-flight window for pipelined update_many chunks
        # (pipeline.RoundPipeline, created lazily; never pickled)
        self._pipeline = None
        self.monitor = Monitor("Booster")
        if params:
            self._apply_params(dict(params))
        if cache:
            for d in cache:
                self._caches[id(d)] = _PredCache()
                self._cache_refs[id(d)] = d
        if model_file is not None:
            self.load_model(model_file)

    # ------------------------------------------------------------------
    # configuration (lazy, like reference Configure())
    # ------------------------------------------------------------------
    def _apply_params(self, params: Dict[str, Any]) -> None:
        unknown = self.lparam.update(params)
        self._extra_params.update(unknown)
        # shared keys consumed by the learner-level ParamSet but ALSO read
        # by the tree layer (see LearnerParam.FIELDS note): forward them
        for k in ("max_delta_step",):
            if k in params:
                self._extra_params[k] = params[k]
        if self.lparam.validate_parameters:
            self._validate_unknown()

    def _validate_unknown(self) -> None:
        """validate_parameters (reference: learner.cc:351) — flag keys no
        component recognized."""
        from .params import GBLinearParam, GBTreeParam, TrainParam

        known = set()
        for P in (GBTreeParam, TrainParam, GBLinearParam):
            known.update(P.FIELDS)
            for f in P.FIELDS.values():
                known.update(f.aliases)
        bad = [k for k in self._extra_params if k not in known]
        if bad:
            raise ValueError(f"Unknown parameters: {bad}")

    def set_param(self, params, value=None) -> None:
        if isinstance(params, str):
            params = {params: value}
        elif isinstance(params, (list, tuple)):
            params = dict(params)
        self._apply_params(dict(params))
        if self._gbm is not None:
            for k, v in params.items():
                try:
                    self._gbm.set_param(k, v)
                except Exception:
                    pass
            if self._obj is not None and hasattr(self._obj, "params"):
                self._obj.params = self.lparam
        self._metrics = []  # re-resolve on next eval

    def _configure(self) -> None:
        if self._obj is None:
            self._obj = create_objective(self.lparam.objective, self.lparam)
        if self._gbm is None:
            n_groups = self._obj.n_targets()
            self._gbm = create_booster(self.lparam.booster, n_groups, self._extra_params)
        base = self.lparam.base_score
        if base is None:
            base = self._obj.default_base_score()
        self._base_margin_val = float(self._obj.prob_to_margin(float(base)))

    @property
    def n_groups(self) -> int:
        self._configure()
        return self._gbm.n_groups

    # ------------------------------------------------------------------
    # margins & caches
    # ------------------------------------------------------------------
    def _base_margin_for(self, dmat: DMatrix, n: int) -> jax.Array:
        K = self.n_groups
        bm = dmat.info.base_margin
        if bm is not None and bm.size:
            b = jnp.asarray(bm, jnp.float32)
            return b.reshape(n, K) if b.ndim != 2 else b
        return jnp.full((n, K), self._base_margin_val, jnp.float32)

    def _cached_margin(self, dtrain: DMatrix) -> jax.Array:
        """PredictRaw with cache (reference learner.cc:1075)."""
        entry = self._caches.setdefault(id(dtrain), _PredCache())
        self._cache_refs.setdefault(id(dtrain), dtrain)
        n = dtrain.num_row()
        if self._gbm.name == "dart":
            # dropout changes old-tree weights: always a fresh dropped pass
            base = self._base_margin_for(dtrain, n)
            return self._gbm.training_margin(dtrain.data, base)
        # native_ok=False: gradients must stay byte-stable regardless of
        # how eval/predict walks are routed (ISSUE 15)
        return self._predict_margin(dtrain, native_ok=False)

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------
    def update(self, dtrain: DMatrix, iteration: int, fobj=None) -> None:
        """One boosting iteration (reference UpdateOneIter learner.cc:1060)."""
        self._configure()
        if fobj is None and _multiprocess_mesh_active():
            # multi-process MESH boosting only exists as scan chunks
            # (per-round deltas stay device-sharded, gbtree.boost_one_round
            # raises) — a single round IS a 1-chunk scan, so train()'s
            # per-round loop with eval/early-stop composes with dsplit=row
            # directly. Multi-process WITHOUT an active mesh is per-process
            # local training and takes the normal path.
            self.update_many(dtrain, iteration, 1, chunk=1)
            return
        with _trace.span("update", iteration=iteration):
            self._update(dtrain, iteration, fobj)
        _REGISTRY.counter(
            "rounds_total", "Boosting rounds dispatched").inc()

    def _update(self, dtrain: DMatrix, iteration: int, fobj=None) -> None:
        fault.begin_version(iteration)
        fault.inject("gradient")
        if fobj is not None:
            margin = self._cached_margin(dtrain)
            pred = np.asarray(margin)
            if pred.shape[1] == 1:
                pred = pred[:, 0]
            grad, hess = fobj(pred, dtrain)
            self.boost(dtrain, grad, hess)
            return
        from .utils import observer

        with self.monitor.section("GetGradient"):
            margin = self._cached_margin(dtrain)
            m = margin[:, 0] if self.n_groups == 1 else margin
            info = dtrain.info
            grad, hess = self._obj.get_gradient(
                m,
                jnp.asarray(info.label) if info.label is not None else jnp.zeros(dtrain.num_row()),
                jnp.asarray(info.weight) if info.weight is not None else None,
                iteration,
                group_ptr=info.group_ptr,
                label_lower=jnp.asarray(info.label_lower_bound) if info.label_lower_bound is not None else None,
                label_upper=jnp.asarray(info.label_upper_bound) if info.label_upper_bound is not None else None,
            )
        if observer.enabled():
            observer.observe("margin", margin, iteration)
            observer.observe("grad", grad, iteration)
            observer.observe("hess", hess, iteration)
        self._do_boost(dtrain, grad, hess, iteration)
        self.monitor.maybe_print()

    def update_many(self, dtrain: DMatrix, start_iteration: int,
                    num_rounds: int, chunk: int = 25) -> None:
        """``num_rounds`` boosting rounds with ONE device dispatch per
        ``chunk`` rounds (a ``lax.scan`` over the fused round program,
        ``gbm/gbtree.py:boost_rounds_scan``) — same trees as calling
        ``update`` per round (identical RNG keys). Falls back to the per-round path whenever the
        configuration is outside the scan-safe envelope (ranking/survival
        objectives, DART, lossguide, categorical, external memory, custom
        objective); multiclass (one tree per group per scanned round) and
        mesh training (the chunk scan runs inside one shard_map) are
        supported."""
        self._configure()
        binned = None
        if (
            self._gbm.name == "gbtree"
            and not getattr(self._gbm, "needs_iteration_sketch", False)
            and not getattr(self._gbm, "needs_local_sketch", False)
            and not getattr(self._gbm, "needs_exact_cuts", False)
            and dtrain.info.label is not None
        ):
            binned = dtrain.get_binned(self._gbm.train_param.max_bin,
                                       dtrain.info.weight)
        if binned is None or not self._gbm.scan_rounds_supported(
                binned, self._obj, self.n_groups):
            if _multiprocess_mesh_active():
                raise NotImplementedError(
                    "this configuration is outside the multi-process scan "
                    "envelope (ranking/survival/DART/lossguide/categorical/"
                    "external-memory/custom objectives are single-process); "
                    "see docs/distributed.md")
            for i in range(start_iteration, start_iteration + num_rounds):
                self.update(dtrain, i)
            return
        from .observability import flight as _flight
        from .pipeline import RoundPipeline, completion_probe

        if self._pipeline is None:
            self._pipeline = RoundPipeline()
        entry = self._caches.setdefault(id(dtrain), _PredCache())
        done = 0
        while done < num_rounds:
            k = min(chunk, num_rounds - done)
            # one flight record per chunk (rounds=k): the scan path's
            # dispatch cadence is per-chunk, so that is the granularity
            # the recorder can honestly time. Under train()'s per-round
            # loop (mesh: update -> 1-chunk scan) the begin is NESTED and
            # owned stays False: the outer loop already times the whole
            # update as "grow", so noting it here too would double-count.
            owned = _flight.RECORDER.begin_round(
                start_iteration + done, rounds=k)
            # profiling is independent of the recorder: owned is False
            # both for a nested begin (outer loop already ticks) AND when
            # XGBTPU_FLIGHT=0 — the profiler window must still open then
            if owned or not _flight.enabled():
                _flight.profile_tick(start_iteration + done)
            try:
                fault.begin_version(start_iteration + done)
                fault.inject("gradient")
                fault.inject("grow")
                margin = self._cached_margin(dtrain)
                # detach before the chunk donates the carried margin: an
                # abort mid-chunk must not leave a deleted buffer in the
                # cache (see _do_boost)
                entry.margin = None
                info = dtrain.info
                _t0 = time.perf_counter()
                margin = self._gbm.boost_rounds_scan(
                    binned, self._obj,
                    jnp.asarray(info.label), info.weight, margin,
                    start_iteration + done, k,
                    feature_weights=info.feature_weights,
                )
                if owned:
                    _flight.note("grow", time.perf_counter() - _t0)
                entry.margin = margin
                entry.num_trees = self._gbm.model.num_trees
                # pipelined chunks (ISSUE 13): the dispatch above is
                # async — admit its output and only block once more than
                # XGBTPU_PIPELINE_DEPTH chunks are in flight, so chunk
                # i+1's host work (gradient staging, dispatch) overlaps
                # chunk i's device execution with a pinned memory
                # watermark. An async fault surfaces here attributed to
                # the chunk's first round (sync time -> 'sync' stage).
                try:
                    self._pipeline.admit(start_iteration + done,
                                         completion_probe(margin))
                except BaseException:
                    self._pipeline.abandon()  # younger chunks are dead too
                    raise
                _REGISTRY.counter(
                    "rounds_total", "Boosting rounds dispatched").inc(k)
                done += k
            finally:
                _flight.RECORDER.end_round()

    def boost(self, dtrain: DMatrix, grad, hess) -> None:
        """Custom-objective boost (reference BoostOneIter learner.cc:1088)."""
        self._configure()
        grad = jnp.asarray(np.asarray(grad, np.float32))
        hess = jnp.asarray(np.asarray(hess, np.float32))
        self._do_boost(dtrain, grad, hess, iteration=self.num_boosted_rounds())

    def _do_boost(self, dtrain: DMatrix, grad, hess, iteration: int) -> None:
        fault.inject("grow")
        entry = self._caches.setdefault(id(dtrain), _PredCache())
        if self._gbm.name in ("gbtree", "dart"):
            if getattr(self._gbm, "_is_update_process", False):
                # process_type=update / updater=refresh: re-stat existing
                # trees on this data, no new trees (updater_refresh.cc:162)
                with self.monitor.section("Refresh"):
                    self._gbm.refresh_one_round(
                        dtrain.data, grad, hess, iteration
                    )
                entry.margin = None  # leaf values changed
                self._forest_snapshots.clear()  # same num_trees, new leaves
                return
            if getattr(self._gbm, "needs_local_sketch", False):
                # updater=grow_local_histmaker: per-node re-sketched cuts,
                # grown from RAW values — no global quantized matrix
                # (updater_histmaker.cc:753)
                if self._gbm.name != "gbtree":
                    raise NotImplementedError(
                        "grow_local_histmaker is a gbtree updater")
                if getattr(dtrain, "data_is_reconstructed", False):
                    # a QuantileDMatrix's .data is bin-reconstructed (at
                    # most max_bin distinct values/feature): re-sketching
                    # it would silently lose exactly the sub-bin
                    # resolution this updater exists for. The reference's
                    # QuantileDMatrix is likewise hist-only.
                    raise NotImplementedError(
                        "grow_local_histmaker needs TRUE raw values; a "
                        "QuantileDMatrix only holds quantized bins — "
                        "construct a DMatrix instead")
                try:
                    X_raw = dtrain.data  # paged matrices refuse this
                except NotImplementedError:
                    X_raw = None
                if X_raw is None:
                    raise NotImplementedError(
                        "grow_local_histmaker needs in-memory data for "
                        "per-node re-sketching")
                if dtrain.categorical_features():
                    raise NotImplementedError(
                        "grow_local_histmaker supports numerical features "
                        "only (the reference's local maker predates "
                        "categorical support)")
                margin_cache = entry.margin
                entry.margin = None  # donated below; see the gbtree branch
                with self.monitor.section("BoostOneRound"):
                    _, new_margin = self._gbm.local_boost_one_round(
                        X_raw, grad, hess, iteration, margin_cache,
                        feature_weights=dtrain.info.feature_weights)
                if new_margin is not None:
                    entry.margin = new_margin
                    entry.num_trees = self._gbm.model.num_trees
                else:
                    entry.margin = None
                return
            with self.monitor.section("GetBinned"):
                if getattr(self._gbm, "needs_iteration_sketch", False):
                    # approx: fresh hessian-weighted cuts every round
                    # (updater_histmaker.cc per-iteration proposal). hess is
                    # already instance-weight-scaled by the objective, so it
                    # is the complete sketch weight. Reuses the cached
                    # get_binned path's categorical + distributed-sketch
                    # machinery via the uncached builder.
                    if not hasattr(dtrain, "build_binned"):
                        raise NotImplementedError(
                            "tree_method='approx' needs in-memory data for "
                            "per-iteration re-sketching; use tpu_hist for "
                            "external-memory matrices"
                        )
                    hw = np.asarray(hess, np.float32)
                    if hw.ndim == 2:
                        hw = hw.sum(axis=1)
                    binned = dtrain.build_binned(
                        self._gbm.train_param.max_bin, hw
                    )
                elif getattr(self._gbm, "needs_exact_cuts", False):
                    # exact: one bin per distinct value (colmaker candidate
                    # set, updater_colmaker.cc:367)
                    if not hasattr(dtrain, "get_binned_exact"):
                        raise NotImplementedError(
                            "tree_method='exact' needs in-memory data; "
                            "use tpu_hist for external-memory matrices"
                        )
                    binned = dtrain.get_binned_exact()
                else:
                    binned = dtrain.get_binned(self._gbm.train_param.max_bin, dtrain.info.weight)
            fw = dtrain.info.feature_weights
            # detach the cache entry for the duration of the round: the
            # margin buffer is DONATED into the round's margin update, and
            # an abort mid-round (chaos fault, watchdog, Ctrl-C) must not
            # leave a deleted array reachable through the cache (the
            # incremental catch-up in _predict_margin would read it)
            margin_cache = entry.margin
            entry.margin = None
            with self.monitor.section("BoostOneRound"):
                _, new_margin = self._gbm.boost_one_round(
                    binned, grad, hess, iteration, margin_cache,
                    feature_weights=fw,
                )
            if new_margin is not None:
                entry.margin = new_margin
                entry.num_trees = self._gbm.model.num_trees
            else:
                entry.margin = None  # DART: invalidate
        else:  # gblinear
            self._gbm.boost_one_round(dtrain.data, grad, hess, iteration)
            entry.margin = None

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _resolve_metrics(self) -> List:
        self._configure()
        if not self._metrics:
            names = list(self.lparam.eval_metric)
            if not names and not self.lparam.disable_default_eval_metric:
                names = [self._obj.default_metric()]
            self._metrics = [create_metric(n) for n in names]
            for m in self._metrics:
                # metrics that share objective configuration (aft-nloglik's
                # distribution/scale — the reference configures the metric
                # with the same AFTParam, survival_metric.cu) read it here
                m.lparam = self.lparam
        return self._metrics

    def eval_set(self, evals, iteration: int = 0, feval=None, output_margin: bool = True) -> str:
        self._configure()
        fault.inject("eval")
        evals = list(evals)
        with _trace.span("eval", iteration=iteration, n_sets=len(evals)):
            return self._eval_set(evals, iteration, feval)

    def _eval_set(self, evals, iteration: int, feval=None) -> str:
        parts = [f"[{iteration}]"]
        for dmat, name in evals:
            # the per-eval-round walk rides the predict_walk dispatch
            # route (native on CPU) — ISSUE 15 tentpole (d)
            margin = self._predict_margin(dmat, native_ok=True)
            preds = self._obj.eval_transform(margin[:, 0] if self.n_groups == 1 else margin)
            info = dmat.info
            for metric in self._resolve_metrics():
                val = metric.evaluate(
                    preds,
                    jnp.asarray(info.label) if info.label is not None else jnp.zeros(dmat.num_row()),
                    info.weight,
                    group_ptr=info.group_ptr,
                    label_lower=info.label_lower_bound,
                    label_upper=info.label_upper_bound,
                )
                parts.append(f"{name}-{metric.name}:{val:.6f}")
            if feval is not None:
                m = np.asarray(margin)
                fname, fval = feval(m[:, 0] if m.shape[1] == 1 else m, dmat)
                parts.append(f"{name}-{fname}:{fval:.6f}")
        return "\t".join(parts)

    def eval(self, data: DMatrix, name: str = "eval", iteration: int = 0) -> str:
        return self.eval_set([(data, name)], iteration)

    # ------------------------------------------------------------------
    # prediction
    # ------------------------------------------------------------------
    def _data_blocks(self, dmat: DMatrix, blk: int = 65536):
        """Yield (lo, hi, X_block) over a matrix's rows WITHOUT densifying
        the whole thing: disk-backed matrices stream quantized pages
        (reconstructed from cut midpoints — the reference's page-streamed
        predict, cpu_predictor.cc:266), CSR-backed ones densify row
        blocks, plain ones yield their array once."""
        n = dmat.num_row()
        paged = getattr(dmat, "_paged", None)
        if paged is not None:
            self._warn_foreign_paged(dmat, paged)
            for k in range(paged.n_pages):
                lo = k * paged.page_rows
                yield lo, lo + paged.rows_of(k), jnp.asarray(
                    paged.float_page(k))
        elif getattr(dmat, "_sparse", None) is not None and dmat._data is None:
            for lo in range(0, n, blk):
                hi = min(lo + blk, n)
                yield lo, hi, dmat._sparse.dense_rows(lo, hi)
        else:
            yield 0, n, dmat.data

    def _warn_foreign_paged(self, dmat: DMatrix, paged) -> None:
        """Page-streamed predict reconstructs features from cut MIDPOINTS,
        which routes exactly only through split thresholds drawn from the
        SAME cuts (data/external.py:midpoints). A foreign booster — loaded
        from file or trained on other data — can flip decisions near
        thresholds, so walking it over a paged matrix gets a loud warning
        (reference cpu_predictor.cc:266 streams raw pages and has no such
        approximation). Checked once per (matrix, model-size) pair: every
        internal-node threshold must be a member of the matrix's own cut
        set for its feature."""
        if self._gbm.name not in ("gbtree", "dart"):
            return
        key = (id(dmat), self._gbm.model.num_trees)
        if getattr(self, "_paged_cuts_checked", None) == key:
            return
        self._paged_cuts_checked = key
        forest = self._gbm.model.stacked()
        if forest.left.shape[0] == 0:
            return
        left = np.asarray(forest.left)
        feat = np.asarray(forest.feature)
        cond = np.asarray(forest.cond, np.float32)
        internal = left >= 0
        if not internal.any():
            return
        cuts = np.asarray(paged.cuts.values, np.float32)  # [F, B]
        f = feat[internal].ravel()
        c = cond[internal].ravel()
        ok = np.zeros(f.shape[0], bool)
        for fi in np.unique(f):
            sel = f == fi
            if not 0 <= int(fi) < cuts.shape[0]:
                continue  # model splits on a feature the matrix lacks:
                # definitely foreign, leave ok=False for these nodes
            ok[sel] = np.isin(c[sel], cuts[int(fi)])
        if not ok.all():
            import warnings

            warnings.warn(
                "predict on an external-memory matrix with a booster whose "
                f"split thresholds are not drawn from this matrix's cuts "
                f"({int((~ok).sum())}/{ok.size} internal nodes foreign): "
                "page-streamed features are reconstructed from cut "
                "midpoints, so decisions near thresholds may flip. "
                "Predict from an in-memory DMatrix for exact results.",
                UserWarning, stacklevel=4)

    def _forest_snapshot(self, iteration_range=None):
        """(StackedForest, tree_weights) for the current model restricted to
        ``iteration_range`` (None or (0, 0) = all rounds), LRU-cached keyed
        by (num_trees, resolved range). The stacking/padding work — host
        tree walks, pow2 padding, device transfer — happens once per model
        version, not once per predict call: this is what lets a serving
        loop issue thousands of ``inplace_predict`` calls without touching
        the tree store (reference analog: gbtree keeps its device model
        resident across PredictBatch calls, gpu_predictor.cu)."""
        self._configure()
        if iteration_range is not None and tuple(iteration_range) == (0, 0):
            iteration_range = None
        cur = self._gbm.model.num_trees
        if iteration_range is None:
            rkey = None
        else:
            lo, hi = iteration_range
            if hi == 0:
                hi = self.num_boosted_rounds()
            rkey = (int(lo), int(hi))
        key = (cur, rkey)
        with self._forest_snapshots_lock:
            hit = self._forest_snapshots.get(key)
            if hit is not None:
                self._forest_snapshots.move_to_end(key)
                _REGISTRY.counter(
                    "predict_forest_snapshot_hits_total",
                    "Predicts served from a cached stacked forest").inc()
                return hit
        _REGISTRY.counter(
            "predict_forest_snapshot_misses_total",
            "Stacked-forest (re)builds for predict").inc()
        tw = self._gbm.tree_weights()
        if rkey is None:
            forest = self._gbm.model.stacked()
        else:
            lo, hi = rkey
            forest = self._gbm.model.slice(lo, hi).stacked()
            if tw is not None:
                per_round = max(1, self._gbm.n_groups) * \
                    self._gbm.gbtree_param.num_parallel_tree
                tw = tw[lo * per_round: hi * per_round]
        with self._forest_snapshots_lock:
            self._forest_snapshots[key] = (forest, tw)
            while len(self._forest_snapshots) > 4:
                self._forest_snapshots.popitem(last=False)
        return forest, tw

    def _predict_margin(self, dmat: DMatrix, iteration_range=None,
                        native_ok: bool = False) -> jax.Array:
        """``native_ok`` (ISSUE 15 tentpole (d)): the EVAL path
        (``_eval_set``) routes its per-round walks through the
        ``predict_walk`` kernel dispatch op — the same table the serving
        plane resolves, which on CPU picks the native SoA walker
        (order-of-magnitude faster than the XLA gather walk; pin away
        with ``XGBTPU_DISPATCH=predict_walk=xla``). Everything else —
        the training margin read (``_cached_margin``) AND the public
        ``predict`` path — keeps ``native_ok=False``: the native walker
        accumulates in double (≈1 ulp off the device path), gradients
        must stay byte-stable so resumed runs remain bit-identical, and
        ``predict`` results must be bit-stable regardless of
        prediction-cache state (cached margins are device-accumulated;
        tests/test_c_api.py pins fresh-load vs cached equality)."""
        self._configure()
        n = dmat.num_row()
        base = self._base_margin_for(dmat, n)
        from .predictor import predict_margin as _pm_xla
        from .predictor import walk_margin as _pm_walk

        _pm = _pm_walk if native_ok else _pm_xla
        # conservative taint marker for cache entries the dispatch route
        # MAY have filled through the native walker. Deliberately NOT
        # keyed on the backend: device platforms route to the native
        # walker too when pallas_predict is degraded (the dispatch
        # table's reason="degraded" fallback), and an untainted native
        # fill there would feed ~1-ulp-off margins to _cached_margin.
        # The cost of over-tainting is one XLA recompute if a
        # native_ok=False reader ever consumes such an entry — rare
        # (training keeps dtrain's cache current itself).
        _taints = native_ok
        if iteration_range is not None and self._gbm.name in ("gbtree", "dart"):
            stacked, tw = self._forest_snapshot(iteration_range)
            parts = [_pm(stacked, X, base[blo:bhi], tw)
                     for blo, bhi, X in self._data_blocks(dmat)]
            return jnp.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        # cache fast path for full-model predictions, with INCREMENTAL
        # catch-up: only trees not yet folded into the cache are walked
        # (reference: gbtree.cc:519 'cache hit? only new trees applied').
        # DART is excluded — dropout rescales old trees every round.
        entry = self._caches.get(id(dmat))
        cur = self._gbm.model.num_trees if hasattr(self._gbm, "model") else -1
        if (entry is not None and entry.margin is not None
                and entry.num_trees == cur
                and (native_ok or not entry.native)):
            return entry.margin
        K = self.n_groups
        per_round = max(1, K) * (
            self._gbm.gbtree_param.num_parallel_tree
            if hasattr(self._gbm, "gbtree_param")
            else 1
        )
        if (
            entry is not None
            and self._gbm.name == "gbtree"
            and entry.margin is not None
            and 0 < entry.num_trees < cur
            and (native_ok or not entry.native)
            # far behind (e.g. predicting after a long training run with no
            # intermediate evals): one full pass beats replaying per-round
            and cur - entry.num_trees <= 16 * per_round
        ):
            model = self._gbm.model
            while entry.num_trees < cur:
                hi = min(entry.num_trees + per_round, cur)
                # stacked_slice keeps device trees on device — no host
                # materialization from inside the eval loop; data streams
                # in blocks (pages / CSR row blocks / one dense array), so
                # out-of-core eval sets catch up in O(new trees) too
                sub = model.stacked_slice(entry.num_trees, hi)
                parts = [
                    _pm(sub, X, jnp.zeros((bhi - blo, K), jnp.float32))
                    for blo, bhi, X in self._data_blocks(dmat)
                ]
                delta = (jnp.concatenate(parts, axis=0) if len(parts) > 1
                         else parts[0])
                entry.margin = entry.margin + delta
                entry.num_trees = hi
                entry.native = entry.native or _taints
            return entry.margin
        if cur == 0:
            # empty model: don't touch dmat.data (streaming matrices
            # reconstruct raw values lazily — the zero-tree margin is base)
            margin = base
        elif native_ok and self._gbm.name in ("gbtree", "dart"):
            # full pass through the dispatch-routed walker (the gbm's own
            # predict stays on the XLA walk — gradient numerics)
            stacked = self._gbm.model.stacked()
            tw = self._gbm.tree_weights()
            parts = [_pm_walk(stacked, X, base[blo:bhi], tw)
                     for blo, bhi, X in self._data_blocks(dmat)]
            margin = (jnp.concatenate(parts, axis=0) if len(parts) > 1
                      else parts[0] if parts else base)
        else:
            # stream whatever the matrix is backed by: quantized disk
            # pages, CSR row blocks, or one dense array (_data_blocks)
            parts = [self._gbm.predict(X, base[blo:bhi])
                     for blo, bhi, X in self._data_blocks(dmat)]
            margin = (jnp.concatenate(parts, axis=0) if len(parts) > 1
                      else parts[0] if parts else base)
        if entry is not None and self._gbm.name == "gbtree":
            entry.margin = margin
            entry.num_trees = cur
            entry.native = _taints
        return margin

    def predict(
        self,
        data: DMatrix,
        output_margin: bool = False,
        pred_leaf: bool = False,
        pred_contribs: bool = False,
        approx_contribs: bool = False,
        pred_interactions: bool = False,
        validate_features: bool = True,
        training: bool = False,
        iteration_range: Optional[Tuple[int, int]] = None,
        strict_shape: bool = False,
        ntree_limit: int = 0,
    ) -> np.ndarray:
        with _trace.span("predict", rows=data.num_row()):
            return self._predict(
                data, output_margin, pred_leaf, pred_contribs,
                approx_contribs, pred_interactions, validate_features,
                training, iteration_range, strict_shape, ntree_limit)

    def _predict(
        self,
        data: DMatrix,
        output_margin: bool = False,
        pred_leaf: bool = False,
        pred_contribs: bool = False,
        approx_contribs: bool = False,
        pred_interactions: bool = False,
        validate_features: bool = True,
        training: bool = False,
        iteration_range: Optional[Tuple[int, int]] = None,
        strict_shape: bool = False,
        ntree_limit: int = 0,
    ) -> np.ndarray:
        self._configure()
        if ntree_limit and iteration_range is None:
            per_round = max(1, self.n_groups) * (
                self._gbm.gbtree_param.num_parallel_tree
                if hasattr(self._gbm, "gbtree_param")
                else 1
            )
            iteration_range = (0, max(1, ntree_limit // per_round))
        if self._gbm.name == "gblinear":
            if pred_leaf:
                raise ValueError(
                    "gblinear does not support prediction of leaf index")
            if pred_interactions:
                # linear models have no interaction effects: zeros with
                # the contribs' shape convention (gblinear.cc:214)
                n = data.num_row()
                F = self.num_features()
                K = max(1, self.n_groups)
                shape = (n, F + 1, F + 1) if K == 1 else (n, K, F + 1,
                                                          F + 1)
                return np.zeros(shape, np.float32)
            if pred_contribs:
                return self._gblinear_contribs(data)
        if pred_leaf:
            parts = [np.asarray(self._gbm.predict_leaf(X))
                     for _, _, X in self._data_blocks(data)]
            return np.concatenate(parts, axis=0) if len(parts) > 1 else parts[0]
        if pred_contribs or pred_interactions:
            from .interpret import predict_contribs, predict_interactions

            if pred_interactions:
                return predict_interactions(self, data)
            return predict_contribs(self, data, approx=approx_contribs)
        margin = self._predict_margin(data, iteration_range)
        if output_margin:
            out = margin
        else:
            out = self._obj.pred_transform(margin[:, 0] if self.n_groups == 1 else margin)
        out = np.asarray(out)
        if out.ndim == 2 and out.shape[1] == 1 and not strict_shape:
            out = out[:, 0]
        return out

    def _inplace_normalize(self, data, missing):
        """Raw input -> [n, F] float32 with NaN missing, with the minimum
        copying the dtype/missing semantics allow. Returns None for inputs
        the zero-copy path does not understand (those take the DMatrix
        fallback)."""
        if hasattr(data, "tocsr") and hasattr(data, "nnz"):
            # scipy CSR/CSC/COO: normalize stored values (user sentinel ->
            # NaN; absent entries are missing) but keep the CSR structure —
            # the native serving walker consumes it without densification
            # (same semantics as DMatrix ingestion, data/sparse.py)
            from .data.sparse import CSRStorage

            return CSRStorage(data, missing)
        if isinstance(data, np.ndarray) and data.ndim == 2:
            X = data
            if X.dtype != np.float32:
                X = X.astype(np.float32)
            if missing is not None and not (
                isinstance(missing, float) and np.isnan(missing)
            ):
                X = np.where(X == missing, np.nan, X)
            return np.ascontiguousarray(X)
        if isinstance(data, (list, tuple)):
            return self._inplace_normalize(
                np.asarray(data, np.float32), missing)
        return None

    def inplace_predict(self, data, iteration_range=None,
                        predict_type="value", missing=np.nan,
                        base_margin=None, validate_features=True,
                        strict_shape=False):
        """In-place predict from raw arrays — no DMatrix, no quantile work,
        no copy of the input beyond the device transfer (reference:
        ``XGBoosterPredictFromDense/CSR``, c_api.cc:833, and core.py
        ``Booster.inplace_predict``).

        Serving-grade: rows pad up to a power-of-two bucket and the
        compiled program is cached per (bucket, forest-shape, output-kind)
        with an LRU bound, so a stream of ragged batch sizes never
        recompiles (``predictor/serving.py``; cache counters live in the
        observability registry). The stacked forest itself is snapshotted
        per (num_trees, iteration_range) on this Booster. ``predict_type``
        is ``"value"`` (transformed, fused into the program) or
        ``"margin"``; anything else raises — leaf/contribution outputs go
        through :meth:`predict`."""
        self._configure()
        if predict_type not in ("value", "margin"):
            raise ValueError(
                f"inplace_predict supports predict_type 'value' and "
                f"'margin', got {predict_type!r}; use Booster.predict for "
                "leaf/contribution outputs")
        if iteration_range is not None and tuple(iteration_range) == (0, 0):
            iteration_range = None
        X = (self._inplace_normalize(data, missing)
             if self._gbm.name in ("gbtree", "dart") else None)
        if X is None:
            d = DMatrix(data, missing=missing)
            if base_margin is not None:
                d.set_base_margin(base_margin)
            return self.predict(
                d, output_margin=(predict_type == "margin"),
                iteration_range=iteration_range, strict_shape=strict_shape)
        n, F = X.shape
        if validate_features:
            # _num_feature() from a loaded model is max(split index)+1 — a
            # LOWER bound on the training width — so only narrower inputs
            # are definitely wrong (the walk would gather out of range)
            nf = self._num_feature()
            if nf and F < nf:
                raise ValueError(
                    f"feature count mismatch: model needs >= {nf} "
                    f"features, input has {F}")
        K = self.n_groups
        if base_margin is not None:
            base = np.asarray(base_margin, np.float32).reshape(n, K)
        else:
            base = np.full((n, K), self._base_margin_val, np.float32)
        forest, tw = self._forest_snapshot(iteration_range)
        from .predictor.serving import predict_serving

        transform = (None if predict_type == "margin"
                     else self._obj.pred_transform)
        out = predict_serving(forest, X, base, tw, transform=transform)
        if out.ndim == 2 and out.shape[1] == 1 and not strict_shape:
            out = out[:, 0]
        elif strict_shape and out.ndim == 1:
            out = out.reshape(n, 1)
        return out

    # ------------------------------------------------------------------
    # model IO (XGBoost-JSON-schema-compatible layout, doc/model.schema)
    # ------------------------------------------------------------------
    def save_json(self) -> dict:
        self._configure()
        # feature metadata: live training data wins, else whatever a loaded
        # model carried (so load -> save preserves names, like reference
        # LearnerIO)
        fn, ft = self._feature_meta()
        learner = {
            "feature_names": list(fn),
            "feature_types": list(ft),
            "learner_model_param": {
                "base_score": str(
                    self.lparam.base_score
                    if self.lparam.base_score is not None
                    else self._obj.default_base_score()
                ),
                "num_class": str(self.lparam.num_class),
                "num_feature": str(self._num_feature()),
            },
            "objective": {"name": self._obj.name},
            "gradient_booster": self._gbm.save_json(),
            "attributes": dict(self.attributes_),
        }
        return {"version": _VERSION, "learner": learner}

    def _num_feature(self) -> int:
        for d in self._cache_refs.values():
            return d.num_col()
        # a loaded model's learner_model_param carries the exact training
        # width (reference LearnerModelParam::num_feature) — prefer it
        # over the max-split-index lower bound, so serving-side width
        # validation can be exact after a save/load round trip
        if getattr(self, "_loaded_num_feature", 0):
            return int(self._loaded_num_feature)
        if getattr(self._gbm, "model", None) and self._gbm.model.trees:
            return int(max(t.split_indices.max(initial=0) for t in self._gbm.model.trees) + 1)
        return 0

    def save_raw(self, raw_format: str = "json") -> bytes:
        return json.dumps(self.save_json()).encode()

    def save_model(self, fname: Union[str, os.PathLike]) -> None:
        with open(fname, "w") as f:
            json.dump(self.save_json(), f)

    def load_json(self, j: dict) -> None:
        learner = j["learner"]
        lmp = learner["learner_model_param"]
        self.lparam.update(
            {
                "base_score": float(lmp["base_score"]),
                "num_class": int(lmp.get("num_class", 0)),
                "objective": learner["objective"]["name"],
            }
        )
        self._obj = None
        self._gbm = None
        self._configure()
        gb = learner["gradient_booster"]
        name = gb.get("name", "gbtree")
        if name != self.lparam.booster:
            self.lparam.update({"booster": name})
            self._gbm = None
            self._configure()
        self._gbm.load_json(gb)
        self.attributes_ = dict(learner.get("attributes", {}))
        try:
            self._loaded_num_feature = int(lmp.get("num_feature", 0))
        except (TypeError, ValueError):
            self._loaded_num_feature = 0
        self._loaded_feature_names = list(learner.get("feature_names", []))
        self._loaded_feature_types = list(learner.get("feature_types", []))
        self._caches.clear()
        self._forest_snapshots.clear()

    def load_model(self, fname: Union[str, bytes, os.PathLike]) -> None:
        if isinstance(fname, (bytes, bytearray)):
            self.load_json(json.loads(fname.decode()))
            return
        with open(fname) as f:
            self.load_json(json.load(f))

    def __getstate__(self):
        # full pickle round-trip incl. config (reference:
        # XGBoosterSerializeToBuffer / test_pickling.py)
        state = {
            "model": self.save_json() if self._gbm is not None else None,
            "lparam": self.lparam.to_dict(),
            # which keys the user actually set: replaying to_dict() through
            # update() would mark every DEFAULT explicit, breaking
            # explicitness-gated defaults (Poisson's max_delta_step 0.7)
            "lparam_explicit": sorted(self.lparam._explicit),
            "extra": dict(self._extra_params),
            "attributes": dict(self.attributes_),
        }
        return state

    def __setstate__(self, state):
        self.__init__()
        self.lparam.update({k: v for k, v in state["lparam"].items() if v is not None})
        self.lparam._explicit = set(
            state.get("lparam_explicit", state["lparam"]))
        self._extra_params = dict(state["extra"])
        self.attributes_ = dict(state["attributes"])
        if state["model"] is not None:
            self.load_json(state["model"])

    def copy(self) -> "Booster":
        import copy as _copy

        return _copy.deepcopy(self)

    def __copy__(self):
        return self.copy()

    def __deepcopy__(self, memo):
        b = Booster()
        b.__setstate__(json.loads(json.dumps(self.__getstate__(), default=float)))
        return b

    # ------------------------------------------------------------------
    # introspection
    # ------------------------------------------------------------------
    def num_boosted_rounds(self) -> int:
        self._configure()
        if self._gbm.name in ("gbtree", "dart"):
            per_round = max(1, self.n_groups) * self._gbm.gbtree_param.num_parallel_tree
            return self._gbm.model.num_trees // per_round
        return getattr(self._gbm, "n_rounds", 0)

    def num_features(self) -> int:
        return self._num_feature()

    def attr(self, key: str) -> Optional[str]:
        return self.attributes_.get(key)

    def set_attr(self, **kwargs) -> None:
        for k, v in kwargs.items():
            if v is None:
                self.attributes_.pop(k, None)
            else:
                self.attributes_[k] = str(v)

    def attributes(self) -> Dict[str, str]:
        return dict(self.attributes_)

    # ------------------------------------------------------------------
    # feature metadata properties + config IO (reference core.py
    # Booster.feature_names/feature_types, save_config/load_config —
    # XGBoosterSaveJsonConfig / learner.cc:SaveConfig)
    # ------------------------------------------------------------------
    @property
    def feature_names(self) -> Optional[List[str]]:
        return self._feature_meta()[0] or None

    @feature_names.setter
    def feature_names(self, names) -> None:
        self._loaded_feature_names = list(names) if names else []
        for d in self._cache_refs.values():
            d.feature_names = list(names) if names else None

    @property
    def feature_types(self) -> Optional[List[str]]:
        return self._feature_meta()[1] or None

    @feature_types.setter
    def feature_types(self, types) -> None:
        self._loaded_feature_types = list(types) if types else []

    def save_config(self) -> str:
        """JSON string of the learner's configuration (reference
        XGBoosterSaveJsonConfig). Covers the learner-level ParamSet, the
        booster/tree params, and the objective — enough for load_config to
        reconstruct an equivalently-configured Booster."""
        self._configure()
        cfg = {
            "version": list(_VERSION),
            "learner": {
                "learner_train_param": self.lparam.to_dict(),
                "gradient_booster": {
                    "name": self._gbm.name,
                    "params": dict(self._extra_params),
                },
                "objective": {"name": self._obj.name},
            },
        }
        return json.dumps(cfg)

    def load_config(self, config: str) -> None:
        c = json.loads(config)
        learner = c.get("learner", {})
        self._apply_params(dict(learner.get("learner_train_param", {})))
        gb = learner.get("gradient_booster", {})
        if gb.get("name"):
            self._apply_params({"booster": gb["name"]})
        self._apply_params(dict(gb.get("params", {})))
        obj = learner.get("objective", {})
        if obj.get("name"):
            self._apply_params({"objective": obj["name"]})
        # rebuild lazily with the new configuration
        if self._gbm is not None:
            for k, v in {**gb.get("params", {})}.items():
                try:
                    self._gbm.set_param(k, v)
                except Exception:
                    pass
        self._metrics = []

    def get_split_value_histogram(self, feature: str, fmap: str = "",
                                  bins: Optional[int] = None,
                                  as_pandas: bool = True):
        """Histogram of a feature's used split values (reference
        ``core.py:2508`` — it regexes the text dump; here the SoA trees are
        read directly). Categorical-split features raise like the
        reference."""
        self._configure()
        names = self._parse_fmap(fmap) or self._feature_meta()[0]
        try:
            fidx = int(feature[1:]) if (not names and feature.startswith("f")
                                        and feature[1:].isdigit()) \
                else names.index(feature)
        except (ValueError, AttributeError):
            raise ValueError(f"unknown feature: {feature!r}")
        values: List[float] = []
        is_cat = False
        for t in self._gbm.model.trees:
            internal = t.left_children != -1
            mask = internal & (t.split_indices == fidx)
            if t.split_type is not None and bool(
                    (np.asarray(t.split_type)[mask] != 0).any()):
                is_cat = True
                continue
            values.extend(float(v) for v in t.split_conditions[mask])
        if not values and is_cat:
            raise ValueError(
                "Split value historgam doesn't support categorical split."
            )
        n_unique = len(np.unique(values))
        bins = max(min(n_unique, bins) if bins is not None else n_unique, 1)
        nph = np.histogram(values, bins=bins)
        nph = np.column_stack((nph[1][1:], nph[0]))
        nph = nph[nph[:, 1] > 0]
        if as_pandas:
            try:
                import pandas as pd

                return pd.DataFrame(nph, columns=["SplitValue", "Count"])
            except ImportError:
                pass
        return nph

    def _feature_meta(self):
        """(feature_names, feature_types) from the first cached matrix
        carrying ANY feature metadata — both fields from the SAME source so
        they always describe one schema — falling back to what a loaded
        model carried."""
        for d in self._cache_refs.values():
            if d.feature_names or getattr(d.info, "feature_types", None):
                return (list(d.feature_names or []),
                        list(d.info.feature_types or []))
        return (list(getattr(self, "_loaded_feature_names", []) or []),
                list(getattr(self, "_loaded_feature_types", []) or []))

    @staticmethod
    def _parse_fmap_full(fmap: str
                         ) -> Optional[Tuple[List[str], List[str]]]:
        """featmap.txt parsing ('<id> <name> <type>' per line — reference
        core.py FeatureMap); (names, types) or None when no file is given.
        Types follow the reference vocabulary: i / q / int / float / c.
        A nonexistent path is an error, matching the reference
        (tests/python/test_basic.py::test_dump expects ValueError)."""
        if not fmap:
            return None
        if not os.path.exists(fmap):
            raise ValueError(f"No such featmap file: {fmap!r}")
        names: Dict[int, str] = {}
        types: Dict[int, str] = {}
        with open(fmap) as f:
            for line in f:
                ps = line.split()
                if len(ps) >= 2:
                    names[int(ps[0])] = ps[1]
                    if len(ps) >= 3:
                        types[int(ps[0])] = ps[2]
        if not names:
            return None
        n = max(names) + 1
        return ([names.get(i, f"f{i}") for i in range(n)],
                [types.get(i, "q") for i in range(n)])

    @classmethod
    def _parse_fmap(cls, fmap: str) -> Optional[List[str]]:
        parsed = cls._parse_fmap_full(fmap)
        return parsed[0] if parsed else None

    def get_dump(self, fmap: str = "", with_stats: bool = False, dump_format: str = "text") -> List[str]:
        """Per-tree dump strings in the reference's generator formats
        (src/tree/tree_model.cc: text :235, json :362 — the per-node
        nodeid/split/children structure downstream parsers consume — and
        ``dot``/``dot:{attrs-json}`` :550). featmap types drive the same
        per-type formatting ('i' indicator, 'int' ceil'd threshold)."""
        self._configure()
        parsed = self._parse_fmap_full(fmap)
        names, types = parsed if parsed else (None, None)
        if not names:
            meta_names, meta_types = self._feature_meta()
            names = meta_names or None
            types = types or (meta_types or None)
        if self._gbm.name == "gblinear":
            # one dump string: bias then per-feature weights
            # (gblinear_model.h:99 DumpModel)
            w = np.asarray(self._gbm.weights)  # [F+1, K], last row = bias
            bias, wt = w[-1], w[:-1]
            if dump_format == "json":
                return [json.dumps(
                    {"bias": [float(b) for b in bias],
                     "weight": [float(v) for row in wt for v in row]},
                    indent=2)]
            lines = ["bias:"] + [f"{float(b):.6g}" for b in bias] + \
                ["weight:"] + [f"{float(v):.6g}" for row in wt for v in row]
            return ["\n".join(lines) + "\n"]
        out = []
        for t in self._gbm.model.trees:
            if dump_format == "json":
                out.append(t.dump_json_ref(names, with_stats, types))
            elif dump_format == "text":
                out.append(t.dump_text(names, with_stats, types))
            elif dump_format.startswith("dot"):
                attrs = None
                if dump_format.startswith("dot:"):
                    attrs = json.loads(dump_format[4:])
                out.append(t.dump_dot(names, types, attrs))
            else:
                raise ValueError(f"Unknown dump format: {dump_format!r}")
        return out

    def dump_model(self, fout, fmap: str = "", with_stats: bool = False, dump_format: str = "text") -> None:
        dumps = self.get_dump(fmap, with_stats, dump_format)
        with open(fout, "w") as f:
            if dump_format == "json":
                f.write("[\n" + ",\n".join(dumps) + "\n]")
            else:
                for i, d in enumerate(dumps):
                    f.write(f"booster[{i}]:\n{d}\n")

    def _gblinear_contribs(self, data: DMatrix) -> np.ndarray:
        """Per-feature linear contributions (gblinear.cc:176
        PredictContribution): present entries contribute x_f * w_f
        (missing contribute 0), and the last column is bias + base
        margin. [n, F+1], or [n, K, F+1] for multiple output groups."""
        w = np.asarray(self._gbm.weights)  # [F+1, K]
        X = np.asarray(data.data, np.float32)
        n, F = X.shape
        K = w.shape[1]
        Xz = np.nan_to_num(X, nan=0.0)
        base = self._base_margin_val
        out = np.empty((n, K, F + 1), np.float32)
        for g in range(K):
            out[:, g, :F] = Xz * w[None, :F, g].reshape(1, F)
            out[:, g, F] = w[F, g] + base
        return out[:, 0, :] if K == 1 else out

    def get_score(self, fmap: str = "", importance_type: str = "weight") -> Dict[str, float]:
        """Feature importances (reference: CalcFeatureScore learner.cc)."""
        self._configure()
        if self._gbm.name == "gblinear":
            # reference gblinear.cc:240: only 'weight' is defined, and the
            # scores ARE the per-feature coefficients (bias excluded)
            if importance_type != "weight":
                raise ValueError(
                    "gblinear only has `weight` defined for feature "
                    "importance")
            w = np.asarray(self._gbm.weights)[:-1]  # [F, K]
            names = self._parse_fmap(fmap) or self._feature_meta()[0] or None

            def lname(f: int) -> str:
                return names[f] if names and f < len(names) else f"f{f}"

            if w.shape[1] == 1:
                return {lname(f): float(w[f, 0]) for f in range(w.shape[0])}
            return {f"{lname(f)}_g{g}": float(w[f, g])
                    for f in range(w.shape[0]) for g in range(w.shape[1])}
        gain: Dict[int, float] = {}
        cover: Dict[int, float] = {}
        weight: Dict[int, float] = {}
        for t in self._gbm.model.trees:
            internal = t.left_children != -1
            for f, g, c in zip(
                t.split_indices[internal], t.loss_changes[internal], t.sum_hessian[internal]
            ):
                f = int(f)
                weight[f] = weight.get(f, 0.0) + 1.0
                gain[f] = gain.get(f, 0.0) + float(g)
                cover[f] = cover.get(f, 0.0) + float(c)
        names = self._parse_fmap(fmap) or self._feature_meta()[0] or None

        def nm(f: int) -> str:
            return names[f] if names and f < len(names) else f"f{f}"

        if importance_type == "weight":
            return {nm(f): v for f, v in weight.items()}
        if importance_type == "total_gain":
            return {nm(f): v for f, v in gain.items()}
        if importance_type == "total_cover":
            return {nm(f): v for f, v in cover.items()}
        if importance_type == "gain":
            return {nm(f): gain[f] / weight[f] for f in gain}
        if importance_type == "cover":
            return {nm(f): cover[f] / weight[f] for f in cover}
        raise ValueError(f"Unknown importance_type: {importance_type}")

    def get_fscore(self, fmap: str = "") -> Dict[str, float]:
        return self.get_score(fmap, "weight")

    def __getitem__(self, val) -> "Booster":
        """Layer slicing (reference: Learner::Slice)."""
        self._configure()
        if self._gbm.name == "gblinear":
            # reference gbm.h:70: the base GradientBooster::Slice fails;
            # only tree boosters implement it
            raise ValueError("Slice is not supported by current booster.")
        if isinstance(val, int):
            val = slice(val, val + 1)
        start = val.start or 0
        stop = val.stop if val.stop is not None else self.num_boosted_rounds()
        step = val.step or 1
        self._configure()
        out = self.copy()
        out._gbm.model = out._gbm.model.slice(start, stop, step)
        out._caches.clear()
        out._forest_snapshots.clear()
        return out

    def trees_to_dataframe(self, fmap: str = ""):
        import pandas as pd

        self._configure()
        if self._gbm.name not in ("gbtree", "dart"):
            raise ValueError(
                "This method is not defined for Booster type "
                f"{self._gbm.name}")
        rows = []
        for ti, t in enumerate(self._gbm.model.trees):
            for i in range(t.num_nodes):
                leaf = t.left_children[i] == -1
                rows.append(
                    {
                        "Tree": ti,
                        "Node": i,
                        "ID": f"{ti}-{i}",
                        "Feature": "Leaf" if leaf else f"f{t.split_indices[i]}",
                        "Split": None if leaf else float(t.split_conditions[i]),
                        "Yes": None if leaf else f"{ti}-{t.left_children[i]}",
                        "No": None if leaf else f"{ti}-{t.right_children[i]}",
                        "Missing": None
                        if leaf
                        else (
                            f"{ti}-{t.left_children[i]}"
                            if t.default_left[i]
                            else f"{ti}-{t.right_children[i]}"
                        ),
                        "Gain": float(t.split_conditions[i]) if leaf else float(t.loss_changes[i]),
                        "Cover": float(t.sum_hessian[i]),
                    }
                )
        return pd.DataFrame(rows)
