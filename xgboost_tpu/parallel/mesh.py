"""Device mesh management: the TPU replacement for the entire rabit
tracker/socket stack (reference: ``rabit/`` + ``tracker.py`` —
SURVEY.md §2.10).

Single-controller JAX needs no rendezvous: the mesh IS the cluster
membership, ranks are mesh coordinates, and the four collective call sites
of the reference (sketch merge quantile.cc:270, histogram AllReduce
hist/histogram.h:201, metric sums, num_feature max learner.cc:596) become
``psum``/``all_gather`` over a named axis. Multi-host: initialize
``jax.distributed`` and build the mesh over all devices — DCN is handled
transparently by the runtime.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

ROW_AXIS = "data"  # the one parallel axis of GBDT training: rows

_state = threading.local()


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the row axis (GBDT's only scalable dimension — the
    'sequence parallelism' analog per SURVEY.md §5: rows sharded, histogram
    reductions fixed-size)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (ROW_AXIS,))


def current_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]) -> Iterator[None]:
    """Activate a mesh: training inside the context shards rows over it."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield
    finally:
        _state.mesh = prev


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def shard_rows(arr: jax.Array, mesh: Mesh) -> jax.Array:
    """Place an array row-sharded over the mesh (rows must divide evenly —
    pad first; padded rows carry zero gradient/hessian so they are inert,
    the fixed-shape analog of the reference's empty-worker handling,
    dask.py:914)."""
    spec = P(ROW_AXIS, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


def replicate(arr: jax.Array, mesh: Mesh) -> jax.Array:
    return jax.device_put(arr, NamedSharding(mesh, P()))
