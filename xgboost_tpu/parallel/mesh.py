"""Device mesh management: the TPU replacement for the entire rabit
tracker/socket stack (reference: ``rabit/`` + ``tracker.py`` —
SURVEY.md §2.10).

Single-controller JAX needs no rendezvous: the mesh IS the cluster
membership, ranks are mesh coordinates, and the four collective call sites
of the reference (sketch merge quantile.cc:270, histogram AllReduce
hist/histogram.h:201, metric sums, num_feature max learner.cc:596) become
``psum``/``all_gather`` over a named axis. Multi-host: initialize
``jax.distributed`` and build the mesh over all devices — DCN is handled
transparently by the runtime.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Iterator, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .. import _compat  # noqa: F401  (pre-0.5 jax shard_map/pcast shims)
from ..resilience import watchdog as _wd

ROW_AXIS = "data"  # the one parallel axis of GBDT training: rows

_state = threading.local()


def make_mesh(n_devices: Optional[int] = None, devices=None) -> Mesh:
    """1-D mesh over the row axis (GBDT's only scalable dimension — the
    'sequence parallelism' analog per SURVEY.md §5: rows sharded, histogram
    reductions fixed-size)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (ROW_AXIS,))


def current_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def collective_active() -> bool:
    """True only when COLLECTIVE multi-process semantics apply: several
    processes AND an active ``mesh_context``. Shared by the learner's
    training routing and the metrics' distributed reductions so they can
    never disagree — a program that merely initialized jax.distributed but
    trains mesh-less per-process boosters must see purely local behavior
    everywhere (no surprise allgathers inside metric evaluation)."""
    return jax.process_count() > 1 and current_mesh() is not None


@contextlib.contextmanager
def mesh_context(mesh: Optional[Mesh]) -> Iterator[None]:
    """Activate a mesh: training inside the context shards rows over it."""
    prev = current_mesh()
    _state.mesh = mesh
    try:
        yield
    finally:
        _state.mesh = prev


def _config_cpu_gloo() -> None:
    """CPU backends need an explicit cross-process collectives
    implementation on this jax (0.4.37 defaults to "none", which makes
    EVERY multi-process computation fail with "Multiprocess computations
    aren't implemented on the CPU backend"): pick gloo when the option
    exists and is unset. TPU runtimes ignore it."""
    import os as _os

    if ("cpu" in (_os.environ.get("JAX_PLATFORMS") or "")
            and not _os.environ.get("JAX_CPU_COLLECTIVES_IMPLEMENTATION")):
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:
            pass  # other jax versions: sensible default, no such knob


def form_world(coordinator_address: str, num_processes: int,
               process_id: int) -> Mesh:
    """Elastic-grade world formation: ``jax.distributed.initialize``
    semantics with a runtime that SURVIVES peer death instead of
    propagating it.

    The stock coordination service health-checks members and, on a missed
    heartbeat, broadcasts a fatal error that LOG(FATAL)s every surviving
    process (xla client.h) — the exact opposite of elasticity. Here the
    service is made deaf (effectively-infinite ``max_missing_heartbeats``;
    liveness is owned by ``parallel.membership``'s file heartbeats) and
    the client skips the shutdown barrier on destruction (a survivor must
    exit cleanly after its peers are gone). Known asymmetry, documented
    in docs/distributed.md: the COORDINATOR process (rank 0 of the
    initial world) hosts the service in-process, so its death still takes
    the runtime down — survivors of a coordinator loss recover by process
    restart + checkpoint resume, not in-process resize (the rabit
    tracker has the same single point of authority)."""
    from jax._src import distributed as _dist
    from jax._src.lib import xla_extension

    _config_cpu_gloo()
    st = _dist.global_state
    if st.client is not None:
        raise RuntimeError(
            "form_world: jax distributed runtime already initialized in "
            "this process; elastic re-formation at world > 1 requires a "
            "process restart (docs/distributed.md, Elastic training)")
    with _wd.watchdog("collective_init",
                      seconds=_wd.deadline_for("collective_init", 900.0)):
        if process_id == 0:
            st.service = xla_extension.get_distributed_runtime_service(
                "[::]:" + coordinator_address.rsplit(":", 1)[1],
                num_processes, heartbeat_interval=10,
                max_missing_heartbeats=1_000_000)
        client = xla_extension.get_distributed_runtime_client(
            coordinator_address, process_id, init_timeout=300,
            shutdown_on_destruction=False, use_compression=True)
        client.connect()
    st.client = client
    st.process_id = process_id
    st.num_processes = num_processes
    st.coordinator_address = coordinator_address
    return make_mesh(devices=jax.devices())


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    local_device_ids: Optional[Sequence[int]] = None,
    elastic: bool = False,
) -> Mesh:
    """Multi-host entry point — the role the reference's dask frontend plays
    (``python-package/xgboost/dask.py:838-952``: start RabitTracker, hand
    every worker its rank/URI, build the rabit ring). Single-controller JAX
    collapses all of that to ``jax.distributed.initialize`` + one mesh over
    every process's devices; DCN transport is handled by the runtime, and
    there is no tracker because the mesh IS the membership.

    Call once per process before building DMatrix/Booster objects, then
    train inside ``mesh_context(mesh)`` with each process ingesting its own
    row shard (the ``load_row_split`` analog — see
    ``docs/distributed.md``). Arguments mirror
    ``jax.distributed.initialize`` and may be omitted when the runtime
    auto-detects (TPU pods). ``elastic=True`` routes through
    :func:`form_world` — a peer-death-tolerant runtime whose liveness is
    owned by ``parallel.membership`` instead of the coordination
    service's fail-everything health check. Returns the global mesh.
    """
    if num_processes is not None and num_processes > 1:
        if elastic:
            return form_world(coordinator_address, num_processes,
                              process_id)
        _config_cpu_gloo()
        # Deadline around the rendezvous: a wedged coordinator/relay here
        # is the mid-claim failure mode that burned bench round 5 —
        # better a clean WatchdogTimeout than a 10-hour hang. Default
        # 900s (a healthy claim takes seconds-to-minutes); tune/disable
        # via XGBTPU_WATCHDOG="collective_init=...".
        with _wd.watchdog("collective_init",
                          seconds=_wd.deadline_for("collective_init",
                                                   900.0)):
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                local_device_ids=local_device_ids,
            )
    return make_mesh(devices=jax.devices())


def pad_to_multiple(n: int, k: int) -> int:
    return ((n + k - 1) // k) * k


def global_pad_rows(n_local: int, unit: int) -> int:
    """The COMMON per-process padded block size: ceil(n_local/unit)*unit,
    maxed over all processes. Multi-process row sharding requires every
    process to contribute equal padded blocks (shard_rows); real row
    counts may be uneven (load_row_split hands ragged slices) — the
    per-process validity masks (grow.py n_arr) make the extra padding
    inert, so processes just agree on the largest block here."""
    n_pad = pad_to_multiple(max(n_local, 1), unit)
    if jax.process_count() > 1:
        from .. import collective

        sizes = collective.process_allgather(
            np.asarray(n_pad, np.int64), site="pad_rows")
        n_pad = int(sizes.max())
    return n_pad


def local_device_count(mesh: Mesh) -> int:
    """Devices of ``mesh`` owned by THIS process (== mesh size when
    single-process). Row padding is computed per process against this, so
    every process's local block is the same fraction of the global array."""
    pi = jax.process_index()
    return sum(1 for d in mesh.devices.flat if d.process_index == pi)


def _put_global(arr, sharding) -> jax.Array:
    """device_put that also works multi-process: each process supplies its
    process-local block (or the full array for replicated specs)."""
    if jax.process_count() > 1:
        return jax.make_array_from_process_local_data(
            sharding, np.asarray(arr))
    return jax.device_put(arr, sharding)


def _check_equal_blocks(n_local: int) -> None:
    """Multi-process row sharding requires every process to contribute the
    SAME padded block size (global shape inference and the per-shard
    validity mask both assume it). Fails loudly instead of deadlocking."""
    from .. import collective

    sizes = collective.process_allgather(
        np.asarray(n_local, np.int64), site="equal_blocks")
    if not (sizes == sizes[0]).all():
        raise ValueError(
            "multi-process training requires equal PADDED row blocks per "
            f"process; got {sizes.tolist()}. Give every process the same "
            "number of rows (pad the short ones — padded rows are inert)."
        )


def shard_rows(arr: jax.Array, mesh: Mesh) -> jax.Array:
    """Place an array row-sharded over the mesh (rows must divide evenly —
    pad first; padded rows carry zero gradient/hessian so they are inert,
    the fixed-shape analog of the reference's empty-worker handling,
    dask.py:914). Multi-process: ``arr`` is THIS process's row block (the
    load_row_split model — each process ingested its own slice) and the
    global array is their concatenation in process order; all processes
    must contribute equally-sized padded blocks."""
    if jax.process_count() > 1:
        _check_equal_blocks(arr.shape[0])
    spec = P(ROW_AXIS, *([None] * (arr.ndim - 1)))
    return _put_global(arr, NamedSharding(mesh, spec))


def replicate(arr: jax.Array, mesh: Mesh) -> jax.Array:
    """Replicate a (process-identical) array over the whole mesh."""
    return _put_global(arr, NamedSharding(mesh, P()))


def local_rows(arr: jax.Array) -> jax.Array:
    """THIS process's row block of a row-sharded global array (identity
    when single-process): the inverse of ``shard_rows``. Used to bring
    per-row outputs (margins, deltas) back to process-local layout."""
    if jax.process_count() == 1:
        return arr
    from ..observability import trace

    with trace.span("local_rows", bytes=int(arr.nbytes)):
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        import jax.numpy as jnp

        # via host: the shards live committed on DIFFERENT local devices
        # and cannot be concatenated device-side without explicit transfers
        return jnp.asarray(
            np.concatenate([np.asarray(s.data) for s in shards], axis=0))
