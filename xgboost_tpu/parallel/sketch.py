"""Distributed quantile sketch: per-shard summaries + all_gather merge.

The TPU analog of the reference's cross-worker sketch AllReduce
(``HostSketchContainer::AllReduce`` quantile.cc:270; GPU
``SketchContainer::AllReduce`` quantile.cu:510): every shard compresses its
rows into a fixed-size weighted summary (value, weight) per feature — the
moral equivalent of a pruned WQSummary — the summaries are all_gathered
over the mesh, merged by a weighted-CDF pass, and every device reads off
identical cuts. Summary size is ``OVERSAMPLE * max_bin`` per feature, so
accuracy matches a GK sketch with eps ~ 1/(OVERSAMPLE * max_bin) per shard.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..collective import psum as _coll_psum
from ..data.quantile import HistogramCuts
from .mesh import ROW_AXIS

OVERSAMPLE = 8


@partial(jax.jit, static_argnames=("max_bin",))
def _local_summary(X: jax.Array, weights: jax.Array, max_bin: int):
    """[n_local, F] -> per-feature summary (values [F, S], weights [F, S])."""
    S = OVERSAMPLE * max_bin
    Xt = X.T
    valid = ~jnp.isnan(Xt)
    big = jnp.float32(np.finfo(np.float32).max)
    keys = jnp.where(valid, Xt, big)
    order = jnp.argsort(keys, axis=1)
    svals = jnp.take_along_axis(keys, order, axis=1)
    w = jnp.where(valid, weights[None, :], 0.0)
    sw = jnp.take_along_axis(w, order, axis=1)
    cdf = jnp.cumsum(sw, axis=1)
    total = cdf[:, -1:]
    levels = (jnp.arange(1, S + 1, dtype=jnp.float32) / S) * total
    idx = jax.vmap(lambda c, l: jnp.searchsorted(c, l, side="left"))(cdf, levels)
    idx = jnp.clip(idx, 0, Xt.shape[1] - 1)
    vals = jnp.take_along_axis(svals, idx, axis=1)  # [F, S]
    wts = jnp.broadcast_to(total / S, vals.shape)
    # features with no valid rows: zero weights
    wts = jnp.where(total > 0, wts, 0.0)
    vals = jnp.where(total > 0, vals, 0.0)
    # also carry per-feature max for the sentinel cut
    n_valid = valid.sum(axis=1)
    fmax = jnp.where(n_valid > 0, jnp.take_along_axis(svals, (n_valid - 1)[:, None], axis=1)[:, 0], 0.0)
    fmin = jnp.where(n_valid > 0, svals[:, 0], 0.0)
    return vals, wts, fmax, fmin


@partial(jax.jit, static_argnames=("max_bin",))
def _merge_summaries(vals: jax.Array, wts: jax.Array, fmax: jax.Array, fmin: jax.Array, max_bin: int):
    """[D, F, S] gathered summaries -> [F, max_bin] global cuts."""
    D, F, S = vals.shape
    v = jnp.transpose(vals, (1, 0, 2)).reshape(F, D * S)
    w = jnp.transpose(wts, (1, 0, 2)).reshape(F, D * S)
    order = jnp.argsort(v, axis=1)
    sv = jnp.take_along_axis(v, order, axis=1)
    sw = jnp.take_along_axis(w, order, axis=1)
    cdf = jnp.cumsum(sw, axis=1)
    total = cdf[:, -1:]
    levels = (jnp.arange(1, max_bin, dtype=jnp.float32) / max_bin) * total
    idx = jax.vmap(lambda c, l: jnp.searchsorted(c, l, side="left"))(cdf, levels)
    idx = jnp.clip(idx, 0, D * S - 1)
    interior = jnp.take_along_axis(sv, idx, axis=1)
    gmax = fmax.max(axis=0)
    gmin = jnp.where(jnp.any(wts.sum(axis=2) > 0, axis=0), fmin.min(axis=0), 0.0)
    sentinel = gmax + jnp.maximum(1.0, jnp.abs(gmax))
    any_valid = (total[:, 0] > 0)
    interior = jnp.where(any_valid[:, None], interior, 0.0)
    cuts = jnp.concatenate([interior, sentinel[:, None]], axis=1)
    return cuts, gmin


def distributed_compute_cuts(
    mesh: Mesh,
    X: jax.Array,  # [n, F] row-sharded dense float32/NaN
    max_bin: int = 256,
    weights: Optional[jax.Array] = None,
) -> HistogramCuts:
    from ..observability import comms, trace

    n, F = X.shape
    if weights is None:
        weights = jnp.ones((n,), jnp.float32)
    # per-device volume of the summary merge: four all_gathers (vals/wts
    # [F, S] + fmax/fmin [F]) over D shards, plus the two psum-broadcasts
    # of the [F, max_bin] cuts — the quantile.cc:270 AllReduce site
    D = mesh.devices.size
    S = OVERSAMPLE * max_bin
    comms.record("all_gather_sketch", D * (2 * F * S + 2 * F) * 4, n_ops=4)
    comms.record("psum_hist", 2 * F * max_bin * 4, n_ops=2)

    def shard_fn(Xs, ws):
        vals, wts, fmax, fmin = _local_summary(Xs, ws, max_bin)
        g_vals = jax.lax.all_gather(vals, ROW_AXIS)  # [D, F, S]
        g_wts = jax.lax.all_gather(wts, ROW_AXIS)
        g_max = jax.lax.all_gather(fmax, ROW_AXIS)
        g_min = jax.lax.all_gather(fmin, ROW_AXIS)
        cuts, mins = _merge_summaries(g_vals, g_wts, g_max, g_min, max_bin)
        # every shard computed identical cuts, but the VMA type system
        # cannot credit that through all_gather; an exact rank-0
        # psum-broadcast (the reference's tree-sync site,
        # updater_sync.cc:20) makes the replication provable so shard_map
        # verifies it (check_vma on)
        r = jax.lax.axis_index(ROW_AXIS)

        def bcast0(a):
            return _coll_psum(jnp.where(r == 0, a, jnp.zeros_like(a)),
                              ROW_AXIS)

        return bcast0(cuts), bcast0(mins)

    with trace.span("sketch", distributed=True, rows=n, features=F,
                    max_bin=max_bin):
        cuts, min_vals = jax.shard_map(
            shard_fn,
            mesh=mesh,
            in_specs=(P(ROW_AXIS, None), P(ROW_AXIS)),
            out_specs=(P(), P()),
        )(X, weights)
        return HistogramCuts(values=np.asarray(cuts),
                             min_vals=np.asarray(min_vals))
