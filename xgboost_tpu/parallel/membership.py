"""File-based worker membership + heartbeat protocol for elastic training.

The reference's rabit tracker knows which workers exist and restarts the
dead ones; JAX's single-controller runtime has no such organ — its
coordination service LOG(FATAL)s the survivors when it notices a death
(xla distributed client), which is exactly wrong for elasticity. This
module supplies the missing organ at the file-system level (a shared
directory — local disk for one host, NFS/GCS-fuse for a pod), so it works
identically under every transport and needs no extra server:

- every worker runs a tiny **heartbeat agent subprocess** writing
  ``<dir>/rank<r>.hb`` (JSON: rank, pid, generation, seq) every
  ``XGBTPU_HEARTBEAT`` seconds (default 1.0). An agent PROCESS, not a
  thread, deliberately: a worker wedged inside a blocking collective can
  sit in C++ holding the GIL for tens of seconds, and thread-based beats
  stop exactly when liveness matters most — measured here as two healthy
  survivors tombstoning each other mid-gloo-stall. The agent's beats
  reflect only true process liveness: it exits within one interval of
  its parent dying (reparenting check), so SIGKILL stops the beats and
  nothing else does;
- a daemon **monitor** thread in the worker scans peers: a rank whose
  ``seq`` has not moved for ``XGBTPU_HEARTBEAT_DEADLINE`` seconds
  (default 5x interval) is declared dead — loss is detected within one
  deadline, per the elastic contract;
- detection is **observable**: ``worker_alive{rank=...}`` gauges, a
  ``membership_changes_total`` counter and trace instants on every
  transition;
- a detected death is made **durable** with a ``rank<r>.dead`` tombstone
  so re-formed generations and restarted processes agree on membership
  without re-timing-out; a live worker that finds its own tombstone is
  FENCED (it lost a partition dispute) and must exit rather than split-
  brain the run — ``Membership.fenced`` flags it;
- the ``heartbeat_drop`` chaos site skips scripted beats, exercising both
  detection and false-positive tolerance deterministically in CI.

Liveness is judged by sequence-number movement against the local
monotonic clock, never by comparing file mtimes across hosts (shared
filesystems make no cross-host clock promises).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["Membership", "WorkerLost", "hb_interval", "hb_deadline"]

_ENV_INTERVAL = "XGBTPU_HEARTBEAT"
_ENV_DEADLINE = "XGBTPU_HEARTBEAT_DEADLINE"

# The heartbeat agent: runs as a direct child of the worker, beats while
# (and only while) the parent lives. STDLIB-ONLY on purpose — importing
# the package (and with it jax) would delay the first beat by seconds,
# longer than a tight test deadline. It therefore carries its own copy of
# the chaos schedule predicate for the ``heartbeat_drop`` site (same
# grammar and crc32(site:hit:seed) hash as resilience/chaos.py — the
# cross-process determinism test in tests/test_elastic.py pins that
# contract; keep the two in sync).
_AGENT_SRC = r"""
import json, os, sys, time, zlib
path = sys.argv[1]
rank = int(sys.argv[2])
gen = int(sys.argv[3])
interval = float(sys.argv[4])
ppid = int(sys.argv[5])

SITE = "heartbeat_drop"


def _preds(cfg):
    out = []
    for clause in (cfg or "").split(";"):
        parts = [p.strip() for p in clause.split(":", 2)]
        if len(parts) != 3 or parts[0] != SITE:
            continue
        for tok in parts[2].split(","):
            tok = tok.strip()
            if not tok:
                continue
            try:
                if tok.startswith("p"):
                    ps, _, ss = tok[1:].partition("@")
                    prob, seed = float(ps), int(ss) if ss else 0
                    out.append(lambda n, p=prob, s=seed: (zlib.crc32(
                        ("%s:%d:%d" % (SITE, n, s)).encode())
                        & 0xFFFFFFFF) / 2**32 < p)
                elif tok.startswith("%"):
                    out.append(lambda n, k=int(tok[1:]): n % k == 0)
                elif tok.endswith("+"):
                    out.append(lambda n, lo=int(tok[:-1]): n >= lo)
                elif "-" in tok:
                    lo, _, hi = tok.partition("-")
                    out.append(lambda n, lo=int(lo), hi=int(hi):
                               lo <= n <= hi)
                else:
                    out.append(lambda n, t=int(tok): n == t)
            except ValueError:
                pass
    return out


preds = _preds(os.environ.get("XGBTPU_CHAOS"))
seq = 0
hit = 0
while os.getppid() == ppid:
    hit += 1
    if not any(p(hit) for p in preds):
        seq += 1
        tmp = path + ".tmp." + str(os.getpid())
        try:
            with open(tmp, "w") as f:
                f.write(json.dumps({"rank": rank, "pid": ppid,
                                    "seq": seq, "generation": gen}))
            os.replace(tmp, path)
        except OSError:
            pass
    time.sleep(interval)
"""


def hb_interval() -> float:
    """Heartbeat write/scan period in seconds (``XGBTPU_HEARTBEAT``)."""
    try:
        return max(0.05, float(os.environ.get(_ENV_INTERVAL, 1.0)))
    except ValueError:
        return 1.0


def hb_deadline() -> float:
    """Seconds of heartbeat silence that mean death
    (``XGBTPU_HEARTBEAT_DEADLINE``, default 5x the interval — a couple of
    dropped beats is jitter, five is a corpse)."""
    try:
        raw = os.environ.get(_ENV_DEADLINE)
        if raw is not None:
            return max(hb_interval(), float(raw))
    except ValueError:
        pass
    return 5.0 * hb_interval()


class WorkerLost(RuntimeError):
    """One or more peers died (heartbeat silence or tombstone). Carries
    the dead base ranks and the round at which loss was observed — the
    signal the elastic training loop quiesces and resizes on."""

    def __init__(self, ranks: List[int], round: int = -1):
        super().__init__(
            f"worker_lost: rank(s) {sorted(ranks)} dead"
            + (f" (observed at round {round})" if round >= 0 else ""))
        self.ranks = sorted(ranks)
        self.round = round


class Membership:
    """Heartbeat writer + peer monitor for one worker.

    ``rank`` is the worker's BASE rank — its identity for the life of the
    elastic run, never renumbered by resizes (generation-local ranks are
    the elastic layer's concern). ``peers`` is the base-rank set of the
    current generation, this worker included.
    """

    def __init__(self, directory: str, rank: int, peers: List[int],
                 generation: int = 0):
        self.directory = directory
        self.rank = int(rank)
        self.peers = sorted(int(p) for p in peers)
        self.generation = int(generation)
        self.round = 0  # bumped by the training guard; exported in beats
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._agent = None  # the heartbeat subprocess
        # peer base rank -> [last_seq_seen, monotonic_when_seen]
        self._seen: Dict[int, List[float]] = {}
        self._dead: set = set()
        self.fenced = False
        self._grace_until = 0.0

    # ------------------------------------------------------------------
    # paths
    # ------------------------------------------------------------------
    def _hb_path(self, rank: int) -> str:
        return os.path.join(self.directory, f"rank{rank}.hb")

    def _tomb_path(self, rank: int) -> str:
        return os.path.join(self.directory, f"rank{rank}.dead")

    # ------------------------------------------------------------------
    # writer: the out-of-process heartbeat agent
    # ------------------------------------------------------------------
    def _spawn_agent(self):
        """Start the beat agent as a direct child. Beats continue while
        this process lives — including through GIL-holding stalls inside
        wedged collectives — and stop within one interval of it dying.
        ``XGBTPU_CHAOS`` rides along in the inherited environment."""
        import subprocess
        import sys

        return subprocess.Popen(
            [sys.executable, "-c", _AGENT_SRC, self._hb_path(self.rank),
             str(self.rank), str(self.generation), str(hb_interval()),
             str(os.getpid())],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)

    # ------------------------------------------------------------------
    # monitor
    # ------------------------------------------------------------------
    def _read_seq(self, rank: int) -> Optional[int]:
        try:
            with open(self._hb_path(rank)) as f:
                return int(json.load(f).get("seq", 0))
        except (OSError, ValueError):
            return None

    def scan(self) -> List[int]:
        """One monitoring pass: refresh peer liveness, publish the
        ``worker_alive`` gauges, return the (possibly updated) dead set.
        A peer is dead when tombstoned, or when its heartbeat sequence
        has not moved for one deadline (missing files count from the
        start of the grace window, so a peer that never comes up is
        detected too)."""
        from ..observability.metrics import REGISTRY
        from ..observability import trace

        now = time.monotonic()
        deadline = hb_deadline()
        newly_dead: List[int] = []
        with self._lock:
            for p in self.peers:
                if p == self.rank:
                    continue
                if p in self._dead:
                    continue
                if os.path.exists(self._tomb_path(p)):
                    self._dead.add(p)
                    newly_dead.append(p)
                    continue
                seq = self._read_seq(p)
                # a NEVER-seen peer gets a doubled allowance: its agent
                # may still be forking/registering while ours already
                # beats (startup skew must not read as death)
                ent = self._seen.setdefault(
                    p, [-1, (self._grace_until or now) + deadline])
                if seq is not None and seq != ent[0]:
                    ent[0], ent[1] = seq, now
                elif now - ent[1] > deadline:
                    self._dead.add(p)
                    newly_dead.append(p)
            if os.path.exists(self._tomb_path(self.rank)):
                self.fenced = True
            dead = sorted(self._dead)
        alive_g = REGISTRY.gauge(
            "worker_alive", "Membership liveness by base rank "
            "(1 alive, 0 dead)")
        for p in self.peers:
            alive_g.labels(rank=p).set(0.0 if p in dead else 1.0)
        for p in newly_dead:
            REGISTRY.counter(
                "membership_changes_total",
                "Membership transitions (worker joins and losses)").inc()
            trace.instant("worker_lost", rank=p,
                          generation=self.generation)
            from ..observability import flight

            flight.RECORDER.event("worker_lost", rank=p,
                                  generation=self.generation)
            from ..utils import console_logger

            console_logger.warning(
                f"membership: rank {p} declared dead (generation "
                f"{self.generation}, heartbeat silence > {deadline:g}s)")
        return dead

    def dead_ranks(self) -> List[int]:
        with self._lock:
            return sorted(self._dead)

    def alive_ranks(self) -> List[int]:
        dead = set(self.dead_ranks())
        return [p for p in self.peers if p not in dead]

    def declare_dead(self, rank: int) -> None:
        """Durable tombstone: later generations (and the fenced worker
        itself, should it still be alive) read membership from these
        instead of re-timing-out."""
        from ..observability import trace

        path = self._tomb_path(rank)
        if not os.path.exists(path):
            from ..resilience.checkpoint import atomic_write_bytes

            try:
                atomic_write_bytes(path, json.dumps(
                    {"rank": rank, "by": self.rank,
                     "generation": self.generation}).encode())
            except OSError:
                pass
            trace.instant("worker_tombstoned", rank=rank, by=self.rank)
            from ..observability import flight

            flight.RECORDER.event("worker_tombstoned", rank=rank,
                                  by=self.rank)
        with self._lock:
            if rank != self.rank:
                self._dead.add(rank)

    def wait_dead(self, ranks: List[int], timeout: float) -> List[int]:
        """Block (scanning) until every rank in ``ranks`` is declared
        dead or ``timeout`` elapses; returns the confirmed-dead subset.
        Used to corroborate a collective failure before resizing — a
        transient network fault must not shrink the world."""
        t0 = time.monotonic()
        want = set(ranks)
        while True:
            dead = set(self.scan())
            if want <= dead or time.monotonic() - t0 > timeout:
                return sorted(want & dead)
            time.sleep(min(0.1, hb_interval() / 2))

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self) -> "Membership":
        """Spawn the beat agent, wait (briefly) for its first beat to
        land — peers must be able to see this worker before it enters any
        collective — then scan peers on a daemon monitor thread."""
        self._grace_until = time.monotonic()
        self._agent = self._spawn_agent()
        t0 = time.monotonic()
        while not os.path.exists(self._hb_path(self.rank)) \
                and time.monotonic() - t0 < hb_deadline():
            time.sleep(0.02)
        self._stop.clear()

        def loop() -> None:
            while not self._stop.wait(hb_interval()):
                self.scan()

        self._thread = threading.Thread(
            target=loop, name=f"xgbtpu-monitor-r{self.rank}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * hb_interval())
            self._thread = None
        if self._agent is not None:
            try:
                self._agent.terminate()
                self._agent.wait(timeout=5)
            except Exception:
                pass
            self._agent = None
