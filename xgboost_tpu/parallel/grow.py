"""Distributed tree growth: shard_map over the row axis.

This is the TPU realization of the reference's inter-node data-parallel
strategy (SURVEY.md §2.11 item 3): each device holds a row shard, the model
is replicated, and the only hot-loop synchronization is the per-level
histogram AllReduce — ``jax.lax.psum`` inside ``grow_tree`` (the analog of
``SyncHistogramDistributed`` hist/histogram.h:201 and ``AllReduceHist``
updater_gpu_hist.cu:526). Histogram size is independent of row count, so
collective cost stays constant as data scales — the same property the
reference's design relies on.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ..tree.grow import GrowParams, HeapTree, grow_tree
from ..tree.grow_fused import GrownTree, grow_tree_fused
from ..tree.grow_lossguide import AllocTree, grow_tree_lossguide
from .mesh import ROW_AXIS


def _row_sharded_call(mesh, grower, out_specs, args, feature_weights):
    """shard_map a grower: rows sharded, cuts/key/feature_weights
    replicated. feature_weights joins the traced args only when present so
    the None default stays bit-identical with the single-device path."""
    in_specs = [P(ROW_AXIS, None), P(ROW_AXIS), P(ROW_AXIS), P(None, None), P()]
    if feature_weights is not None:
        in_specs.append(P())
        args = args + (feature_weights,)
    fn = jax.shard_map(
        grower,
        mesh=mesh,
        in_specs=tuple(in_specs),
        out_specs=out_specs,
        check_vma=True,
    )
    return fn(*args)


def distributed_grow_tree(
    mesh: Mesh,
    bins: jax.Array,  # [n, F] row-sharded (n divisible by mesh size)
    grad: jax.Array,  # [n] row-sharded
    hess: jax.Array,
    cut_values: jax.Array,  # [F, B] replicated
    key: jax.Array,
    cfg: GrowParams,
    feature_weights: Optional[jax.Array] = None,  # [F] replicated
) -> HeapTree:
    """Grow one tree over row shards. Tree tensors come back replicated
    (bitwise identical on every device — the property the reference asserts
    with gpu_hist's debug_synchronize, updater_gpu_hist.cu:49); row
    positions stay sharded."""
    import dataclasses

    from ..observability import comms, trace

    cfg_dist = dataclasses.replace(cfg, axis_name=ROW_AXIS)

    # Build the out_specs programmatically from HeapTree._fields so the
    # spec can never drift from the NamedTuple definition: every tree
    # tensor comes back replicated, only per-row positions stay sharded.
    out_specs = HeapTree(
        **{f: (P(ROW_AXIS) if f == "positions" else P()) for f in HeapTree._fields}
    )
    comms.record_grow_collectives(cfg.max_depth, bins.shape[1],
                                  cut_values.shape[1])
    with trace.span("distributed_grow_tree", depth=cfg.max_depth):
        return _row_sharded_call(
            mesh, partial(grow_tree, cfg=cfg_dist), out_specs,
            (bins, grad, hess, cut_values, key), feature_weights,
        )


def distributed_grow_tree_fused(
    mesh: Mesh,
    bins: jax.Array,  # [n_pad, F] int32 row-sharded (missing == B padding)
    grad: jax.Array,  # [n_pad] row-sharded (pad rows zero)
    hess: jax.Array,
    cut_values: jax.Array,  # [F, B] replicated
    key: jax.Array,
    eta: float,
    gamma: float,
    cfg: GrowParams,
    feature_weights: Optional[jax.Array] = None,
    onehot: Optional[jax.Array] = None,  # [n_pad, Fh*B] int8 row-sharded
) -> GrownTree:
    """The fused fast-path grower over row shards: per-level histograms and
    root totals are psum'd inside ``grow_tree_fused`` (the reference's two
    collective sites, hist/histogram.h:201 + InitRoot); tree tensors come
    back replicated, the per-row cache delta stays sharded.

    ``onehot`` is the PRE-BUILT row-sharded hoisted expansion
    (``BinnedMatrix.fused_onehot_mesh`` — one build per (fit, mesh), not
    one per tree; VERDICT r4 weak #5): it enters the shard_map as a
    row-sharded operand, so each device streams its own resident shard."""
    import dataclasses

    from ..observability import comms

    comms.record_grow_collectives(cfg.max_depth, bins.shape[1],
                                  cut_values.shape[1])
    cfg_dist = dataclasses.replace(cfg, axis_name=ROW_AXIS)
    out_specs = GrownTree(
        **{f: (P(ROW_AXIS) if f == "delta" else P()) for f in GrownTree._fields}
    )
    use_oh = onehot is not None and not cfg.has_categorical

    def grower(bins_s, g_s, h_s, cuts_s, key_s, eta_s, gamma_s, *rest):
        rest = list(rest)
        oh_s = rest.pop(0) if use_oh else None
        fw = rest.pop(0) if rest else None
        return grow_tree_fused(bins_s, g_s, h_s, cuts_s, key_s, eta_s,
                               gamma_s, cfg=cfg_dist, feature_weights=fw,
                               onehot=oh_s)

    in_specs = [P(ROW_AXIS, None), P(ROW_AXIS), P(ROW_AXIS), P(None, None),
                P(), P(), P()]
    args = (bins, grad, hess, cut_values, key, eta, gamma)
    if use_oh:
        in_specs.append(P(ROW_AXIS, None))
        args = args + (onehot,)
    if feature_weights is not None:
        in_specs.append(P())
        args = args + (feature_weights,)
    fn = jax.shard_map(
        grower, mesh=mesh, in_specs=tuple(in_specs), out_specs=out_specs,
        check_vma=True,
    )
    return fn(*args)


def distributed_grow_tree_lossguide(
    mesh: Mesh,
    bins: jax.Array,  # [n, F] row-sharded
    grad: jax.Array,
    hess: jax.Array,
    cut_values: jax.Array,  # [F, B] replicated
    key: jax.Array,
    cfg: GrowParams,
    max_leaves: int,
    feature_weights: Optional[jax.Array] = None,  # [F] replicated
) -> AllocTree:
    """Lossguide growth over row shards: per-step child histograms are
    psum'd, the priority queue runs identically on every device (the
    single-best-candidate argmax is deterministic on the reduced
    histograms), so tree tensors come back replicated."""
    import dataclasses

    from ..observability import comms

    # lossguide reduces one [F, 2, B] child-pair histogram per expansion
    # step (max_leaves - 1 splits) rather than whole levels
    comms.record(
        "psum_hist",
        max(max_leaves - 1, 1) * bins.shape[1] * 2 * cut_values.shape[1] * 4,
        n_ops=max(max_leaves - 1, 1),
    )
    cfg_dist = dataclasses.replace(cfg, axis_name=ROW_AXIS)
    out_specs = AllocTree(
        **{f: (P(ROW_AXIS) if f == "positions" else P()) for f in AllocTree._fields}
    )
    return _row_sharded_call(
        mesh, partial(grow_tree_lossguide, cfg=cfg_dist, max_leaves=max_leaves),
        out_specs, (bins, grad, hess, cut_values, key), feature_weights,
    )


def distributed_boost_rounds_scan(
    mesh: Mesh,
    obj,  # scan-safe objective (elementwise/rowwise gradient)
    bins: jax.Array,  # [n_pad, F] row-sharded narrow-int bins
    label: jax.Array,  # [n_pad] row-sharded (pad rows arbitrary)
    weight: Optional[jax.Array],  # [n_pad] row-sharded or None
    margin: jax.Array,  # [n_pad, K] row-sharded
    iters: jax.Array,  # [R] int32 iteration numbers
    cut_values: jax.Array,  # [F, B] replicated
    eta: jax.Array,
    gamma: jax.Array,
    feature_weights: Optional[jax.Array],
    seed_base: jax.Array,  # uint32
    n: int,  # real (unpadded) global row count
    cfg: GrowParams,
    onehot: Optional[jax.Array] = None,  # [n_pad, Fh*B] row-sharded, cached
    fh_plan: Optional[int] = None,  # caller's frozen synced plan
):
    """A chunk of boosting rounds over row shards as ONE program: the
    ``lax.scan`` of (gradient -> fused tree -> margin update) runs inside a
    single ``shard_map``, with the per-level histogram / root-total psums
    inside ``grow_tree_fused`` (hist/histogram.h:201's collective). Returns
    (sharded margin [n_pad, K], replicated stacked trees [R, K, ...]).

    Gradients are computed per shard (scan-safe objectives are rowwise);
    rows past ``n`` (padding) get their gradients masked to zero every
    round — the fixed-shape analog of the reference's empty-worker
    handling."""
    from ..gbm.gbtree import _obj_fingerprint
    from ..observability import comms
    from .mesh import local_device_count, replicate

    # one fused tree per group per scanned round, each with the per-level
    # histogram psums + root-total psum of grow_tree_fused
    comms.record_grow_collectives(
        cfg.max_depth, bins.shape[1], cut_values.shape[1],
        n_trees=int(iters.shape[0]) * margin.shape[1],
    )
    n_procs = jax.process_count()
    if n_procs > 1:
        # the r // d_local shard->process attribution below requires the
        # mesh device order to be process-major contiguous blocks of equal
        # size — true for make_mesh(jax.devices()); anything else would
        # SILENTLY mis-mask padding rows, so verify loudly
        pidx = [d.process_index for d in mesh.devices.flat]
        dl = local_device_count(mesh)
        ok = (len(pidx) == dl * n_procs and all(
            pidx[i] == i // dl for i in range(len(pidx))))
        if not ok:
            raise ValueError(
                "multi-process mesh must list devices process-major with "
                f"equal per-process counts; got process order {pidx}"
            )
        # per-process real row counts (the validity mask must know where
        # each PROCESS's padding tail starts — real rows are not a global
        # prefix under load_row_split ingestion), plus explicit replication
        # of the small operands: multi-process programs only accept global
        # arrays
        from .. import collective

        n_arr = jnp.asarray(collective.process_allgather(
            np.asarray(n, np.int32), site="row_counts"))
        rep = lambda x: None if x is None else replicate(  # noqa: E731
            jnp.asarray(x), mesh)
        iters, cut_values, eta, gamma, feature_weights, seed_base, n_arr = (
            rep(iters), rep(cut_values), rep(eta), rep(gamma),
            rep(feature_weights), rep(seed_base), rep(n_arr))
    else:
        n_arr = jnp.asarray([n], jnp.int32)
    if cfg.has_categorical:
        onehot, fh = None, 0
    elif onehot is not None:
        # the caller's cached per-fit expansion (BinnedMatrix.
        # fused_onehot_mesh): its width IS the (already process-synced)
        # plan, and passing it as an operand means chunks — per ROUND
        # under train()'s chunk=1 routing — never replan (a blocking
        # allgather) or rebuild (multi-GB of HBM writes)
        fh = onehot.shape[1] // cut_values.shape[1]
    elif fh_plan is not None:
        # the caller's frozen plan with no resident expansion (plan 0, or
        # a standalone caller managing its own build): no per-chunk
        # allgather, no free-HBM drift flipping this jit static arg
        fh = fh_plan
    else:
        from ..tree.hist_kernel import hoist_plan_synced

        # no caller plan (direct/test callers): per-shard plan decided
        # OUTSIDE the jit and agreed across processes (min over ranks) —
        # it is baked statically into the traced SPMD program, and ranks
        # can see different free HBM. The shard_fn then builds per
        # dispatch.
        D = mesh.devices.size
        fh = hoist_plan_synced(margin.shape[0] // D, bins.shape[1],
                               cut_values.shape[1], cfg.max_depth)
    return _dist_scan_impl(
        bins, label, weight, margin, iters, cut_values, eta, gamma,
        feature_weights, seed_base, n_arr, onehot, mesh=mesh, obj=obj,
        obj_fp=_obj_fingerprint(obj), cfg=cfg,
        d_local=local_device_count(mesh), fh=fh,
    )


@partial(jax.jit, static_argnames=("mesh", "obj", "obj_fp", "cfg",
                                   "d_local", "fh"))
def _dist_scan_impl(bins, label, weight, margin, iters, cut_values, eta,
                    gamma, feature_weights, seed_base, n_arr, onehot, *,
                    mesh, obj, obj_fp, cfg, d_local, fh):
    import dataclasses

    import jax.numpy as jnp
    import jax.tree_util as jtu

    from ..gbm.gbtree import round_seed_traced

    from ..tree.hist_kernel import build_onehot

    cfg_dist = dataclasses.replace(cfg, axis_name=ROW_AXIS)
    D = mesh.devices.size
    n_pad, K = margin.shape
    rows_local = n_pad // D
    B = cut_values.shape[1]

    def shard_fn(bins_s, label_s, weight_s, m_s, fw, n_a, oh_s):
        r = jax.lax.axis_index(ROW_AXIS)
        # shard r belongs to process r // d_local; its real-row budget is
        # that process's count, measured within the process's block
        q = r % d_local
        n_own = n_a[r // d_local]
        valid = (q * rows_local
                 + jax.lax.broadcasted_iota(jnp.int32, (rows_local, 1), 0)[:, 0]
                 ) < n_own
        validf = valid.astype(jnp.float32)
        if oh_s is not None:
            onehot_s = oh_s
        else:
            onehot_s = (build_onehot(bins_s[:, :fh], B=B, vma=(ROW_AXIS,))
                        if fh else None)

        def body(m_loc, i):
            m = m_loc[:, 0] if K == 1 else m_loc
            g, h = obj.get_gradient(m, label_s, weight_s, i)
            trees = []
            for k in range(K):
                gk = (g[:, k] if g.ndim == 2 else g) * validf
                hk = (h[:, k] if h.ndim == 2 else h) * validf
                seed = round_seed_traced(seed_base, i, k)
                key = jax.random.PRNGKey(seed.astype(jnp.int32))
                t = grow_tree_fused(bins_s, gk, hk, cut_values, key, eta,
                                    gamma, cfg_dist, feature_weights=fw,
                                    onehot=onehot_s)
                m_loc = m_loc.at[:, k].add(t.delta)
                trees.append(t._replace(delta=jnp.zeros((0,), jnp.float32)))
            return m_loc, jtu.tree_map(lambda *xs: jnp.stack(xs), *trees)

        return jax.lax.scan(body, m_s, iters)

    tree_specs = GrownTree(**{f: P() for f in GrownTree._fields})
    in_specs = [P(ROW_AXIS, None), P(ROW_AXIS)]
    args = [bins, label]
    if weight is not None:
        in_specs.append(P(ROW_AXIS))
        args.append(weight)
    else:
        in_specs.append(None)
        args.append(None)
    in_specs.append(P(ROW_AXIS, None))
    args.append(margin)
    if feature_weights is not None:
        in_specs.append(P())
        args.append(feature_weights)
    else:
        in_specs.append(None)
        args.append(None)
    in_specs.append(P())
    args.append(n_arr)
    if onehot is not None:
        in_specs.append(P(ROW_AXIS, None))
        args.append(onehot)
    else:
        in_specs.append(None)
        args.append(None)
    fn = jax.shard_map(
        shard_fn, mesh=mesh, in_specs=tuple(in_specs),
        out_specs=(P(ROW_AXIS, None), tree_specs),
        check_vma=True,
    )
    return fn(*args)
