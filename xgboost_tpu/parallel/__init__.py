from .mesh import (  # noqa: F401
    current_mesh,
    make_mesh,
    mesh_context,
    pad_to_multiple,
    shard_rows,
)
from .grow import distributed_grow_tree, distributed_grow_tree_lossguide  # noqa: F401
from .sketch import distributed_compute_cuts  # noqa: F401
