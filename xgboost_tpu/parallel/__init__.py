from .mesh import (  # noqa: F401
    current_mesh,
    form_world,
    init_distributed,
    make_mesh,
    mesh_context,
    pad_to_multiple,
    shard_rows,
)
from .membership import Membership, WorkerLost  # noqa: F401
from .grow import (  # noqa: F401
    distributed_grow_tree,
    distributed_grow_tree_fused,
    distributed_grow_tree_lossguide,
)
from .sketch import distributed_compute_cuts  # noqa: F401
