"""Multiclass objectives (reference: ``src/objective/multiclass_obj.cu`` —
``multi:softmax``/``multi:softprob`` registered at :198,202)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..registry import OBJECTIVES
from .base import ObjFunction, Task, apply_weight

_EPS = 1e-16


class _SoftmaxBase(ObjFunction):
    task = Task.CLASSIFICATION
    scan_safe = True  # pure jnp rowwise softmax: traceable in update_many

    def n_targets(self) -> int:
        nc = getattr(self.params, "num_class", 0) if self.params else 0
        if nc < 2:
            raise ValueError("multi:* objectives need num_class >= 2")
        return nc

    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        # margin [n, K]
        p = jax.nn.softmax(margin, axis=-1)
        onehot = jax.nn.one_hot(label.astype(jnp.int32), margin.shape[1], dtype=margin.dtype)
        grad = p - onehot
        hess = jnp.maximum(2.0 * p * (1.0 - p), _EPS)
        return apply_weight(grad, hess, weight)

    def default_metric(self):
        return "mlogloss"


@OBJECTIVES.register("multi:softprob")
class SoftProb(_SoftmaxBase):
    def pred_transform(self, margin):
        return jax.nn.softmax(margin, axis=-1)


@OBJECTIVES.register("multi:softmax")
class SoftMax(_SoftmaxBase):
    def pred_transform(self, margin):
        return jnp.argmax(margin, axis=-1).astype(jnp.float32)

    def eval_transform(self, margin):
        # metrics (merror/mlogloss) need the full distribution
        return jax.nn.softmax(margin, axis=-1)
