"""LambdaMART ranking objectives (reference: ``src/objective/rank_obj.cu`` —
``rank:pairwise``/``rank:ndcg``/``rank:map`` registered at :950-958).

TPU-first design: the reference samples explicit pairs per query group
(CPU: random pair loops; GPU: SegmentSorter). On TPU we pad each query group
to a fixed ``max_group_size``, compute ALL pairwise lambdas inside the padded
[G, S, S] tensor with masking, and weight by |delta metric| for the
ndcg/map variants — an all-pairs formulation that is a better fit for the
MXU than sampling, and equivalent to the reference with
``num_pairsample -> inf`` normalization.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import OBJECTIVES
from .base import ObjFunction, Task, apply_weight


def _pad_groups(group_ptr: np.ndarray) -> Tuple[np.ndarray, int]:
    sizes = np.diff(group_ptr)
    max_size = int(sizes.max(initial=1))
    return sizes, max_size


@partial(jax.jit, static_argnames=("n_groups", "max_size", "scheme"))
def _lambda_grad(
    margin: jax.Array,  # [n]
    label: jax.Array,  # [n]
    group_of: jax.Array,  # [n] int32
    rank_in_group: jax.Array,  # [n] int32
    n_groups: int,
    max_size: int,
    scheme: str,
) -> Tuple[jax.Array, jax.Array]:
    n = margin.shape[0]
    # scatter rows into padded [G, S] layout
    flat = group_of * max_size + rank_in_group
    S = n_groups * max_size
    pad_margin = jnp.zeros((S,), margin.dtype).at[flat].set(margin).reshape(n_groups, max_size)
    pad_label = jnp.zeros((S,), label.dtype).at[flat].set(label).reshape(n_groups, max_size)
    pad_valid = jnp.zeros((S,), bool).at[flat].set(True).reshape(n_groups, max_size)

    def per_group(m, y, v):
        # all-pairs lambdas within one (padded) group
        diff_label = y[:, None] - y[None, :]  # >0 where i should rank above j
        pair = (diff_label > 0) & v[:, None] & v[None, :]
        s_diff = m[:, None] - m[None, :]
        # RankNet lambda: sigmoid(-(si - sj)) for positive pairs
        rho = jax.nn.sigmoid(-s_diff)
        if scheme == "ndcg":
            # delta-NDCG weighting: |gain_i - gain_j| * |1/log2(ri+2) - 1/log2(rj+2)| / IDCG
            order = jnp.argsort(-jnp.where(v, m, -jnp.inf))
            ranks = jnp.zeros_like(order).at[order].set(jnp.arange(max_size))
            gains = (2.0 ** y - 1.0)
            discounts = 1.0 / jnp.log2(ranks.astype(m.dtype) + 2.0)
            ideal_order = jnp.sort(jnp.where(v, gains, 0.0))[::-1]
            idcg = (ideal_order / jnp.log2(jnp.arange(max_size, dtype=m.dtype) + 2.0)).sum()
            idcg = jnp.maximum(idcg, 1e-10)
            delta = (
                jnp.abs(gains[:, None] - gains[None, :])
                * jnp.abs(discounts[:, None] - discounts[None, :])
                / idcg
            )
            w_pair = jnp.where(pair, delta, 0.0)
        else:  # pairwise (and map approximated by pairwise delta=1)
            w_pair = jnp.where(pair, 1.0, 0.0)
        lam = rho * w_pair  # [S, S] contribution for (i above j)
        hessian = rho * (1.0 - rho) * w_pair
        grad = -lam.sum(axis=1) + lam.sum(axis=0)  # winners pushed up, losers down
        hess = hessian.sum(axis=1) + hessian.sum(axis=0)
        return grad, jnp.maximum(hess, 1e-16)

    g_pad, h_pad = jax.vmap(per_group)(pad_margin, pad_label, pad_valid)
    grad = g_pad.reshape(-1)[flat]
    hess = h_pad.reshape(-1)[flat]
    return grad, hess


class _LambdaRankBase(ObjFunction):
    task = Task.RANKING
    scheme = "pairwise"

    def get_gradient(self, margin, label, weight, iteration=0, *, group_ptr=None, **kw):
        n = margin.shape[0]
        if group_ptr is None:
            group_ptr = np.array([0, n], dtype=np.int64)
        sizes = np.diff(group_ptr)
        n_groups = len(sizes)
        max_size = int(sizes.max(initial=1))
        group_of = np.repeat(np.arange(n_groups, dtype=np.int32), sizes)
        rank_in_group = np.concatenate([np.arange(s, dtype=np.int32) for s in sizes]) if n else np.zeros(0, np.int32)
        grad, hess = _lambda_grad(
            margin, label, jnp.asarray(group_of), jnp.asarray(rank_in_group),
            n_groups, max_size, self.scheme,
        )
        # per-group query weights (reference: weights are per-group for ranking)
        if weight is not None and len(weight) == n_groups:
            w_row = jnp.asarray(np.repeat(np.asarray(weight), sizes))
            grad, hess = grad * w_row, hess * w_row
        elif weight is not None and len(weight) == n:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def default_metric(self):
        return "map" if self.scheme == "map" else ("ndcg" if self.scheme == "ndcg" else "map")


@OBJECTIVES.register("rank:pairwise")
class RankPairwise(_LambdaRankBase):
    scheme = "pairwise"


@OBJECTIVES.register("rank:ndcg")
class RankNDCG(_LambdaRankBase):
    scheme = "ndcg"


@OBJECTIVES.register("rank:map")
class RankMAP(_LambdaRankBase):
    scheme = "map"
