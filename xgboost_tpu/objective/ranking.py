"""LambdaMART ranking objectives (reference: ``src/objective/rank_obj.cu`` —
``rank:pairwise``/``rank:ndcg``/``rank:map`` registered at :950-958).

TPU-first design, two regimes:

- small groups: pad each query group to ``max_group_size`` and compute ALL
  pairwise lambdas inside a masked [G, S, S] tensor — MXU-friendly,
  equivalent to the reference with ``num_pairsample -> inf``.
- large groups (MSLR-WEB30K-class, 1000+ docs/query): the cubic tensor is
  hundreds of GB, so pairs are SAMPLED the way the reference's
  ``rank_obj.cu:143-198`` segmented sampler does — every document draws
  ``lambdarank_num_pair_per_sample`` opponents uniformly from its group
  (mismatched labels kept), ranks/IDCG come from one global lexsort instead
  of padding, and both pair ends receive their lambda. Peak memory is
  O(n * num_pair), independent of group size.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import OBJECTIVES
from .base import ObjFunction, Task, apply_weight


def _pad_groups(group_ptr: np.ndarray) -> Tuple[np.ndarray, int]:
    sizes = np.diff(group_ptr)
    max_size = int(sizes.max(initial=1))
    return sizes, max_size


def _map_pair_delta(gather, hits, acc1, acc2, acc3, a, b, lab_a, lab_b,
                    total):
    """|delta AP| of swapping the docs at sorted positions ``a < b``
    (rank_obj.cu:436 GetLambdaMAP), shared by the padded and sampled paths;
    ``gather(arr, idx)`` resolves a (possibly local) position index into the
    caller's stats layout, returning 0 for idx == -1 (exclusive prefix)."""
    original = gather(acc1, b) - gather(acc1, a - 1)
    up = gather(acc3, b - 1) - gather(acc3, a) \
        + (gather(hits, a) + 1.0) / (a + 1.0)
    down = gather(acc2, b - 1) - gather(acc2, a) \
        + gather(hits, b) / (b + 1.0)
    changed = jnp.where(lab_a < lab_b, up, down)
    delta = jnp.abs(changed - original) / jnp.maximum(total, 1.0)
    return jnp.where((lab_a != lab_b) & (a != b) & (total > 0), delta, 0.0)


@partial(jax.jit, static_argnames=("n_groups", "max_size", "scheme"))
def _lambda_grad(
    margin: jax.Array,  # [n]
    label: jax.Array,  # [n]
    group_of: jax.Array,  # [n] int32
    rank_in_group: jax.Array,  # [n] int32
    n_groups: int,
    max_size: int,
    scheme: str,
) -> Tuple[jax.Array, jax.Array]:
    n = margin.shape[0]
    # scatter rows into padded [G, S] layout
    flat = group_of * max_size + rank_in_group
    S = n_groups * max_size
    pad_margin = jnp.zeros((S,), margin.dtype).at[flat].set(margin).reshape(n_groups, max_size)
    pad_label = jnp.zeros((S,), label.dtype).at[flat].set(label).reshape(n_groups, max_size)
    pad_valid = jnp.zeros((S,), bool).at[flat].set(True).reshape(n_groups, max_size)

    def per_group(m, y, v):
        # all-pairs lambdas within one (padded) group
        diff_label = y[:, None] - y[None, :]  # >0 where i should rank above j
        pair = (diff_label > 0) & v[:, None] & v[None, :]
        s_diff = m[:, None] - m[None, :]
        # RankNet lambda: sigmoid(-(si - sj)) for positive pairs
        rho = jax.nn.sigmoid(-s_diff)
        # the reference samples each doc's opponents uniformly among
        # DIFFERENT-label docs from both pair ends (rank_obj.cu:97-127,
        # scale 1/num_pairsample); its expectation gives every unordered
        # pair the weight 1/n_opp(i) + 1/n_opp(j) — the all-pairs path
        # applies that expectation exactly
        vf = v.astype(m.dtype)
        vcount = vf.sum()
        same_cnt = ((y[:, None] == y[None, :]) & v[:, None]
                    & v[None, :]).astype(m.dtype).sum(axis=1)
        opp = jnp.maximum(vcount - same_cnt, 1.0)
        end_w = jnp.where(v, 1.0 / opp, 0.0)
        samp_w = end_w[:, None] + end_w[None, :]  # [S, S]
        if scheme == "ndcg":
            # delta-NDCG weighting: |gain_i - gain_j| * |1/log2(ri+2) - 1/log2(rj+2)| / IDCG
            order = jnp.argsort(-jnp.where(v, m, -jnp.inf))
            ranks = jnp.zeros_like(order).at[order].set(jnp.arange(max_size))
            gains = (2.0 ** y - 1.0)
            discounts = 1.0 / jnp.log2(ranks.astype(m.dtype) + 2.0)
            ideal_order = jnp.sort(jnp.where(v, gains, 0.0))[::-1]
            idcg = (ideal_order / jnp.log2(jnp.arange(max_size, dtype=m.dtype) + 2.0)).sum()
            idcg = jnp.maximum(idcg, 1e-10)
            delta = (
                jnp.abs(gains[:, None] - gains[None, :])
                * jnp.abs(discounts[:, None] - discounts[None, :])
                / idcg
            )
            w_pair = jnp.where(pair, delta, 0.0)
        elif scheme == "map":
            # true MAP delta weights (rank_obj.cu:378 MAPLambdaWeightComputer):
            # prefix stats over the prediction-sorted list — ap_acc,
            # ap_acc_miss (a positive removed), ap_acc_add (a positive
            # inserted ahead), hit counts — then |delta AP| of swapping the
            # pair's sorted positions
            order = jnp.argsort(-jnp.where(v, m, -jnp.inf))
            ranks = jnp.zeros_like(order).at[order].set(jnp.arange(max_size))
            rel = ((y > 0) & v).astype(m.dtype)
            rel_sorted = jnp.zeros((max_size,), m.dtype).at[ranks].set(rel)
            hits = jnp.cumsum(rel_sorted)  # inclusive per position
            p1 = jnp.arange(max_size, dtype=m.dtype) + 1.0
            acc1 = jnp.cumsum(rel_sorted * hits / p1)
            acc2 = jnp.cumsum(rel_sorted * (hits - 1.0) / p1)
            acc3 = jnp.cumsum(rel_sorted * (hits + 1.0) / p1)
            total = hits[-1]

            def at(arr, idx):  # gather; idx == -1 -> 0 (exclusive prefix)
                return jnp.where(idx >= 0,
                                 arr[jnp.clip(idx, 0, max_size - 1)], 0.0)

            ri, rj = ranks[:, None], ranks[None, :]
            a, b = jnp.minimum(ri, rj), jnp.maximum(ri, rj)
            rel_i, rel_j = rel[:, None], rel[None, :]
            lab_a = jnp.where(ri <= rj, rel_i, rel_j)  # binary, earlier pos
            lab_b = jnp.where(ri <= rj, rel_j, rel_i)
            delta = _map_pair_delta(at, hits, acc1, acc2, acc3, a, b,
                                    lab_a, lab_b, total)
            w_pair = jnp.where(pair, delta, 0.0)
        else:  # pairwise: unit delta
            w_pair = jnp.where(pair, 1.0, 0.0)
        w_pair = w_pair * samp_w
        lam = rho * w_pair  # [S, S] contribution for (i above j)
        # reference hessian per pair end: 2 * w * p * (1 - p)
        # (rank_obj.cu:142 'gpair[...] += GradientPair(g*w, 2.0f*w*h)')
        hessian = 2.0 * rho * (1.0 - rho) * w_pair
        grad = -lam.sum(axis=1) + lam.sum(axis=0)  # winners pushed up, losers down
        hess = hessian.sum(axis=1) + hessian.sum(axis=0)
        return grad, jnp.maximum(hess, 1e-16)

    g_pad, h_pad = jax.vmap(per_group)(pad_margin, pad_label, pad_valid)
    grad = g_pad.reshape(-1)[flat]
    hess = h_pad.reshape(-1)[flat]
    return grad, hess


# all-pairs only while G * S^2 stays under this many elements; above it the
# sampled-pair path keeps memory O(n * num_pair) (rank_obj.cu:143-198)
_ALL_PAIRS_BUDGET = 1 << 25


@partial(jax.jit, static_argnames=("n_groups", "n_pair", "scheme"))
def _lambda_grad_sampled(
    margin: jax.Array,  # [n]
    label: jax.Array,  # [n]
    group_of: jax.Array,  # [n] int32
    group_start: jax.Array,  # [n] int32 (start row of own group)
    group_size: jax.Array,  # [n] int32 (own group's size)
    key: jax.Array,
    n_groups: int,
    n_pair: int,
    scheme: str,
) -> Tuple[jax.Array, jax.Array]:
    """Sampled-pair LambdaMART without any [G, S] padding: per-group ranks
    and IDCG come from one global lexsort keyed (group, -margin)."""
    n = margin.shape[0]
    # ranks within group by current margin
    order = jnp.lexsort((-margin, group_of))
    pos_sorted = jnp.zeros((n,), jnp.int32).at[order].set(
        jnp.arange(n, dtype=jnp.int32))
    rank = pos_sorted - group_start  # 0-based rank inside own group

    gains = 2.0 ** label - 1.0
    disc = 1.0 / jnp.log2(rank.astype(margin.dtype) + 2.0)
    if scheme == "ndcg":
        # IDCG per group: labels sorted descending within group
        lorder = jnp.lexsort((-label, group_of))
        lrank = (jnp.zeros((n,), jnp.int32).at[lorder].set(
            jnp.arange(n, dtype=jnp.int32)) - group_start)
        ideal_terms = gains / jnp.log2(lrank.astype(margin.dtype) + 2.0)
        idcg = jax.ops.segment_sum(ideal_terms, group_of,
                                   num_segments=n_groups)
        idcg_row = jnp.maximum(idcg[group_of], 1e-10)  # [n]

    # opponents: j uniform in own group, n_pair draws per row
    u = jax.random.uniform(key, (n, n_pair))
    j_local = jnp.minimum((u * group_size[:, None]).astype(jnp.int32),
                          group_size[:, None] - 1)
    j = group_start[:, None] + j_local  # [n, P] global row ids
    m_j = margin[j]
    y_j = label[j]
    valid = label[:, None] != y_j

    # per-row different-label opponent count (for the reference sampler's
    # expectation weights 1/n_opp(i) + 1/n_opp(j), rank_obj.cu:97-127):
    # run-lengths of equal (group, label) from one lexsort
    lorder2 = jnp.lexsort((label, group_of))
    gs, ys2 = group_of[lorder2], label[lorder2]
    new_run = jnp.concatenate(
        [jnp.ones((1,), bool),
         (gs[1:] != gs[:-1]) | (ys2[1:] != ys2[:-1])])
    run_id = jnp.cumsum(new_run) - 1
    run_cnt = jax.ops.segment_sum(jnp.ones((n,), margin.dtype), run_id,
                                  num_segments=n)
    same_cnt = jnp.zeros((n,), margin.dtype).at[lorder2].set(
        run_cnt[run_id])
    opp = jnp.maximum(group_size.astype(margin.dtype) - same_cnt, 1.0)
    end_w = 1.0 / opp  # [n]
    # scale so E[update] equals the reference sampler's expectation: each
    # unordered pair is hit from BOTH ends ~n_pair/size times here
    samp_w = (group_size.astype(margin.dtype)[:, None]
              * (end_w[:, None] + end_w[j]) / (2.0 * n_pair))

    # orient each pair: hi = higher label
    i_is_hi = label[:, None] > y_j
    s_hi = jnp.where(i_is_hi, margin[:, None], m_j)
    s_lo = jnp.where(i_is_hi, m_j, margin[:, None])
    rho = jax.nn.sigmoid(-(s_hi - s_lo))
    if scheme == "ndcg":
        g_j = gains[j]
        d_j = disc[j]
        delta = (jnp.abs(gains[:, None] - g_j)
                 * jnp.abs(disc[:, None] - d_j) / idcg_row[:, None])
        w_pair = jnp.where(valid, delta, 0.0)
    elif scheme == "map":
        # MAP delta on sampled pairs: the same MAPStats prefix scan
        # (rank_obj.cu:474 GetMAPStats) segmented over the one global
        # prediction sort — groups are contiguous blocks in sorted layout,
        # so within-group inclusive cumsums are cumsum minus the value
        # just before each block start
        rel = (label > 0).astype(margin.dtype)
        rel_sorted = rel[order]

        def segcum(x):
            cs = jnp.cumsum(x)
            base = jnp.where(group_start > 0,
                             cs[jnp.maximum(group_start - 1, 0)], 0.0)
            return cs - base

        hits_s = segcum(rel_sorted)
        p_loc = (jnp.arange(n) - group_start).astype(margin.dtype) + 1.0
        acc1_s = segcum(rel_sorted * hits_s / p_loc)
        acc2_s = segcum(rel_sorted * (hits_s - 1.0) / p_loc)
        acc3_s = segcum(rel_sorted * (hits_s + 1.0) / p_loc)
        total = jax.ops.segment_sum(rel, group_of,
                                    num_segments=n_groups)[group_of]  # [n]

        r_i = rank[:, None]
        r_j = rank[j]
        a = jnp.minimum(r_i, r_j)
        b = jnp.maximum(r_i, r_j)
        st = group_start[:, None]

        def at(arr, local_idx):  # sorted-layout gather; local -1 -> 0
            gi = st + jnp.clip(local_idx, 0, None)
            return jnp.where(local_idx >= 0,
                             arr[jnp.clip(gi, 0, n - 1)], 0.0)

        rel_i = rel[:, None]
        rel_j = rel[j]
        lab_a = jnp.where(r_i <= r_j, rel_i, rel_j)
        lab_b = jnp.where(r_i <= r_j, rel_j, rel_i)
        delta = _map_pair_delta(at, hits_s, acc1_s, acc2_s, acc3_s, a, b,
                                lab_a, lab_b, total[:, None])
        w_pair = jnp.where(valid, delta, 0.0)
    else:
        w_pair = jnp.where(valid, 1.0, 0.0)
    w_pair = w_pair * samp_w
    lam = rho * w_pair  # pushes hi up, lo down
    # reference hessian per pair end: 2 * w * p * (1-p) (rank_obj.cu:142)
    hes = jnp.maximum(2.0 * rho * (1.0 - rho), 1e-16) * w_pair

    sign_i = jnp.where(i_is_hi, -1.0, 1.0)  # hi gets -lambda
    grad = (sign_i * lam).sum(axis=1)
    hess = hes.sum(axis=1)
    # the opponent end of every pair gets the mirrored update
    grad = grad.at[j.reshape(-1)].add((-sign_i * lam).reshape(-1))
    hess = hess.at[j.reshape(-1)].add(hes.reshape(-1))
    return grad, jnp.maximum(hess, 1e-16)


class _LambdaRankBase(ObjFunction):
    task = Task.RANKING
    scheme = "pairwise"

    def get_gradient(self, margin, label, weight, iteration=0, *, group_ptr=None, **kw):
        n = margin.shape[0]
        if group_ptr is None:
            group_ptr = np.array([0, n], dtype=np.int64)
        sizes = np.diff(group_ptr)
        n_groups = len(sizes)
        max_size = int(sizes.max(initial=1))
        group_of = np.repeat(np.arange(n_groups, dtype=np.int32), sizes)
        if n_groups * max_size * max_size > _ALL_PAIRS_BUDGET:
            n_pair = max(1, int(getattr(self.params,
                                        "lambdarank_num_pair_per_sample", 1)))
            starts = np.asarray(group_ptr[:-1], np.int32)
            grad, hess = _lambda_grad_sampled(
                margin, label, jnp.asarray(group_of),
                jnp.asarray(starts[group_of]),
                jnp.asarray(sizes.astype(np.int32)[group_of]),
                jax.random.PRNGKey(iteration * 2654435761 & 0x7FFFFFFF),
                n_groups, n_pair, self.scheme,
            )
        else:
            rank_in_group = np.concatenate(
                [np.arange(s, dtype=np.int32) for s in sizes]
            ) if n else np.zeros(0, np.int32)
            grad, hess = _lambda_grad(
                margin, label, jnp.asarray(group_of), jnp.asarray(rank_in_group),
                n_groups, max_size, self.scheme,
            )
        # per-group query weights, normalized so the group-weight SUM drops
        # out (reference ComputeWeightNormalizationFactor: ngroup / sum_w)
        if weight is not None and len(weight) == n_groups:
            w_np = np.asarray(weight, np.float64)
            norm = n_groups / max(float(w_np.sum()), 1e-30)
            w_row = jnp.asarray(np.repeat(w_np * norm, sizes)
                                .astype(np.float32))
            grad, hess = grad * w_row, hess * w_row
        elif weight is not None and len(weight) == n:
            grad, hess = grad * weight, hess * weight
        return grad, hess

    def default_metric(self):
        return "map" if self.scheme == "map" else ("ndcg" if self.scheme == "ndcg" else "map")


@OBJECTIVES.register("rank:pairwise")
class RankPairwise(_LambdaRankBase):
    scheme = "pairwise"


@OBJECTIVES.register("rank:ndcg")
class RankNDCG(_LambdaRankBase):
    scheme = "ndcg"


@OBJECTIVES.register("rank:map")
class RankMAP(_LambdaRankBase):
    scheme = "map"
