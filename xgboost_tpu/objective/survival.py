"""Survival objectives: AFT (reference: ``src/objective/aft_obj.cu:144``,
math in ``src/common/probability_distribution.h`` /
``src/common/survival_util.h``) and Cox PH
(``regression_obj.cu:400`` survival:cox).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import OBJECTIVES
from .base import ObjFunction, Task, apply_weight

_SQRT2PI = math.sqrt(2.0 * math.pi)
_EPS = 1e-12
# clamped gradient/hessian bounds, as in survival_util.h kMaxGradient etc.
_MAX_G, _MIN_H = 15.0, 1e-16


def _norm_pdf(z):
    return jnp.exp(-0.5 * z * z) / _SQRT2PI


def _norm_cdf(z):
    # erfc form: exact in the lower tail where 0.5*(1+erf) cancels to 0
    # in float32 (the interval-censored AFT denominator lives there)
    return 0.5 * jax.lax.erfc(-z / math.sqrt(2.0))


def _logis_pdf(z):
    e = jnp.exp(-jnp.abs(z))
    return e / (1.0 + e) ** 2


def _logis_cdf(z):
    return jax.nn.sigmoid(z)


def _extreme_pdf(z):
    w = jnp.exp(jnp.clip(z, -50.0, 50.0))
    return w * jnp.exp(-w)


def _extreme_cdf(z):
    w = jnp.exp(jnp.clip(z, -50.0, 50.0))
    return 1.0 - jnp.exp(-w)


_DISTS = {
    "normal": (_norm_pdf, _norm_cdf),
    "logistic": (_logis_pdf, _logis_cdf),
    "extreme": (_extreme_pdf, _extreme_cdf),
}


def _normal_hazard(z):
    """pdf(z)/(1-cdf(z)), stable out to any z: exact ratio where erfc has
    range, the Mills-ratio asymptote (z + 1/z - 2/z^3) in the far tail."""
    zc = jnp.minimum(z, 8.0)
    direct = _norm_pdf(zc) / jnp.maximum(
        0.5 * jax.lax.erfc(zc / math.sqrt(2.0)), 1e-30)
    zs = jnp.maximum(z, 1.0)
    asym = zs + 1.0 / zs - 2.0 / zs ** 3
    return jnp.where(z > 8.0, asym, direct)


@OBJECTIVES.register("survival:aft")
class AFT(ObjFunction):
    """Accelerated failure time with censoring, in the reference's closed
    forms (``src/common/survival_util.h``: per-distribution grad/hess for
    uncensored / right- / left- / interval-censored rows, gradients clipped
    to +-15 and hessians to [1e-16, 15] — kMin/MaxGradient, kMin/MaxHessian
    there). Float32-stable compositions: the normal censoring terms go
    through a guarded hazard (Mills asymptote in the far tail), the
    logistic ones through sigmoids, the extreme ones through the exact
    algebraic ratios; non-finite fallout in the doubly-saturated interval
    tail rails to the clamp of the correct sign."""

    task = Task.SURVIVAL

    def _loglik(self, margin, y_lower, y_upper):
        """Interval log-likelihood (used by the aft-nloglik metric;
        training uses the closed-form gradients below)."""
        p = self.params
        dist = getattr(p, "aft_loss_distribution", "normal") if p else "normal"
        sigma = float(getattr(p, "aft_loss_distribution_scale", 1.0) or 1.0) if p else 1.0
        pdf, cdf = _DISTS[dist]
        log_yl = jnp.log(jnp.maximum(y_lower, _EPS))
        z_l = (log_yl - margin) / sigma
        uncensored = y_upper == y_lower
        inf_upper = ~jnp.isfinite(y_upper)
        log_yu = jnp.log(jnp.maximum(
            jnp.where(jnp.isfinite(y_upper), y_upper, 1.0), _EPS))
        z_u = (log_yu - margin) / sigma
        # uncensored density includes the 1/(sigma*y) change-of-variables
        # Jacobian (survival_util.h AFTLoss::Loss kUncensored) — constant
        # in the margin, so gradients are unaffected but the METRIC value
        # must carry it (test_survival_metric.cu:50 pins the aggregate)
        ll_unc = jnp.log(
            jnp.maximum(pdf(z_l), _EPS)
            / (sigma * jnp.maximum(y_lower, _EPS)))
        ll_right = jnp.log(jnp.maximum(1.0 - cdf(z_l), _EPS))
        ll_int = jnp.log(jnp.maximum(cdf(z_u) - cdf(z_l), _EPS))
        return jnp.where(uncensored, ll_unc,
                         jnp.where(inf_upper, ll_right, ll_int))

    def get_gradient(self, margin, label, weight, iteration=0, *,
                     label_lower=None, label_upper=None, **kw):
        if label_lower is None:
            label_lower = label
        if label_upper is None:
            label_upper = label
        p = self.params
        dist = getattr(p, "aft_loss_distribution", "normal") if p else "normal"
        sigma = float(getattr(p, "aft_loss_distribution_scale", 1.0) or 1.0) if p else 1.0
        y_l = jnp.asarray(label_lower, jnp.float32)
        y_u = jnp.asarray(label_upper, jnp.float32)
        log_yl = jnp.where(y_l > 0, jnp.log(jnp.maximum(y_l, _EPS)), -jnp.inf)
        finite_u = jnp.isfinite(y_u)
        log_yu = jnp.where(finite_u,
                           jnp.log(jnp.maximum(jnp.where(finite_u, y_u, 1.0),
                                               _EPS)), jnp.inf)
        z_l = (log_yl - margin) / sigma  # -inf when y_l == 0
        z_u = (log_yu - margin) / sigma  # +inf when right-censored
        zl_f = jnp.where(jnp.isfinite(z_l), z_l, 0.0)
        zu_f = jnp.where(jnp.isfinite(z_u), z_u, 0.0)

        if dist == "normal":
            pdf_l = jnp.where(jnp.isfinite(z_l), _norm_pdf(zl_f), 0.0)
            pdf_u = jnp.where(jnp.isfinite(z_u), _norm_pdf(zu_f), 0.0)
            dpdf_l = -zl_f * pdf_l  # pdf'(z); 0 at infinite z
            dpdf_u = -zu_f * pdf_u
            cdf_l = jnp.where(jnp.isfinite(z_l), _norm_cdf(zl_f), 0.0)
            cdf_u = jnp.where(jnp.isfinite(z_u), _norm_cdf(zu_f), 1.0)
            g_unc = -z_l / sigma
            h_unc = jnp.ones_like(margin) / sigma ** 2
            hz = _normal_hazard(zl_f)  # right-censored hazard
            g_right = -hz / sigma
            h_right = hz * (hz - zl_f) / sigma ** 2
            rh = _normal_hazard(-zu_f)  # left-censored: mirrored hazard
            g_left = rh / sigma
            h_left = rh * (rh + zu_f) / sigma ** 2
        elif dist == "logistic":
            sig_l = _logis_cdf(zl_f)
            sig_u = _logis_cdf(zu_f)
            pdf_l = jnp.where(jnp.isfinite(z_l), _logis_pdf(zl_f), 0.0)
            pdf_u = jnp.where(jnp.isfinite(z_u), _logis_pdf(zu_f), 0.0)
            dpdf_l = pdf_l * (1.0 - 2.0 * sig_l)
            dpdf_u = pdf_u * (1.0 - 2.0 * sig_u)
            cdf_l = jnp.where(jnp.isfinite(z_l), sig_l, 0.0)
            cdf_u = jnp.where(jnp.isfinite(z_u), sig_u, 1.0)
            g_unc = (1.0 - 2.0 * sig_l) / sigma
            h_unc = 2.0 * pdf_l / sigma ** 2
            g_right = -sig_l / sigma  # pdf/S = sigmoid(z), exact
            h_right = pdf_l / sigma ** 2
            g_left = (1.0 - sig_u) / sigma  # pdf/F = sigmoid(-z), exact
            h_left = pdf_u / sigma ** 2
        else:  # extreme (Gumbel minimum)
            w_l = jnp.exp(jnp.clip(zl_f, -50.0, 50.0))
            w_u = jnp.exp(jnp.clip(zu_f, -50.0, 50.0))
            pdf_l = jnp.where(jnp.isfinite(z_l), _extreme_pdf(zl_f), 0.0)
            pdf_u = jnp.where(jnp.isfinite(z_u), _extreme_pdf(zu_f), 0.0)
            dpdf_l = pdf_l * (1.0 - w_l)
            dpdf_u = pdf_u * (1.0 - w_u)
            cdf_l = jnp.where(jnp.isfinite(z_l), _extreme_cdf(zl_f), 0.0)
            cdf_u = jnp.where(jnp.isfinite(z_u), _extreme_cdf(zu_f), 1.0)
            g_unc = (1.0 - w_l) / sigma
            h_unc = w_l / sigma ** 2
            g_right = -w_l / sigma  # pdf/S = w, exact
            h_right = w_l / sigma ** 2
            # left-censored: pdf/F = w/(e^w - 1), exact via expm1
            E = jnp.expm1(jnp.minimum(w_u, 80.0))
            g_left = w_u / jnp.maximum(E, 1e-30) / sigma
            h_left = (w_u * (w_u * (E + 1.0) - E)
                      / jnp.maximum(E * E, 1e-30)) / sigma ** 2

        # interval / left-censored shared form: loss = -log(F_u - F_l)
        D = cdf_u - cdf_l
        N = pdf_u - pdf_l
        g_int = N / (sigma * jnp.maximum(D, 1e-30))
        h_int = g_int * g_int + (dpdf_l - dpdf_u) / (
            sigma ** 2 * jnp.maximum(D, 1e-30))

        uncensored = y_u == y_l
        right = ~finite_u
        left = y_l <= 0  # z_l = -inf: pure left censoring
        grad = jnp.where(uncensored, g_unc,
                         jnp.where(right, g_right,
                                   jnp.where(left, g_left, g_int)))
        hess = jnp.where(uncensored, h_unc,
                         jnp.where(right, h_right,
                                   jnp.where(left, h_left, h_int)))

        # doubly-saturated tails (D underflowed to 0): rail with the sign
        # of the side the prediction fell past, like the double-precision
        # reference saturating through its Clip (survival_util.h)
        blown = ~jnp.isfinite(grad) | (~uncensored & ~right & ~left
                                       & (D <= 0))
        rail = jnp.where(z_u + z_l < 0, _MAX_G, -_MAX_G)
        rail = jnp.where(jnp.isfinite(z_u + z_l), rail,
                         jnp.where(zu_f + zl_f < 0, _MAX_G, -_MAX_G))
        grad = jnp.where(blown, rail, grad)
        hess = jnp.where(blown | ~jnp.isfinite(hess), _MAX_G, hess)
        grad = jnp.clip(grad, -_MAX_G, _MAX_G)
        hess = jnp.clip(hess, _MIN_H, _MAX_G)
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def eval_transform(self, margin):
        # no-op: the AFT metrics expect the UNtransformed (log-space)
        # score (reference aft_obj.cu:117 EvalTransform comment)
        return margin

    def prob_to_margin(self, base_score):
        return math.log(max(base_score, 1e-16))

    def default_metric(self):
        return "aft-nloglik"


@OBJECTIVES.register("survival:cox")
class CoxPH(ObjFunction):
    """Cox proportional hazards partial likelihood (reference:
    ``regression_obj.cu:304`` CoxRegression — negative labels mark
    censored rows). Matching the reference exactly: rows are processed in
    |label| ascending order (``MetaInfo::LabelAbsSort``, so the input need
    NOT be pre-sorted), the risk-set denominator is held constant across
    tied times (Breslow's method, the ``last_abs_y < abs_y`` gate at
    :354), and ``r_k``/``s_k`` accumulate 1/denominator at event rows
    inclusively."""

    task = Task.SURVIVAL

    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        n = margin.shape[0]
        abs_y = jnp.abs(label)
        order = jnp.argsort(abs_y)  # stable, ascending |time|
        exp_s = jnp.exp(margin)[order]
        ys = label[order]
        abs_s = abs_y[order]
        # suffix sums of exp(p); the risk set of row i is every row whose
        # |time| >= |time_i|, i.e. the suffix starting at i's TIE GROUP's
        # first row (Breslow: tied times share one denominator)
        suffix = jnp.cumsum(exp_s[::-1])[::-1]
        idx = jnp.arange(n)
        first = jnp.concatenate(
            [jnp.ones((1,), bool), abs_s[1:] != abs_s[:-1]])
        group_start = jax.lax.cummax(jnp.where(first, idx, 0))
        denom = jnp.maximum(suffix[group_start], 1e-30)
        event = ys > 0
        r_k = jnp.cumsum(jnp.where(event, 1.0 / denom, 0.0))  # inclusive
        s_k = jnp.cumsum(jnp.where(event, 1.0 / (denom * denom), 0.0))
        grad_s = exp_s * r_k - event.astype(margin.dtype)
        hess_s = exp_s * r_k - exp_s * exp_s * s_k
        grad = jnp.zeros_like(margin).at[order].set(grad_s)
        hess = jnp.zeros_like(margin).at[order].set(hess_s)
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def default_metric(self):
        return "cox-nloglik"
