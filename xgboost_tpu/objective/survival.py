"""Survival objectives: AFT (reference: ``src/objective/aft_obj.cu:144``,
math in ``src/common/probability_distribution.h`` /
``src/common/survival_util.h``) and Cox PH
(``regression_obj.cu:400`` survival:cox).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import OBJECTIVES
from .base import ObjFunction, Task, apply_weight

_SQRT2PI = math.sqrt(2.0 * math.pi)
_EPS = 1e-12
# clamped gradient/hessian bounds, as in survival_util.h kMaxGradient etc.
_MAX_G, _MIN_H = 15.0, 1e-16


def _norm_pdf(z):
    return jnp.exp(-0.5 * z * z) / _SQRT2PI


def _norm_cdf(z):
    return 0.5 * (1.0 + jax.lax.erf(z / math.sqrt(2.0)))


def _logis_pdf(z):
    e = jnp.exp(-jnp.abs(z))
    return e / (1.0 + e) ** 2


def _logis_cdf(z):
    return jax.nn.sigmoid(z)


def _extreme_pdf(z):
    w = jnp.exp(jnp.clip(z, -50.0, 50.0))
    return w * jnp.exp(-w)


def _extreme_cdf(z):
    w = jnp.exp(jnp.clip(z, -50.0, 50.0))
    return 1.0 - jnp.exp(-w)


_DISTS = {
    "normal": (_norm_pdf, _norm_cdf),
    "logistic": (_logis_pdf, _logis_cdf),
    "extreme": (_extreme_pdf, _extreme_cdf),
}


@OBJECTIVES.register("survival:aft")
class AFT(ObjFunction):
    """Accelerated failure time with censoring. Gradients computed
    numerically-stably via autodiff of the interval log-likelihood — same
    math as the closed forms in survival_util.h, but one source."""

    task = Task.SURVIVAL

    def _loglik(self, margin, y_lower, y_upper):
        dist = getattr(self.params, "aft_loss_distribution", "normal") if self.params else "normal"
        sigma = getattr(self.params, "aft_loss_distribution_scale", 1.0) if self.params else 1.0
        pdf, cdf = _DISTS[dist]
        log_yl = jnp.log(jnp.maximum(y_lower, _EPS))
        z_l = (log_yl - margin) / sigma
        uncensored = y_upper == y_lower
        inf_upper = ~jnp.isfinite(y_upper)
        log_yu = jnp.log(jnp.maximum(jnp.where(jnp.isfinite(y_upper), y_upper, 1.0), _EPS))
        z_u = (log_yu - margin) / sigma
        # uncensored: log pdf(z)/sigma ; right-censored: log(1-cdf(zl));
        # interval: log(cdf(zu)-cdf(zl))
        ll_unc = jnp.log(jnp.maximum(pdf(z_l), _EPS) / sigma)
        ll_right = jnp.log(jnp.maximum(1.0 - cdf(z_l), _EPS))
        ll_int = jnp.log(jnp.maximum(cdf(z_u) - cdf(z_l), _EPS))
        return jnp.where(uncensored, ll_unc, jnp.where(inf_upper, ll_right, ll_int))

    def get_gradient(self, margin, label, weight, iteration=0, *, label_lower=None, label_upper=None, **kw):
        if label_lower is None:
            label_lower = label
        if label_upper is None:
            label_upper = label
        neg_ll = lambda m: -self._loglik(m, label_lower, label_upper).sum()
        grad = jax.grad(neg_ll)(margin)
        # diagonal hessian via grad-of-grad vectorized with HVP on ones is
        # wrong for coupled losses, but AFT is elementwise => exact
        hess = jax.grad(lambda m: jax.grad(neg_ll)(m).sum())(margin)
        grad = jnp.clip(grad, -_MAX_G, _MAX_G)
        hess = jnp.clip(hess, _MIN_H, _MAX_G)
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def prob_to_margin(self, base_score):
        return math.log(max(base_score, 1e-16))

    def default_metric(self):
        return "aft-nloglik"


@OBJECTIVES.register("survival:cox")
class CoxPH(ObjFunction):
    """Cox proportional hazards partial likelihood (reference:
    ``regression_obj.cu:400`` CoxRegression — negative labels mark censored
    rows; data assumed sorted by observed time ascending, as the reference
    requires)."""

    task = Task.SURVIVAL

    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        # risk set of row i = rows with time >= t_i  (suffix sums given the
        # required time-ascending sort)
        exp_p = jnp.exp(margin)
        w = weight if weight is not None else jnp.ones_like(margin)
        # suffix cumulative sums of exp(pred)
        rev = lambda x: x[::-1]
        r_k = rev(jnp.cumsum(rev(exp_p * 1.0)))  # sum_{j: j >= i} exp_p[j]
        # accumulated censoring terms: for each event row e (label>0),
        # rows i <= e get + exp_p[i]/r_k[e] style terms
        is_event = label > 0
        inv_r = jnp.where(is_event, 1.0 / jnp.maximum(r_k, 1e-30), 0.0)
        inv_r2 = jnp.where(is_event, 1.0 / jnp.maximum(r_k * r_k, 1e-30), 0.0)
        acc1 = jnp.cumsum(inv_r)  # prefix: sum over events e <= i of 1/r_e
        acc2 = jnp.cumsum(inv_r2)
        grad = exp_p * acc1 - is_event.astype(margin.dtype)
        hess = exp_p * acc1 - (exp_p ** 2) * acc2
        return apply_weight(grad * 1.0, jnp.maximum(hess, 1e-16), None if weight is None else w)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def default_metric(self):
        return "cox-nloglik"
