"""ObjFunction base class (reference: ``include/xgboost/objective.h``,
task typing via ObjInfo ``include/xgboost/task.h:22``)."""

from __future__ import annotations

import enum
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..registry import OBJECTIVES


class Task(enum.Enum):
    REGRESSION = "regression"
    BINARY = "binary"
    CLASSIFICATION = "classification"
    RANKING = "ranking"
    SURVIVAL = "survival"


class ObjFunction:
    """Gradient/hessian provider. Shapes: margin [n] or [n, n_targets]."""

    task: Task = Task.REGRESSION
    name: str = ""
    #: elementwise, jax-traceable gradient with no group/bound state — safe
    #: to trace inside a multi-round lax.scan (Booster.update_many)
    scan_safe: bool = False

    def __init__(self, params=None):
        self.params = params

    def n_targets(self) -> int:
        return 1

    def get_gradient(
        self,
        margin: jax.Array,
        label: jax.Array,
        weight: Optional[jax.Array],
        iteration: int = 0,
        *,
        group_ptr: Optional[np.ndarray] = None,
        label_lower: Optional[jax.Array] = None,
        label_upper: Optional[jax.Array] = None,
    ) -> Tuple[jax.Array, jax.Array]:
        raise NotImplementedError

    # margin -> user-facing prediction (reference: PredTransform)
    def pred_transform(self, margin: jax.Array) -> jax.Array:
        return margin

    # same but for evaluation-time predictions (softmax differs)
    def eval_transform(self, margin: jax.Array) -> jax.Array:
        return self.pred_transform(margin)

    # base_score (prob space) -> initial margin (reference: ProbToMargin)
    def prob_to_margin(self, base_score: float) -> float:
        return base_score

    def default_base_score(self) -> float:
        return 0.5

    def default_metric(self) -> str:
        return "rmse"


def create_objective(name: str, params=None) -> ObjFunction:
    obj = OBJECTIVES.create(name, params)
    obj.name = OBJECTIVES.resolve(name)
    return obj


def apply_weight(
    grad: jax.Array, hess: jax.Array, weight: Optional[jax.Array]
) -> Tuple[jax.Array, jax.Array]:
    if weight is None:
        return grad, hess
    if grad.ndim == 2:
        weight = weight[:, None]
    return grad * weight, hess * weight
