"""Regression / binary / counts objectives.

Formula parity with ``src/objective/regression_obj.cu`` (registrations at
:163-183, :189, :298, :400, :485, :599) and ``regression_loss.h``;
``hinge.cu:95``.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..registry import OBJECTIVES
from .base import ObjFunction, Task, apply_weight

_EPS = 1e-16
_HESS_EPS = 1e-6


def _sigmoid(x):
    return jax.nn.sigmoid(x)


@OBJECTIVES.register("reg:squarederror", "reg:linear")
class SquaredError(ObjFunction):
    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        grad = margin - label
        hess = jnp.ones_like(margin)
        return apply_weight(grad, hess, weight)

    def default_metric(self):
        return "rmse"


@OBJECTIVES.register("reg:squaredlogerror")
class SquaredLogError(ObjFunction):
    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        p = jnp.maximum(margin, -1 + 1e-6)
        d = jnp.log1p(p) - jnp.log1p(label)
        grad = d / (p + 1.0)
        hess = jnp.maximum((-d + 1.0) / ((p + 1.0) ** 2), _HESS_EPS)
        return apply_weight(grad, hess, weight)

    def default_metric(self):
        return "rmsle"


@OBJECTIVES.register("reg:pseudohubererror")
class PseudoHuber(ObjFunction):
    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        slope = getattr(self.params, "huber_slope", 1.0) if self.params else 1.0
        z = margin - label
        scale = 1.0 + (z / slope) ** 2
        sqrt_s = jnp.sqrt(scale)
        grad = z / sqrt_s
        hess = 1.0 / (scale * sqrt_s)
        return apply_weight(grad, hess, weight)

    def default_metric(self):
        return "mphe"


class _LogisticBase(ObjFunction):
    task = Task.BINARY

    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        p = _sigmoid(margin)
        grad = p - label
        hess = jnp.maximum(p * (1.0 - p), _EPS)
        spw = getattr(self.params, "scale_pos_weight", 1.0) if self.params else 1.0
        if spw != 1.0:
            w = jnp.where(label == 1.0, spw, 1.0)
            grad, hess = grad * w, hess * w
        return apply_weight(grad, hess, weight)

    def prob_to_margin(self, base_score):
        import math

        base_score = min(max(base_score, 1e-7), 1 - 1e-7)
        return -math.log(1.0 / base_score - 1.0)


@OBJECTIVES.register("binary:logistic")
class BinaryLogistic(_LogisticBase):
    def pred_transform(self, margin):
        return _sigmoid(margin)

    def default_metric(self):
        return "logloss"


@OBJECTIVES.register("reg:logistic")
class RegLogistic(_LogisticBase):
    task = Task.REGRESSION

    def pred_transform(self, margin):
        return _sigmoid(margin)

    def default_metric(self):
        return "rmse"


@OBJECTIVES.register("binary:logitraw")
class LogitRaw(_LogisticBase):
    def pred_transform(self, margin):
        return margin

    def default_metric(self):
        return "logloss"


@OBJECTIVES.register("binary:hinge")
class Hinge(ObjFunction):
    task = Task.BINARY

    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        y = 2.0 * label - 1.0
        active = y * margin < 1.0
        grad = jnp.where(active, -y, 0.0)
        hess = jnp.where(active, 1.0, _HESS_EPS)
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return (margin > 0.0).astype(jnp.float32)

    def default_metric(self):
        return "error"


@OBJECTIVES.register("count:poisson")
class Poisson(ObjFunction):
    def _max_delta_step(self) -> float:
        """The Poisson-specific max_delta_step (reference
        regression_obj.cu:197: its OWN param, default 0.7, fed from the
        same user key as the tree one). Explicitly-set values win,
        including an explicit 0."""
        p = self.params
        if p is not None:
            v = getattr(p, "max_delta_step", None)
            if v is not None and (not hasattr(p, "is_explicit")
                                  or p.is_explicit("max_delta_step")):
                return float(v)
        return 0.7

    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        grad = jnp.exp(margin) - label
        # hess = exp(p + max_delta_step): the reference's capped-step
        # hessian inflation (regression_obj.cu:249)
        hess = jnp.exp(margin + self._max_delta_step())
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def prob_to_margin(self, base_score):
        import math

        return math.log(max(base_score, 1e-16))

    def default_metric(self):
        return "poisson-nloglik"


@OBJECTIVES.register("reg:gamma")
class GammaDeviance(ObjFunction):
    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        expm = jnp.exp(-margin)
        grad = 1.0 - label * expm
        hess = jnp.maximum(label * expm, _EPS)
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def prob_to_margin(self, base_score):
        import math

        return math.log(max(base_score, 1e-16))

    def default_metric(self):
        return "gamma-nloglik"


@OBJECTIVES.register("reg:tweedie")
class Tweedie(ObjFunction):
    def get_gradient(self, margin, label, weight, iteration=0, **kw):
        rho = getattr(self.params, "tweedie_variance_power", 1.5) if self.params else 1.5
        e1 = jnp.exp((1.0 - rho) * margin)
        e2 = jnp.exp((2.0 - rho) * margin)
        grad = -label * e1 + e2
        hess = jnp.maximum(-label * (1.0 - rho) * e1 + (2.0 - rho) * e2, _EPS)
        return apply_weight(grad, hess, weight)

    def pred_transform(self, margin):
        return jnp.exp(margin)

    def prob_to_margin(self, base_score):
        import math

        return math.log(max(base_score, 1e-16))

    def default_metric(self):
        rho = getattr(self.params, "tweedie_variance_power", 1.5) if self.params else 1.5
        return f"tweedie-nloglik@{rho}"


# every objective in this module is elementwise and jax-traceable: safe to
# trace inside the multi-round scan (learner.Booster.update_many)
for _cls in (SquaredError, SquaredLogError, PseudoHuber, BinaryLogistic,
             RegLogistic, LogitRaw, Hinge, Poisson, GammaDeviance, Tweedie):
    _cls.scan_safe = True
del _cls
