"""Objective functions.

Reference: ``src/objective/`` — ``regression_obj.cu`` (loss templates in
``regression_loss.h``, GetGradient pattern at :59-126), ``multiclass_obj.cu``,
``hinge.cu``, ``rank_obj.cu``, ``aft_obj.cu``. The reference single-sources
CPU/GPU via ``common::Transform``; here every objective is a pure jnp
function, so one source serves TPU and host automatically.
"""

from .base import ObjFunction, create_objective  # noqa: F401
from . import regression  # noqa: F401  (registers)
from . import multiclass  # noqa: F401
from . import ranking  # noqa: F401
from . import survival  # noqa: F401
