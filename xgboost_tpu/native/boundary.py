"""The guarded native boundary: contract-checked FFI dispatch + fault
containment (ISSUE 20 tentpole, parts b/c).

Every native kernel invocation crosses HERE. The module owns three
things:

* **The capability map** — one ``resilience.degrade`` capability per
  native library (``native_tree``, ``native_hist``, ``native_sketch``,
  ``native_serving``). ``dispatch/ops.py`` attaches them to the native
  impl rows, so a degraded library re-routes ``resolve`` onto the
  XLA/per-level impls with a ``dispatch_route_change`` flight event —
  no call site carries fallback logic of its own.
* **``ffi_call``** — a drop-in for ``jax.extend.ffi.ffi_call`` that
  first validates the call against the binder signature parsed from the
  handler's C++ TU (``analysis/ffi_contract.parse_cpp_handlers`` — the
  same parse NB6xx lints with, now enforced at run time): operand
  arity, attr name-set, result count, and every statically-known dtype.
  A drifted call raises a typed :class:`NativeContractError` (and
  degrades the library) instead of letting the handler reinterpret
  device memory. The checks run at TRACE time — ``ffi_call`` sites
  execute once per compilation, never per round — so the guard adds no
  per-round host work (acceptance: no rounds/s regression). The
  wrapper is named ``ffi_call`` on purpose: the NB6xx scanner matches
  any call whose attribute chain ends in ``ffi_call``, so call sites
  routed through it keep their static lint coverage.
* **Containment** — :func:`contain` classifies a fault raised while a
  native train route was active, burns the owning libraries' degrade
  countdowns, counts ``native_faults_total{lib,kind}`` and returns a
  TRANSIENT-classified :class:`NativeFault` for
  ``RetryPolicy("native_dispatch")`` to retry: the re-run re-resolves
  dispatch (capability state is part of the cache key) and lands on the
  fallback route. :func:`tick` burns one unit of each degraded
  library's countdown per round so a transient fault heals — the route
  flips back (another ``dispatch_route_change``) after ``retry_after``
  rounds. Canary verdicts (``native/canary.py``) use a process-lifetime
  countdown instead: a build that failed its golden run is never
  retried by time alone.

The in-kernel half of the guard (``XGBTPU_NATIVE_GUARD=1`` bounds
checks inside hist_build.cpp / tree_build.cpp) is documented in
docs/resilience.md, "The native boundary".
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Optional, Tuple

from ..resilience import degrade, policy

__all__ = [
    "CAPS", "OP_LIBS", "TRAIN_OPS", "NativeContractError", "NativeFault",
    "ffi_call", "contain", "round_chaos", "tick", "degrade_lib",
    "record_native_fault", "record_build_failure", "capability_for",
    "cap_snapshot",
]

#: native library -> its degrade capability
CAPS: Dict[str, str] = {
    "tree_build": "native_tree",
    "hist_build": "native_hist",
    "sketch_bin": "native_sketch",
    "serving_walk": "native_serving",
}

#: dispatch op -> the native library its ``native`` impl dispatches into
OP_LIBS: Dict[str, str] = {
    "tree_grow": "tree_build",
    "level_hist": "hist_build",
    "level_partition": "hist_build",
    "sketch_cuts": "sketch_bin",
    "bin_matrix": "sketch_bin",
    "predict_walk": "serving_walk",
}

#: the ops the per-round training containment watches
TRAIN_OPS: Tuple[str, ...] = ("tree_grow", "level_hist", "level_partition")

#: FFI target -> (C++ TU basename, handler symbol): the run-time edge of
#: the NB6xx static map. ``xgbtpu_canary_*`` targets alias the same
#: symbols from the canary child's registrations.
TARGETS: Dict[str, Tuple[str, str]] = {
    "xgbtpu_tree_grow": ("tree_build.cpp", "XgbtpuTreeGrow"),
    "xgbtpu_hb_level_sub": ("tree_build.cpp", "XgbtpuHbLevelSub"),
    "xgbtpu_hb_level_quant": ("tree_build.cpp", "XgbtpuHbLevelQuant"),
    "xgbtpu_hb_level": ("hist_build.cpp", "XgbtpuHbLevel"),
    "xgbtpu_hb_partition": ("hist_build.cpp", "XgbtpuHbPartition"),
    "xgbtpu_sketch_cuts": ("sketch_bin.cpp", "XgbtpuSketchCuts"),
    "xgbtpu_bin_matrix_u8": ("sketch_bin.cpp", "XgbtpuBinMatrixU8"),
    "xgbtpu_bin_matrix_u16": ("sketch_bin.cpp", "XgbtpuBinMatrixU16"),
}

#: runtime faults heal after this many skipped rounds; canary verdicts
#: stick for the process (a failed golden run condemns the BUILD)
RUNTIME_RETRY_AFTER = 32
PROCESS_RETRY_AFTER = 1 << 30


class NativeContractError(TypeError):
    """An ``ffi_call`` whose operands/attrs/results drifted from the
    handler's binder signature — refused before the handler runs."""

    chaos_kind = policy.PERMANENT  # a drifted call never self-heals


class NativeFault(RuntimeError):
    """A contained native-boundary fault. Classified TRANSIENT so the
    round-level ``RetryPolicy("native_dispatch")`` retries it — the
    retry re-resolves dispatch and runs on the fallback route (the
    original kind already burned the library's degrade countdown)."""

    chaos_kind = policy.TRANSIENT

    def __init__(self, msg: str, original: Optional[BaseException] = None):
        super().__init__(msg)
        self.original = original


def cap_snapshot() -> Tuple[Tuple[str, int], ...]:
    """Read-only (capability, worst-state) snapshot of every native
    capability, via ``degrade.worst`` (no retry countdown burned). Baked
    into ``GrowParams.native_caps`` so the compiled tree builder's static
    key tracks route health — trace-time resolves re-run on any flip."""
    return tuple((name, degrade.worst(name))
                 for name in sorted(set(CAPS.values())))


def capability_for(lib: str) -> Optional[degrade.CapabilityHealth]:
    name = CAPS.get(lib)
    if name is None:
        return None
    return degrade.capability(name, retry_after=RUNTIME_RETRY_AFTER)


def record_native_fault(lib: str, kind: str) -> None:
    from ..observability.metrics import REGISTRY

    REGISTRY.counter(
        "native_faults_total",
        "Faults observed at the native boundary by library and kind",
    ).labels(lib=lib, kind=kind).inc()


def record_build_failure(lib: str, detail: str = "") -> None:
    """A ``_compile``/dlopen failure for ``lib`` (``native/__init__.py``):
    counted and — for canaried libraries — degraded for the process, so
    a pure-Python box resolves every op to the XLA impls out of the box
    instead of re-probing a toolchain that is not there."""
    from ..observability.metrics import REGISTRY

    REGISTRY.counter(
        "native_build_failures_total",
        "Native library build/load failures by library",
    ).labels(lib=lib).inc()
    cap = capability_for(lib)
    if cap is not None:
        cap.failure(kind=policy.PERMANENT, retry_after=PROCESS_RETRY_AFTER)
    from ..utils import console_logger

    console_logger.info(
        f"native library {lib!r} unavailable"
        + (f" ({detail})" if detail else "")
        + "; dispatch keeps the XLA/level impls")


def degrade_lib(lib: str, *, kind_hint: str = "", detail: str = "",
                for_process: bool = False) -> None:
    """Burn ``lib``'s degrade capability. ``kind_hint`` is a boundary
    fault label (crash/timeout/corrupt/mismatch/refused/...) mapped onto
    the resilience kinds; TRANSIENT is promoted to RESOURCE because
    ``CapabilityHealth.failure`` deliberately ignores transients and the
    boundary's whole point is to re-route the next rounds."""
    cap = capability_for(lib)
    if cap is None:
        return
    kind = {"timeout": policy.RESOURCE, "resource": policy.RESOURCE,
            "transient": policy.RESOURCE}.get(kind_hint, policy.PERMANENT)
    cap.failure(kind=kind,
                retry_after=(PROCESS_RETRY_AFTER if for_process
                             else RUNTIME_RETRY_AFTER))
    if detail:
        from ..utils import console_logger

        console_logger.warning(f"native library {lib!r} degraded: {detail}")


# ---------------------------------------------------------------------------
# guarded ffi_call (tentpole part b, Python half)
# ---------------------------------------------------------------------------

_contract_lock = threading.Lock()
_contracts: Dict[str, Optional[object]] = {}  # target -> CppHandler | None


def _handler_for(target: str):
    """The parsed binder signature for ``target``, memoized. None when
    the TU is absent (prebuilt-only deployment) or the parse finds no
    handler — the guard then passes the call through unchecked, exactly
    like the NB6xx lint skips what it cannot see."""
    with _contract_lock:
        if target in _contracts:
            return _contracts[target]
    handler = None
    spec = TARGETS.get(target)
    if spec is not None:
        from ..analysis.ffi_contract import parse_cpp_handlers

        cpp, symbol = spec
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), cpp)
        for h in parse_cpp_handlers(path, cpp):
            if h.symbol == symbol:
                handler = h
                break
    with _contract_lock:
        _contracts[target] = handler
    return handler


def _dtype_name(x) -> Optional[str]:
    dt = getattr(x, "dtype", None)
    return None if dt is None else str(dt)


def _refuse(target: str, msg: str) -> NativeContractError:
    spec = TARGETS.get(target)
    libname = ""
    if spec is not None:
        libname = spec[0].rsplit(".", 1)[0]
        record_native_fault(libname, "contract")
        degrade_lib(libname, kind_hint="permanent",
                    detail=f"contract violation at target {target!r}")
    return NativeContractError(
        f"ffi_call target {target!r} refused: {msg} — the call drifted "
        f"from the binder signature"
        + (f" in native/{spec[0]}" if spec else ""))


def check_contract(target: str, ret_specs, operands, attrs: dict) -> None:
    """Validate one ffi_call against its handler's parsed binder. Raises
    :class:`NativeContractError` on drift; silently passes targets whose
    TU is unavailable. Trace-time only — never on the per-round path."""
    h = _handler_for(target)
    if h is None:
        return
    if len(operands) != len(h.args):
        raise _refuse(target, f"{len(operands)} operands passed, binder "
                              f"declares {len(h.args)}")
    want_attrs = {a for a, _ in h.attrs}
    got_attrs = set(attrs)
    if want_attrs != got_attrs:
        raise _refuse(
            target,
            f"attr set {sorted(got_attrs)} != binder {sorted(want_attrs)}")
    rets = (list(ret_specs) if isinstance(ret_specs, (tuple, list))
            else [ret_specs])
    if len(rets) != len(h.rets):
        raise _refuse(target, f"{len(rets)} result specs passed, binder "
                              f"declares {len(h.rets)}")
    for i, (op, want) in enumerate(zip(operands, h.args)):
        got = _dtype_name(op)
        if got is not None and want != "any" and got != want:
            raise _refuse(target, f"operand {i} dtype {got} != binder "
                                  f"ffi::Buffer<{want}>")
    for i, (spec, want) in enumerate(zip(rets, h.rets)):
        got = _dtype_name(spec)
        if got is not None and want != "any" and got != want:
            raise _refuse(target, f"result {i} dtype {got} != binder "
                                  f"ffi::Buffer<{want}>")


def ffi_call(target: str, ret_specs, *operands, **attrs):
    """Contract-checked drop-in for ``jax.extend.ffi.ffi_call`` — every
    production native call site routes through here."""
    check_contract(target, ret_specs, operands, attrs)
    from jax.extend import ffi as jffi

    return jffi.ffi_call(target, ret_specs, *operands, **attrs)


# ---------------------------------------------------------------------------
# run-time containment (tentpole part c)
# ---------------------------------------------------------------------------


def _active_native_libs() -> Tuple[str, ...]:
    """Libraries behind the native TRAIN routes most recently resolved —
    the candidates a mid-round fault condemns. Decisions are recorded at
    TRACE time only, so a round served from a warm jit cache leaves no
    fresh decision even though it runs native kernels; when no train op
    has resolved native this process, fall back to the train libraries
    already dlopened in — ground truth for 'native code can be running'
    that a warm cache cannot disarm."""
    from .. import dispatch

    decs = dispatch.last_decisions()
    libs = []
    for op in TRAIN_OPS:
        if decs.get(op) == "native":
            lib = OP_LIBS[op]
            if lib not in libs:
                libs.append(lib)
    if not libs and not any(op in decs for op in TRAIN_OPS):
        # no train op resolved AT ALL this process: routing evidence is
        # absent (not 'resolved to XLA'), so trust the dlopen memos
        import xgboost_tpu.native as _native

        train_libs = set(OP_LIBS[op] for op in TRAIN_OPS)
        libs = [lib for lib in _native.loaded_libs() if lib in train_libs]
    return tuple(libs)


def _looks_native(exc: Exception) -> bool:
    """Only faults that plausibly ORIGINATE at the native boundary are
    containable: the scripted native chaos modes, a wedged dispatch
    (watchdog), an XLA runtime failure (the FFI handler's typed errors
    and crashes both present as ``XlaRuntimeError``), or a resource
    death. A ``ValueError`` from parameter validation — or the legacy
    ``InjectedFault`` kill drill — is semantics, not a kernel fault;
    re-routing a round around it would mask a real bug (or defeat the
    restart harness that scripted it)."""
    if getattr(exc, "chaos_mode", "") in ("crash", "timeout", "corrupt"):
        return True
    from ..resilience.watchdog import WatchdogTimeout

    if isinstance(exc, (NativeContractError, WatchdogTimeout,
                        MemoryError, OSError)):
        return True
    return any(t.__name__ == "XlaRuntimeError"
               for t in type(exc).__mro__)


def contain(exc: BaseException) -> NativeFault:
    """Classify a round-dispatch fault. When a native train route was
    active AND the fault plausibly came from the boundary: degrade the
    owning libraries, count the fault, and RETURN a :class:`NativeFault`
    for the caller to raise into its RetryPolicy. Otherwise (pure-XLA
    round, a non-Exception like KeyboardInterrupt, or a semantic error
    that merely happened DURING a native round) the original exception
    is re-raised — the boundary only contains faults it can re-route
    around."""
    if not isinstance(exc, Exception) or isinstance(exc, NativeFault):
        raise exc
    if not _looks_native(exc):
        raise exc
    libs = _active_native_libs()
    if not libs:
        raise exc
    kind = getattr(exc, "chaos_mode", "") or policy.classify(exc)
    for lib in libs:
        record_native_fault(lib, kind)
        degrade_lib(lib, kind_hint=kind,
                    detail=f"round fault {type(exc).__name__} ({kind})")
    from ..observability import flight

    flight.RECORDER.event("native_fault_contained", libs=",".join(libs),
                          kind=kind, error=type(exc).__name__)
    return NativeFault(
        f"contained native fault ({kind}) in {'/'.join(libs)}: "
        f"{type(exc).__name__}: {exc}", original=exc)


def round_chaos() -> None:
    """The ``native_dispatch`` chaos site's training edge: fires once per
    boosting round while a native train route is active (and never on
    pure-XLA rounds — the site scripts NATIVE faults)."""
    if not _active_native_libs():
        return
    from ..resilience import chaos

    chaos.hit("native_dispatch")


def tick() -> None:
    """Once per round: burn one unit of each DEGRADED native capability's
    recovery countdown. ``resolve`` reads capability state read-only
    (``degrade.worst``), so without this the countdown would never move
    and a transiently-degraded library could never route back in."""
    caps = degrade.capabilities()
    for name in CAPS.values():
        cap = caps.get(name)
        if cap is not None and cap.worst_state() == degrade.DEGRADED:
            cap.allowed()
