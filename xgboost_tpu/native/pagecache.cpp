// Binary page cache with background prefetch — the native runtime piece of
// the external-memory DMatrix. Reference analog: the disk-backed page
// source with its ring of in-flight reads (xgboost's sparse_page_source
// design: pages written to a cache file, a small window prefetched ahead of
// the training loop). Plain C ABI for ctypes (no pybind11 in the image).
//
// Writer: one file per page (quantized bins, 1-2 bytes/entry).
// Reader: N slots of prefetched pages; a worker thread reads ahead in
// sequence order while the grower consumes the current page, so disk
// latency overlaps host->device transfer + TPU compute.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Reader {
  std::vector<std::string> paths;
  std::vector<long long> sizes;
  long long max_bytes = 0;
  int ring = 4;

  std::vector<std::vector<char>> slot_buf;
  std::vector<long long> slot_page;  // which page a slot holds (-1 empty)
  std::vector<bool> slot_ready;

  std::mutex mu;
  std::condition_variable cv;
  long long next_want = 0;  // prefetcher target (sequential)
  std::atomic<bool> stop{false};
  std::thread worker;

  int slot_of(long long k) const { return static_cast<int>(k % ring); }

  bool read_file(long long k, std::vector<char>* out) {
    FILE* f = std::fopen(paths[k].c_str(), "rb");
    if (!f) return false;
    out->resize(sizes[k]);
    size_t got = std::fread(out->data(), 1, sizes[k], f);
    std::fclose(f);
    return got == static_cast<size_t>(sizes[k]);
  }

  void run() {
    for (;;) {
      long long k;
      {
        std::unique_lock<std::mutex> lk(mu);
        // only advance into a FREE slot — never clobber a prefetched page
        // the consumer has not taken yet
        cv.wait(lk, [&] {
          if (stop.load()) return true;
          if (next_want >= static_cast<long long>(paths.size())) return false;
          return !slot_ready[slot_of(next_want)];
        });
        if (stop.load()) return;
        k = next_want;
        next_want++;
      }
      std::vector<char> buf;
      bool ok = read_file(k, &buf);
      {
        std::lock_guard<std::mutex> lk(mu);
        int s = slot_of(k);
        if (ok) {
          slot_buf[s] = std::move(buf);
          slot_page[s] = k;
          slot_ready[s] = true;
        }
      }
      cv.notify_all();
    }
  }
};

}  // namespace

extern "C" {

int pc_write(const char* path, const void* buf, long long nbytes) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return 1;
  size_t put = std::fwrite(buf, 1, nbytes, f);
  std::fclose(f);
  return put == static_cast<size_t>(nbytes) ? 0 : 2;
}

void* pc_open(const char* prefix, long long n_pages,
              const long long* sizes, int ring) {
  auto* r = new Reader();
  r->ring = ring > 0 ? ring : 4;
  for (long long k = 0; k < n_pages; ++k) {
    r->paths.push_back(std::string(prefix) + ".page" + std::to_string(k) +
                       ".bin");
    r->sizes.push_back(sizes[k]);
    if (sizes[k] > r->max_bytes) r->max_bytes = sizes[k];
  }
  r->slot_buf.resize(r->ring);
  r->slot_page.assign(r->ring, -1);
  r->slot_ready.assign(r->ring, false);
  r->worker = std::thread([r] { r->run(); });
  return r;
}

// Blocking read of page k into dst; steers the prefetcher to k+1 onward.
// A miss (including the wrap-around at the start of each re-streaming
// sweep) resets the window: all slots are invalidated and the worker
// restarts at k+1.
int pc_read(void* h, long long k, void* dst) {
  auto* r = static_cast<Reader*>(h);
  if (k < 0 || k >= static_cast<long long>(r->paths.size())) return 1;
  {
    std::lock_guard<std::mutex> lk(r->mu);
    int s = r->slot_of(k);
    if (r->slot_ready[s] && r->slot_page[s] == k) {
      std::memcpy(dst, r->slot_buf[s].data(), r->sizes[k]);
      r->slot_ready[s] = false;  // slot reusable
      if (r->next_want <= k) r->next_want = k + 1;
      r->cv.notify_all();
      return 0;
    }
    // miss: new sweep (or random access) — rewind the prefetch window
    for (int i = 0; i < r->ring; ++i) r->slot_ready[i] = false;
    r->next_want = k + 1;
  }
  r->cv.notify_all();
  std::vector<char> buf;
  if (!r->read_file(k, &buf)) return 2;
  std::memcpy(dst, buf.data(), r->sizes[k]);
  return 0;
}

void pc_close(void* h) {
  auto* r = static_cast<Reader*>(h);
  r->stop.store(true);
  r->cv.notify_all();
  if (r->worker.joinable()) r->worker.join();
  delete r;
}

}  // extern "C"
