// fastparse: native text-format data loader (libsvm + CSV).
//
// TPU-native analog of the reference's dmlc-core text parsers
// (dmlc/data.h ParseLibSVM/CSV used via DMatrix::Load, src/data/data.cc):
// the runtime around the accelerator stays native where the reference's is.
// mmap + single pass with hand-rolled number scanning — the host here has
// one core, so per-byte efficiency is the whole game (Python-level parsing
// of an 8GB HIGGS csv takes minutes; this does ~300MB/s).
//
// Exposed via a plain C ABI for ctypes (no pybind11 in the image).

#include <cerrno>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Mapped {
  const char* data = nullptr;
  size_t size = 0;
  int fd = -1;
  bool ok() const { return data != nullptr; }
};

Mapped map_file(const char* path) {
  Mapped m;
  m.fd = ::open(path, O_RDONLY);
  if (m.fd < 0) return m;
  struct stat st;
  if (fstat(m.fd, &st) != 0 || st.st_size == 0) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  void* p = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, m.fd, 0);
  if (p == MAP_FAILED) {
    ::close(m.fd);
    m.fd = -1;
    return m;
  }
  ::madvise(p, st.st_size, MADV_SEQUENTIAL);
  m.data = static_cast<const char*>(p);
  m.size = static_cast<size_t>(st.st_size);
  return m;
}

void unmap(Mapped& m) {
  if (m.data) ::munmap(const_cast<char*>(m.data), m.size);
  if (m.fd >= 0) ::close(m.fd);
  m.data = nullptr;
  m.fd = -1;
}

inline const char* skip_ws(const char* p, const char* end) {
  while (p < end && (*p == ' ' || *p == '\t' || *p == '\r')) ++p;
  return p;
}

// fast float scan: sign, digits, '.', digits, optional exponent.
// Falls back to strtof for unusual forms (inf/nan/hex).
inline const char* scan_float(const char* p, const char* end, float* out) {
  const char* start = p;
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  double mant = 0.0;
  bool any = false;
  while (p < end && *p >= '0' && *p <= '9') {
    mant = mant * 10.0 + (*p - '0');
    ++p;
    any = true;
  }
  if (p < end && *p == '.') {
    ++p;
    double scale = 0.1;
    while (p < end && *p >= '0' && *p <= '9') {
      mant += (*p - '0') * scale;
      scale *= 0.1;
      ++p;
      any = true;
    }
  }
  if (!any) {  // nan / inf / weird: defer to libc via a bounded NUL'd copy
    // (the mmap is not NUL-terminated; strtof on the raw pointer could read
    // past the mapping on a page-aligned file)
    char buf[64];
    size_t len = static_cast<size_t>(end - start);
    if (len > sizeof(buf) - 1) len = sizeof(buf) - 1;
    memcpy(buf, start, len);
    buf[len] = '\0';
    char* e = nullptr;
    float v = strtof(buf, &e);
    if (e == buf) {
      *out = NAN;
      return start;  // no progress: caller must skip the token
    }
    *out = v;
    return start + (e - buf);
  }
  if (p < end && (*p == 'e' || *p == 'E')) {
    ++p;
    bool eneg = false;
    if (p < end && (*p == '-' || *p == '+')) {
      eneg = (*p == '-');
      ++p;
    }
    int ex = 0;
    while (p < end && *p >= '0' && *p <= '9') {
      ex = ex * 10 + (*p - '0');
      ++p;
    }
    mant *= pow(10.0, eneg ? -ex : ex);
  }
  *out = static_cast<float>(neg ? -mant : mant);
  return p;
}

inline const char* scan_int(const char* p, const char* end, long* out) {
  bool neg = false;
  if (p < end && (*p == '-' || *p == '+')) {
    neg = (*p == '-');
    ++p;
  }
  long v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10 + (*p - '0');
    ++p;
  }
  *out = neg ? -v : v;
  return p;
}

// skip a malformed token so the scan loops always make progress
inline const char* skip_token(const char* p, const char* end) {
  while (p < end && *p != ' ' && *p != '\t' && *p != '\n' && *p != '\r') ++p;
  return p;
}

// A CSV "data line" starts with something number-like; headers and comments
// don't (np.loadtxt likewise skips '#' and chokes on text headers — we skip
// both kinds of non-data line). 'nan'/'inf' tokens count as numeric.
inline bool csv_data_line(const char* p, const char* end) {
  p = skip_ws(p, end);
  if (p >= end) return false;
  char c = *p;
  if ((c >= '0' && c <= '9') || c == '-' || c == '+' || c == '.' || c == ',')
    return true;
  auto tok3 = [&](const char* w) {
    if (end - p < 3) return false;
    for (int i = 0; i < 3; ++i)
      if ((p[i] | 0x20) != w[i]) return false;
    const char* q = skip_ws(p + 3, end);
    return q >= end || *q == ',' || *q == '\n' || *q == '\r';
  };
  return tok3("nan") || tok3("inf");
}

}  // namespace

extern "C" {

// ---- libsvm ----------------------------------------------------------
// Pass 1: count rows/entries and find max feature index.
// Returns 0 on success.
int fp_libsvm_dims(const char* path, int64_t* n_rows, int64_t* n_entries,
                   int64_t* max_col, int32_t* has_qid) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* p = m.data;
  const char* end = m.data + m.size;
  int64_t rows = 0, entries = 0, maxc = -1;
  *has_qid = 0;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '#') {
      while (p < end && *p != '\n') ++p;
      continue;
    }
    // label
    float lbl;
    const char* before = p;
    p = scan_float(p, end, &lbl);
    if (p == before) {  // malformed label: skip token, drop the line
      p = skip_token(p, end);
      while (p < end && *p != '\n') ++p;
      continue;
    }
    ++rows;
    // features until newline
    while (p < end && *p != '\n') {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n' || *p == '#') {
        if (p < end && *p == '#')
          while (p < end && *p != '\n') ++p;
        break;
      }
      if (strncmp(p, "qid:", 4) == 0) {
        p += 4;
        long q;
        p = scan_int(p, end, &q);
        *has_qid = 1;
        continue;
      }
      before = p;
      long idx;
      p = scan_int(p, end, &idx);
      if (p < end && *p == ':') {
        ++p;
        float v;
        const char* vb = p;
        p = scan_float(p, end, &v);
        if (p == vb) p = skip_token(p, end);  // malformed value
        else {
          ++entries;
          if (idx > maxc) maxc = idx;
        }
      } else if (p == before) {
        p = skip_token(p, end);  // non-numeric junk: always make progress
      }
    }
  }
  *n_rows = rows;
  *n_entries = entries;
  *max_col = maxc;
  unmap(m);
  return 0;
}

// Pass 2: fill COO triplets + labels (+qids when present). Capacities from
// the dims pass bound every write — if the file changed in between, excess
// content is dropped rather than overrunning the caller's buffers.
int fp_libsvm_parse(const char* path, int64_t* row_idx, int32_t* col_idx,
                    float* values, float* labels, int64_t* qids,
                    int64_t cap_rows, int64_t cap_entries) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* p = m.data;
  const char* end = m.data + m.size;
  int64_t r = -1, e = 0;
  while (p < end) {
    p = skip_ws(p, end);
    if (p >= end) break;
    if (*p == '\n') {
      ++p;
      continue;
    }
    if (*p == '#') {
      while (p < end && *p != '\n') ++p;
      continue;
    }
    float lbl;
    const char* before = p;
    p = scan_float(p, end, &lbl);
    if (p == before) {
      p = skip_token(p, end);
      while (p < end && *p != '\n') ++p;
      continue;
    }
    if (r + 1 >= cap_rows) break;
    labels[++r] = lbl;
    if (qids) qids[r] = 0;
    while (p < end && *p != '\n') {
      p = skip_ws(p, end);
      if (p >= end || *p == '\n' || *p == '#') {
        if (p < end && *p == '#')
          while (p < end && *p != '\n') ++p;
        break;
      }
      if (strncmp(p, "qid:", 4) == 0) {
        p += 4;
        long q;
        p = scan_int(p, end, &q);
        if (qids) qids[r] = q;
        continue;
      }
      before = p;
      long idx;
      p = scan_int(p, end, &idx);
      if (p < end && *p == ':') {
        ++p;
        float v;
        const char* vb = p;
        p = scan_float(p, end, &v);
        if (p == vb) {
          p = skip_token(p, end);
        } else if (e < cap_entries) {
          row_idx[e] = r;
          col_idx[e] = static_cast<int32_t>(idx);
          values[e] = v;
          ++e;
        }
      } else if (p == before) {
        p = skip_token(p, end);
      }
    }
  }
  unmap(m);
  return 0;
}

// ---- CSV -------------------------------------------------------------
int fp_csv_dims(const char* path, int64_t* n_rows, int64_t* n_cols) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* p = m.data;
  const char* end = m.data + m.size;
  int64_t rows = 0, cols = 0;
  while (p < end) {
    while (p < end && *p == '\n') ++p;
    if (p >= end) break;
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    if (csv_data_line(p, line_end)) {
      if (cols == 0) {  // first data line determines column count
        int64_t c = 1;
        for (const char* q = p; q < line_end; ++q)
          if (*q == ',') ++c;
        cols = c;
      }
      ++rows;
    }
    p = line_end;
  }
  *n_rows = rows;
  *n_cols = cols;
  unmap(m);
  return 0;
}

// Dense row-major fill; empty fields -> NaN; header/comment lines skipped
// (must mirror fp_csv_dims's line acceptance).
int fp_csv_parse(const char* path, float* out, int64_t n_rows, int64_t n_cols) {
  Mapped m = map_file(path);
  if (!m.ok()) return -1;
  const char* p = m.data;
  const char* end = m.data + m.size;
  int64_t r = 0;
  while (p < end && r < n_rows) {
    while (p < end && *p == '\n') ++p;
    if (p >= end) break;
    const char* line_end = p;
    while (line_end < end && *line_end != '\n') ++line_end;
    if (!csv_data_line(p, line_end)) {
      p = line_end;
      continue;
    }
    for (int64_t c = 0; c < n_cols; ++c) {
      p = skip_ws(p, line_end);
      if (p >= line_end || *p == ',') {
        out[r * n_cols + c] = NAN;  // empty field
      } else {
        float v;
        const char* vb = p;
        p = scan_float(p, line_end, &v);
        if (p == vb) {
          v = NAN;
          p = skip_token(p, line_end);
        }
        out[r * n_cols + c] = v;
      }
      p = skip_ws(p, line_end);
      if (p < line_end && *p == ',') ++p;
    }
    p = line_end;
    ++r;
  }
  unmap(m);
  return 0;
}

}  // extern "C"
