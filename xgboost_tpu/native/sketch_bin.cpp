// Native quantile sketch + binning for the CPU data plane, registered as
// XLA FFI custom calls (sibling of hist_build.cpp — ISSUE 15 tentpole).
//
// The XLA route (`data/quantile.py:_cuts_kernel` / `_bin_kernel`) computes
// per-feature cuts as argsort -> weighted-CDF cumsum -> vmapped
// searchsorted, and bins as a vmapped searchsorted; on XLA:CPU that whole
// pipeline runs single-core through generic sort/scan loops and was
// measured ~1.6 s (cuts) + ~0.4 s (bins) at the 100k x 50 bench shape —
// the dominant cost of DMatrix construction now that the grow stage is
// 139 ms/round. These handlers are the reference's host-side sketch move
// (`src/common/quantile.h` WQSummary feeding `hist_util.cc` SketchOnDMatrix):
// a plain per-feature stable sort + sequential f32 scan + binary-search
// selection, doing the same float operations IN THE SAME ORDER as the XLA
// program, so the produced cuts and bin ids are BIT-IDENTICAL to the XLA
// route (pinned by tests/test_data_plane.py — the PR 5 canonical-cuts
// manifest contract depends on it).
//
// Bit-identity notes (each mirrors one XLA op):
//  - NaN keys are replaced by FLT_MAX before the sort (`jnp.where(valid,
//    Xt, big)`), and std::stable_sort on the key alone reproduces the
//    stable argsort's permutation including tie order;
//  - the weighted CDF is a sequential f32 accumulation, matching XLA:CPU's
//    serial cumsum;
//  - quantile levels are computed as (float)k / (float)B * total — the
//    same two f32 ops as `arange/B * total`;
//  - selection is std::lower_bound on the CDF (== searchsorted side="left")
//    clipped to n-1; binning is std::upper_bound (== side="right") clipped
//    to B-1, missing mapped to B.
//
// Bin output is written directly in the narrow storage dtype (u8 below
// 255 symbols, u16 otherwise) — no widened int32 intermediate anywhere.

#include <algorithm>
#include <cfloat>
#include <cmath>
#include <cstdint>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

struct KeyW {
  float key;
  float w;
};

ffi::Error SketchCutsImpl(ffi::Buffer<ffi::F32> X, ffi::Buffer<ffi::F32> w,
                          int64_t B,
                          ffi::Result<ffi::Buffer<ffi::F32>> cuts,
                          ffi::Result<ffi::Buffer<ffi::F32>> min_vals) {
  const auto dims = X.dimensions();
  if (dims.size() != 2 || B < 1) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "X must be [n, F] and B >= 1");
  }
  const int64_t n = dims[0], F = dims[1];
  if (n < 1) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument, "n must be >= 1");
  }
  const float* x = X.typed_data();
  const float* wp = w.typed_data();
  float* out = cuts->typed_data();        // [F, B]
  float* mins = min_vals->typed_data();   // [F]

  std::vector<KeyW> kv(n);
  std::vector<float> cdf(n);
  const float big = FLT_MAX;
  for (int64_t f = 0; f < F; ++f) {
    int64_t n_valid = 0;
    for (int64_t i = 0; i < n; ++i) {
      const float v = x[i * F + f];
      const bool valid = !std::isnan(v);
      kv[i].key = valid ? v : big;
      kv[i].w = valid ? wp[i] : 0.0f;
      n_valid += valid ? 1 : 0;
    }
    // stable sort by key only: ties keep submission order, reproducing
    // the stable argsort's permutation for both keys and weights
    std::stable_sort(kv.begin(), kv.end(),
                     [](const KeyW& a, const KeyW& b) { return a.key < b.key; });
    float acc = 0.0f;
    for (int64_t i = 0; i < n; ++i) {
      acc += kv[i].w;  // sequential f32, same order as the XLA cumsum
      cdf[i] = acc;
    }
    const float total = cdf[n - 1];
    float* row = out + f * B;
    for (int64_t k = 1; k < B; ++k) {
      const float level = (float)k / (float)B * total;
      int64_t idx = std::lower_bound(cdf.begin(), cdf.end(), level)
                    - cdf.begin();              // searchsorted side="left"
      if (idx > n - 1) idx = n - 1;
      if (idx < 0) idx = 0;
      row[k - 1] = (n_valid > 0) ? kv[idx].key : 0.0f;
    }
    const float max_val = (n_valid > 0) ? kv[n_valid - 1].key : 0.0f;
    const float a = std::fabs(max_val);
    row[B - 1] = max_val + (a > 1.0f ? a : 1.0f);  // strict-upper sentinel
    mins[f] = (n_valid > 0) ? kv[0].key : 0.0f;
  }
  return ffi::Error::Success();
}

template <typename OutT>
void bin_loop(const float* x, const float* cuts, int64_t n, int64_t F,
              int64_t B, OutT* out) {
  for (int64_t i = 0; i < n; ++i) {
    const float* xr = x + i * F;
    OutT* orow = out + i * F;
    for (int64_t f = 0; f < F; ++f) {
      const float v = xr[f];
      if (std::isnan(v)) {
        orow[f] = (OutT)B;  // dedicated missing bin
        continue;
      }
      const float* row = cuts + f * B;
      int64_t b = std::upper_bound(row, row + B, v) - row;  // side="right"
      if (b > B - 1) b = B - 1;
      orow[f] = (OutT)b;
    }
  }
}

template <typename OutT, typename Buf>
ffi::Error BinMatrixImpl(ffi::Buffer<ffi::F32> X, ffi::Buffer<ffi::F32> cuts,
                         Buf* bins) {
  const auto dims = X.dimensions();
  const auto cdims = cuts.dimensions();
  if (dims.size() != 2 || cdims.size() != 2 || cdims[0] != dims[1]) {
    return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                      "X must be [n, F] and cuts [F, B]");
  }
  bin_loop<OutT>(X.typed_data(), cuts.typed_data(), dims[0], dims[1],
                 cdims[1], (*bins)->typed_data());
  return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuSketchCuts, SketchCutsImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()    // X [n, F]
        .Arg<ffi::Buffer<ffi::F32>>()    // weights [n]
        .Attr<int64_t>("B")
        .Ret<ffi::Buffer<ffi::F32>>()    // cuts [F, B]
        .Ret<ffi::Buffer<ffi::F32>>());  // min_vals [F]

static ffi::Error BinU8(ffi::Buffer<ffi::F32> X, ffi::Buffer<ffi::F32> cuts,
                        ffi::Result<ffi::Buffer<ffi::U8>> bins) {
  return BinMatrixImpl<uint8_t>(X, cuts, &bins);
}

static ffi::Error BinU16(ffi::Buffer<ffi::F32> X, ffi::Buffer<ffi::F32> cuts,
                         ffi::Result<ffi::Buffer<ffi::U16>> bins) {
  return BinMatrixImpl<uint16_t>(X, cuts, &bins);
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuBinMatrixU8, BinU8,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()    // X [n, F]
        .Arg<ffi::Buffer<ffi::F32>>()    // cuts [F, B]
        .Ret<ffi::Buffer<ffi::U8>>());   // bins [n, F]

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuBinMatrixU16, BinU16,
    ffi::Ffi::Bind()
        .Arg<ffi::Buffer<ffi::F32>>()    // X [n, F]
        .Arg<ffi::Buffer<ffi::F32>>()    // cuts [F, B]
        .Ret<ffi::Buffer<ffi::U16>>());  // bins [n, F]
