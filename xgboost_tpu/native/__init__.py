"""Native (C++) runtime components, loaded via ctypes.

The reference keeps its data loaders, allocators and runtime in C++
(dmlc-core parsers, src/common/io.cc); the TPU build does the same for the
host-side pieces that sit outside the XLA compute path. The shared library
is built on demand with g++ (no pybind11 in the image — plain C ABI).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "fastparse.cpp")
_LIB_PATH = os.path.join(_HERE, "libfastparse.so")
_PC_SRC = os.path.join(_HERE, "pagecache.cpp")
_PC_LIB = os.path.join(_HERE, "libpagecache.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False
_pc_lib: Optional[ctypes.CDLL] = None
_pc_tried = False


def _san_mode() -> Optional[str]:
    """Sanitizer lane (the reference's CMake ``USE_SANITIZER`` analog):
    ``XGBTPU_SAN=1`` (or ``=address``) builds every native library with
    ASan+UBSan into ``.san.so`` artifacts; ``XGBTPU_SAN=thread`` builds
    TSan ``.tsan.so`` variants instead, so the data-race lane can watch
    the OpenMP kernels and the threaded prefetcher/checkpoint writers.
    Separate artifact suffixes mean no lane ever clobbers (or reuses)
    production builds. A sanitized library only *loads* under a
    preloaded process (``LD_PRELOAD=libasan.so`` / ``libtsan.so``) —
    plain processes get the usual graceful None fallback. See
    ``tests/test_sanitizer.py`` and docs/static_analysis.md."""
    v = os.environ.get("XGBTPU_SAN", "")
    if v in ("1", "address"):
        return "address"
    if v == "thread":
        return "thread"
    return None


def _san_enabled() -> bool:
    return _san_mode() is not None


_SAN_FLAGS = (
    "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
    "-fno-omit-frame-pointer", "-g", "-Wall", "-Wextra", "-Werror",
)

# TSan and ASan are mutually exclusive in one binary, so the thread lane
# is its own artifact. No -Werror here: the lane must instrument the FFI
# kernels, and the jaxlib FFI headers themselves trip -Wsign-compare —
# warning hygiene is the address lane's job.
_TSAN_FLAGS = (
    "-fsanitize=thread", "-fno-omit-frame-pointer", "-g",
)


def _lib_variant(lib_path: str) -> str:
    """The artifact path for the active lane (``.san.so`` under the
    address lane, ``.tsan.so`` under the thread lane). Single source of
    truth for builders AND loaders."""
    mode = _san_mode()
    if mode and lib_path.endswith(".so"):
        return lib_path[:-3] + (".tsan.so" if mode == "thread"
                                else ".san.so")
    return lib_path


def _find_san_runtime(name: str) -> Optional[str]:
    try:
        out = subprocess.run(
            ["g++", f"-print-file-name={name}"],
            capture_output=True, timeout=30, check=True,
        ).stdout.decode().strip()
    except Exception:
        return None
    return out if out and os.path.sep in out else None


def find_libasan() -> Optional[str]:
    """Path of the toolchain's libasan runtime (for ``LD_PRELOAD`` when
    running a sanitized library under an uninstrumented Python), or None
    when the toolchain can't say."""
    return _find_san_runtime("libasan.so")


def find_libtsan() -> Optional[str]:
    """Path of the toolchain's libtsan runtime, for preloading the
    thread lane the same way (``LD_PRELOAD=libtsan.so``)."""
    return _find_san_runtime("libtsan.so")


def _compile(src: str, lib_path: str, extra: list, timeout: int = 120) -> bool:
    """Build ``lib_path`` from ``src`` when stale (single-sourced
    staleness + existence logic for all the on-demand libraries).
    True when a usable library exists afterwards. Under a sanitizer lane
    the caller passes a ``.san.so``/``.tsan.so`` path (via
    ``_lib_variant``) and the lane's flags are appended here."""
    if not os.path.exists(src):
        return os.path.exists(lib_path)  # prebuilt-only deployment
    if os.path.exists(lib_path) and             os.path.getmtime(lib_path) >= os.path.getmtime(src):
        return True
    mode = _san_mode()
    if mode == "address":
        extra = list(extra) + list(_SAN_FLAGS)
    elif mode == "thread":
        extra = list(extra) + list(_TSAN_FLAGS)
    cmd = ["g++", "-shared", "-fPIC", "-o", lib_path, src] + extra
    try:
        # ``native_load`` chaos site: a scripted fault here exercises the
        # graceful every-caller-falls-back-to-None contract of the
        # on-demand native builds (resilience tentpole)
        from ..resilience import chaos

        chaos.hit("native_load")
        subprocess.run(cmd, check=True, capture_output=True, timeout=timeout)
        return True
    except Exception:
        return False


def _build_failed(lib_name: str, detail: str) -> None:
    """Account a native build/load failure (ISSUE 20 satellite): bumps
    ``native_build_failures_total{lib}`` and — for the canaried kernel
    libraries — degrades the library's capability for the process, so a
    box without a toolchain resolves every op to the XLA impls instead
    of raising (or re-probing) at call sites. Never raises: accounting
    must not break the graceful None contract of the loaders."""
    try:
        from . import boundary

        boundary.record_build_failure(lib_name, detail)
    except Exception:
        pass


def loaded_libs() -> tuple:
    """Names of the kernel libraries ALREADY dlopened into this process
    (memo reads only — never triggers a build). The containment layer
    uses this as ground truth for 'native code can be running': dispatch
    decisions are only recorded at trace time, so a jit-cache-reused
    program runs native kernels without leaving a fresh decision."""
    with _lock:
        out = []
        if _tb_lib is not None:
            out.append("tree_build")
        if _hb_lib is not None:
            out.append("hist_build")
        if _sb_lib is not None:
            out.append("sketch_bin")
        if _sv_lib is not None:
            out.append("serving_walk")
        return tuple(out)


def _prove(lib_name: str, lib_path: str) -> bool:
    """Load-time canary gate (ISSUE 20 tentpole): the library must pass
    its golden run in a forked subprocess (``canary.prove`` — cached per
    build) before this process dlopens it. A refused/crashed/mismatched
    build degrades the capability and the loader returns None."""
    from . import canary

    return canary.prove(lib_name, lib_path)


def get_pagecache_lib() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native page cache; None if unavailable
    (callers fall back to plain numpy file IO)."""
    global _pc_lib, _pc_tried
    with _lock:
        if _pc_lib is not None or _pc_tried:
            return _pc_lib
        _pc_tried = True
        lp = _lib_variant(_PC_LIB)
        if not _compile(_PC_SRC, lp,
                        ["-O3", "-std=c++17", "-pthread", "-ffp-contract=off"]):
            return None
        try:
            lib = ctypes.CDLL(lp)
        except OSError:
            return None
        lib.pc_write.argtypes = [ctypes.c_char_p, ctypes.c_void_p,
                                 ctypes.c_longlong]
        lib.pc_write.restype = ctypes.c_int
        lib.pc_open.argtypes = [ctypes.c_char_p, ctypes.c_longlong,
                                ctypes.POINTER(ctypes.c_longlong),
                                ctypes.c_int]
        lib.pc_open.restype = ctypes.c_void_p
        lib.pc_read.argtypes = [ctypes.c_void_p, ctypes.c_longlong,
                                ctypes.c_void_p]
        lib.pc_read.restype = ctypes.c_int
        lib.pc_close.argtypes = [ctypes.c_void_p]
        lib.pc_close.restype = None
        _pc_lib = lib
        return _pc_lib


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native parser; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        lp = _lib_variant(_LIB_PATH)
        if not _compile(_SRC, lp,
                        ["-O3", "-march=native", "-ffp-contract=off"]):
            return None
        try:
            lib = ctypes.CDLL(lp)
        except OSError:
            return None
        lib.fp_libsvm_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.fp_libsvm_dims.restype = ctypes.c_int
        lib.fp_libsvm_parse.argtypes = (
            [ctypes.c_char_p] + [ctypes.c_void_p] * 5 + [ctypes.c_int64] * 2
        )
        lib.fp_libsvm_parse.restype = ctypes.c_int
        lib.fp_csv_dims.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.fp_csv_dims.restype = ctypes.c_int
        lib.fp_csv_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.fp_csv_parse.restype = ctypes.c_int
        _lib = lib
        return _lib


def load_svmlight_native(path: str) -> Optional[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]]:
    """Native libsvm load -> (X dense NaN-missing, y, qid|None); None if the
    native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n_rows = ctypes.c_int64()
    n_entries = ctypes.c_int64()
    max_col = ctypes.c_int64()
    has_qid = ctypes.c_int32()
    if lib.fp_libsvm_dims(path.encode(), ctypes.byref(n_rows), ctypes.byref(n_entries),
                          ctypes.byref(max_col), ctypes.byref(has_qid)) != 0:
        return None
    n, e, mc = n_rows.value, n_entries.value, max_col.value
    rows = np.empty(e, np.int64)
    cols = np.empty(e, np.int32)
    vals = np.empty(e, np.float32)
    labels = np.empty(n, np.float32)
    qids = np.empty(n, np.int64) if has_qid.value else None
    rc = lib.fp_libsvm_parse(
        path.encode(),
        rows.ctypes.data_as(ctypes.c_void_p),
        cols.ctypes.data_as(ctypes.c_void_p),
        vals.ctypes.data_as(ctypes.c_void_p),
        labels.ctypes.data_as(ctypes.c_void_p),
        qids.ctypes.data_as(ctypes.c_void_p) if qids is not None else None,
        n, e,
    )
    if rc != 0:
        return None
    X = np.full((n, mc + 1 if mc >= 0 else 0), np.nan, np.float32)
    if e:
        X[rows, cols] = vals
    return X, labels, qids


def load_csv_native(path: str) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native CSV load (first column = label) -> (X, y); None if unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    n_rows = ctypes.c_int64()
    n_cols = ctypes.c_int64()
    if lib.fp_csv_dims(path.encode(), ctypes.byref(n_rows), ctypes.byref(n_cols)) != 0:
        return None
    n, c = n_rows.value, n_cols.value
    out = np.empty((n, c), np.float32)
    if lib.fp_csv_parse(path.encode(), out.ctypes.data_as(ctypes.c_void_p), n, c) != 0:
        return None
    y = out[:, 0].copy()
    X = np.ascontiguousarray(out[:, 1:])
    return X, y


_SV_SRC = os.path.join(_HERE, "serving_walk.cpp")
_SV_LIB = os.path.join(_HERE, "libservingwalk.so")
_sv_lib: Optional[ctypes.CDLL] = None
_sv_tried = False


def get_serving_lib() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native serving forest walker
    (``serving_walk.cpp`` — the cpu_predictor.cc block-of-rows analog);
    None when unavailable (callers fall back to the XLA walk)."""
    global _sv_lib, _sv_tried
    with _lock:
        if _sv_lib is not None or _sv_tried:
            return _sv_lib
        _sv_tried = True
        lp = _lib_variant(_SV_LIB)
        sv_flags = ["-O3", "-march=native", "-ffp-contract=off"]
        ok = _compile(_SV_SRC, lp, sv_flags + ["-fopenmp"])
        if not ok:  # toolchains without OpenMP: single-threaded walker
            ok = _compile(_SV_SRC, lp, sv_flags)
        if not ok:
            _build_failed("serving_walk", "build failed")
            return None
        if not _prove("serving_walk", lp):
            return None
        try:
            lib = ctypes.CDLL(lp)
        except OSError as e:
            _build_failed("serving_walk", f"dlopen: {e}")
            return None
        c = ctypes
        lib.sv_predict_dense.argtypes = [
            c.c_void_p, c.c_int64, c.c_int64,  # X, n, F
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,  # ...T, N
            c.c_void_p, c.c_void_p, c.c_int64,  # base, out, K
        ]
        lib.sv_predict_dense.restype = c.c_int
        lib.sv_predict_csr.argtypes = [
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,
            c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
            c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,
            c.c_void_p, c.c_void_p, c.c_int64,
        ]
        lib.sv_predict_csr.restype = c.c_int
        _sv_lib = lib
        return _sv_lib


def serving_lib_available() -> bool:
    """Availability probe for the kernel dispatch registry
    (``dispatch/ops.py``, op ``predict_walk`` impl ``native``): whether
    the SoA forest walker builds/loads on this host. First call pays the
    on-demand build; afterwards it is a memo read. (The ``level_hist``
    impl probes through ``tree.hist_kernel._ensure_ffi`` instead — load
    and XLA target registration are one step there.)"""
    return get_serving_lib() is not None


_HB_SRC = os.path.join(_HERE, "hist_build.cpp")
_HB_LIB = os.path.join(_HERE, "libhistbuild.so")
_hb_lib: Optional[ctypes.CDLL] = None
_hb_tried = False


def get_hist_lib() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native level-histogram + partition
    kernel (``hist_build.cpp`` — the GHistBuilder analog the CPU training
    fallback dispatches as an XLA FFI custom call; ``tree/hist_kernel.py``
    registers the exported ``XgbtpuHbLevel``/``XgbtpuHbPartition`` handler
    symbols). None when the toolchain or the jaxlib FFI headers are
    unavailable (callers fall back to the XLA segment_sum path)."""
    global _hb_lib, _hb_tried
    with _lock:
        if _hb_lib is not None or _hb_tried:
            return _hb_lib
        _hb_tried = True
        try:
            from jax.extend import ffi as _jffi

            inc = _jffi.include_dir()
        except Exception:
            _build_failed("hist_build", "jax FFI headers unavailable")
            return None
        lp = _lib_variant(_HB_LIB)
        if not _compile(_HB_SRC, lp,
                        ["-O3", "-march=native", "-std=c++17",
                         "-ffp-contract=off", f"-I{inc}"]):
            _build_failed("hist_build", "build failed")
            return None
        if not _prove("hist_build", lp):
            return None
        try:
            _hb_lib = ctypes.CDLL(lp)
        except OSError as e:
            _build_failed("hist_build", f"dlopen: {e}")
            return None
        return _hb_lib


_TB_SRC = os.path.join(_HERE, "tree_build.cpp")
_TB_LIB = os.path.join(_HERE, "libtreebuild.so")
_tb_lib: Optional[ctypes.CDLL] = None
_tb_tried = False


def get_tree_lib() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the whole-tree native grow kernel
    (``tree_build.cpp`` — one custom call per boosting round; the
    ``tree_grow`` dispatch op resolves to it on CPU and
    ``tree/tree_kernel.py`` registers the exported ``XgbtpuTreeGrow`` /
    ``XgbtpuHbLevelSub`` handler symbols as XLA FFI targets). Built with
    ``-ffp-contract=off`` — the split-eval port is bit-identical to the
    XLA ``_level_update`` only without FMA contraction — and with OpenMP
    when the toolchain has it (falls back to single-threaded). None when
    the toolchain or the jaxlib FFI headers are unavailable (callers keep
    the per-level path)."""
    global _tb_lib, _tb_tried
    with _lock:
        if _tb_lib is not None or _tb_tried:
            return _tb_lib
        _tb_tried = True
        try:
            from jax.extend import ffi as _jffi

            inc = _jffi.include_dir()
        except Exception:
            _build_failed("tree_build", "jax FFI headers unavailable")
            return None
        lp = _lib_variant(_TB_LIB)
        flags = ["-O3", "-march=native", "-std=c++17",
                 "-ffp-contract=off", f"-I{inc}"]
        ok = _compile(_TB_SRC, lp, flags + ["-fopenmp"])
        if not ok:  # toolchains without OpenMP: single-threaded kernel
            ok = _compile(_TB_SRC, lp, flags)
        if not ok:
            _build_failed("tree_build", "build failed")
            return None
        if not _prove("tree_build", lp):
            return None
        try:
            _tb_lib = ctypes.CDLL(lp)
        except OSError as e:
            _build_failed("tree_build", f"dlopen: {e}")
            return None
        return _tb_lib


_SB_SRC = os.path.join(_HERE, "sketch_bin.cpp")
_SB_LIB = os.path.join(_HERE, "libsketchbin.so")
_sb_lib: Optional[ctypes.CDLL] = None
_sb_tried = False


def get_sketch_lib() -> Optional[ctypes.CDLL]:
    """Load (building on demand) the native quantile-sketch + binning
    kernel (``sketch_bin.cpp`` — the data-plane fast path the ``sketch_cuts``
    / ``bin_matrix`` dispatch ops resolve to on CPU; ``data/quantile.py``
    registers the exported ``XgbtpuSketchCuts``/``XgbtpuBinMatrixU8``/
    ``XgbtpuBinMatrixU16`` handler symbols as XLA FFI targets). None when
    the toolchain or the jaxlib FFI headers are unavailable (callers fall
    back to the XLA sort/searchsorted path)."""
    global _sb_lib, _sb_tried
    with _lock:
        if _sb_lib is not None or _sb_tried:
            return _sb_lib
        _sb_tried = True
        try:
            from jax.extend import ffi as _jffi

            inc = _jffi.include_dir()
        except Exception:
            _build_failed("sketch_bin", "jax FFI headers unavailable")
            return None
        lp = _lib_variant(_SB_LIB)
        if not _compile(_SB_SRC, lp,
                        ["-O3", "-march=native", "-std=c++17",
                         "-ffp-contract=off", f"-I{inc}"]):
            _build_failed("sketch_bin", "build failed")
            return None
        if not _prove("sketch_bin", lp):
            return None
        try:
            _sb_lib = ctypes.CDLL(lp)
        except OSError as e:
            _build_failed("sketch_bin", f"dlopen: {e}")
            return None
        return _sb_lib


_CAPI_SRC = os.path.join(_HERE, "c_api.cpp")
_CAPI_LIB = os.path.join(_HERE, "libxgbtpu.so")
_capi_path: Optional[str] = None
_capi_tried = False


def build_capi() -> Optional[str]:
    """Build (if stale) and return the path of the embedded-interpreter C
    API library ``libxgbtpu.so`` (reference ABI: include/xgboost/c_api.h).
    None when the toolchain or Python embedding flags are unavailable.
    Returns the PATH rather than a loaded CDLL: C hosts dlopen it
    themselves, and the ctypes test loads it explicitly."""
    global _capi_path, _capi_tried
    with _lock:
        if _capi_path is not None or _capi_tried:
            return _capi_path
        _capi_tried = True
        import sysconfig

        repo_root = os.path.dirname(os.path.dirname(_HERE))
        paths = sysconfig.get_paths()
        site = paths.get("purelib", "")
        inc = paths["include"]
        libdir = sysconfig.get_config_var("LIBDIR") or ""
        pyver = sysconfig.get_config_var("LDVERSION") or \
            sysconfig.get_config_var("VERSION") or ""
        lp = _lib_variant(_CAPI_LIB)
        if not _compile(_CAPI_SRC, lp,
                        ["-O2", "-std=c++17", "-ffp-contract=off", f"-I{inc}",
                         f'-DXGBTPU_ROOT="{repo_root}"',
                         f'-DXGBTPU_SITE="{site}"',
                         f"-L{libdir}", f"-lpython{pyver}",
                         f"-Wl,-rpath,{libdir}", "-ldl", "-lm"],
                        timeout=180):
            return None
        _capi_path = lp if os.path.exists(lp) else None
        return _capi_path
