// Whole-tree native grow kernel for the CPU training path, registered as
// XLA FFI custom calls.
//
// `hist_build.cpp` moved the level histogram + partition into one native
// call per level, but the round still pays ~2 dispatches per depth
// (`fused_level` + `_level_update_jit`) plus the XLA glue between them.
// This kernel runs the ENTIRE depth loop of one boosting round in a
// single custom call (`XgbtpuTreeGrow`): per-level partition, histogram
// build, split evaluation, and heap/node update, returning the finalized
// heap arrays that `_finalize_jit` consumes — one host round-trip per
// round instead of ~2 per level.
//
// Bit-identity contract (the same methodology hist_build.cpp pinned):
//  * Histogram accumulation preserves the per-cell order of the XLA
//    segment_sum (rows ascending per cell). The cache-blocked loop below
//    only re-tiles the FEATURE axis — per-cell row order is unchanged, so
//    blocking is bit-transparent.
//  * Split evaluation replicates `_level_update` exactly: the repo's
//    eval uses `seq_cumsum` (strict left-to-right f32 association), which
//    a sequential C loop reproduces; gain/weight formulas are ported
//    term-for-term from `tree/param.py` and validated bitwise against the
//    jitted `_level_update` (see tests). Two codegen hazards are handled
//    explicitly: this file must compile with -ffp-contract=off (gcc -O3
//    defaults to contract=fast and would fuse mul+add into FMA), and the
//    max_delta_step>0 gain path is NOT claimed bit-identical (XLA:CPU
//    contracts `2*G*w + denom*w*w` into an FMA there) — the dispatcher
//    only routes max_delta_step==0 configs to this kernel.
//  * Sibling subtraction (attr `sibling_sub`): at depth >= 1 build only
//    the child with fewer rows and derive the other as parent - child
//    (exact on count-valued data; model-equal otherwise). When one child
//    is empty, parent - 0 reproduces the direct build bit-for-bit, so the
//    off switch (XGBTPU_SIBLING_SUB=0) pins the whole kernel bit-identical
//    to the per-level native path.
//
// `XgbtpuHbLevelSub` exposes ONE level of the same machinery (partition +
// subtraction histogram) for the kernelprof mirror: sampled rounds replay
// the round per-level for attribution, and because the mirror kernel
// shares these exact core loops, its histograms match the in-kernel ones
// bit-for-bit by construction.
//
// Blocking parameters: feature blocks are sized so one block's histogram
// slab ([fb, 2K, B] f32) fits the kHistL2Budget bytes (256 KiB — a
// conservative 1-core L2 share); rows stream once per block. OpenMP
// parallelism follows serving_walk.cpp: static row/node splits guarded by
// a minimum size so small batches skip team spawn, and every parallel
// region writes disjoint slabs (feature blocks / nodes / rows), keeping
// results independent of thread count.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

constexpr int64_t kHistL2Budget = 256 * 1024;  // bytes per feature block
constexpr float kRtEps = 1e-6f;                // param.py RT_EPS

struct SplitP {
    float lam, alpha, mds, mcw;
};

// ---- param.py ports (f32 term-for-term; see tree/param.py) -------------

inline float thresh_l1(float g, float a) {
    if (a == 0.0f) return g;
    float t = std::fabs(g) - a;
    if (t < 0.0f) t = 0.0f;  // NaN compares false and passes through
    const float s = (g > 0.0f) ? 1.0f : ((g < 0.0f) ? -1.0f : g);
    return s * t;
}

inline float calc_weight_c(float G, float H, const SplitP& p) {
    const float denom = H + p.lam;
    float w = 0.0f;
    if (denom > 0.0f) {
        const float t = thresh_l1(G, p.alpha);
        const float d2 = (denom < 1e-38f) ? 1e-38f : denom;
        w = -t / d2;
    }
    if (p.mds > 0.0f) {
        if (w < -p.mds) w = -p.mds;
        if (w > p.mds) w = p.mds;  // NaN stays NaN, like jnp.clip
    }
    if (H < p.mcw || H <= 0.0f) return 0.0f;
    return w;
}

inline float calc_gain_c(float G, float H, const SplitP& p) {
    const float denom = H + p.lam;
    float g = 0.0f;
    if (p.mds == 0.0f) {
        if (denom > 0.0f) {
            const float t = thresh_l1(G, p.alpha);
            const float d2 = (denom < 1e-38f) ? 1e-38f : denom;
            g = (t * t) / d2;
        }
    } else {
        // Not dispatched for bit-identity (XLA contracts this into FMA);
        // kept faithful to the source association for manual pins.
        const float w = calc_weight_c(G, H, p);
        g = -((2.0f * G) * w + (denom * w) * w);
    }
    if (H < p.mcw) return 0.0f;
    return g;
}

// ---- shared core loops -------------------------------------------------

// Route rows through a level's decisions (typed arrays, one entry per
// previous-level node). Semantics mirror hist_build.cpp partition_loop:
// missing (bv >= B) goes the default direction, bin compare is <=.
template <typename BinT>
void partition_rows(const BinT* bins, int32_t* pos, const uint8_t* isplit,
                    const int32_t* feat, const int32_t* bin,
                    const uint8_t* dleft, int64_t n, int64_t F, int64_t B,
                    int64_t Kp, int64_t poff) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (n >= 8192)
#endif
    for (int64_t i = 0; i < n; ++i) {
        const int32_t pcur = pos[i];
        const int64_t lp = (int64_t)pcur - poff;
        if (lp < 0 || lp >= Kp) continue;
        if (!isplit[lp]) continue;
        const int64_t f = feat[lp];
        const int64_t bv = (int64_t)bins[i * F + f];
        const bool left = (bv >= B) ? (dleft[lp] != 0) : (bv <= bin[lp]);
        pos[i] = (int32_t)(2 * pcur + (left ? 1 : 2));
    }
}

void count_rows(const int32_t* pos, int64_t n, int64_t off, int64_t K,
                int64_t* counts) {
    std::fill(counts, counts + K, (int64_t)0);
    for (int64_t i = 0; i < n; ++i) {
        const int64_t s = (int64_t)pos[i] - off;
        if (s >= 0 && s < K) ++counts[s];
    }
}

// Accumulate (g, h) into hist [F, 2K, B] for rows landing in this level's
// slots (optionally only slots with build_mask set). Cache-blocked over
// features: each block's hist slab stays L2-resident while rows stream.
// Per-cell accumulation order is rows ascending — identical to
// hist_build.cpp level_loop — for any block size or thread count, because
// blocks/threads own disjoint feature slabs.
template <typename BinT>
void accumulate_level(const BinT* bins, const int32_t* pos, const float* gh,
                      int64_t n, int64_t F, int64_t B, int64_t K, int64_t off,
                      const uint8_t* build_mask, float* hist) {
    const int64_t feat_stride = 2 * K * B;
    int64_t fb = kHistL2Budget / (int64_t)(2 * K * B * sizeof(float));
    if (fb < 1) fb = 1;
    if (fb > F) fb = F;
    const int64_t nblk = (F + fb - 1) / fb;
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic, 1) if (nblk > 1 && n >= 8192)
#endif
    for (int64_t blk = 0; blk < nblk; ++blk) {
        const int64_t f0 = blk * fb;
        const int64_t f1 = std::min<int64_t>(F, f0 + fb);
        for (int64_t i = 0; i < n; ++i) {
            const int64_t s = (int64_t)pos[i] - off;
            if (s < 0 || s >= K) continue;
            if (build_mask && !build_mask[s]) continue;
            const float g = gh[2 * i], h = gh[2 * i + 1];
            const BinT* br = bins + i * F;
            float* gbase = hist + s * B;
            for (int64_t f = f0; f < f1; ++f) {
                const int64_t bv = br[f];
                if (bv >= B) continue;  // missing: recovered as total - sum
                float* cell = gbase + f * feat_stride + bv;
                cell[0] += g;
                cell[K * B] += h;
            }
        }
    }
}

// Mark, per sibling pair, the child with fewer rows as the one to build
// directly. Pairs with no rows at all stay unbuilt (their cells stay 0,
// matching a direct build of zero rows).
void plan_siblings(const int64_t* counts, int64_t Kp, uint8_t* build_mask) {
    for (int64_t j = 0; j < Kp; ++j) {
        const int64_t sl = 2 * j, sr = 2 * j + 1;
        build_mask[sl] = 0;
        build_mask[sr] = 0;
        if (counts[sl] + counts[sr] == 0) continue;
        build_mask[counts[sl] <= counts[sr] ? sl : sr] = 1;
    }
}

// Derive each unbuilt sibling as parent - built (f32 subtraction per
// cell). prev is the previous level's hist [F, 2Kp, B]; cur is this
// level's [F, 2K, B] with the built children already accumulated.
void derive_siblings(const float* prev, float* cur, int64_t F, int64_t B,
                     int64_t K, int64_t Kp, const int64_t* counts) {
    const int64_t fs_cur = 2 * K * B, fs_prev = 2 * Kp * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (F >= 8)
#endif
    for (int64_t f = 0; f < F; ++f) {
        for (int64_t j = 0; j < Kp; ++j) {
            const int64_t sl = 2 * j, sr = 2 * j + 1;
            if (counts[sl] + counts[sr] == 0) continue;
            const int64_t built = counts[sl] <= counts[sr] ? sl : sr;
            const int64_t other = sl + sr - built;
            const float* pg = prev + f * fs_prev + j * B;
            const float* ph = pg + Kp * B;
            const float* bg = cur + f * fs_cur + built * B;
            const float* bh = bg + K * B;
            float* og = cur + f * fs_cur + other * B;
            float* oh = og + K * B;
            for (int64_t b = 0; b < B; ++b) {
                og[b] = pg[b] - bg[b];
                oh[b] = ph[b] - bh[b];
            }
        }
    }
}

// Split evaluation for one level — a sequential-association port of
// `_level_update` (grow_fused.py). Scans candidates dir-major then
// feature then bin with first-max/first-NaN argmax semantics matching
// jnp.argmax on the [K, 2*F*B] score tensor. Writes this level's slot
// decisions unconditionally and child stats only for can_split nodes
// (the XLA path's mode="drop" scatter).
void eval_level(const float* hist, const float* cuts, const int32_t* fmask,
                int64_t F, int64_t B, int64_t K, int64_t off,
                const SplitP& p, bool* is_split, int32_t* feature,
                int32_t* split_bin, float* split_cond, bool* default_left,
                float* node_g, float* node_h, float* node_w, float* loss_chg,
                int64_t max_nodes) {
    const int64_t feat_stride = 2 * K * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (K >= 8)
#endif
    for (int64_t k = 0; k < K; ++k) {
        std::vector<float> GL((size_t)(F * B)), HL((size_t)(F * B));
        std::vector<float> gm((size_t)F), hm((size_t)F);
        const float Gtot = node_g[off + k], Htot = node_h[off + k];
        for (int64_t f = 0; f < F; ++f) {
            const float* hg = hist + f * feat_stride + k * B;
            const float* hh = hg + K * B;
            float accg = 0.0f, acch = 0.0f;
            for (int64_t b = 0; b < B; ++b) {
                accg = accg + hg[b];  // seq_cumsum association
                acch = acch + hh[b];
                GL[f * B + b] = accg;
                HL[f * B + b] = acch;
            }
            gm[f] = Gtot - accg;
            hm[f] = Htot - acch;
        }
        const float parent_gain = calc_gain_c(Gtot, Htot, p);
        float best = -INFINITY;
        int64_t best_idx = 0;
        for (int64_t dd = 0; dd < 2; ++dd) {
            for (int64_t f = 0; f < F; ++f) {
                if (!fmask[f]) continue;
                for (int64_t b = 0; b < B; ++b) {
                    const float GLd =
                        dd == 0 ? GL[f * B + b] : GL[f * B + b] + gm[f];
                    const float HLd =
                        dd == 0 ? HL[f * B + b] : HL[f * B + b] + hm[f];
                    const float GRd = Gtot - GLd;
                    const float HRd = Htot - HLd;
                    if (!(HLd >= p.mcw && HRd >= p.mcw)) continue;
                    const float gain =
                        calc_gain_c(GLd, HLd, p) + calc_gain_c(GRd, HRd, p);
                    const float chg = gain - parent_gain;
                    if (std::isnan(best)) {
                        // first NaN wins and sticks (jnp.argmax semantics)
                    } else if (std::isnan(chg) || chg > best) {
                        best = chg;
                        best_idx = dd * F * B + f * B + b;
                    }
                }
            }
        }
        const int64_t dd = best_idx / (F * B);
        const int64_t f = (best_idx % (F * B)) / B;
        const int64_t b = best_idx % B;
        const float GLb = dd == 0 ? GL[f * B + b] : GL[f * B + b] + gm[f];
        const float HLb = dd == 0 ? HL[f * B + b] : HL[f * B + b] + hm[f];
        const int64_t slot = off + k;
        const bool can = (best > kRtEps) && (Htot > 0.0f);
        is_split[slot] = can;
        feature[slot] = (int32_t)f;
        split_bin[slot] = (int32_t)b;
        split_cond[slot] = cuts[f * B + b];
        default_left[slot] = (dd == 1);
        node_w[slot] = calc_weight_c(Gtot, Htot, p);
        loss_chg[slot] = can ? best : 0.0f;
        if (can) {
            const int64_t l = 2 * slot + 1, r = 2 * slot + 2;
            if (r < max_nodes) {
                const float GRb = Gtot - GLb, HRb = Htot - HLb;
                node_g[l] = GLb;
                node_h[l] = HLb;
                node_w[l] = calc_weight_c(GLb, HLb, p);
                node_g[r] = GRb;
                node_h[r] = HRb;
                node_w[r] = calc_weight_c(GRb, HRb, p);
            }
        }
    }
}

// Snapshot a level's decisions from the heap output arrays into the
// compact typed form partition_rows consumes (Kp <= 2^(D-1) entries).
void snapshot_decisions(const bool* is_split, const int32_t* feature,
                        const int32_t* split_bin, const bool* default_left,
                        int64_t poff, int64_t Kp, uint8_t* isplit,
                        int32_t* feat, int32_t* bin, uint8_t* dleft) {
    for (int64_t j = 0; j < Kp; ++j) {
        isplit[j] = is_split[poff + j] ? 1 : 0;
        feat[j] = feature[poff + j];
        bin[j] = split_bin[poff + j];
        dleft[j] = default_left[poff + j] ? 1 : 0;
    }
}

// ---- whole-tree driver -------------------------------------------------

template <typename BinT>
void tree_grow_loop(const BinT* bins, const float* gh, const float* cuts,
                    const int32_t* fmask, float G0, float H0, int64_t n,
                    int64_t F, int64_t B, int64_t D, bool sub,
                    const SplitP& p, int32_t* pos, bool* is_split,
                    int32_t* feature, int32_t* split_bin, float* split_cond,
                    bool* default_left, float* node_g, float* node_h,
                    float* node_w, float* loss_chg) {
    const int64_t max_nodes = (1LL << (D + 1)) - 1;
    node_g[0] = G0;
    node_h[0] = H0;
    node_w[0] = calc_weight_c(G0, H0, p);
    const int64_t Km = 1LL << (D - 1);  // widest evaluated level
    std::vector<float> hist_a((size_t)(F * 2 * Km * B));
    std::vector<float> hist_b((size_t)(F * 2 * Km * B));
    float* cur = hist_a.data();
    float* prev = hist_b.data();
    std::vector<int64_t> counts((size_t)(2 * Km));
    std::vector<uint8_t> bmask((size_t)(2 * Km));
    std::vector<uint8_t> disp((size_t)Km), ddef((size_t)Km);
    std::vector<int32_t> dfeat((size_t)Km), dbin((size_t)Km);
    for (int64_t d = 0; d < D; ++d) {
        const int64_t K = 1LL << d, off = K - 1;
        const int64_t Kp = K >> 1, poff = Kp - 1;
        if (d > 0) {
            snapshot_decisions(is_split, feature, split_bin, default_left,
                               poff, Kp, disp.data(), dfeat.data(),
                               dbin.data(), ddef.data());
            partition_rows(bins, pos, disp.data(), dfeat.data(), dbin.data(),
                           ddef.data(), n, F, B, Kp, poff);
        }
        std::memset(cur, 0, (size_t)(F * 2 * K * B) * sizeof(float));
        if (sub && d >= 1) {
            count_rows(pos, n, off, K, counts.data());
            plan_siblings(counts.data(), Kp, bmask.data());
            accumulate_level(bins, pos, gh, n, F, B, K, off, bmask.data(),
                             cur);
            derive_siblings(prev, cur, F, B, K, Kp, counts.data());
        } else {
            accumulate_level(bins, pos, gh, n, F, B, K, off,
                             (const uint8_t*)nullptr, cur);
        }
        eval_level(cur, cuts, fmask, F, B, K, off, p, is_split, feature,
                   split_bin, split_cond, default_left, node_g, node_h,
                   node_w, loss_chg, max_nodes);
        std::swap(cur, prev);
    }
    // Final routing into the leaf level (the driver's partition_apply).
    const int64_t Kp = 1LL << (D - 1), poff = Kp - 1;
    snapshot_decisions(is_split, feature, split_bin, default_left, poff, Kp,
                       disp.data(), dfeat.data(), dbin.data(), ddef.data());
    partition_rows(bins, pos, disp.data(), dfeat.data(), dbin.data(),
                   ddef.data(), n, F, B, Kp, poff);
}

ffi::Error TreeGrowImpl(
    ffi::AnyBuffer bins, ffi::Buffer<ffi::F32> gh,
    ffi::Buffer<ffi::F32> cut_values, ffi::Buffer<ffi::S32> tree_mask,
    ffi::Buffer<ffi::F32> G0, ffi::Buffer<ffi::F32> H0, int64_t max_depth,
    int64_t B, int64_t sibling_sub, float reg_lambda, float reg_alpha,
    float max_delta_step, float min_child_weight,
    ffi::Result<ffi::Buffer<ffi::S32>> pos_out,
    ffi::Result<ffi::Buffer<ffi::PRED>> is_split,
    ffi::Result<ffi::Buffer<ffi::S32>> feature,
    ffi::Result<ffi::Buffer<ffi::S32>> split_bin,
    ffi::Result<ffi::Buffer<ffi::F32>> split_cond,
    ffi::Result<ffi::Buffer<ffi::PRED>> default_left,
    ffi::Result<ffi::Buffer<ffi::F32>> node_g,
    ffi::Result<ffi::Buffer<ffi::F32>> node_h,
    ffi::Result<ffi::Buffer<ffi::F32>> node_w,
    ffi::Result<ffi::Buffer<ffi::F32>> loss_chg) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    if (max_depth < 1) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "max_depth must be >= 1");
    }
    const int64_t n = dims[0], F = dims[1];
    const int64_t max_nodes = (1LL << (max_depth + 1)) - 1;
    if ((int64_t)is_split->element_count() != max_nodes) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "heap outputs must be [2^(max_depth+1) - 1]");
    }
    int32_t* pos = pos_out->typed_data();
    std::memset(pos, 0, (size_t)n * sizeof(int32_t));
    bool* isl = is_split->typed_data();
    bool* dfl = default_left->typed_data();
    std::memset(isl, 0, (size_t)max_nodes * sizeof(bool));
    std::memset(dfl, 0, (size_t)max_nodes * sizeof(bool));
    std::memset(feature->typed_data(), 0,
                (size_t)max_nodes * sizeof(int32_t));
    std::memset(split_bin->typed_data(), 0,
                (size_t)max_nodes * sizeof(int32_t));
    std::memset(split_cond->typed_data(), 0,
                (size_t)max_nodes * sizeof(float));
    std::memset(node_g->typed_data(), 0, (size_t)max_nodes * sizeof(float));
    std::memset(node_h->typed_data(), 0, (size_t)max_nodes * sizeof(float));
    std::memset(node_w->typed_data(), 0, (size_t)max_nodes * sizeof(float));
    std::memset(loss_chg->typed_data(), 0,
                (size_t)max_nodes * sizeof(float));
    const SplitP p{reg_lambda, reg_alpha, max_delta_step, min_child_weight};
    const float g0 = G0.typed_data()[0], h0 = H0.typed_data()[0];
    if (bins.element_type() == ffi::U8) {
        tree_grow_loop(reinterpret_cast<const uint8_t*>(bins.untyped_data()),
                       gh.typed_data(), cut_values.typed_data(),
                       tree_mask.typed_data(), g0, h0, n, F, B, max_depth,
                       sibling_sub != 0, p, pos, isl, feature->typed_data(),
                       split_bin->typed_data(), split_cond->typed_data(),
                       dfl, node_g->typed_data(), node_h->typed_data(),
                       node_w->typed_data(), loss_chg->typed_data());
    } else if (bins.element_type() == ffi::U16) {
        tree_grow_loop(reinterpret_cast<const uint16_t*>(bins.untyped_data()),
                       gh.typed_data(), cut_values.typed_data(),
                       tree_mask.typed_data(), g0, h0, n, F, B, max_depth,
                       sibling_sub != 0, p, pos, isl, feature->typed_data(),
                       split_bin->typed_data(), split_cond->typed_data(),
                       dfl, node_g->typed_data(), node_h->typed_data(),
                       node_w->typed_data(), loss_chg->typed_data());
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

// ---- per-level sibling-subtraction kernel (kernelprof mirror) ----------

template <typename BinT>
void level_sub_impl(const BinT* bins, int32_t* pos, const float* gh,
                    const float* ptab, const float* prev_hist, int64_t n,
                    int64_t F, int64_t B, int64_t K, int64_t Kp,
                    int64_t poff, int64_t off, float* hist) {
    std::vector<uint8_t> isplit((size_t)Kp), dleft((size_t)Kp);
    std::vector<int32_t> feat((size_t)Kp), bin((size_t)Kp);
    for (int64_t j = 0; j < Kp; ++j) {
        const float* dec = ptab + j * 4;
        isplit[j] = dec[0] > 0.5f ? 1 : 0;
        feat[j] = (int32_t)dec[1];
        bin[j] = (int32_t)dec[2];
        dleft[j] = dec[3] > 0.5f ? 1 : 0;
    }
    partition_rows(bins, pos, isplit.data(), feat.data(), bin.data(),
                   dleft.data(), n, F, B, Kp, poff);
    std::vector<int64_t> counts((size_t)K);
    std::vector<uint8_t> bmask((size_t)K);
    count_rows(pos, n, off, K, counts.data());
    plan_siblings(counts.data(), Kp, bmask.data());
    accumulate_level(bins, pos, gh, n, F, B, K, off, bmask.data(), hist);
    derive_siblings(prev_hist, hist, F, B, K, Kp, counts.data());
}

ffi::Error HbLevelSubImpl(ffi::AnyBuffer bins, ffi::Buffer<ffi::S32> pos,
                          ffi::Buffer<ffi::F32> gh,
                          ffi::Buffer<ffi::F32> ptab,
                          ffi::Buffer<ffi::F32> prev_hist,
                          ffi::Buffer<ffi::S32> prev_offset,
                          ffi::Buffer<ffi::S32> offset, int64_t K,
                          int64_t Kp, int64_t B,
                          ffi::Result<ffi::Buffer<ffi::S32>> pos_out,
                          ffi::Result<ffi::Buffer<ffi::F32>> hist) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    if (Kp < 1 || K != 2 * Kp) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "sibling level needs K == 2 * Kp, Kp >= 1");
    }
    const int64_t n = dims[0], F = dims[1];
    const int64_t poff = prev_offset.typed_data()[0];
    const int64_t off = offset.typed_data()[0];
    int32_t* po_out = pos_out->typed_data();
    std::memcpy(po_out, pos.typed_data(), (size_t)n * sizeof(int32_t));
    float* h = hist->typed_data();
    std::memset(h, 0, (size_t)(F * 2 * K * B) * sizeof(float));
    if (bins.element_type() == ffi::U8) {
        level_sub_impl(reinterpret_cast<const uint8_t*>(bins.untyped_data()),
                       po_out, gh.typed_data(), ptab.typed_data(),
                       prev_hist.typed_data(), n, F, B, K, Kp, poff, off, h);
    } else if (bins.element_type() == ffi::U16) {
        level_sub_impl(reinterpret_cast<const uint16_t*>(bins.untyped_data()),
                       po_out, gh.typed_data(), ptab.typed_data(),
                       prev_hist.typed_data(), n, F, B, K, Kp, poff, off, h);
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuTreeGrow, TreeGrowImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::F32>>()    // gh [n, 2]
        .Arg<ffi::Buffer<ffi::F32>>()    // cut_values [F, B]
        .Arg<ffi::Buffer<ffi::S32>>()    // tree_mask [F] (0/1)
        .Arg<ffi::Buffer<ffi::F32>>()    // G0 (0-d)
        .Arg<ffi::Buffer<ffi::F32>>()    // H0 (0-d)
        .Attr<int64_t>("max_depth")
        .Attr<int64_t>("B")
        .Attr<int64_t>("sibling_sub")
        .Attr<float>("reg_lambda")
        .Attr<float>("reg_alpha")
        .Attr<float>("max_delta_step")
        .Attr<float>("min_child_weight")
        .Ret<ffi::Buffer<ffi::S32>>()    // pos_out [n, 1] (leaf level)
        .Ret<ffi::Buffer<ffi::PRED>>()   // is_split [max_nodes]
        .Ret<ffi::Buffer<ffi::S32>>()    // feature [max_nodes]
        .Ret<ffi::Buffer<ffi::S32>>()    // split_bin [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // split_cond [max_nodes]
        .Ret<ffi::Buffer<ffi::PRED>>()   // default_left [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // node_g [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // node_h [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // node_w [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>());  // loss_chg [max_nodes]

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuHbLevelSub, HbLevelSubImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::S32>>()    // pos [n, 1] (previous level)
        .Arg<ffi::Buffer<ffi::F32>>()    // gh [n, 2]
        .Arg<ffi::Buffer<ffi::F32>>()    // ptab [Kp, 4]
        .Arg<ffi::Buffer<ffi::F32>>()    // prev_hist [F, 2Kp, B]
        .Arg<ffi::Buffer<ffi::S32>>()    // prev_offset (0-d)
        .Arg<ffi::Buffer<ffi::S32>>()    // offset (0-d)
        .Attr<int64_t>("K")
        .Attr<int64_t>("Kp")
        .Attr<int64_t>("B")
        .Ret<ffi::Buffer<ffi::S32>>()    // pos_out [n, 1]
        .Ret<ffi::Buffer<ffi::F32>>());  // hist [F, 2K, B]
