// Whole-tree native grow kernel for the CPU training path, registered as
// XLA FFI custom calls.
//
// `hist_build.cpp` moved the level histogram + partition into one native
// call per level, but the round still pays ~2 dispatches per depth
// (`fused_level` + `_level_update_jit`) plus the XLA glue between them.
// This kernel runs the ENTIRE depth loop of one boosting round in a
// single custom call (`XgbtpuTreeGrow`): per-level partition, histogram
// build, split evaluation, and heap/node update, returning the finalized
// heap arrays that `_finalize_jit` consumes — one host round-trip per
// round instead of ~2 per level.
//
// Bit-identity contract (the same methodology hist_build.cpp pinned):
//  * Histogram accumulation preserves the per-cell order of the XLA
//    segment_sum (rows ascending per cell). The cache-blocked loop below
//    only re-tiles the FEATURE axis — per-cell row order is unchanged, so
//    blocking is bit-transparent.
//  * Split evaluation replicates `_level_update` exactly: the repo's
//    eval uses `seq_cumsum` (strict left-to-right f32 association), which
//    a sequential C loop reproduces; gain/weight formulas are ported
//    term-for-term from `tree/param.py` and validated bitwise against the
//    jitted `_level_update` (see tests). Two codegen hazards are handled
//    explicitly: this file must compile with -ffp-contract=off (gcc -O3
//    defaults to contract=fast and would fuse mul+add into FMA), and the
//    max_delta_step>0 gain path is NOT claimed bit-identical (XLA:CPU
//    contracts `2*G*w + denom*w*w` into an FMA there) — the dispatcher
//    only routes max_delta_step==0 configs to this kernel.
//  * Sibling subtraction (attr `sibling_sub`): at depth >= 1 build only
//    the child with fewer rows and derive the other as parent - child
//    (exact on count-valued data; model-equal otherwise). When one child
//    is empty, parent - 0 reproduces the direct build bit-for-bit, so the
//    off switch (XGBTPU_SIBLING_SUB=0) pins the whole kernel bit-identical
//    to the per-level native path.
//
//  * Quantized histogram engine (attr `hist_acc`, ISSUE 19): with
//    hist_acc=1 ("quant") the histogram core runs on fixed-point
//    quantized gradients — one per-round quantiser (power-of-two scales
//    from the global max |g| / |h|) packs (g, h) into the two int32
//    lanes of one int64; rows stream through per-node row lists built by
//    a stable counting sort (only rows of BUILT siblings are touched, vs
//    all n masked on the float path) into per-(node, slab) packed
//    partials whose lane sums provably fit int32 (kSlabRows * 2^kQBits =
//    2^30), then widen into an int64 level histogram. Integer addition
//    is associative, so accumulation order — and therefore OpenMP thread
//    count and slab schedule — cannot change the result by construction;
//    sibling derivation (parent - built) is EXACT in the integer domain.
//    Dequantization to f32 happens once per level, at eval time, so
//    eval_level's math is unchanged. hist_acc=0 ("float") keeps the r17
//    float core untouched — the bit-identity kill switch.
//
// `XgbtpuHbLevelSub` exposes ONE level of the same machinery (partition +
// subtraction histogram) for the kernelprof mirror: sampled rounds replay
// the round per-level for attribution, and because the mirror kernel
// shares these exact core loops, its histograms match the in-kernel ones
// bit-for-bit by construction. `XgbtpuHbLevelQuant` is its quant-route
// twin: one level of partition + quantize + row-list build + integer
// accumulate (+ integer sibling derive), carrying the previous level's
// int64 histogram across calls as packed int32 word pairs (an f32
// carry would drop bits once sums exceed 24 mantissa bits).
//
// Blocking parameters: feature blocks are sized so one block's histogram
// slab ([fb, 2K, B] f32) fits the kHistL2Budget bytes (256 KiB — a
// conservative 1-core L2 share); rows stream once per block. OpenMP
// parallelism follows serving_walk.cpp: static row/node splits guarded by
// a minimum size so small batches skip team spawn, and every parallel
// region writes disjoint slabs (feature blocks / nodes / rows), keeping
// results independent of thread count.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// ---- opt-in in-kernel guard mode (XGBTPU_NATIVE_GUARD=1) ---------------
//
// The per-level mirror handlers take a caller-supplied decision table
// whose feature column drives an unchecked bins[i * F + f] read in
// partition_rows. Guard mode validates every active row up front and
// returns a typed ffi::Error instead of a wild read. Env read per call
// (no static latch) so in-process tests can flip it; cost is O(Kp).

bool guard_enabled() {
    const char* v = std::getenv("XGBTPU_NATIVE_GUARD");
    return v != nullptr && v[0] == '1' && v[1] == '\0';
}

// First split row whose feature index falls outside [0, F), or -1.
int64_t bad_ptab_feature(const float* ptab, int64_t rows, int64_t F) {
    for (int64_t k = 0; k < rows; ++k) {
        const float* dec = ptab + k * 4;
        if (dec[0] <= 0.5f) continue;  // inactive row: never dereferenced
        const int64_t f = (int64_t)dec[1];
        if (f < 0 || f >= F) return k;
    }
    return -1;
}

ffi::Error ptab_guard_error(int64_t row) {
    return ffi::Error(
        ffi::ErrorCode::kOutOfRange,
        "XGBTPU_NATIVE_GUARD: decision table row " + std::to_string(row) +
            " has a feature index outside [0, F)");
}

constexpr int64_t kHistL2Budget = 256 * 1024;  // bytes per feature block
constexpr float kRtEps = 1e-6f;                // param.py RT_EPS

struct SplitP {
    float lam, alpha, mds, mcw;
};

// ---- param.py ports (f32 term-for-term; see tree/param.py) -------------

inline float thresh_l1(float g, float a) {
    if (a == 0.0f) return g;
    float t = std::fabs(g) - a;
    if (t < 0.0f) t = 0.0f;  // NaN compares false and passes through
    const float s = (g > 0.0f) ? 1.0f : ((g < 0.0f) ? -1.0f : g);
    return s * t;
}

inline float calc_weight_c(float G, float H, const SplitP& p) {
    const float denom = H + p.lam;
    float w = 0.0f;
    if (denom > 0.0f) {
        const float t = thresh_l1(G, p.alpha);
        const float d2 = (denom < 1e-38f) ? 1e-38f : denom;
        w = -t / d2;
    }
    if (p.mds > 0.0f) {
        if (w < -p.mds) w = -p.mds;
        if (w > p.mds) w = p.mds;  // NaN stays NaN, like jnp.clip
    }
    if (H < p.mcw || H <= 0.0f) return 0.0f;
    return w;
}

inline float calc_gain_c(float G, float H, const SplitP& p) {
    const float denom = H + p.lam;
    float g = 0.0f;
    if (p.mds == 0.0f) {
        if (denom > 0.0f) {
            const float t = thresh_l1(G, p.alpha);
            const float d2 = (denom < 1e-38f) ? 1e-38f : denom;
            g = (t * t) / d2;
        }
    } else {
        // Not dispatched for bit-identity (XLA contracts this into FMA);
        // kept faithful to the source association for manual pins.
        const float w = calc_weight_c(G, H, p);
        g = -((2.0f * G) * w + (denom * w) * w);
    }
    if (H < p.mcw) return 0.0f;
    return g;
}

// ---- shared core loops -------------------------------------------------

// Route rows through a level's decisions (typed arrays, one entry per
// previous-level node). Semantics mirror hist_build.cpp partition_loop:
// missing (bv >= B) goes the default direction, bin compare is <=.
template <typename BinT>
void partition_rows(const BinT* bins, int32_t* pos, const uint8_t* isplit,
                    const int32_t* feat, const int32_t* bin,
                    const uint8_t* dleft, int64_t n, int64_t F, int64_t B,
                    int64_t Kp, int64_t poff) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (n >= 8192)
#endif
    for (int64_t i = 0; i < n; ++i) {
        const int32_t pcur = pos[i];
        const int64_t lp = (int64_t)pcur - poff;
        if (lp < 0 || lp >= Kp) continue;
        if (!isplit[lp]) continue;
        const int64_t f = feat[lp];
        const int64_t bv = (int64_t)bins[i * F + f];
        const bool left = (bv >= B) ? (dleft[lp] != 0) : (bv <= bin[lp]);
        pos[i] = (int32_t)(2 * pcur + (left ? 1 : 2));
    }
}

void count_rows(const int32_t* pos, int64_t n, int64_t off, int64_t K,
                int64_t* counts) {
    std::fill(counts, counts + K, (int64_t)0);
    for (int64_t i = 0; i < n; ++i) {
        const int64_t s = (int64_t)pos[i] - off;
        if (s >= 0 && s < K) ++counts[s];
    }
}

// Accumulate (g, h) into hist [F, 2K, B] for rows landing in this level's
// slots (optionally only slots with build_mask set). Cache-blocked over
// features: each block's hist slab stays L2-resident while rows stream.
// Per-cell accumulation order is rows ascending — identical to
// hist_build.cpp level_loop — for any block size or thread count, because
// blocks/threads own disjoint feature slabs.
template <typename BinT>
void accumulate_level(const BinT* bins, const int32_t* pos, const float* gh,
                      int64_t n, int64_t F, int64_t B, int64_t K, int64_t off,
                      const uint8_t* build_mask, float* hist) {
    const int64_t feat_stride = 2 * K * B;
    int64_t fb = kHistL2Budget / (int64_t)(2 * K * B * sizeof(float));
    if (fb < 1) fb = 1;
    if (fb > F) fb = F;
    const int64_t nblk = (F + fb - 1) / fb;
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic, 1) if (nblk > 1 && n >= 8192)
#endif
    for (int64_t blk = 0; blk < nblk; ++blk) {
        const int64_t f0 = blk * fb;
        const int64_t f1 = std::min<int64_t>(F, f0 + fb);
        for (int64_t i = 0; i < n; ++i) {
            const int64_t s = (int64_t)pos[i] - off;
            if (s < 0 || s >= K) continue;
            if (build_mask && !build_mask[s]) continue;
            const float g = gh[2 * i], h = gh[2 * i + 1];
            const BinT* br = bins + i * F;
            float* gbase = hist + s * B;
            for (int64_t f = f0; f < f1; ++f) {
                const int64_t bv = br[f];
                if (bv >= B) continue;  // missing: recovered as total - sum
                float* cell = gbase + f * feat_stride + bv;
                cell[0] += g;
                cell[K * B] += h;
            }
        }
    }
}

// Mark, per sibling pair, the child with fewer rows as the one to build
// directly. Pairs with no rows at all stay unbuilt (their cells stay 0,
// matching a direct build of zero rows).
void plan_siblings(const int64_t* counts, int64_t Kp, uint8_t* build_mask) {
    for (int64_t j = 0; j < Kp; ++j) {
        const int64_t sl = 2 * j, sr = 2 * j + 1;
        build_mask[sl] = 0;
        build_mask[sr] = 0;
        if (counts[sl] + counts[sr] == 0) continue;
        build_mask[counts[sl] <= counts[sr] ? sl : sr] = 1;
    }
}

// Derive each unbuilt sibling as parent - built (f32 subtraction per
// cell). prev is the previous level's hist [F, 2Kp, B]; cur is this
// level's [F, 2K, B] with the built children already accumulated.
void derive_siblings(const float* prev, float* cur, int64_t F, int64_t B,
                     int64_t K, int64_t Kp, const int64_t* counts) {
    const int64_t fs_cur = 2 * K * B, fs_prev = 2 * Kp * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (F >= 8)
#endif
    for (int64_t f = 0; f < F; ++f) {
        for (int64_t j = 0; j < Kp; ++j) {
            const int64_t sl = 2 * j, sr = 2 * j + 1;
            if (counts[sl] + counts[sr] == 0) continue;
            const int64_t built = counts[sl] <= counts[sr] ? sl : sr;
            const int64_t other = sl + sr - built;
            const float* pg = prev + f * fs_prev + j * B;
            const float* ph = pg + Kp * B;
            const float* bg = cur + f * fs_cur + built * B;
            const float* bh = bg + K * B;
            float* og = cur + f * fs_cur + other * B;
            float* oh = og + K * B;
            for (int64_t b = 0; b < B; ++b) {
                og[b] = pg[b] - bg[b];
                oh[b] = ph[b] - bh[b];
            }
        }
    }
}

// ---- fixed-point quantized gradient engine (ISSUE 19) ------------------
//
// One per-round quantiser: per-lane power-of-two scales 2^Eg / 2^Eh with
// E = kQBits - e where frexp(max|x|) = m * 2^e (m in [0.5, 1)), so every
// quantized magnitude is <= 2^kQBits. Count-valued gradients (small
// integers) land exactly on the grid whenever E >= 0 — the PR-13
// power-of-two-grid argument — so quantize -> sum -> dequantize
// reproduces the float path bit-for-bit on such data. (g, h) pack into
// the two int32 lanes of one int64 (g high, h low); a slab of kSlabRows
// rows keeps each lane's partial within kSlabRows * 2^kQBits = 2^30 <
// INT32_MAX, so packed lane adds cannot carry across lanes and every
// per-slab partial is exact. Integer addition is associative, so ANY
// merge order — and therefore any OpenMP thread count or slab schedule —
// produces identical histograms by construction: the determinism the
// OMP701-703 rules forbid float reductions to claim.

constexpr int64_t kQBits = 18;       // |q| <= 2^18 per lane
constexpr int64_t kSlabRows = 4096;  // 4096 * 2^18 = 2^30 < INT32_MAX
constexpr int64_t kPrefetchAhead = 16;

struct QScale {
    int eg, eh;     // grid exponents: q = rint(x * 2^e)
    double sg, sh;  // 2^eg, 2^eh (quantize)
    double ig, ih;  // 2^-eg, 2^-eh (dequantize)
};

inline int grid_exp(double maxabs) {
    if (!(maxabs > 0.0)) return 0;  // all-zero lane: any grid is exact
    int e;
    std::frexp(maxabs, &e);  // maxabs = m * 2^e, m in [0.5, 1)
    return (int)kQBits - e;
}

// Scales from the global max |g| / |h| — a serial scan (max is exact and
// order-independent, but the lint's reduction rules are regex-level, and
// one pass over 2n floats is noise next to the histogram work).
QScale compute_qscale(const float* gh, int64_t n) {
    double mg = 0.0, mh = 0.0;
    for (int64_t i = 0; i < n; ++i) {
        const double g = std::fabs((double)gh[2 * i]);
        const double h = std::fabs((double)gh[2 * i + 1]);
        if (std::isfinite(g) && g > mg) mg = g;
        if (std::isfinite(h) && h > mh) mh = h;
    }
    QScale q;
    q.eg = grid_exp(mg);
    q.eh = grid_exp(mh);
    q.sg = std::ldexp(1.0, q.eg);
    q.sh = std::ldexp(1.0, q.eh);
    q.ig = std::ldexp(1.0, -q.eg);
    q.ih = std::ldexp(1.0, -q.eh);
    return q;
}

// Pack quantized (g, h) into one int64: g in the high 32 bits, h in the
// low 32. Lane partials stay within int32 per slab (bound above), so
// packed adds never carry between lanes and unpacking recovers the
// exact per-lane sums.
inline int64_t pack_q(int32_t qg, int32_t qh) {
    return ((int64_t)qg << 32) + (int64_t)qh;
}

inline void unpack_q(int64_t v, int64_t* qg, int64_t* qh) {
    const int32_t h = (int32_t)(uint32_t)(v & 0xffffffffLL);
    *qh = (int64_t)h;
    *qg = (v - (int64_t)h) >> 32;
}

// Quantize every row once per round (disjoint writes; non-finite
// gradients quantize to 0 — the dispatch envelope never routes such
// data here, but the kernel must not exhibit UB on it).
void quantize_rows(const float* gh, int64_t n, const QScale& q,
                   int64_t* qrow) {
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (n >= 8192)
#endif
    for (int64_t i = 0; i < n; ++i) {
        const double g = (double)gh[2 * i] * q.sg;
        const double h = (double)gh[2 * i + 1] * q.sh;
        const int32_t qg = std::isfinite(g) ? (int32_t)std::llrint(g) : 0;
        const int32_t qh = std::isfinite(h) ? (int32_t)std::llrint(h) : 0;
        qrow[i] = pack_q(qg, qh);
    }
}

// Stable counting sort of this level's rows into per-slot row lists off
// the `count_rows` counts: rows ascending per slot, unbuilt slots empty
// (their rows are never touched — with sibling subtraction that is
// <= half of n at depth >= 1, vs all n masked on the float path).
// rl_start has K + 1 entries; rows receives the concatenated lists.
void build_row_lists(const int64_t* counts, const uint8_t* build_mask,
                     const int32_t* pos, int64_t n, int64_t off, int64_t K,
                     int64_t* rl_start, int32_t* rows) {
    int64_t total = 0;
    for (int64_t s = 0; s < K; ++s) {
        rl_start[s] = total;
        if (!build_mask || build_mask[s]) total += counts[s];
    }
    rl_start[K] = total;
    std::vector<int64_t> cursor(rl_start, rl_start + K);
    for (int64_t i = 0; i < n; ++i) {
        const int64_t s = (int64_t)pos[i] - off;
        if (s < 0 || s >= K) continue;
        if (build_mask && !build_mask[s]) continue;
        rows[cursor[s]++] = (int32_t)i;
    }
}

// Integer histogram accumulation: per-(slot, slab) tasks, each owning
// ONE packed [F, B] int64 partial slab (L2-resident: F * B * 8 bytes),
// with software prefetch on upcoming rows' bin lines. Phase 2 widens
// each slab's int32 lanes into the int64 level histogram hq [F, 2K, B]
// (g at [f, s, b], h at [f, K + s, b] — the float hist layout). Slots
// own disjoint hq slabs and integer adds are exact, so both phases are
// thread-count invariant for ANY schedule.
template <typename BinT>
void accumulate_level_quant(const BinT* bins, const int64_t* qrow,
                            const int32_t* rows, const int64_t* rl_start,
                            int64_t F, int64_t B, int64_t K,
                            const uint8_t* build_mask, int64_t* hq,
                            std::vector<int64_t>& scratch) {
    struct Task {
        int32_t slot;
        int64_t beg, end;
    };
    std::vector<Task> tasks;
    std::vector<int64_t> slot_t0((size_t)(K + 1));
    for (int64_t s = 0; s < K; ++s) {
        slot_t0[s] = (int64_t)tasks.size();
        if (build_mask && !build_mask[s]) continue;
        for (int64_t b = rl_start[s]; b < rl_start[s + 1]; b += kSlabRows) {
            tasks.push_back(
                {(int32_t)s, b, std::min(rl_start[s + 1], b + kSlabRows)});
        }
    }
    slot_t0[K] = (int64_t)tasks.size();
    const int64_t ntasks = (int64_t)tasks.size();
    const int64_t slab_sz = F * B;
    const int64_t total = rl_start[K];
    scratch.assign((size_t)(ntasks * slab_sz), 0);
#ifdef _OPENMP
#pragma omp parallel for schedule(dynamic, 1) if (ntasks > 1 && total >= 8192)
#endif
    for (int64_t t = 0; t < ntasks; ++t) {
        int64_t* slab = scratch.data() + t * slab_sz;
        const int64_t beg = tasks[t].beg, end = tasks[t].end;
        for (int64_t idx = beg; idx < end; ++idx) {
            if (idx + kPrefetchAhead < end) {
                const int64_t rp = rows[idx + kPrefetchAhead];
                __builtin_prefetch(bins + rp * F, 0, 1);
                __builtin_prefetch(qrow + rp, 0, 1);
            }
            const int64_t i = rows[idx];
            const int64_t q = qrow[i];
            const BinT* br = bins + i * F;
            for (int64_t f = 0; f < F; ++f) {
                const int64_t bv = br[f];
                if (bv >= B) continue;  // missing: recovered at eval
                slab[f * B + bv] += q;
            }
        }
    }
    const int64_t fs = 2 * K * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (K >= 8)
#endif
    for (int64_t s = 0; s < K; ++s) {
        for (int64_t t = slot_t0[s]; t < slot_t0[s + 1]; ++t) {
            const int64_t* slab = scratch.data() + t * slab_sz;
            for (int64_t f = 0; f < F; ++f) {
                int64_t* hg = hq + f * fs + s * B;
                int64_t* hh = hg + K * B;
                const int64_t* sl = slab + f * B;
                for (int64_t b = 0; b < B; ++b) {
                    int64_t qg, qh;
                    unpack_q(sl[b], &qg, &qh);
                    hg[b] += qg;
                    hh[b] += qh;
                }
            }
        }
    }
}

// Integer-domain sibling derivation: parent - built per cell, EXACT for
// any data (each row's quantized pair is fixed and the partition is
// exact — stronger than the float path's ~1 ulp claim).
void derive_siblings_quant(const int64_t* prev, int64_t* cur, int64_t F,
                           int64_t B, int64_t K, int64_t Kp,
                           const int64_t* counts) {
    const int64_t fs_cur = 2 * K * B, fs_prev = 2 * Kp * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (F >= 8)
#endif
    for (int64_t f = 0; f < F; ++f) {
        for (int64_t j = 0; j < Kp; ++j) {
            const int64_t sl = 2 * j, sr = 2 * j + 1;
            if (counts[sl] + counts[sr] == 0) continue;
            const int64_t built = counts[sl] <= counts[sr] ? sl : sr;
            const int64_t other = sl + sr - built;
            const int64_t* pg = prev + f * fs_prev + j * B;
            const int64_t* ph = pg + Kp * B;
            const int64_t* bg = cur + f * fs_cur + built * B;
            const int64_t* bh = bg + K * B;
            int64_t* og = cur + f * fs_cur + other * B;
            int64_t* oh = og + K * B;
            for (int64_t b = 0; b < B; ++b) {
                og[b] = pg[b] - bg[b];
                oh[b] = ph[b] - bh[b];
            }
        }
    }
}

// Dequantize one level's int64 histogram to the f32 layout eval_level
// consumes: a double multiply by the exact power of two, then one f32
// rounding — bit-identical to the float path on count-valued data
// (where both sides hold the same exact integers).
void dequantize_level(const int64_t* hq, const QScale& q, int64_t F,
                      int64_t B, int64_t K, float* hist) {
    const int64_t fs = 2 * K * B, half = K * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (F >= 8)
#endif
    for (int64_t f = 0; f < F; ++f) {
        const int64_t* hrow = hq + f * fs;
        float* out = hist + f * fs;
        for (int64_t c = 0; c < half; ++c)
            out[c] = (float)((double)hrow[c] * q.ig);
        for (int64_t c = half; c < fs; ++c)
            out[c] = (float)((double)hrow[c] * q.ih);
    }
}

// Split evaluation for one level — a sequential-association port of
// `_level_update` (grow_fused.py). Scans candidates dir-major then
// feature then bin with first-max/first-NaN argmax semantics matching
// jnp.argmax on the [K, 2*F*B] score tensor. Writes this level's slot
// decisions unconditionally and child stats only for can_split nodes
// (the XLA path's mode="drop" scatter).
void eval_level(const float* hist, const float* cuts, const int32_t* fmask,
                int64_t F, int64_t B, int64_t K, int64_t off,
                const SplitP& p, bool* is_split, int32_t* feature,
                int32_t* split_bin, float* split_cond, bool* default_left,
                float* node_g, float* node_h, float* node_w, float* loss_chg,
                int64_t max_nodes) {
    const int64_t feat_stride = 2 * K * B;
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (K >= 8)
#endif
    for (int64_t k = 0; k < K; ++k) {
        std::vector<float> GL((size_t)(F * B)), HL((size_t)(F * B));
        std::vector<float> gm((size_t)F), hm((size_t)F);
        const float Gtot = node_g[off + k], Htot = node_h[off + k];
        for (int64_t f = 0; f < F; ++f) {
            const float* hg = hist + f * feat_stride + k * B;
            const float* hh = hg + K * B;
            float accg = 0.0f, acch = 0.0f;
            for (int64_t b = 0; b < B; ++b) {
                accg = accg + hg[b];  // seq_cumsum association
                acch = acch + hh[b];
                GL[f * B + b] = accg;
                HL[f * B + b] = acch;
            }
            gm[f] = Gtot - accg;
            hm[f] = Htot - acch;
        }
        const float parent_gain = calc_gain_c(Gtot, Htot, p);
        float best = -INFINITY;
        int64_t best_idx = 0;
        for (int64_t dd = 0; dd < 2; ++dd) {
            for (int64_t f = 0; f < F; ++f) {
                if (!fmask[f]) continue;
                for (int64_t b = 0; b < B; ++b) {
                    const float GLd =
                        dd == 0 ? GL[f * B + b] : GL[f * B + b] + gm[f];
                    const float HLd =
                        dd == 0 ? HL[f * B + b] : HL[f * B + b] + hm[f];
                    const float GRd = Gtot - GLd;
                    const float HRd = Htot - HLd;
                    if (!(HLd >= p.mcw && HRd >= p.mcw)) continue;
                    const float gain =
                        calc_gain_c(GLd, HLd, p) + calc_gain_c(GRd, HRd, p);
                    const float chg = gain - parent_gain;
                    if (std::isnan(best)) {
                        // first NaN wins and sticks (jnp.argmax semantics)
                    } else if (std::isnan(chg) || chg > best) {
                        best = chg;
                        best_idx = dd * F * B + f * B + b;
                    }
                }
            }
        }
        const int64_t dd = best_idx / (F * B);
        const int64_t f = (best_idx % (F * B)) / B;
        const int64_t b = best_idx % B;
        const float GLb = dd == 0 ? GL[f * B + b] : GL[f * B + b] + gm[f];
        const float HLb = dd == 0 ? HL[f * B + b] : HL[f * B + b] + hm[f];
        const int64_t slot = off + k;
        const bool can = (best > kRtEps) && (Htot > 0.0f);
        is_split[slot] = can;
        feature[slot] = (int32_t)f;
        split_bin[slot] = (int32_t)b;
        split_cond[slot] = cuts[f * B + b];
        default_left[slot] = (dd == 1);
        node_w[slot] = calc_weight_c(Gtot, Htot, p);
        loss_chg[slot] = can ? best : 0.0f;
        if (can) {
            const int64_t l = 2 * slot + 1, r = 2 * slot + 2;
            if (r < max_nodes) {
                const float GRb = Gtot - GLb, HRb = Htot - HLb;
                node_g[l] = GLb;
                node_h[l] = HLb;
                node_w[l] = calc_weight_c(GLb, HLb, p);
                node_g[r] = GRb;
                node_h[r] = HRb;
                node_w[r] = calc_weight_c(GRb, HRb, p);
            }
        }
    }
}

// Snapshot a level's decisions from the heap output arrays into the
// compact typed form partition_rows consumes (Kp <= 2^(D-1) entries).
void snapshot_decisions(const bool* is_split, const int32_t* feature,
                        const int32_t* split_bin, const bool* default_left,
                        int64_t poff, int64_t Kp, uint8_t* isplit,
                        int32_t* feat, int32_t* bin, uint8_t* dleft) {
    for (int64_t j = 0; j < Kp; ++j) {
        isplit[j] = is_split[poff + j] ? 1 : 0;
        feat[j] = feature[poff + j];
        bin[j] = split_bin[poff + j];
        dleft[j] = default_left[poff + j] ? 1 : 0;
    }
}

// ---- whole-tree driver -------------------------------------------------

template <typename BinT>
void tree_grow_loop(const BinT* bins, const float* gh, const float* cuts,
                    const int32_t* fmask, float G0, float H0, int64_t n,
                    int64_t F, int64_t B, int64_t D, bool sub,
                    const SplitP& p, int32_t* pos, bool* is_split,
                    int32_t* feature, int32_t* split_bin, float* split_cond,
                    bool* default_left, float* node_g, float* node_h,
                    float* node_w, float* loss_chg) {
    const int64_t max_nodes = (1LL << (D + 1)) - 1;
    node_g[0] = G0;
    node_h[0] = H0;
    node_w[0] = calc_weight_c(G0, H0, p);
    const int64_t Km = 1LL << (D - 1);  // widest evaluated level
    std::vector<float> hist_a((size_t)(F * 2 * Km * B));
    std::vector<float> hist_b((size_t)(F * 2 * Km * B));
    float* cur = hist_a.data();
    float* prev = hist_b.data();
    std::vector<int64_t> counts((size_t)(2 * Km));
    std::vector<uint8_t> bmask((size_t)(2 * Km));
    std::vector<uint8_t> disp((size_t)Km), ddef((size_t)Km);
    std::vector<int32_t> dfeat((size_t)Km), dbin((size_t)Km);
    for (int64_t d = 0; d < D; ++d) {
        const int64_t K = 1LL << d, off = K - 1;
        const int64_t Kp = K >> 1, poff = Kp - 1;
        if (d > 0) {
            snapshot_decisions(is_split, feature, split_bin, default_left,
                               poff, Kp, disp.data(), dfeat.data(),
                               dbin.data(), ddef.data());
            partition_rows(bins, pos, disp.data(), dfeat.data(), dbin.data(),
                           ddef.data(), n, F, B, Kp, poff);
        }
        std::memset(cur, 0, (size_t)(F * 2 * K * B) * sizeof(float));
        if (sub && d >= 1) {
            count_rows(pos, n, off, K, counts.data());
            plan_siblings(counts.data(), Kp, bmask.data());
            accumulate_level(bins, pos, gh, n, F, B, K, off, bmask.data(),
                             cur);
            derive_siblings(prev, cur, F, B, K, Kp, counts.data());
        } else {
            accumulate_level(bins, pos, gh, n, F, B, K, off,
                             (const uint8_t*)nullptr, cur);
        }
        eval_level(cur, cuts, fmask, F, B, K, off, p, is_split, feature,
                   split_bin, split_cond, default_left, node_g, node_h,
                   node_w, loss_chg, max_nodes);
        std::swap(cur, prev);
    }
    // Final routing into the leaf level (the driver's partition_apply).
    const int64_t Kp = 1LL << (D - 1), poff = Kp - 1;
    snapshot_decisions(is_split, feature, split_bin, default_left, poff, Kp,
                       disp.data(), dfeat.data(), dbin.data(), ddef.data());
    partition_rows(bins, pos, disp.data(), dfeat.data(), dbin.data(),
                   ddef.data(), n, F, B, Kp, poff);
}

// Quant-route twin of tree_grow_loop: partition / eval / heap update are
// the SAME code; only the histogram core differs (quantize once per
// round, per-node row lists, packed integer slabs, int64 level
// histograms, dequantize at eval). Row lists are built on BOTH sub
// settings — streaming only in-level rows replaces the float path's
// full-n masked scan.
template <typename BinT>
void tree_grow_loop_quant(const BinT* bins, const float* gh,
                          const float* cuts, const int32_t* fmask, float G0,
                          float H0, int64_t n, int64_t F, int64_t B,
                          int64_t D, bool sub, const SplitP& p, int32_t* pos,
                          bool* is_split, int32_t* feature,
                          int32_t* split_bin, float* split_cond,
                          bool* default_left, float* node_g, float* node_h,
                          float* node_w, float* loss_chg) {
    const int64_t max_nodes = (1LL << (D + 1)) - 1;
    node_g[0] = G0;
    node_h[0] = H0;
    node_w[0] = calc_weight_c(G0, H0, p);
    const int64_t Km = 1LL << (D - 1);
    const QScale qs = compute_qscale(gh, n);
    std::vector<int64_t> qrow((size_t)n);
    quantize_rows(gh, n, qs, qrow.data());
    std::vector<int64_t> hq_a((size_t)(F * 2 * Km * B));
    std::vector<int64_t> hq_b((size_t)(F * 2 * Km * B));
    std::vector<float> histf((size_t)(F * 2 * Km * B));
    int64_t* cur = hq_a.data();
    int64_t* prev = hq_b.data();
    std::vector<int64_t> counts((size_t)(2 * Km));
    std::vector<int64_t> rl_start((size_t)(2 * Km + 1));
    std::vector<int32_t> rows((size_t)n);
    std::vector<int64_t> scratch;
    std::vector<uint8_t> bmask((size_t)(2 * Km));
    std::vector<uint8_t> disp((size_t)Km), ddef((size_t)Km);
    std::vector<int32_t> dfeat((size_t)Km), dbin((size_t)Km);
    for (int64_t d = 0; d < D; ++d) {
        const int64_t K = 1LL << d, off = K - 1;
        const int64_t Kp = K >> 1, poff = Kp - 1;
        if (d > 0) {
            snapshot_decisions(is_split, feature, split_bin, default_left,
                               poff, Kp, disp.data(), dfeat.data(),
                               dbin.data(), ddef.data());
            partition_rows(bins, pos, disp.data(), dfeat.data(), dbin.data(),
                           ddef.data(), n, F, B, Kp, poff);
        }
        std::memset(cur, 0, (size_t)(F * 2 * K * B) * sizeof(int64_t));
        count_rows(pos, n, off, K, counts.data());
        const uint8_t* mask = nullptr;
        if (sub && d >= 1) {
            plan_siblings(counts.data(), Kp, bmask.data());
            mask = bmask.data();
        }
        build_row_lists(counts.data(), mask, pos, n, off, K, rl_start.data(),
                        rows.data());
        accumulate_level_quant(bins, qrow.data(), rows.data(),
                               rl_start.data(), F, B, K, mask, cur, scratch);
        if (mask) derive_siblings_quant(prev, cur, F, B, K, Kp,
                                        counts.data());
        dequantize_level(cur, qs, F, B, K, histf.data());
        eval_level(histf.data(), cuts, fmask, F, B, K, off, p, is_split,
                   feature, split_bin, split_cond, default_left, node_g,
                   node_h, node_w, loss_chg, max_nodes);
        std::swap(cur, prev);
    }
    const int64_t Kp = 1LL << (D - 1), poff = Kp - 1;
    snapshot_decisions(is_split, feature, split_bin, default_left, poff, Kp,
                       disp.data(), dfeat.data(), dbin.data(), ddef.data());
    partition_rows(bins, pos, disp.data(), dfeat.data(), dbin.data(),
                   ddef.data(), n, F, B, Kp, poff);
}

ffi::Error TreeGrowImpl(
    ffi::AnyBuffer bins, ffi::Buffer<ffi::F32> gh,
    ffi::Buffer<ffi::F32> cut_values, ffi::Buffer<ffi::S32> tree_mask,
    ffi::Buffer<ffi::F32> G0, ffi::Buffer<ffi::F32> H0, int64_t max_depth,
    int64_t B, int64_t sibling_sub, int64_t hist_acc, float reg_lambda,
    float reg_alpha, float max_delta_step, float min_child_weight,
    ffi::Result<ffi::Buffer<ffi::S32>> pos_out,
    ffi::Result<ffi::Buffer<ffi::PRED>> is_split,
    ffi::Result<ffi::Buffer<ffi::S32>> feature,
    ffi::Result<ffi::Buffer<ffi::S32>> split_bin,
    ffi::Result<ffi::Buffer<ffi::F32>> split_cond,
    ffi::Result<ffi::Buffer<ffi::PRED>> default_left,
    ffi::Result<ffi::Buffer<ffi::F32>> node_g,
    ffi::Result<ffi::Buffer<ffi::F32>> node_h,
    ffi::Result<ffi::Buffer<ffi::F32>> node_w,
    ffi::Result<ffi::Buffer<ffi::F32>> loss_chg) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    if (max_depth < 1) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "max_depth must be >= 1");
    }
    const int64_t n = dims[0], F = dims[1];
    const int64_t max_nodes = (1LL << (max_depth + 1)) - 1;
    if ((int64_t)is_split->element_count() != max_nodes) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "heap outputs must be [2^(max_depth+1) - 1]");
    }
    if ((int64_t)gh.element_count() < 2 * n ||
        (int64_t)cut_values.element_count() < F * B ||
        (int64_t)tree_mask.element_count() < F) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "gh must be [n, 2], cut_values [F, B], "
                          "tree_mask [F]");
    }
    int32_t* pos = pos_out->typed_data();
    std::memset(pos, 0, (size_t)n * sizeof(int32_t));
    bool* isl = is_split->typed_data();
    bool* dfl = default_left->typed_data();
    std::memset(isl, 0, (size_t)max_nodes * sizeof(bool));
    std::memset(dfl, 0, (size_t)max_nodes * sizeof(bool));
    std::memset(feature->typed_data(), 0,
                (size_t)max_nodes * sizeof(int32_t));
    std::memset(split_bin->typed_data(), 0,
                (size_t)max_nodes * sizeof(int32_t));
    std::memset(split_cond->typed_data(), 0,
                (size_t)max_nodes * sizeof(float));
    std::memset(node_g->typed_data(), 0, (size_t)max_nodes * sizeof(float));
    std::memset(node_h->typed_data(), 0, (size_t)max_nodes * sizeof(float));
    std::memset(node_w->typed_data(), 0, (size_t)max_nodes * sizeof(float));
    std::memset(loss_chg->typed_data(), 0,
                (size_t)max_nodes * sizeof(float));
    const SplitP p{reg_lambda, reg_alpha, max_delta_step, min_child_weight};
    const float g0 = G0.typed_data()[0], h0 = H0.typed_data()[0];
    const bool quant = hist_acc != 0;
    if (bins.element_type() == ffi::U8) {
        const auto* b8 =
            reinterpret_cast<const uint8_t*>(bins.untyped_data());
        (quant ? tree_grow_loop_quant<uint8_t> : tree_grow_loop<uint8_t>)(
            b8, gh.typed_data(), cut_values.typed_data(),
            tree_mask.typed_data(), g0, h0, n, F, B, max_depth,
            sibling_sub != 0, p, pos, isl, feature->typed_data(),
            split_bin->typed_data(), split_cond->typed_data(), dfl,
            node_g->typed_data(), node_h->typed_data(),
            node_w->typed_data(), loss_chg->typed_data());
    } else if (bins.element_type() == ffi::U16) {
        const auto* b16 =
            reinterpret_cast<const uint16_t*>(bins.untyped_data());
        (quant ? tree_grow_loop_quant<uint16_t> : tree_grow_loop<uint16_t>)(
            b16, gh.typed_data(), cut_values.typed_data(),
            tree_mask.typed_data(), g0, h0, n, F, B, max_depth,
            sibling_sub != 0, p, pos, isl, feature->typed_data(),
            split_bin->typed_data(), split_cond->typed_data(), dfl,
            node_g->typed_data(), node_h->typed_data(),
            node_w->typed_data(), loss_chg->typed_data());
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

// ---- per-level sibling-subtraction kernel (kernelprof mirror) ----------

template <typename BinT>
void level_sub_impl(const BinT* bins, int32_t* pos, const float* gh,
                    const float* ptab, const float* prev_hist, int64_t n,
                    int64_t F, int64_t B, int64_t K, int64_t Kp,
                    int64_t poff, int64_t off, float* hist) {
    std::vector<uint8_t> isplit((size_t)Kp), dleft((size_t)Kp);
    std::vector<int32_t> feat((size_t)Kp), bin((size_t)Kp);
    for (int64_t j = 0; j < Kp; ++j) {
        const float* dec = ptab + j * 4;
        isplit[j] = dec[0] > 0.5f ? 1 : 0;
        feat[j] = (int32_t)dec[1];
        bin[j] = (int32_t)dec[2];
        dleft[j] = dec[3] > 0.5f ? 1 : 0;
    }
    partition_rows(bins, pos, isplit.data(), feat.data(), bin.data(),
                   dleft.data(), n, F, B, Kp, poff);
    std::vector<int64_t> counts((size_t)K);
    std::vector<uint8_t> bmask((size_t)K);
    count_rows(pos, n, off, K, counts.data());
    plan_siblings(counts.data(), Kp, bmask.data());
    accumulate_level(bins, pos, gh, n, F, B, K, off, bmask.data(), hist);
    derive_siblings(prev_hist, hist, F, B, K, Kp, counts.data());
}

ffi::Error HbLevelSubImpl(ffi::AnyBuffer bins, ffi::Buffer<ffi::S32> pos,
                          ffi::Buffer<ffi::F32> gh,
                          ffi::Buffer<ffi::F32> ptab,
                          ffi::Buffer<ffi::F32> prev_hist,
                          ffi::Buffer<ffi::S32> prev_offset,
                          ffi::Buffer<ffi::S32> offset, int64_t K,
                          int64_t Kp, int64_t B,
                          ffi::Result<ffi::Buffer<ffi::S32>> pos_out,
                          ffi::Result<ffi::Buffer<ffi::F32>> hist) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    if (Kp < 1 || K != 2 * Kp) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "sibling level needs K == 2 * Kp, Kp >= 1");
    }
    const int64_t n = dims[0], F = dims[1];
    if ((int64_t)ptab.element_count() < Kp * 4) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "ptab must hold at least Kp rows of 4");
    }
    if (guard_enabled()) {
        const int64_t bad = bad_ptab_feature(ptab.typed_data(), Kp, F);
        if (bad >= 0) return ptab_guard_error(bad);
    }
    const int64_t poff = prev_offset.typed_data()[0];
    const int64_t off = offset.typed_data()[0];
    int32_t* po_out = pos_out->typed_data();
    std::memcpy(po_out, pos.typed_data(), (size_t)n * sizeof(int32_t));
    float* h = hist->typed_data();
    std::memset(h, 0, (size_t)(F * 2 * K * B) * sizeof(float));
    if (bins.element_type() == ffi::U8) {
        level_sub_impl(reinterpret_cast<const uint8_t*>(bins.untyped_data()),
                       po_out, gh.typed_data(), ptab.typed_data(),
                       prev_hist.typed_data(), n, F, B, K, Kp, poff, off, h);
    } else if (bins.element_type() == ffi::U16) {
        level_sub_impl(reinterpret_cast<const uint16_t*>(bins.untyped_data()),
                       po_out, gh.typed_data(), ptab.typed_data(),
                       prev_hist.typed_data(), n, F, B, K, Kp, poff, off, h);
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

// ---- per-level quantized kernel (kernelprof mirror, quant route) -------
//
// One level of the quant engine: quantiser recomputed from the FULL gh
// (deterministic — identical to the whole-tree kernel's once-per-round
// computation), partition, row lists, integer accumulate, and (with
// sibling_sub) integer derive from the previous level's int64 histogram.
// The int64 histogram crosses the FFI boundary as packed little-endian
// int32 word pairs ([F, 2K, B, 2] s32) because the mirror runs with
// jax x64 disabled — an f32 carry would drop bits once a cell's sum
// exceeds 24 mantissa bits and break the sampled-round bit-identity
// contract. hist_f is the dequantized f32 view `_level_update_jit`
// consumes. At the root (Kp == 0) partition and derive are skipped and
// every slot builds directly.

template <typename BinT>
void level_quant_impl(const BinT* bins, int32_t* pos, const float* gh,
                      const float* ptab, const int64_t* prev_q, int64_t n,
                      int64_t F, int64_t B, int64_t K, int64_t Kp,
                      int64_t poff, int64_t off, bool sub, int64_t* hq,
                      float* hist_f) {
    const QScale qs = compute_qscale(gh, n);
    std::vector<int64_t> qrow((size_t)n);
    quantize_rows(gh, n, qs, qrow.data());
    if (Kp >= 1) {
        std::vector<uint8_t> isplit((size_t)Kp), dleft((size_t)Kp);
        std::vector<int32_t> feat((size_t)Kp), bin((size_t)Kp);
        for (int64_t j = 0; j < Kp; ++j) {
            const float* dec = ptab + j * 4;
            isplit[j] = dec[0] > 0.5f ? 1 : 0;
            feat[j] = (int32_t)dec[1];
            bin[j] = (int32_t)dec[2];
            dleft[j] = dec[3] > 0.5f ? 1 : 0;
        }
        partition_rows(bins, pos, isplit.data(), feat.data(), bin.data(),
                       dleft.data(), n, F, B, Kp, poff);
    }
    std::vector<int64_t> counts((size_t)K);
    count_rows(pos, n, off, K, counts.data());
    std::vector<uint8_t> bmask((size_t)K);
    const uint8_t* mask = nullptr;
    if (sub && Kp >= 1) {
        plan_siblings(counts.data(), Kp, bmask.data());
        mask = bmask.data();
    }
    std::vector<int64_t> rl_start((size_t)(K + 1));
    std::vector<int32_t> rows((size_t)n);
    std::vector<int64_t> scratch;
    build_row_lists(counts.data(), mask, pos, n, off, K, rl_start.data(),
                    rows.data());
    accumulate_level_quant(bins, qrow.data(), rows.data(), rl_start.data(),
                           F, B, K, mask, hq, scratch);
    if (mask) derive_siblings_quant(prev_q, hq, F, B, K, Kp, counts.data());
    dequantize_level(hq, qs, F, B, K, hist_f);
}

ffi::Error HbLevelQuantImpl(ffi::AnyBuffer bins, ffi::Buffer<ffi::S32> pos,
                            ffi::Buffer<ffi::F32> gh,
                            ffi::Buffer<ffi::F32> ptab,
                            ffi::Buffer<ffi::S32> prev_hist_q,
                            ffi::Buffer<ffi::S32> prev_offset,
                            ffi::Buffer<ffi::S32> offset, int64_t K,
                            int64_t Kp, int64_t B, int64_t sibling_sub,
                            ffi::Result<ffi::Buffer<ffi::S32>> pos_out,
                            ffi::Result<ffi::Buffer<ffi::S32>> hist_q,
                            ffi::Result<ffi::Buffer<ffi::F32>> hist_f) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    if (!(K == 2 * Kp || (K == 1 && Kp == 0))) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "quant level needs K == 2 * Kp (or K == 1 at "
                          "the root)");
    }
    const int64_t n = dims[0], F = dims[1];
    if ((int64_t)ptab.element_count() < Kp * 4) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "ptab must hold at least Kp rows of 4");
    }
    if (guard_enabled()) {
        const int64_t bad = bad_ptab_feature(ptab.typed_data(), Kp, F);
        if (bad >= 0) return ptab_guard_error(bad);
    }
    if ((int64_t)prev_hist_q.element_count() != F * 2 * Kp * B * 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "prev_hist_q must be [F, 2Kp, B, 2] int32 "
                          "word pairs");
    }
    const int64_t poff = prev_offset.typed_data()[0];
    const int64_t off = offset.typed_data()[0];
    int32_t* po_out = pos_out->typed_data();
    std::memcpy(po_out, pos.typed_data(), (size_t)n * sizeof(int32_t));
    // the int64 histograms live in the s32 result buffer: same bytes,
    // [F, 2K, B, 2] little-endian word pairs on the wire
    auto* hq = static_cast<int64_t*>(hist_q->untyped_data());
    const auto* pq = static_cast<const int64_t*>(prev_hist_q.untyped_data());
    std::memset(hq, 0, (size_t)(F * 2 * K * B) * sizeof(int64_t));
    float* hf = hist_f->typed_data();
    std::memset(hf, 0, (size_t)(F * 2 * K * B) * sizeof(float));
    if (bins.element_type() == ffi::U8) {
        level_quant_impl(reinterpret_cast<const uint8_t*>(
                             bins.untyped_data()),
                         po_out, gh.typed_data(), ptab.typed_data(), pq, n,
                         F, B, K, Kp, poff, off, sibling_sub != 0, hq, hf);
    } else if (bins.element_type() == ffi::U16) {
        level_quant_impl(reinterpret_cast<const uint16_t*>(
                             bins.untyped_data()),
                         po_out, gh.typed_data(), ptab.typed_data(), pq, n,
                         F, B, K, Kp, poff, off, sibling_sub != 0, hq, hf);
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuTreeGrow, TreeGrowImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::F32>>()    // gh [n, 2]
        .Arg<ffi::Buffer<ffi::F32>>()    // cut_values [F, B]
        .Arg<ffi::Buffer<ffi::S32>>()    // tree_mask [F] (0/1)
        .Arg<ffi::Buffer<ffi::F32>>()    // G0 (0-d)
        .Arg<ffi::Buffer<ffi::F32>>()    // H0 (0-d)
        .Attr<int64_t>("max_depth")
        .Attr<int64_t>("B")
        .Attr<int64_t>("sibling_sub")
        .Attr<int64_t>("hist_acc")
        .Attr<float>("reg_lambda")
        .Attr<float>("reg_alpha")
        .Attr<float>("max_delta_step")
        .Attr<float>("min_child_weight")
        .Ret<ffi::Buffer<ffi::S32>>()    // pos_out [n, 1] (leaf level)
        .Ret<ffi::Buffer<ffi::PRED>>()   // is_split [max_nodes]
        .Ret<ffi::Buffer<ffi::S32>>()    // feature [max_nodes]
        .Ret<ffi::Buffer<ffi::S32>>()    // split_bin [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // split_cond [max_nodes]
        .Ret<ffi::Buffer<ffi::PRED>>()   // default_left [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // node_g [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // node_h [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>()    // node_w [max_nodes]
        .Ret<ffi::Buffer<ffi::F32>>());  // loss_chg [max_nodes]

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuHbLevelSub, HbLevelSubImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::S32>>()    // pos [n, 1] (previous level)
        .Arg<ffi::Buffer<ffi::F32>>()    // gh [n, 2]
        .Arg<ffi::Buffer<ffi::F32>>()    // ptab [Kp, 4]
        .Arg<ffi::Buffer<ffi::F32>>()    // prev_hist [F, 2Kp, B]
        .Arg<ffi::Buffer<ffi::S32>>()    // prev_offset (0-d)
        .Arg<ffi::Buffer<ffi::S32>>()    // offset (0-d)
        .Attr<int64_t>("K")
        .Attr<int64_t>("Kp")
        .Attr<int64_t>("B")
        .Ret<ffi::Buffer<ffi::S32>>()    // pos_out [n, 1]
        .Ret<ffi::Buffer<ffi::F32>>());  // hist [F, 2K, B]

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuHbLevelQuant, HbLevelQuantImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::S32>>()    // pos [n, 1] (previous level)
        .Arg<ffi::Buffer<ffi::F32>>()    // gh [n, 2]
        .Arg<ffi::Buffer<ffi::F32>>()    // ptab [max(Kp, 1), 4]
        .Arg<ffi::Buffer<ffi::S32>>()    // prev_hist_q [F, 2Kp, B, 2]
        .Arg<ffi::Buffer<ffi::S32>>()    // prev_offset (0-d)
        .Arg<ffi::Buffer<ffi::S32>>()    // offset (0-d)
        .Attr<int64_t>("K")
        .Attr<int64_t>("Kp")
        .Attr<int64_t>("B")
        .Attr<int64_t>("sibling_sub")
        .Ret<ffi::Buffer<ffi::S32>>()    // pos_out [n, 1]
        .Ret<ffi::Buffer<ffi::S32>>()    // hist_q [F, 2K, B, 2]
        .Ret<ffi::Buffer<ffi::F32>>());  // hist_f [F, 2K, B]
