// Native batched forest traversal for CPU serving (the inplace-predict
// fast path). Reference analog: src/predictor/cpu_predictor.cc — the
// block-of-64-rows PredictBatchByBlockOfRowsKernel. The XLA gather walk
// (predictor/__init__.py:_walk_leaves) is the right shape for
// device-resident training-loop predicts, but XLA:CPU lowers each
// (tree, level) step to a generic gather at ~2-3ns/element; a pointer
// chase over the same padded SoA arrays runs an order of magnitude
// faster, which is the whole margin a serving frontend lives on.
//
// Layout contract (predictor/serving.py:_HostForest): all arrays are the
// StackedForest tensors pulled to host, C-contiguous:
//   left/right/feature  int32  [T, N]
//   cond                float  [T, N]  (leaf value at leaves)
//   default_left        uint8  [T, N]
//   tree_group          int32  [T]
//   tree_weights        float  [T]    (DART scaling; ones otherwise)
// Missing values are NaN and route to the default child; categorical
// forests never take this path (the caller gates on has_cats).
//
// Accumulation is double per (row, group) so the result is independent of
// tree order and within 1 ulp of the f32 ideal — the parity contract with
// the XLA path is |diff| < 1e-5 on margins.
//
// Build (native/__init__.py:get_serving_lib): g++ -O3 -march=native
//   -fopenmp (falls back to single-thread when OpenMP is unavailable).

#include <cmath>
#include <cstdint>

#ifdef _OPENMP
#include <omp.h>
#endif

extern "C" {

// out[n, K] = base[n, K] + sum over trees; returns 0 on success
int sv_predict_dense(const float *X, int64_t n, int64_t F,
                     const int32_t *left, const int32_t *right,
                     const int32_t *feature, const float *cond,
                     const uint8_t *default_left, const int32_t *tree_group,
                     const float *tree_weights, int64_t T, int64_t N,
                     const float *base, float *out, int64_t K) {
  if (K <= 0 || n < 0 || T < 0) return 1;
  constexpr int64_t kBlock = 64;  // rows per block: tree tables stay in L1
  // small batches stay single-threaded: a serving stream of tiny requests
  // must not pay team spawn + post-region spin-wait per call (libgomp
  // spins after parallel regions; thousands of small predicts interleaved
  // with XLA's own thread pool oversubscribe a cgroup-throttled host)
#ifdef _OPENMP
#pragma omp parallel for schedule(static) if (n >= 8192)
#endif
  for (int64_t b = 0; b < n; b += kBlock) {
    const int64_t hi = b + kBlock < n ? b + kBlock : n;
    double acc[kBlock * 8];     // K <= 8 fast path; larger K heap-allocs
    double *accp = acc;
    double *heap = nullptr;
    if (K > 8) {
      heap = new double[kBlock * K];
      accp = heap;
    }
    for (int64_t i = b; i < hi; ++i)
      for (int64_t k = 0; k < K; ++k)
        accp[(i - b) * K + k] = base[i * K + k];
    for (int64_t t = 0; t < T; ++t) {
      const int32_t *lc = left + t * N;
      const int32_t *rc = right + t * N;
      const int32_t *fi = feature + t * N;
      const float *co = cond + t * N;
      const uint8_t *dl = default_left + t * N;
      const double w = tree_weights[t];
      const int64_t g = tree_group[t];
      for (int64_t i = b; i < hi; ++i) {
        const float *x = X + i * F;
        int32_t pos = 0;
        // bounded by N: a valid tree's walk visits < N nodes, and a
        // malformed model (cyclic children in an untrusted JSON) must
        // terminate like the XLA walk's fixed fori_loop does
        for (int64_t step = 0; step < N && lc[pos] != -1; ++step) {
          const float v = x[fi[pos]];
          const bool go_left = std::isnan(v) ? (dl[pos] != 0) : (v < co[pos]);
          pos = go_left ? lc[pos] : rc[pos];
        }
        accp[(i - b) * K + g] += static_cast<double>(co[pos]) * w;
      }
    }
    for (int64_t i = b; i < hi; ++i)
      for (int64_t k = 0; k < K; ++k)
        out[i * K + k] = static_cast<float>(accp[(i - b) * K + k]);
    delete[] heap;
  }
  return 0;
}

// CSR rows: absent entries are missing (NaN semantics) without
// densification — the zero-copy CSR serving path. indptr is int64[n+1],
// indices int32[nnz], values float[nnz] (caller-normalized dtypes).
// Returns 0 ok, 1 bad arguments, 2 out-of-range column index (scipy does
// NOT bounds-check caller-built index arrays, and an unchecked index
// would be an OOB write into the row buffer — the check lives here, next
// to the scatter, so hot-path callers don't pre-scan the indices).
int sv_predict_csr(const int64_t *indptr, const int32_t *indices,
                   const float *values, int64_t n, int64_t F,
                   const int32_t *left, const int32_t *right,
                   const int32_t *feature, const float *cond,
                   const uint8_t *default_left, const int32_t *tree_group,
                   const float *tree_weights, int64_t T, int64_t N,
                   const float *base, float *out, int64_t K) {
  if (K <= 0 || n < 0 || T < 0) return 1;
  const float kNaN = std::nanf("");
  int bad_index = 0;  // benign racy writes: every writer stores 1
#ifdef _OPENMP
#pragma omp parallel if (n >= 8192)
#endif
  {
    // Fill/Drop discipline (reference cpu_predictor.cc FVec): the row
    // buffer is NaN-initialized ONCE per thread; after each row's walk,
    // only the indices that row actually set are reset — O(nnz + walk)
    // per row instead of O(F), which matters for wide one-hot matrices
    float *row = new float[F];
    for (int64_t f = 0; f < F; ++f) row[f] = kNaN;
    double *acc = new double[K];
#ifdef _OPENMP
#pragma omp for schedule(static)
#endif
    for (int64_t i = 0; i < n; ++i) {
      if (bad_index) continue;  // poisoned: result will be discarded
      for (int64_t e = indptr[i]; e < indptr[i + 1]; ++e) {
        const int32_t c = indices[e];
        if (c < 0 || c >= F) {
          bad_index = 1;
          break;
        }
        row[c] = values[e];
      }
      if (bad_index) continue;
      for (int64_t k = 0; k < K; ++k) acc[k] = base[i * K + k];
      for (int64_t t = 0; t < T; ++t) {
        const int32_t *lc = left + t * N;
        const int32_t *rc = right + t * N;
        const int32_t *fi = feature + t * N;
        const float *co = cond + t * N;
        const uint8_t *dl = default_left + t * N;
        int32_t pos = 0;
        for (int64_t step = 0; step < N && lc[pos] != -1; ++step) {
          const float v = row[fi[pos]];
          const bool go_left = std::isnan(v) ? (dl[pos] != 0) : (v < co[pos]);
          pos = go_left ? lc[pos] : rc[pos];
        }
        acc[tree_group[t]] +=
            static_cast<double>(co[pos]) * tree_weights[t];
      }
      for (int64_t k = 0; k < K; ++k)
        out[i * K + k] = static_cast<float>(acc[k]);
      for (int64_t e = indptr[i]; e < indptr[i + 1]; ++e)
        row[indices[e]] = kNaN;  // drop: indices validated above
    }
    delete[] row;
    delete[] acc;
  }
  return bad_index ? 2 : 0;
}

}  // extern "C"
