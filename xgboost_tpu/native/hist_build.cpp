// Native level-histogram + partition kernel for the CPU training path,
// registered as an XLA FFI custom call.
//
// The XLA fallback (`tree/hist_kernel.py:fused_level_xla`) builds the level
// histogram with jax.ops.segment_sum; XLA:CPU lowers that to a serialized
// per-update scatter whose cost was measured at ~68ns per (row, feature)
// element regardless of table size or update width — at the headline bench
// shape (100k x 50, bin64, depth 6) that single op IS the round (~6 x 345ms
// of a ~2s round on the bench container). This kernel is the reference's
// GHistBuilder (hist_util.h:323) move: a plain C loop over rows doing the
// same f32 additions IN THE SAME ORDER (row-major, rows ascending per
// segment), measured ~7ms per level — and bit-identical to the XLA
// segment_sum result standalone (in-program results differ only by XLA's
// own fusion rounding).
//
// Why an FFI custom call and not jax.pure_callback: on a single-core CPU
// client, callback operands arrive as jax arrays whose backing copy is
// queued on the SAME (size-1) thread pool that is blocked executing the
// program — converting them (np.asarray) deadlocks and reading their
// buffer pointer races the in-flight copy (observed: zeros beyond ~1MB).
// An FFI handler runs synchronously inside the thunk with materialized
// operand buffers: correct by construction, no Python, no GIL.
//
// Bins stay in their narrow storage dtype end to end (uint8 below 256
// bins, uint16 above — the int8 bin-packing half of the ISSUE 13
// tentpole): the kernel reads the quantized matrix exactly as the DMatrix
// stores it; no widened int32 copy anywhere on the path.
//
// The partition step (route rows through the previous level's decision
// table) rides in the same pass: it is a handful of scalar ops per row
// and folding it here saves the [n, Kp] one-hot matmul the XLA path pays.
// Decision semantics mirror `partition_apply_xla` exactly (numerical
// table layout [Kp, 4]: is_split, feature, bin, default_left; missing ==
// bin >= B goes the default direction). Categorical tables (W > 4) never
// reach this kernel — the dispatcher routes them to XLA. The heap
// offsets arrive as 0-d i32 OPERANDS (not attributes) so the
// depth-scanned grow can feed them from the traced scan counter.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

namespace {

// ---- opt-in in-kernel guard mode (XGBTPU_NATIVE_GUARD=1) ---------------
//
// The decision table's feature column drives an UNCHECKED read of
// bins[i * F + f] in both loops below — a corrupted ptab row is a wild
// read. Guard mode validates every active row up front and returns a
// typed ffi::Error instead. The env var is read per call (no static
// latch) so in-process tests can flip it between dispatches; the check
// is O(Kp), never O(n), so even guards-on cost is negligible.

bool guard_enabled() {
    const char* v = std::getenv("XGBTPU_NATIVE_GUARD");
    return v != nullptr && v[0] == '1' && v[1] == '\0';
}

// First split row whose feature index falls outside [0, F), or -1.
int64_t bad_ptab_feature(const float* ptab, int64_t rows, int64_t F) {
    for (int64_t k = 0; k < rows; ++k) {
        const float* dec = ptab + k * 4;
        if (dec[0] <= 0.5f) continue;  // inactive row: never dereferenced
        const int64_t f = (int64_t)dec[1];
        if (f < 0 || f >= F) return k;
    }
    return -1;
}

ffi::Error ptab_guard_error(int64_t row) {
    return ffi::Error(
        ffi::ErrorCode::kOutOfRange,
        "XGBTPU_NATIVE_GUARD: decision table row " + std::to_string(row) +
            " has a feature index outside [0, F)");
}

// Core loop shared by the level handler: route row i through the previous
// level's decision (when Kp > 0), then accumulate (g, h) into hist.
template <typename BinT>
void level_loop(const BinT* bins, int32_t* pos, const float* gh,
                const float* ptab, int64_t n, int64_t F, int64_t B,
                int64_t K, int64_t Kp, int64_t prev_offset, int64_t offset,
                float* hist /* [F, 2K, B] zero-initialised */) {
    const int64_t feat_stride = 2 * K * B;
    for (int64_t i = 0; i < n; ++i) {
        int32_t p = pos[i];
        if (Kp > 0) {
            const int64_t lp = (int64_t)p - prev_offset;
            if (lp >= 0 && lp < Kp) {
                const float* dec = ptab + lp * 4;
                if (dec[0] > 0.5f) {  // is_split
                    const int64_t f = (int64_t)dec[1];
                    const int64_t bv = (int64_t)bins[i * F + f];
                    const bool left =
                        (bv >= B) ? (dec[3] > 0.5f)       // missing: default
                                  : ((float)bv <= dec[2]);
                    p = 2 * p + (left ? 1 : 2);
                    pos[i] = p;
                }
            }
        }
        const int64_t s = (int64_t)p - offset;
        if (s < 0 || s >= K) continue;
        const float g = gh[2 * i], h = gh[2 * i + 1];
        float* gbase = hist + s * B;
        const BinT* br = bins + i * F;
        for (int64_t f = 0; f < F; ++f) {
            const int64_t bv = br[f];
            if (bv >= B) continue;  // missing: recovered as total - sum
            float* cell = gbase + f * feat_stride + bv;
            cell[0] += g;
            cell[K * B] += h;
        }
    }
}

template <typename BinT>
void partition_loop(const BinT* bins, int32_t* pos, const float* ptab,
                    int64_t n, int64_t F, int64_t B, int64_t Kp,
                    int64_t prev_offset) {
    for (int64_t i = 0; i < n; ++i) {
        const int32_t p = pos[i];
        const int64_t lp = (int64_t)p - prev_offset;
        if (lp < 0 || lp >= Kp) continue;
        const float* dec = ptab + lp * 4;
        if (dec[0] <= 0.5f) continue;
        const int64_t f = (int64_t)dec[1];
        const int64_t bv = (int64_t)bins[i * F + f];
        const bool left = (bv >= B) ? (dec[3] > 0.5f) : ((float)bv <= dec[2]);
        pos[i] = 2 * p + (left ? 1 : 2);
    }
}

ffi::Error HbLevelImpl(ffi::AnyBuffer bins, ffi::Buffer<ffi::S32> pos,
                       ffi::Buffer<ffi::F32> gh, ffi::Buffer<ffi::F32> ptab,
                       ffi::Buffer<ffi::S32> prev_offset,
                       ffi::Buffer<ffi::S32> offset, int64_t K, int64_t Kp,
                       int64_t B,
                       ffi::Result<ffi::Buffer<ffi::S32>> pos_out,
                       ffi::Result<ffi::Buffer<ffi::F32>> hist) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    const int64_t n = dims[0], F = dims[1];
    if ((int64_t)ptab.element_count() < Kp * 4) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "ptab must hold at least Kp rows of 4");
    }
    if (guard_enabled()) {
        const int64_t bad = bad_ptab_feature(ptab.typed_data(), Kp, F);
        if (bad >= 0) return ptab_guard_error(bad);
    }
    const int64_t po = prev_offset.typed_data()[0];
    const int64_t off = offset.typed_data()[0];
    int32_t* po_out = pos_out->typed_data();
    std::memcpy(po_out, pos.typed_data(), n * sizeof(int32_t));
    float* h = hist->typed_data();
    std::memset(h, 0, (size_t)(F * 2 * K * B) * sizeof(float));
    if (bins.element_type() == ffi::U8) {
        level_loop(reinterpret_cast<const uint8_t*>(bins.untyped_data()),
                   po_out, gh.typed_data(), ptab.typed_data(), n, F, B, K,
                   Kp, po, off, h);
    } else if (bins.element_type() == ffi::U16) {
        level_loop(reinterpret_cast<const uint16_t*>(bins.untyped_data()),
                   po_out, gh.typed_data(), ptab.typed_data(), n, F, B, K,
                   Kp, po, off, h);
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

ffi::Error HbPartitionImpl(ffi::AnyBuffer bins, ffi::Buffer<ffi::S32> pos,
                           ffi::Buffer<ffi::F32> ptab, int64_t Kp,
                           int64_t B, int64_t prev_offset,
                           ffi::Result<ffi::Buffer<ffi::S32>> pos_out) {
    const auto dims = bins.dimensions();
    if (dims.size() != 2) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be [n, F]");
    }
    const int64_t n = dims[0], F = dims[1];
    if ((int64_t)ptab.element_count() < Kp * 4) {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "ptab must hold at least Kp rows of 4");
    }
    if (guard_enabled()) {
        const int64_t bad = bad_ptab_feature(ptab.typed_data(), Kp, F);
        if (bad >= 0) return ptab_guard_error(bad);
    }
    int32_t* po_out = pos_out->typed_data();
    std::memcpy(po_out, pos.typed_data(), n * sizeof(int32_t));
    if (bins.element_type() == ffi::U8) {
        partition_loop(reinterpret_cast<const uint8_t*>(bins.untyped_data()),
                       po_out, ptab.typed_data(), n, F, B, Kp, prev_offset);
    } else if (bins.element_type() == ffi::U16) {
        partition_loop(reinterpret_cast<const uint16_t*>(bins.untyped_data()),
                       po_out, ptab.typed_data(), n, F, B, Kp, prev_offset);
    } else {
        return ffi::Error(ffi::ErrorCode::kInvalidArgument,
                          "bins must be uint8 or uint16");
    }
    return ffi::Error::Success();
}

}  // namespace

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuHbLevel, HbLevelImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::S32>>()    // pos [n, 1]
        .Arg<ffi::Buffer<ffi::F32>>()    // gh [n, 2]
        .Arg<ffi::Buffer<ffi::F32>>()    // ptab [Kp|K, 4]
        .Arg<ffi::Buffer<ffi::S32>>()    // prev_offset (0-d)
        .Arg<ffi::Buffer<ffi::S32>>()    // offset (0-d)
        .Attr<int64_t>("K")
        .Attr<int64_t>("Kp")
        .Attr<int64_t>("B")
        .Ret<ffi::Buffer<ffi::S32>>()    // pos_out [n, 1]
        .Ret<ffi::Buffer<ffi::F32>>());  // hist [F, 2K, B]

XLA_FFI_DEFINE_HANDLER_SYMBOL(
    XgbtpuHbPartition, HbPartitionImpl,
    ffi::Ffi::Bind()
        .Arg<ffi::AnyBuffer>()           // bins [n, F] u8/u16
        .Arg<ffi::Buffer<ffi::S32>>()    // pos [n, 1]
        .Arg<ffi::Buffer<ffi::F32>>()    // ptab [Kp, 4]
        .Attr<int64_t>("Kp")
        .Attr<int64_t>("B")
        .Attr<int64_t>("prev_offset")
        .Ret<ffi::Buffer<ffi::S32>>());  // pos_out [n, 1]
