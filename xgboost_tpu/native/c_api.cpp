// C API for xgboost_tpu — the reference's include/xgboost/c_api.h surface
// (signature-compatible core subset) realized over the Python-first TPU
// runtime by EMBEDDING CPython: each exported function acquires the GIL
// (initializing an interpreter first when the host process is not Python —
// e.g. a C program dlopen'ing this library) and forwards to the
// xgboost_tpu package. This is the reverse of the reference's layering
// (its Python package wraps libxgboost.so; here the native library wraps
// the Python package) but presents the same ABI to C callers:
//   XGBGetLastError                  c_api.h:64
//   XGDMatrixCreateFromMat           c_api.h:186
//   XGDMatrixCreateFromFile          c_api.h:132
//   XGDMatrixSetFloatInfo/GetFloatInfo, SetUIntInfo
//   XGDMatrixNumRow/NumCol/Free
//   XGBoosterCreate/Free/SetParam    c_api.h:747,760,795
//   XGBoosterUpdateOneIter           c_api.h:807
//   XGBoosterBoostOneIter            c_api.h:820
//   XGBoosterEvalOneIter             c_api.h:835
//   XGBoosterPredict                 c_api.h:865 (option_mask 0/1)
//   XGBoosterPredictFromDense/CSR    c_api.cc:833 (zero-copy inplace)
//   XGBoosterSaveModel/LoadModel, XGBoosterGetNumFeature
//   XGBoosterSerializeToBuffer/UnserializeFromBuffer  c_api.h:1030 (model
//     + learner configuration — the full-state pair Save/LoadModel drops)
//   XGBoosterSaveJsonConfig/LoadJsonConfig            c_api.h:990
//   XGDMatrixSliceDMatrix                             c_api.h:240
//   XGBoosterSetStrFeatureInfo/GetStrFeatureInfo      c_api.h:1146,1182
//   XGBoosterSetAttr/GetAttr, XGBVersion
// Error contract matches the reference: every call returns 0 on success,
// -1 on failure with the message retrievable via XGBGetLastError().
//
// Build (native/__init__.py:load_capi): g++ -shared -fPIC c_api.cpp
//   $(python3-config --includes) $(python3-config --ldflags --embed)
//   -DXGBTPU_ROOT=... -DXGBTPU_SITE=...

#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#define XGB_DLL extern "C" __attribute__((visibility("default")))

typedef uint64_t bst_ulong;
typedef void *DMatrixHandle;
typedef void *BoosterHandle;

static thread_local std::string g_last_error;

#ifndef XGBTPU_ROOT
#define XGBTPU_ROOT ""
#endif
#ifndef XGBTPU_SITE
#define XGBTPU_SITE ""
#endif

static void ensure_python() {
  static std::once_flag once;
  std::call_once(once, [] {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // the embedded interpreter must see the venv's site-packages (jax,
      // numpy) and the repo root (xgboost_tpu); both are baked at build
      // time and overridable via the environment
      PyRun_SimpleString(
          "import sys, os\n"
          "for p in (os.environ.get('XGBTPU_SITE', '" XGBTPU_SITE "'),\n"
          "          os.environ.get('XGBTPU_ROOT', '" XGBTPU_ROOT "')):\n"
          "    if p and p not in sys.path:\n"
          "        sys.path.insert(0, p)\n");
      // release the GIL the initializer holds: every API entry point
      // re-acquires via PyGILState_Ensure (works for foreign threads too)
      PyEval_SaveThread();
    }
  });
}

namespace {

struct Gil {
  PyGILState_STATE st;
  Gil() {
    ensure_python();
    st = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(st); }
};

int fail() {  // capture the live Python exception into g_last_error
  PyObject *t = nullptr, *v = nullptr, *tb = nullptr;
  PyErr_Fetch(&t, &v, &tb);
  PyErr_NormalizeException(&t, &v, &tb);
  g_last_error = "unknown error";
  if (v != nullptr) {
    PyObject *s = PyObject_Str(v);
    if (s != nullptr) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c != nullptr) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(t);
  Py_XDECREF(v);
  Py_XDECREF(tb);
  return -1;
}

int fail_msg(const char *msg) {
  PyErr_Clear();
  g_last_error = msg;
  return -1;
}

// borrowed-module helper (Python caches imports; no refcount juggling of
// long-lived module objects across handles)
PyObject *imp(const char *name) { return PyImport_ImportModule(name); }

struct MatWrap {
  explicit MatWrap(PyObject *o) : obj(o) {}
  PyObject *obj;  // xgboost_tpu.DMatrix
  std::vector<float> finfo;  // GetFloatInfo out-buffer
  std::vector<unsigned> uinfo;  // GetUIntInfo out-buffer
};

struct BoosterWrap {
  explicit BoosterWrap(PyObject *o) : obj(o) {}
  PyObject *obj;  // xgboost_tpu.Booster
  std::vector<float> pred;  // XGBoosterPredict out-buffer
  std::string eval_out;     // XGBoosterEvalOneIter out-string
  std::string attr_out;     // XGBoosterGetAttr out-string
  std::string raw_out;      // XGBoosterSaveModelToBuffer out-bytes
  std::string serialize_out;  // XGBoosterSerializeToBuffer out-bytes
  std::string config_out;     // XGBoosterSaveJsonConfig out-string
  std::vector<bst_ulong> pred_shape;  // PredictFromDMatrix out-shape
  std::vector<std::string> dump;      // XGBoosterDumpModel storage
  std::vector<const char *> dump_ptrs;
  std::vector<std::string> feat_info;  // GetStrFeatureInfo storage
  std::vector<const char *> feat_ptrs;
};


// float buffer -> numpy float32 array (copy), shaped [n] or [rows, cols]
PyObject *np_from(const float *data, bst_ulong n, bst_ulong rows = 0,
                  bst_ulong cols = 0) {
  PyObject *np = imp("numpy");
  if (np == nullptr) return nullptr;
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<float *>(data)),
      static_cast<Py_ssize_t>(n * sizeof(float)), PyBUF_READ);
  if (mv == nullptr) return nullptr;
  PyObject *r = PyObject_CallMethod(np, "frombuffer", "Os", mv, "float32");
  Py_DECREF(mv);
  if (r == nullptr) return nullptr;
  PyObject *copy = PyObject_CallMethod(r, "copy", nullptr);
  Py_DECREF(r);
  if (copy == nullptr) return nullptr;
  if (rows != 0) {
    PyObject *shaped = PyObject_CallMethod(
        copy, "reshape", "(nn)", static_cast<Py_ssize_t>(rows),
        static_cast<Py_ssize_t>(cols));
    Py_DECREF(copy);
    return shaped;
  }
  return copy;
}

// unsigned buffer -> numpy int64 array (copy). Reading the uint32 payload
// directly keeps values >= 2^24 exact — a float32 detour would round them
PyObject *np_from_uint(const unsigned *data, bst_ulong n) {
  PyObject *np = imp("numpy");
  if (np == nullptr) return nullptr;
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<unsigned *>(data)),
      static_cast<Py_ssize_t>(n * sizeof(unsigned)), PyBUF_READ);
  if (mv == nullptr) return nullptr;
  PyObject *r = PyObject_CallMethod(np, "frombuffer", "Os", mv, "uint32");
  Py_DECREF(mv);
  if (r == nullptr) return nullptr;
  PyObject *i64 = PyObject_CallMethod(r, "astype", "s", "int64");  // copy
  Py_DECREF(r);
  return i64;
}

// DMatrix.set_info is keyword-only: call set_info(**{field: value})
int set_info_kw(PyObject *dmat, const char *field, PyObject *value) {
  PyObject *meth = PyObject_GetAttrString(dmat, "set_info");
  PyObject *args = PyTuple_New(0);
  PyObject *kw = PyDict_New();
  if (meth == nullptr || args == nullptr || kw == nullptr) {
    Py_XDECREF(meth);
    Py_XDECREF(args);
    Py_XDECREF(kw);
    return fail();
  }
  PyDict_SetItemString(kw, field, value);
  PyObject *r = PyObject_Call(meth, args, kw);
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kw);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

// numpy array -> this->buf (float32 ravel); returns 0/-1
int np_to(PyObject *arr, std::vector<float> *buf) {
  PyObject *np = imp("numpy");
  if (np == nullptr) return fail();
  PyObject *flat = PyObject_CallMethod(np, "ascontiguousarray", "Os", arr,
                                       "float32");
  if (flat == nullptr) return fail();
  PyObject *rav = PyObject_CallMethod(flat, "ravel", nullptr);
  Py_DECREF(flat);
  if (rav == nullptr) return fail();
  PyObject *bytes = PyObject_CallMethod(rav, "tobytes", nullptr);
  Py_ssize_t nb = 0;
  char *raw = nullptr;
  if (bytes == nullptr || PyBytes_AsStringAndSize(bytes, &raw, &nb) != 0) {
    Py_XDECREF(bytes);
    Py_DECREF(rav);
    return fail();
  }
  buf->resize(static_cast<size_t>(nb) / sizeof(float));
  std::memcpy(buf->data(), raw, static_cast<size_t>(nb));
  Py_DECREF(bytes);
  Py_DECREF(rav);
  return 0;
}

}  // namespace

XGB_DLL const char *XGBGetLastError(void) { return g_last_error.c_str(); }

XGB_DLL void XGBVersion(int *major, int *minor, int *patch) {
  if (major) *major = 2;
  if (minor) *minor = 0;
  if (patch) *patch = 0;
}

// ---------------------------------------------------------------- DMatrix

XGB_DLL int XGDMatrixCreateFromMat(const float *data, bst_ulong nrow,
                                   bst_ulong ncol, float missing,
                                   DMatrixHandle *out) {
  Gil gil;
  PyObject *arr = np_from(data, nrow * ncol, nrow, ncol);
  if (arr == nullptr) return fail();
  // reference semantics: entries equal to `missing` are treated missing
  // (NaN missing needs no rewrite — NaN == NaN is false anyway)
  if (!std::isnan(missing)) {
    PyObject *np = imp("numpy");
    PyObject *nan = PyFloat_FromDouble(NAN);
    PyObject *m = PyFloat_FromDouble(static_cast<double>(missing));
    PyObject *eq = PyObject_CallMethod(arr, "__eq__", "O", m);
    PyObject *where = (np && nan && eq)
        ? PyObject_CallMethod(np, "where", "OOO", eq, nan, arr) : nullptr;
    Py_XDECREF(eq);
    Py_XDECREF(m);
    Py_XDECREF(nan);
    Py_DECREF(arr);
    if (where == nullptr) return fail();
    PyObject *f32 = PyObject_CallMethod(where, "astype", "s", "float32");
    Py_DECREF(where);
    if (f32 == nullptr) return fail();
    arr = f32;
  }
  PyObject *mod = imp("xgboost_tpu");
  if (mod == nullptr) {
    Py_DECREF(arr);
    return fail();
  }
  PyObject *d = PyObject_CallMethod(mod, "DMatrix", "O", arr);
  Py_DECREF(arr);
  if (d == nullptr) return fail();
  auto *w = new MatWrap(d);
  *out = w;
  return 0;
}

XGB_DLL int XGDMatrixCreateFromFile(const char *fname, int /*silent*/,
                                    DMatrixHandle *out) {
  Gil gil;
  PyObject *mod = imp("xgboost_tpu");
  if (mod == nullptr) return fail();
  PyObject *d = PyObject_CallMethod(mod, "DMatrix", "s", fname);
  if (d == nullptr) return fail();
  *out = new MatWrap(d);
  return 0;
}

XGB_DLL int XGDMatrixSetFloatInfo(DMatrixHandle handle, const char *field,
                                  const float *data, bst_ulong len) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *arr = np_from(data, len);
  if (arr == nullptr) return fail();
  int rc = set_info_kw(w->obj, field, arr);
  Py_DECREF(arr);
  return rc;
}

XGB_DLL int XGDMatrixSetUIntInfo(DMatrixHandle handle, const char *field,
                                 const unsigned *data, bst_ulong len) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *i64 = np_from_uint(data, len);
  if (i64 == nullptr) return fail();
  int rc = set_info_kw(w->obj, field, i64);
  Py_DECREF(i64);
  return rc;
}

XGB_DLL int XGDMatrixGetFloatInfo(DMatrixHandle handle, const char *field,
                                  bst_ulong *out_len,
                                  const float **out_dptr) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "get_float_info", "s", field);
  if (r == nullptr) return fail();
  int rc = np_to(r, &w->finfo);
  Py_DECREF(r);
  if (rc != 0) return rc;
  *out_len = static_cast<bst_ulong>(w->finfo.size());
  *out_dptr = w->finfo.data();
  return 0;
}

XGB_DLL int XGDMatrixGetUIntInfo(DMatrixHandle handle, const char *field,
                                 bst_ulong *out_len,
                                 const unsigned **out_dptr) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "get_uint_info", "s", field);
  if (r == nullptr) return fail();
  PyObject *np = imp("numpy");
  PyObject *flat = np == nullptr ? nullptr : PyObject_CallMethod(
      np, "ascontiguousarray", "Os", r, "uint32");
  Py_DECREF(r);
  if (flat == nullptr) return fail();
  PyObject *bytes = PyObject_CallMethod(flat, "tobytes", nullptr);
  Py_DECREF(flat);
  Py_ssize_t nb = 0;
  char *raw = nullptr;
  if (bytes == nullptr || PyBytes_AsStringAndSize(bytes, &raw, &nb) != 0) {
    Py_XDECREF(bytes);
    return fail();
  }
  w->uinfo.resize(static_cast<size_t>(nb) / sizeof(unsigned));
  std::memcpy(w->uinfo.data(), raw, static_cast<size_t>(nb));
  Py_DECREF(bytes);
  *out_len = static_cast<bst_ulong>(w->uinfo.size());
  *out_dptr = w->uinfo.data();
  return 0;
}

XGB_DLL int XGDMatrixNumRow(DMatrixHandle handle, bst_ulong *out) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "num_row", nullptr);
  if (r == nullptr) return fail();
  *out = static_cast<bst_ulong>(PyLong_AsUnsignedLongLong(r));
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGDMatrixNumCol(DMatrixHandle handle, bst_ulong *out) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "num_col", nullptr);
  if (r == nullptr) return fail();
  *out = static_cast<bst_ulong>(PyLong_AsUnsignedLongLong(r));
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGDMatrixSliceDMatrix(DMatrixHandle handle, const int *idxset,
                                  bst_ulong len, DMatrixHandle *out) {
  // reference c_api.h:240: a new DMatrix holding the selected rows with
  // per-row metadata sliced along (serving-side train/validate splits
  // without re-ingesting the data)
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  PyObject *np = imp("numpy");
  if (np == nullptr) return fail();
  PyObject *mv = PyMemoryView_FromMemory(
      reinterpret_cast<char *>(const_cast<int *>(idxset)),
      static_cast<Py_ssize_t>(len * sizeof(int)), PyBUF_READ);
  if (mv == nullptr) return fail();
  PyObject *raw = PyObject_CallMethod(np, "frombuffer", "Os", mv, "int32");
  Py_DECREF(mv);
  if (raw == nullptr) return fail();
  PyObject *idx = PyObject_CallMethod(raw, "astype", "s", "int64");  // copy
  Py_DECREF(raw);
  if (idx == nullptr) return fail();
  PyObject *d = PyObject_CallMethod(w->obj, "slice", "O", idx);
  Py_DECREF(idx);
  if (d == nullptr) return fail();
  *out = new MatWrap(d);
  return 0;
}

XGB_DLL int XGDMatrixFree(DMatrixHandle handle) {
  Gil gil;
  auto *w = static_cast<MatWrap *>(handle);
  Py_XDECREF(w->obj);
  delete w;
  return 0;
}

XGB_DLL int XGDMatrixCreateFromCSREx(const size_t *indptr,
                                     const unsigned *indices,
                                     const float *data, size_t nindptr,
                                     size_t nelem, size_t num_col,
                                     DMatrixHandle *out) {
  // c_api.h:114 — CSR ingestion straight into the sparse (never-densified)
  // storage path via scipy.sparse.csr_matrix
  Gil gil;
  PyObject *np = imp("numpy");
  PyObject *sp = imp("scipy.sparse");
  PyObject *mod = imp("xgboost_tpu");
  if (np == nullptr || sp == nullptr || mod == nullptr) return fail();
  auto arr1d = [&](const void *ptr, size_t n, size_t itemsize,
                   const char *dtype) -> PyObject * {
    PyObject *mv = PyMemoryView_FromMemory(
        reinterpret_cast<char *>(const_cast<void *>(ptr)),
        static_cast<Py_ssize_t>(n * itemsize), PyBUF_READ);
    if (mv == nullptr) return nullptr;
    PyObject *r = PyObject_CallMethod(np, "frombuffer", "Os", mv, dtype);
    Py_DECREF(mv);
    if (r == nullptr) return nullptr;
    PyObject *c = PyObject_CallMethod(r, "copy", nullptr);
    Py_DECREF(r);
    return c;
  };
  PyObject *pi = arr1d(indptr, nindptr, sizeof(size_t), "uint64");
  PyObject *px = arr1d(indices, nelem, sizeof(unsigned), "uint32");
  PyObject *pv = arr1d(data, nelem, sizeof(float), "float32");
  PyObject *csr = nullptr, *d = nullptr;
  if (pi != nullptr && px != nullptr && pv != nullptr) {
    PyObject *inner = Py_BuildValue("(OOO)", pv, px, pi);
    PyObject *shape = Py_BuildValue(
        "(nn)", static_cast<Py_ssize_t>(nindptr - 1),
        static_cast<Py_ssize_t>(num_col));
    if (inner != nullptr && shape != nullptr) {
      csr = PyObject_CallMethod(sp, "csr_matrix", "OO", inner, shape);
    }
    Py_XDECREF(inner);
    Py_XDECREF(shape);
  }
  Py_XDECREF(pi);
  Py_XDECREF(px);
  Py_XDECREF(pv);
  if (csr == nullptr) return fail();
  d = PyObject_CallMethod(mod, "DMatrix", "O", csr);
  Py_DECREF(csr);
  if (d == nullptr) return fail();
  *out = new MatWrap(d);
  return 0;
}

// ---------------------------------------------------------------- Booster

XGB_DLL int XGBoosterCreate(const DMatrixHandle dmats[], bst_ulong len,
                            BoosterHandle *out) {
  Gil gil;
  PyObject *mod = imp("xgboost_tpu");
  if (mod == nullptr) return fail();
  PyObject *cache = PyList_New(static_cast<Py_ssize_t>(len));
  if (cache == nullptr) return fail();
  for (bst_ulong i = 0; i < len; ++i) {
    PyObject *o = static_cast<MatWrap *>(dmats[i])->obj;
    Py_INCREF(o);
    PyList_SET_ITEM(cache, static_cast<Py_ssize_t>(i), o);
  }
  PyObject *params = PyDict_New();
  PyObject *b = PyObject_CallMethod(mod, "Booster", "OO", params, cache);
  Py_DECREF(params);
  Py_DECREF(cache);
  if (b == nullptr) return fail();
  *out = new BoosterWrap(b);
  return 0;
}

XGB_DLL int XGBoosterFree(BoosterHandle handle) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  Py_XDECREF(w->obj);
  delete w;
  return 0;
}

XGB_DLL int XGBoosterSetParam(BoosterHandle handle, const char *name,
                              const char *value) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "set_param", "ss", name, value);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterUpdateOneIter(BoosterHandle handle, int iter,
                                   DMatrixHandle dtrain) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  auto *d = static_cast<MatWrap *>(dtrain);
  PyObject *r = PyObject_CallMethod(w->obj, "update", "Oi", d->obj, iter);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterBoostOneIter(BoosterHandle handle, DMatrixHandle dtrain,
                                  float *grad, float *hess, bst_ulong len) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  auto *d = static_cast<MatWrap *>(dtrain);
  PyObject *g = np_from(grad, len);
  PyObject *h = g != nullptr ? np_from(hess, len) : nullptr;
  if (g == nullptr || h == nullptr) {
    Py_XDECREF(g);
    Py_XDECREF(h);
    return fail();
  }
  PyObject *r = PyObject_CallMethod(w->obj, "boost", "OOO", d->obj, g, h);
  Py_DECREF(g);
  Py_DECREF(h);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterEvalOneIter(BoosterHandle handle, int iter,
                                 DMatrixHandle dmats[],
                                 const char *evnames[], bst_ulong len,
                                 const char **out_result) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *evals = PyList_New(static_cast<Py_ssize_t>(len));
  if (evals == nullptr) return fail();
  for (bst_ulong i = 0; i < len; ++i) {
    PyObject *pair = Py_BuildValue(
        "(Os)", static_cast<MatWrap *>(dmats[i])->obj, evnames[i]);
    if (pair == nullptr) {
      Py_DECREF(evals);
      return fail();
    }
    PyList_SET_ITEM(evals, static_cast<Py_ssize_t>(i), pair);
  }
  PyObject *r = PyObject_CallMethod(w->obj, "eval_set", "Oi", evals, iter);
  Py_DECREF(evals);
  if (r == nullptr) return fail();
  const char *s = PyUnicode_AsUTF8(r);
  if (s == nullptr) {
    Py_DECREF(r);
    return fail();
  }
  w->eval_out = s;
  Py_DECREF(r);
  *out_result = w->eval_out.c_str();
  return 0;
}

XGB_DLL int XGBoosterPredict(BoosterHandle handle, DMatrixHandle dmat,
                             int option_mask, unsigned ntree_limit,
                             int /*training*/, bst_ulong *out_len,
                             const float **out_result) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  auto *d = static_cast<MatWrap *>(dmat);
  if ((option_mask & ~1) != 0) {
    return fail_msg(
        "XGBoosterPredict: only option_mask 0 (value) and 1 "
        "(output_margin) are supported; use the Python API for "
        "leaf/contribution predictions");
  }
  PyObject *kw = PyDict_New();
  PyObject *args = Py_BuildValue("(O)", d->obj);
  PyObject *om = PyBool_FromLong(option_mask & 1);
  PyObject *meth = PyObject_GetAttrString(w->obj, "predict");
  int bad = (kw == nullptr || args == nullptr || om == nullptr ||
             meth == nullptr);
  if (!bad) {
    PyDict_SetItemString(kw, "output_margin", om);
    if (ntree_limit > 0) {
      // ntree_limit counts TREES, not rounds: forward it verbatim and let
      // Booster.predict divide by trees-per-round (groups x parallel
      // trees) — mapping it to iteration_range here would over-slice
      // multiclass / random-forest models (reference c_api.cc keeps the
      // same tree-count semantics)
      PyObject *ntl = PyLong_FromUnsignedLong(ntree_limit);
      if (ntl != nullptr) {
        PyDict_SetItemString(kw, "ntree_limit", ntl);
        Py_DECREF(ntl);
      }
    }
  }
  PyObject *r = bad ? nullptr : PyObject_Call(meth, args, kw);
  Py_XDECREF(meth);
  Py_XDECREF(om);
  Py_XDECREF(args);
  Py_XDECREF(kw);
  if (r == nullptr) return fail();
  int rc = np_to(r, &w->pred);
  Py_DECREF(r);
  if (rc != 0) return rc;
  *out_len = static_cast<bst_ulong>(w->pred.size());
  *out_result = w->pred.data();
  return 0;
}

XGB_DLL int XGBoosterSaveModel(BoosterHandle handle, const char *fname) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "save_model", "s", fname);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterLoadModel(BoosterHandle handle, const char *fname) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "load_model", "s", fname);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterGetNumFeature(BoosterHandle handle, bst_ulong *out) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "num_features", nullptr);
  if (r == nullptr) return fail();
  *out = static_cast<bst_ulong>(PyLong_AsUnsignedLongLong(r));
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterSetAttr(BoosterHandle handle, const char *key,
                             const char *value) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *kw = PyDict_New();
  PyObject *args = PyTuple_New(0);
  PyObject *meth = PyObject_GetAttrString(w->obj, "set_attr");
  if (kw == nullptr || args == nullptr || meth == nullptr) {
    Py_XDECREF(kw);
    Py_XDECREF(args);
    Py_XDECREF(meth);
    return fail();
  }
  if (value == nullptr) {
    PyDict_SetItemString(kw, key, Py_None);
  } else {
    PyObject *v = PyUnicode_FromString(value);
    PyDict_SetItemString(kw, key, v);
    Py_XDECREF(v);
  }
  PyObject *r = PyObject_Call(meth, args, kw);
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kw);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterGetAttr(BoosterHandle handle, const char *key,
                             const char **out, int *success) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "attr", "s", key);
  if (r == nullptr) return fail();
  if (r == Py_None) {
    *success = 0;
    *out = nullptr;
  } else {
    const char *s = PyUnicode_AsUTF8(r);
    if (s == nullptr) {
      Py_DECREF(r);
      return fail();
    }
    w->attr_out = s;
    *out = w->attr_out.c_str();
    *success = 1;
  }
  Py_DECREF(r);
  return 0;
}

namespace {

// "feature_name" / "feature_type" (reference c_api.h field grammar) ->
// the Booster property carrying it; nullptr for anything else
const char *feat_attr_for(const char *field) {
  if (field != nullptr && std::strcmp(field, "feature_name") == 0)
    return "feature_names";
  if (field != nullptr && std::strcmp(field, "feature_type") == 0)
    return "feature_types";
  return nullptr;
}

}  // namespace

XGB_DLL int XGBoosterSetStrFeatureInfo(BoosterHandle handle,
                                       const char *field,
                                       const char **features,
                                       bst_ulong size) {
  // reference c_api.h:1146: attach feature names/types to the MODEL (not
  // a DMatrix), so they survive save/load and drive dump output
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  const char *attr = feat_attr_for(field);
  if (attr == nullptr)
    return fail_msg(
        "XGBoosterSetStrFeatureInfo: field must be 'feature_name' or "
        "'feature_type'");
  PyObject *value = nullptr;
  if (size == 0) {
    value = Py_None;
    Py_INCREF(value);
  } else {
    value = PyList_New(static_cast<Py_ssize_t>(size));
    if (value == nullptr) return fail();
    for (bst_ulong i = 0; i < size; ++i) {
      PyObject *s = PyUnicode_FromString(
          features[i] == nullptr ? "" : features[i]);
      if (s == nullptr) {
        Py_DECREF(value);
        return fail();
      }
      PyList_SET_ITEM(value, static_cast<Py_ssize_t>(i), s);  // steals s
    }
  }
  int rc = PyObject_SetAttrString(w->obj, attr, value);
  Py_DECREF(value);
  return rc == 0 ? 0 : fail();
}

XGB_DLL int XGBoosterGetStrFeatureInfo(BoosterHandle handle,
                                       const char *field, bst_ulong *len,
                                       const char ***out_features) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  const char *attr = feat_attr_for(field);
  if (attr == nullptr)
    return fail_msg(
        "XGBoosterGetStrFeatureInfo: field must be 'feature_name' or "
        "'feature_type'");
  PyObject *r = PyObject_GetAttrString(w->obj, attr);
  if (r == nullptr) return fail();
  w->feat_info.clear();
  w->feat_ptrs.clear();
  if (r != Py_None) {
    Py_ssize_t n = PySequence_Size(r);
    if (n < 0) {
      Py_DECREF(r);
      return fail();
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject *it = PySequence_GetItem(r, i);
      const char *c = it != nullptr ? PyUnicode_AsUTF8(it) : nullptr;
      if (c == nullptr) {
        Py_XDECREF(it);
        Py_DECREF(r);
        return fail();
      }
      w->feat_info.emplace_back(c);
      Py_DECREF(it);
    }
  }
  Py_DECREF(r);
  for (auto &st : w->feat_info) w->feat_ptrs.push_back(st.c_str());
  *len = static_cast<bst_ulong>(w->feat_info.size());
  *out_features = w->feat_ptrs.data();
  return 0;
}

XGB_DLL int XGBoosterSaveModelToBuffer(BoosterHandle handle,
                                       const char * /*json_config*/,
                                       bst_ulong *out_len,
                                       const char **out_dptr) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "save_raw", "s", "json");
  if (r == nullptr) return fail();
  char *raw = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(r, &raw, &n) != 0) {
    Py_DECREF(r);
    return fail();
  }
  w->raw_out.assign(raw, static_cast<size_t>(n));
  Py_DECREF(r);
  *out_len = static_cast<bst_ulong>(w->raw_out.size());
  *out_dptr = w->raw_out.data();
  return 0;
}

XGB_DLL int XGBoosterLoadModelFromBuffer(BoosterHandle handle,
                                         const void *buf, bst_ulong len) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *b = PyBytes_FromStringAndSize(
      static_cast<const char *>(buf), static_cast<Py_ssize_t>(len));
  if (b == nullptr) return fail();
  PyObject *r = PyObject_CallMethod(w->obj, "load_model", "O", b);
  Py_DECREF(b);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterSaveJsonConfig(BoosterHandle handle,
                                    bst_ulong *out_len,
                                    char const **out_str) {
  // learner configuration as JSON (reference c_api.h:990 /
  // learner.cc:SaveConfig) — params + booster + objective, enough for
  // LoadJsonConfig to reconstruct an equivalently-configured Booster
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *r = PyObject_CallMethod(w->obj, "save_config", nullptr);
  if (r == nullptr) return fail();
  const char *s = PyUnicode_AsUTF8(r);
  if (s == nullptr) {
    Py_DECREF(r);
    return fail();
  }
  w->config_out = s;
  Py_DECREF(r);
  *out_len = static_cast<bst_ulong>(w->config_out.size());
  *out_str = w->config_out.c_str();
  return 0;
}

XGB_DLL int XGBoosterLoadJsonConfig(BoosterHandle handle,
                                    char const *config) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  if (config == nullptr) return fail_msg("LoadJsonConfig: null config");
  PyObject *r = PyObject_CallMethod(w->obj, "load_config", "s", config);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterSerializeToBuffer(BoosterHandle handle,
                                       bst_ulong *out_len,
                                       char const **out_dptr) {
  // FULL state — model AND learner configuration (reference c_api.h:1030;
  // SaveModelToBuffer drops the config). Payload is the Booster's pickle
  // state dict as JSON (json.dumps(booster.__getstate__(), default=float)
  // — the exact round-trip Booster.__deepcopy__ relies on).
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *st = PyObject_CallMethod(w->obj, "__getstate__", nullptr);
  if (st == nullptr) return fail();
  PyObject *jmod = imp("json");
  PyObject *builtins = imp("builtins");
  PyObject *dumps = jmod ? PyObject_GetAttrString(jmod, "dumps") : nullptr;
  PyObject *flt =
      builtins ? PyObject_GetAttrString(builtins, "float") : nullptr;
  PyObject *args = Py_BuildValue("(O)", st);
  PyObject *kw = PyDict_New();
  PyObject *r = nullptr;
  if (dumps != nullptr && flt != nullptr && args != nullptr &&
      kw != nullptr) {
    PyDict_SetItemString(kw, "default", flt);
    r = PyObject_Call(dumps, args, kw);
  }
  Py_XDECREF(kw);
  Py_XDECREF(args);
  Py_XDECREF(flt);
  Py_XDECREF(dumps);
  Py_DECREF(st);
  if (r == nullptr) return fail();
  const char *s = PyUnicode_AsUTF8(r);
  if (s == nullptr) {
    Py_DECREF(r);
    return fail();
  }
  w->serialize_out = s;
  Py_DECREF(r);
  *out_len = static_cast<bst_ulong>(w->serialize_out.size());
  *out_dptr = w->serialize_out.data();
  return 0;
}

XGB_DLL int XGBoosterUnserializeFromBuffer(BoosterHandle handle,
                                           const void *buf,
                                           bst_ulong len) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  if (buf == nullptr) return fail_msg("UnserializeFromBuffer: null buffer");
  PyObject *jmod = imp("json");
  if (jmod == nullptr) return fail();
  PyObject *text = PyUnicode_DecodeUTF8(
      static_cast<const char *>(buf), static_cast<Py_ssize_t>(len),
      nullptr);
  if (text == nullptr) return fail();
  PyObject *state = PyObject_CallMethod(jmod, "loads", "O", text);
  Py_DECREF(text);
  if (state == nullptr) return fail();
  PyObject *r = PyObject_CallMethod(w->obj, "__setstate__", "O", state);
  Py_DECREF(state);
  if (r == nullptr) return fail();
  Py_DECREF(r);
  return 0;
}

XGB_DLL int XGBoosterDumpModel(BoosterHandle handle, const char *fmap,
                               int with_stats, bst_ulong *out_len,
                               const char ***out_dump_array) {
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *ws = PyBool_FromLong(with_stats);
  PyObject *r = (ws == nullptr) ? nullptr : PyObject_CallMethod(
      w->obj, "get_dump", "sO", fmap == nullptr ? "" : fmap, ws);
  Py_XDECREF(ws);
  if (r == nullptr) return fail();
  Py_ssize_t n = PySequence_Size(r);
  if (n < 0) {
    Py_DECREF(r);
    return fail();
  }
  w->dump.clear();
  w->dump_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *it = PySequence_GetItem(r, i);
    const char *c = it != nullptr ? PyUnicode_AsUTF8(it) : nullptr;
    if (c == nullptr) {
      Py_XDECREF(it);
      Py_DECREF(r);
      return fail();
    }
    w->dump.emplace_back(c);
    Py_DECREF(it);
  }
  Py_DECREF(r);
  for (auto &st : w->dump) w->dump_ptrs.push_back(st.c_str());
  *out_len = static_cast<bst_ulong>(w->dump.size());
  *out_dump_array = w->dump_ptrs.data();
  return 0;
}

namespace {

// capture a predict result (1-D or 2-D numpy array) into the wrap's
// shape + flat-float buffers (shared by the DMatrix and inplace entries)
int capture_pred(BoosterWrap *w, PyObject *r, bst_ulong const **out_shape,
                 bst_ulong *out_dim, float const **out_result) {
  PyObject *shp = PyObject_GetAttrString(r, "shape");
  if (shp == nullptr) return fail();
  Py_ssize_t nd = PyTuple_Check(shp) ? PyTuple_Size(shp) : -1;
  if (nd < 0) {
    Py_DECREF(shp);
    return fail_msg("predict returned a non-array");
  }
  w->pred_shape.clear();
  for (Py_ssize_t i = 0; i < nd; ++i) {
    PyObject *dim = PyTuple_GetItem(shp, i);
    w->pred_shape.push_back(
        static_cast<bst_ulong>(PyLong_AsUnsignedLongLong(dim)));
  }
  Py_DECREF(shp);
  int rc = np_to(r, &w->pred);
  if (rc != 0) return rc;
  *out_shape = w->pred_shape.data();
  *out_dim = static_cast<bst_ulong>(w->pred_shape.size());
  *out_result = w->pred.data();
  return 0;
}

// shared body of XGBoosterPredictFromDense/CSR: `data` (borrowed ref) is a
// numpy array / scipy CSR built zero-copy over caller memory; the JSON
// config carries type (0 value / 1 margin), missing, iteration_begin/end,
// strict_shape (reference c_api.cc:833). `m` is the reference's optional
// proxy-DMatrix metadata carrier: its base_margin is forwarded when set.
int inplace_predict_common(BoosterWrap *w, PyObject *data,
                           char const *c_json_config, DMatrixHandle m,
                           bst_ulong const **out_shape, bst_ulong *out_dim,
                           float const **out_result) {
  PyObject *jmod = imp("json");
  if (jmod == nullptr) return fail();
  PyObject *cfg = PyObject_CallMethod(
      jmod, "loads", "s",
      (c_json_config == nullptr || c_json_config[0] == '\0') ? "{}"
                                                             : c_json_config);
  if (cfg == nullptr) return fail();
  long type = 0, it_begin = 0, it_end = 0, strict = 0;
  double missing = NAN;
  PyObject *v;
  if ((v = PyDict_GetItemString(cfg, "type"))) type = PyLong_AsLong(v);
  if ((v = PyDict_GetItemString(cfg, "missing")) && v != Py_None) {
    if (!PyNumber_Check(v)) {
      Py_DECREF(cfg);
      return fail_msg(
          "inplace predict: 'missing' must be a number (or null)");
    }
    missing = PyFloat_AsDouble(v);
  }
  if ((v = PyDict_GetItemString(cfg, "iteration_begin")))
    it_begin = PyLong_AsLong(v);
  if ((v = PyDict_GetItemString(cfg, "iteration_end")))
    it_end = PyLong_AsLong(v);
  if ((v = PyDict_GetItemString(cfg, "strict_shape")))
    strict = PyObject_IsTrue(v);
  if (PyErr_Occurred()) {
    // a malformed field (e.g. iteration_end as a string) must surface as
    // an error, not silently drop the option and predict with all trees
    Py_DECREF(cfg);
    return fail();
  }
  Py_DECREF(cfg);
  if (type != 0 && type != 1) {
    return fail_msg(
        "inplace predict supports type 0 (value) and 1 (margin); use "
        "XGBoosterPredictFromDMatrix for leaf/contribution predictions");
  }
  PyObject *kw = PyDict_New();
  PyObject *args = Py_BuildValue("(O)", data);
  PyObject *meth = PyObject_GetAttrString(w->obj, "inplace_predict");
  if (kw == nullptr || args == nullptr || meth == nullptr) {
    Py_XDECREF(kw);
    Py_XDECREF(args);
    Py_XDECREF(meth);
    return fail();
  }
  PyObject *pt = PyUnicode_FromString(type == 1 ? "margin" : "value");
  if (pt != nullptr) {
    PyDict_SetItemString(kw, "predict_type", pt);
    Py_DECREF(pt);
  }
  PyObject *ms = PyFloat_FromDouble(missing);
  if (ms != nullptr) {
    PyDict_SetItemString(kw, "missing", ms);
    Py_DECREF(ms);
  }
  if (strict) PyDict_SetItemString(kw, "strict_shape", Py_True);
  // pass the range through when EITHER bound is set: Python resolves
  // end==0 to the last round, so {begin: 2, end: 0} means rounds 2..end
  if (it_begin > 0 || it_end > 0) {
    PyObject *rng = Py_BuildValue("(ll)", it_begin, it_end);
    if (rng != nullptr) {
      PyDict_SetItemString(kw, "iteration_range", rng);
      Py_DECREF(rng);
    }
  }
  if (m != nullptr) {
    auto *mw = static_cast<MatWrap *>(m);
    PyObject *info = PyObject_GetAttrString(mw->obj, "info");
    PyObject *bm = info == nullptr
                       ? nullptr
                       : PyObject_GetAttrString(info, "base_margin");
    if (bm != nullptr && bm != Py_None)
      PyDict_SetItemString(kw, "base_margin", bm);
    Py_XDECREF(bm);
    Py_XDECREF(info);
    PyErr_Clear();  // a metadata-less matrix is fine
  }
  PyObject *r = PyObject_Call(meth, args, kw);
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kw);
  if (r == nullptr) return fail();
  int rc = capture_pred(w, r, out_shape, out_dim, out_result);
  Py_DECREF(r);
  return rc;
}

}  // namespace

XGB_DLL int XGBoosterPredictFromDense(BoosterHandle handle,
                                      char const *values,
                                      char const *c_json_config,
                                      DMatrixHandle m,
                                      bst_ulong const **out_shape,
                                      bst_ulong *out_dim,
                                      float const **out_result) {
  // zero-copy inplace predict (c_api.cc:833): `values` is an
  // __array_interface__ JSON over caller memory; no DMatrix is built —
  // rows go straight into the bucketed serving predictor
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *ad = imp("xgboost_tpu.data.adapters");
  if (ad == nullptr) return fail();
  PyObject *arr = PyObject_CallMethod(ad, "from_array_interface", "s",
                                      values);
  if (arr == nullptr) return fail();
  int rc = inplace_predict_common(w, arr, c_json_config, m, out_shape,
                                  out_dim, out_result);
  Py_DECREF(arr);
  return rc;
}

XGB_DLL int XGBoosterPredictFromCSR(BoosterHandle handle,
                                    char const *indptr, char const *indices,
                                    char const *values, bst_ulong ncol,
                                    char const *c_json_config,
                                    DMatrixHandle m,
                                    bst_ulong const **out_shape,
                                    bst_ulong *out_dim,
                                    float const **out_result) {
  // CSR twin of PredictFromDense (c_api.cc:878): three array-interface
  // JSON documents over the caller's indptr/indices/data buffers
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  PyObject *ad = imp("xgboost_tpu.data.adapters");
  if (ad == nullptr) return fail();
  PyObject *csr = PyObject_CallMethod(
      ad, "csr_from_array_interface", "sssK", indptr, indices, values,
      static_cast<unsigned long long>(ncol));
  if (csr == nullptr) return fail();
  int rc = inplace_predict_common(w, csr, c_json_config, m, out_shape,
                                  out_dim, out_result);
  Py_DECREF(csr);
  return rc;
}

XGB_DLL int XGBoosterPredictFromDMatrix(BoosterHandle handle,
                                        DMatrixHandle dmat,
                                        char const *c_json_config,
                                        bst_ulong const **out_shape,
                                        bst_ulong *out_dim,
                                        float const **out_result) {
  // the modern predict entry (c_api.h:928): JSON-configured type
  // (0 value, 1 margin, 2 contribs, 4 interactions, 6 leaf),
  // iteration_begin/end, strict_shape; shape reported explicitly
  Gil gil;
  auto *w = static_cast<BoosterWrap *>(handle);
  auto *d = static_cast<MatWrap *>(dmat);
  PyObject *jmod = imp("json");
  if (jmod == nullptr) return fail();
  PyObject *cfg = PyObject_CallMethod(
      jmod, "loads", "s",
      (c_json_config == nullptr || c_json_config[0] == '\0') ? "{}"
                                                             : c_json_config);
  if (cfg == nullptr) return fail();
  long type = 0, it_begin = 0, it_end = 0, strict = 0;
  PyObject *v;
  if ((v = PyDict_GetItemString(cfg, "type"))) type = PyLong_AsLong(v);
  if ((v = PyDict_GetItemString(cfg, "iteration_begin")))
    it_begin = PyLong_AsLong(v);
  if ((v = PyDict_GetItemString(cfg, "iteration_end")))
    it_end = PyLong_AsLong(v);
  if ((v = PyDict_GetItemString(cfg, "strict_shape")))
    strict = PyObject_IsTrue(v);
  Py_DECREF(cfg);
  if (type == 3) type = 2;  // approx contribs -> exact
  if (type == 5) type = 4;  // approx interactions -> exact
  if (type < 0 || type > 6 || (type != 0 && type != 1 && type != 2 &&
                               type != 4 && type != 6)) {
    return fail_msg("XGBoosterPredictFromDMatrix: unsupported type");
  }
  PyObject *kw = PyDict_New();
  PyObject *args = Py_BuildValue("(O)", d->obj);
  PyObject *meth = PyObject_GetAttrString(w->obj, "predict");
  if (kw == nullptr || args == nullptr || meth == nullptr) {
    Py_XDECREF(kw);
    Py_XDECREF(args);
    Py_XDECREF(meth);
    return fail();
  }
  auto set_true = [&](const char *k) {
    PyDict_SetItemString(kw, k, Py_True);
  };
  if (type == 1) set_true("output_margin");
  if (type == 2) set_true("pred_contribs");
  if (type == 4) set_true("pred_interactions");
  if (type == 6) set_true("pred_leaf");
  if (strict) set_true("strict_shape");
  if (it_end > 0) {
    PyObject *rng = Py_BuildValue("(ll)", it_begin, it_end);
    if (rng != nullptr) {
      PyDict_SetItemString(kw, "iteration_range", rng);
      Py_DECREF(rng);
    }
  }
  PyObject *r = PyObject_Call(meth, args, kw);
  Py_DECREF(meth);
  Py_DECREF(args);
  Py_DECREF(kw);
  if (r == nullptr) return fail();
  int rc = capture_pred(w, r, out_shape, out_dim, out_result);
  Py_DECREF(r);
  return rc;
}
