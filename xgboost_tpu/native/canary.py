"""Load-time canary: prove each native ``.so`` in a forked subprocess
before its first in-process use (ISSUE 20 tentpole, part a).

PRs 13/15/17/19 moved the whole hot path into in-process C++ kernels, so
one bad library — a stale build, a miscompiled ``-march=native`` binary
on a new box, an OOB write under a fresh shape — used to take the
trainer down with a raw SIGSEGV. The reference never hard-requires an
impl (``gpu_hist`` unavailable falls back to ``hist``); this module is
the native half of that posture: a library that cannot survive a tiny
golden workload in a SACRIFICIAL child process never gets dlopened into
the trainer at all, and the per-library degrade capability
(``native_tree``, ``native_hist``, ``native_sketch``,
``native_serving`` — ``native/boundary.py``) routes dispatch onto the
XLA/per-level impls instead.

Protocol, per (library, build):

1. **Symbol refusal** (the NB604 ``nm -D`` probe promoted from lint time
   to load time): a library missing any registered handler symbol is
   refused outright — no subprocess, verdict ``refused``.
2. **Verdict cache**: ``<so>.canary.json`` records (mtime, size,
   sha256, verdict). Warm startup is ONE stat — mtime+size match trusts
   the cached verdict; an mtime-only change re-hashes and a matching
   sha256 refreshes the entry without re-running. Only a genuinely new
   build pays the subprocess.
3. **Golden run**: ``python -m xgboost_tpu.native.canary <lib> <so>``
   executes a tiny grow / hist+partition / sketch+bin / walk on
   count-valued inputs (integer-valued f32 — sums exact regardless of
   accumulation order, so the expected output bytes are knowable in
   numpy) against THIS ``.so``, registered under ``xgbtpu_canary_*``
   target names so the child never touches the production loaders. Exit
   0 = pass; exit 3 = output mismatch; a signal death = crash; a parent
   deadline (``XGBTPU_CANARY_TIMEOUT``, default 300 s) = timeout.
4. **Verdict**: anything but ``healthy`` degrades the library's
   capability for the process lifetime, counts
   ``native_faults_total{lib,kind}`` and drops the
   ``native_canary_state{lib}`` gauge to -1. ``healthy`` sets it to 1.

``XGBTPU_NATIVE_CANARY=0`` skips the whole protocol (emergency hatch +
the child's own recursion guard). The ``native_canary`` chaos site fires
INSIDE the child: ``crash`` aborts it (the SIGSEGV-equivalent the
acceptance criterion injects), ``timeout`` parks it, ``corrupt`` flips
the computed result so the parent sees a mismatch. The child also fires
``native_dispatch`` once before its golden run — a canary run IS a
native dispatch, so a ``native_dispatch:crash:1`` schedule dies in the
subprocess, never in the trainer.
"""

from __future__ import annotations

import hashlib
import json
import os
import subprocess
import sys
import time
from typing import Dict, Optional, Tuple

_ENV_SKIP = "XGBTPU_NATIVE_CANARY"
_ENV_TIMEOUT = "XGBTPU_CANARY_TIMEOUT"

HEALTHY = "healthy"
REFUSED = "refused"
CRASH = "crash"
TIMEOUT = "timeout"
MISMATCH = "mismatch"
ERROR = "error"

#: lib name -> the handler symbols the loaders register (the refusal
#: set); single source of truth shared with the nm probe
LIB_SYMBOLS: Dict[str, Tuple[str, ...]] = {
    "tree_build": ("XgbtpuTreeGrow", "XgbtpuHbLevelSub",
                   "XgbtpuHbLevelQuant"),
    "hist_build": ("XgbtpuHbLevel", "XgbtpuHbPartition"),
    "sketch_bin": ("XgbtpuSketchCuts", "XgbtpuBinMatrixU8",
                   "XgbtpuBinMatrixU16"),
    "serving_walk": ("sv_predict_dense", "sv_predict_csr"),
}


def enabled() -> bool:
    return os.environ.get(_ENV_SKIP, "1") != "0"


def _timeout_s() -> float:
    try:
        return float(os.environ.get(_ENV_TIMEOUT, "300"))
    except ValueError:
        return 300.0


def _cache_path(so_path: str) -> str:
    return so_path + ".canary.json"


def _sha256(path: str) -> Optional[str]:
    try:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        return h.hexdigest()
    except OSError:
        return None


def _read_cache(so_path: str) -> Optional[dict]:
    try:
        with open(_cache_path(so_path), encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _write_cache(so_path: str, entry: dict) -> None:
    tmp = _cache_path(so_path) + ".tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(entry, f)
        os.replace(tmp, _cache_path(so_path))
    except OSError:
        pass  # an unwritable cache just means re-verifying next process


def cached_verdict(so_path: str) -> Optional[Tuple[str, str]]:
    """(verdict, detail) when the cache entry still describes this build,
    else None. Warm path: one stat (mtime+size match). An mtime-only
    drift re-hashes; a matching sha256 refreshes the entry in place."""
    entry = _read_cache(so_path)
    if not entry:
        return None
    try:
        st = os.stat(so_path)
    except OSError:
        return None
    if entry.get("size") != st.st_size:
        return None
    if entry.get("mtime") == st.st_mtime:
        return entry.get("verdict", ""), entry.get("detail", "")
    sha = _sha256(so_path)
    if sha is not None and sha == entry.get("sha256"):
        entry["mtime"] = st.st_mtime
        _write_cache(so_path, entry)
        return entry.get("verdict", ""), entry.get("detail", "")
    return None


def nm_symbols(so_path: str) -> Optional[set]:
    """Dynamic symbol table per ``nm -D``, or None when nm is unavailable
    / the file is unreadable (the probe stays silent — same posture as
    the lint-time NB604 probe it was promoted from)."""
    try:
        out = subprocess.run(
            ["nm", "-D", so_path], capture_output=True, timeout=30,
            check=True).stdout.decode(errors="replace")
        return {ln.split()[-1] for ln in out.splitlines() if ln.split()}
    except Exception:
        return None


def missing_symbols(lib: str, so_path: str) -> Tuple[str, ...]:
    syms = nm_symbols(so_path)
    if syms is None:
        return ()
    return tuple(s for s in LIB_SYMBOLS.get(lib, ()) if s not in syms)


def _gauge(lib: str, value: int) -> None:
    from ..observability.metrics import REGISTRY

    REGISTRY.gauge(
        "native_canary_state",
        "Load-time canary verdict per native library: "
        "1 passed, 0 unverified, -1 failed",
    ).labels(lib=lib).set(value)


def run_subprocess(lib: str, so_path: str) -> Tuple[str, str]:
    """One golden run of ``so_path`` in a sacrificial child. Returns
    (verdict, detail)."""
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env[_ENV_SKIP] = "0"  # the child must never recurse into proving
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-m", "xgboost_tpu.native.canary", lib, so_path]
    t0 = time.monotonic()
    try:
        proc = subprocess.run(cmd, capture_output=True, cwd=repo_root,
                              timeout=_timeout_s(), env=env)
    except subprocess.TimeoutExpired:
        return TIMEOUT, f"no verdict after {_timeout_s():.0f}s"
    except Exception as e:  # missing interpreter etc.: inconclusive
        return ERROR, f"{type(e).__name__}: {e}"
    dt = time.monotonic() - t0
    tail = proc.stderr.decode(errors="replace")[-500:].strip()
    if proc.returncode == 0:
        return HEALTHY, f"golden run passed in {dt:.1f}s"
    if proc.returncode < 0:  # killed by signal: the contained SIGSEGV
        return CRASH, f"child died with signal {-proc.returncode}: {tail}"
    if proc.returncode == 3:
        return MISMATCH, tail or "golden output mismatch"
    return ERROR, f"child exit {proc.returncode}: {tail}"


def prove(lib: str, so_path: str) -> bool:
    """The loaders' gate: True only for a library whose current build is
    proven (or the canary is switched off). Every failure path degrades
    the library's capability and counts ``native_faults_total`` — the
    caller just returns None and dispatch re-routes."""
    if not enabled():
        return True
    if lib not in LIB_SYMBOLS:
        return True  # non-canaried library (fastparse/pagecache/c_api)
    from . import boundary

    _gauge(lib, 0)
    missing = missing_symbols(lib, so_path)
    if missing:
        verdict, detail = REFUSED, f"symbols missing: {missing}"
    else:
        cached = cached_verdict(so_path)
        if cached is not None:
            verdict, detail = cached
            detail = f"cached: {detail}"
        else:
            verdict, detail = run_subprocess(lib, so_path)
            st = None
            try:
                st = os.stat(so_path)
            except OSError:
                pass
            if st is not None and verdict != ERROR:
                # ERROR verdicts (no interpreter, spawn failure) describe
                # the HOST, not the build — never cache them
                _write_cache(so_path, {
                    "lib": lib, "mtime": st.st_mtime, "size": st.st_size,
                    "sha256": _sha256(so_path), "verdict": verdict,
                    "detail": detail})
    if verdict == HEALTHY:
        _gauge(lib, 1)
        return True
    _gauge(lib, -1)
    boundary.record_native_fault(lib, verdict)
    boundary.degrade_lib(lib, kind_hint=verdict, detail=detail,
                         for_process=True)
    from ..utils import console_logger

    console_logger.warning(
        f"native canary refused {lib!r} ({so_path}): {verdict} — {detail}; "
        f"dispatch falls back to the XLA/per-level route")
    return False


# ---------------------------------------------------------------------------
# the child driver: golden checks against ONE .so, no production loaders
# ---------------------------------------------------------------------------


def _golden_serving(so_path: str, corrupt: bool) -> Optional[str]:
    import ctypes

    import numpy as np

    lib = ctypes.CDLL(so_path)
    c = ctypes
    lib.sv_predict_dense.argtypes = [
        c.c_void_p, c.c_int64, c.c_int64,
        c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p, c.c_void_p,
        c.c_void_p, c.c_void_p, c.c_int64, c.c_int64,
        c.c_void_p, c.c_void_p, c.c_int64,
    ]
    lib.sv_predict_dense.restype = c.c_int
    T, N, K, n, F = 2, 3, 1, 4, 1
    left = np.array([[1, -1, -1]] * T, np.int32)
    right = np.array([[2, -1, -1]] * T, np.int32)
    feature = np.zeros((T, N), np.int32)
    cond = np.array([[0.5, 1.0, 2.0], [0.5, 10.0, 20.0]], np.float32)
    default_left = np.array([[1, 0, 0]] * T, np.uint8)
    tree_group = np.zeros((T,), np.int32)
    tw = np.ones((T,), np.float32)
    X = np.array([[0.0], [1.0], [np.nan], [0.3]], np.float32)
    base = np.zeros((n, K), np.float32)
    out = np.empty((n, K), np.float32)

    def p(a):
        return a.ctypes.data

    rc = lib.sv_predict_dense(p(X), n, F, p(left), p(right), p(feature),
                              p(cond), p(default_left), p(tree_group),
                              p(tw), T, N, p(base), p(out), K)
    if rc != 0:
        return f"sv_predict_dense rc={rc}"
    # integer leaf values: the double accumulation is exact
    want = np.array([[11.0], [22.0], [11.0], [11.0]], np.float32)
    if corrupt:
        out = out + 1.0
    if out.tobytes() != want.tobytes():
        return f"walk margins {out.ravel().tolist()} != " \
               f"{want.ravel().tolist()}"
    return None


def _golden_hist(so_path: str, corrupt: bool) -> Optional[str]:
    import ctypes

    import numpy as np
    from jax.extend import ffi as jffi

    lib = ctypes.CDLL(so_path)
    jffi.register_ffi_target(
        "xgbtpu_canary_hb_level", jffi.pycapsule(lib.XgbtpuHbLevel),
        platform="cpu")
    jffi.register_ffi_target(
        "xgbtpu_canary_hb_partition", jffi.pycapsule(lib.XgbtpuHbPartition),
        platform="cpu")
    import jax
    import jax.numpy as jnp

    n, F, B, K = 8, 2, 4, 1
    bins = np.array([[0, 1], [1, 2], [2, 3], [3, 0], [0, 2], [1, 3],
                     [2, 0], [4, 4]]).astype(np.uint8)
    g = np.array([1, -2, 3, -1, 2, 1, -3, 5], np.float32)
    h = np.array([1, 2, 1, 3, 2, 1, 2, 1], np.float32)
    gh = np.stack([g, h], axis=-1).astype(np.float32)
    pos = np.zeros((n, 1), np.int32)
    ptab = np.zeros((1, 4), np.float32)
    zero = np.zeros((), np.int32)
    pos_out, hist = jffi.ffi_call(
        "xgbtpu_canary_hb_level",
        (jax.ShapeDtypeStruct((n, 1), jnp.int32),
         jax.ShapeDtypeStruct((F, 2 * K, B), jnp.float32)),
        bins, pos, gh, ptab, zero, zero, K=K, Kp=0, B=B)
    want = np.zeros((F, 2 * K, B), np.float32)
    for i in range(n):  # count-valued g/h: sums exact in any order
        for f in range(F):
            bv = int(bins[i, f])
            if bv >= B:
                continue
            want[f, 0, bv] += g[i]
            want[f, K, bv] += h[i]
    got = np.asarray(hist)
    if corrupt:
        got = got + 1.0
    if got.tobytes() != want.tobytes():
        return "level histogram bytes diverged from the numpy reference"
    if np.asarray(pos_out).tobytes() != pos.tobytes():
        return "root-level pos_out mutated"

    ptab1 = np.array([[1.0, 0.0, 1.0, 1.0]], np.float32)  # split f0 @ bin 1
    pos2 = jffi.ffi_call(
        "xgbtpu_canary_hb_partition",
        jax.ShapeDtypeStruct((n, 1), jnp.int32),
        bins, pos, ptab1, Kp=1, B=B, prev_offset=0)
    bv0 = bins[:, 0].astype(np.int64)
    go_left = np.where(bv0 >= B, True, bv0 <= 1)
    want_pos = np.where(go_left, 1, 2).astype(np.int32).reshape(n, 1)
    if np.asarray(pos2).tobytes() != want_pos.tobytes():
        return "partition routing diverged from the decision table"
    return None


def _golden_tree(so_path: str, corrupt: bool) -> Optional[str]:
    import ctypes

    import numpy as np
    from jax.extend import ffi as jffi

    lib = ctypes.CDLL(so_path)
    jffi.register_ffi_target(
        "xgbtpu_canary_tree_grow", jffi.pycapsule(lib.XgbtpuTreeGrow),
        platform="cpu")
    import jax
    import jax.numpy as jnp

    n, F, B, max_depth = 8, 1, 4, 1
    max_nodes = (1 << (max_depth + 1)) - 1
    mn = (max_nodes,)
    bins = np.array([[0], [0], [1], [1], [2], [2], [3], [3]], np.uint8)
    g = np.array([2, 2, 1, 1, -1, -1, -2, -2], np.float32)
    h = np.ones((n,), np.float32)
    gh = np.stack([g, h], axis=-1).astype(np.float32)
    cut_values = np.array([[0.5, 1.5, 2.5, 3.5]], np.float32)
    tree_mask = np.ones((F,), np.int32)
    G0 = np.float32(g.sum())
    H0 = np.float32(h.sum())
    out = jffi.ffi_call(
        "xgbtpu_canary_tree_grow",
        (jax.ShapeDtypeStruct((n, 1), jnp.int32),
         jax.ShapeDtypeStruct(mn, jnp.bool_),
         jax.ShapeDtypeStruct(mn, jnp.int32),
         jax.ShapeDtypeStruct(mn, jnp.int32),
         jax.ShapeDtypeStruct(mn, jnp.float32),
         jax.ShapeDtypeStruct(mn, jnp.bool_),
         jax.ShapeDtypeStruct(mn, jnp.float32),
         jax.ShapeDtypeStruct(mn, jnp.float32),
         jax.ShapeDtypeStruct(mn, jnp.float32),
         jax.ShapeDtypeStruct(mn, jnp.float32)),
        bins, gh, cut_values, tree_mask, G0, H0,
        max_depth=max_depth, B=B, sibling_sub=1, hist_acc=1,
        reg_lambda=np.float32(1.0), reg_alpha=np.float32(0.0),
        max_delta_step=np.float32(0.0), min_child_weight=np.float32(1.0))
    pos, is_split, feature, split_bin, split_cond = \
        (np.asarray(a) for a in out[:5])
    node_g, node_h = np.asarray(out[6]), np.asarray(out[7])
    if corrupt:
        node_g = node_g + 1.0
    # analytically-known round: gains 7.62 / 14.4 / 7.62 -> split @ bin 1;
    # count-valued g/h make every node stat an exact integer sum
    if not (bool(is_split[0]) and int(feature[0]) == 0
            and int(split_bin[0]) == 1):
        return (f"root split diverged: is_split={bool(is_split[0])} "
                f"feature={int(feature[0])} bin={int(split_bin[0])}")
    if float(split_cond[0]) != 1.5:
        return f"split_cond {float(split_cond[0])} != cut_values[0,1]"
    want_g = np.array([0.0, 6.0, -6.0], np.float32)
    want_h = np.array([8.0, 4.0, 4.0], np.float32)
    if node_g.tobytes() != want_g.tobytes() \
            or node_h.tobytes() != want_h.tobytes():
        return (f"node stats diverged: g={node_g.tolist()} "
                f"h={node_h.tolist()}")
    want_pos = np.where(bins[:, 0] <= 1, 1, 2).astype(np.int32)
    if pos.ravel().tobytes() != want_pos.tobytes():
        return f"leaf positions diverged: {pos.ravel().tolist()}"
    return None


def _golden_sketch(so_path: str, corrupt: bool) -> Optional[str]:
    import ctypes

    import numpy as np
    from jax.extend import ffi as jffi

    lib = ctypes.CDLL(so_path)
    jffi.register_ffi_target(
        "xgbtpu_canary_sketch_cuts", jffi.pycapsule(lib.XgbtpuSketchCuts),
        platform="cpu")
    jffi.register_ffi_target(
        "xgbtpu_canary_bin_u8", jffi.pycapsule(lib.XgbtpuBinMatrixU8),
        platform="cpu")
    import jax
    import jax.numpy as jnp

    n, F, B = 8, 1, 4
    X = np.arange(1, n + 1, dtype=np.float32).reshape(n, F)
    w = np.ones((n,), np.float32)
    cuts, min_vals = jffi.ffi_call(
        "xgbtpu_canary_sketch_cuts",
        (jax.ShapeDtypeStruct((F, B), jnp.float32),
         jax.ShapeDtypeStruct((F,), jnp.float32)),
        X, w, B=B)
    cuts, min_vals = np.asarray(cuts), np.asarray(min_vals)
    if not np.isfinite(cuts).all() or (np.diff(cuts, axis=1) < 0).any():
        return f"sketch cuts not finite/monotone: {cuts.tolist()}"
    if not (min_vals[0] <= X.min() and cuts[0, B - 1] > X.max()):
        return f"sketch envelope wrong: min={min_vals.tolist()} " \
               f"cuts={cuts.tolist()}"
    # binning against FIXED cuts is pure searchsorted: exact golden bytes
    Xb = X.copy()
    Xb[7, 0] = np.nan
    fixed = np.array([[2.5, 4.5, 6.5, 100.0]], np.float32)
    bins = jffi.ffi_call(
        "xgbtpu_canary_bin_u8",
        jax.ShapeDtypeStruct((n, F), jnp.uint8), Xb, fixed)
    want = np.array([0, 0, 1, 1, 2, 2, 3, B], np.uint8).reshape(n, F)
    got = np.asarray(bins)
    if corrupt:
        got = (got + 1).astype(np.uint8)
    if got.tobytes() != want.tobytes():
        return f"bin matrix diverged: {got.ravel().tolist()}"
    return None


_GOLDEN = {
    "tree_build": _golden_tree,
    "hist_build": _golden_hist,
    "sketch_bin": _golden_sketch,
    "serving_walk": _golden_serving,
}


def _child_main(argv) -> int:
    if len(argv) != 3 or argv[1] not in _GOLDEN:
        sys.stderr.write(f"usage: canary <{'|'.join(_GOLDEN)}> <so_path>\n")
        return 2
    lib, so_path = argv[1], argv[2]
    from ..resilience import chaos
    from ..resilience.chaos import ChaosError

    corrupt = False
    try:
        chaos.hit("native_canary")
        chaos.hit("native_dispatch")  # a canary run IS a native dispatch
    except ChaosError as e:
        mode = getattr(e, "chaos_mode", "")
        if mode == "crash":
            os.abort()  # the scripted SIGSEGV-equivalent, contained here
        elif mode == "timeout":
            time.sleep(max(_timeout_s() * 4, 3600))
        elif mode == "corrupt":
            corrupt = True
        else:
            raise  # plain-kind schedules present as a child error
    detail = _GOLDEN[lib](so_path, corrupt)
    if detail is not None:
        sys.stderr.write(detail + "\n")
        return 3
    return 0


if __name__ == "__main__":  # pragma: no cover (subprocess entry)
    sys.exit(_child_main(sys.argv))
