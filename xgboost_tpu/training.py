"""train() / cv() loops (reference: ``python-package/xgboost/training.py`` —
train at :49, cv + folds at :189-459)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .callback import (
    CallbackContainer,
    EarlyStopping,
    EvaluationMonitor,
    TrainingCallback,
)
from .data.dmatrix import DMatrix
from .learner import Booster

__all__ = ["train", "cv"]


class _AtomicCheckpoint(TrainingCallback):
    """Per-round crash-safe checkpointing for ``train(resume_from=...)``:
    atomic tmp+fsync+rename writes with a checksum trailer
    (``resilience/checkpoint.py``), pruned to the 2 newest so a previous
    good snapshot always survives the one in flight."""

    def __init__(self, directory: str, interval: int = 1):
        self.directory = directory
        self.interval = max(1, int(interval))

    def _save(self, model) -> None:
        from .resilience import checkpoint as _ckpt

        rounds = model.num_boosted_rounds()
        if rounds and _ckpt.read_checkpoint(
                _ckpt.checkpoint_path(self.directory, rounds)) is None:
            _ckpt.save_checkpoint(self.directory, model, rounds)

    def after_iteration(self, model, epoch, evals_log) -> bool:
        if (epoch + 1) % self.interval == 0:
            self._save(model)
        return False

    def after_training(self, model):
        self._save(model)  # the final round is always committed
        return model


def train(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    evals: Optional[Sequence[Tuple[DMatrix, str]]] = None,
    obj=None,
    feval=None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[dict] = None,
    verbose_eval: Any = True,
    xgb_model: Optional[Booster] = None,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    custom_metric=None,
    resume_from: Optional[str] = None,
    checkpoint_interval: int = 1,
) -> Booster:
    """``resume_from`` (ISSUE 5 tentpole): a directory of crash-safe
    checkpoints. When set, training (a) resumes from the newest VERIFIED
    checkpoint found there — rerunning the same command after a crash
    picks up at the last committed round and grows the same trees as an
    uninterrupted run — and (b) commits an atomic checkpoint every
    ``checkpoint_interval`` rounds. ``num_boost_round`` stays the TOTAL
    round count: a run resumed at round r trains the remaining
    ``num_boost_round - r``."""
    callbacks = list(callbacks) if callbacks else []
    evals = list(evals) if evals else []
    feval = custom_metric if custom_metric is not None else feval
    # scan fast-path eligibility, decided on USER-supplied state before the
    # auto-added monitor/early-stop/checkpoint callbacks join the list
    _no_per_iter_consumer = (
        not evals and not callbacks and obj is None and feval is None
        and early_stopping_rounds is None and resume_from is None
    )

    ckpt_dir: Optional[str] = None
    if resume_from is not None:
        from .resilience import checkpoint as _ckpt

        ckpt_dir = _ckpt.process_dir(resume_from)
        loaded = _ckpt.load_latest(ckpt_dir)
        if loaded is not None and xgb_model is None:
            raw, done_rounds = loaded
            xgb_model = bytes(raw)
            # total-round semantics: an already-complete checkpoint trains
            # 0 further rounds (but still flows through the normal path so
            # caches/callbacks see the same state as a live run)
            num_boost_round = max(0, num_boost_round - done_rounds)
        callbacks.append(_AtomicCheckpoint(ckpt_dir, checkpoint_interval))

    if verbose_eval:
        period = verbose_eval if isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool) else 1
        callbacks.append(EvaluationMonitor(period=period))
    if early_stopping_rounds is not None:
        callbacks.append(EarlyStopping(rounds=early_stopping_rounds, maximize=maximize))

    if xgb_model is not None:
        from .learner import _PredCache

        bst = xgb_model.copy() if isinstance(xgb_model, Booster) else Booster(params, model_file=xgb_model)
        bst.set_param(params)
        for d, _ in [(dtrain, "train")] + evals:
            bst._caches.setdefault(id(d), _PredCache())
            bst._cache_refs.setdefault(id(d), d)
        start_round = bst.num_boosted_rounds()
    else:
        bst = Booster(params, cache=[dtrain] + [d for d, _ in evals])
        start_round = 0

    container = CallbackContainer(callbacks)
    bst = container.before_training(bst)

    import jax

    from .observability import trace as _trace
    from .resilience.watchdog import WatchdogTimeout, watchdog as _watchdog

    def _commit_on_abort() -> None:
        """A watchdog abort mid-dispatch must not lose the committed
        rounds: flush the last consistent model state as a checkpoint
        (in-flight, uncommitted tree state is never serialized — save_raw
        walks only committed trees)."""
        if ckpt_dir is None:
            return
        try:
            from .resilience import checkpoint as _ckpt

            rounds = bst.num_boosted_rounds()
            if rounds:
                _ckpt.save_checkpoint(ckpt_dir, bst, rounds)
        except Exception:
            pass  # the abort itself must still surface

    try:
        if _no_per_iter_consumer and jax.default_backend() == "tpu":
            # no per-iteration consumer (no eval lines, early stopping,
            # checkpoints or custom callbacks): train whole chunks as single
            # scan dispatches (Booster.update_many; falls back per-round for
            # ineligible configs). TPU-only: the scan amortizes dispatch
            # latency, which is what accelerator backends pay; on CPU it only
            # multiplies XLA:CPU compile load (observed LLVM segfaults under
            # the full-suite compile volume), so the classic loop stays.
            with _trace.span("train", rounds=num_boost_round, path="scan"):
                with _watchdog("train_dispatch"):
                    bst.update_many(dtrain, start_round, num_boost_round)
        else:
            with _trace.span("train", rounds=num_boost_round,
                             path="per_round"):
                for i in range(start_round, start_round + num_boost_round):
                    if container.before_iteration(bst, i, dtrain, evals):
                        break
                    with _trace.span("round", iteration=i):
                        # deadline around the per-round host dispatch
                        # (off unless XGBTPU_WATCHDOG names round_dispatch
                        # or *): a wedged relay aborts cleanly — raise +
                        # checkpoint — instead of hanging the run
                        with _watchdog("round_dispatch"):
                            bst.update(dtrain, i, fobj=obj)
                        stop = container.after_iteration(
                            bst, i, dtrain, evals, feval=feval)
                    if stop:
                        break
    except WatchdogTimeout:
        _commit_on_abort()
        raise

    bst = container.after_training(bst)

    if evals_result is not None:
        for k, v in container.history.items():
            evals_result[k] = {mk: list(mv) for mk, mv in v.items()}
    return bst


def _make_folds(
    dtrain: DMatrix,
    nfold: int,
    params: Dict[str, Any],
    seed: int,
    stratified: bool,
    folds,
    shuffle: bool = True,
):
    n = dtrain.num_row()
    rng = np.random.RandomState(seed)
    if folds is not None:
        splits = folds if not hasattr(folds, "split") else list(
            folds.split(X=np.zeros(n), y=dtrain.get_label())
        )
    else:
        idx = np.arange(n)
        if shuffle:
            rng.shuffle(idx)
        if stratified and dtrain.info.label is not None:
            label = dtrain.get_label()[idx]
            order = np.argsort(label, kind="stable")
            idx = idx[order]  # interleave classes across folds
            fold_of = np.arange(n) % nfold
        else:
            fold_of = np.repeat(np.arange(nfold), int(np.ceil(n / nfold)))[:n]
        splits = []
        for k in range(nfold):
            test = idx[fold_of == k]
            trainix = idx[fold_of != k]
            splits.append((trainix, test))
    out = []
    for trainix, testix in splits:
        dtr = dtrain.slice(np.asarray(trainix))
        dte = dtrain.slice(np.asarray(testix))
        out.append((dtr, dte))
    return out


def cv(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    nfold: int = 3,
    stratified: bool = False,
    folds=None,
    metrics: Sequence[str] = (),
    obj=None,
    feval=None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    fpreproc=None,
    as_pandas: bool = True,
    verbose_eval: Any = None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    shuffle: bool = True,
    custom_metric=None,
):
    """K-fold cross-validation (reference training.py:189-459)."""
    params = dict(params)
    if isinstance(metrics, str):
        metrics = [metrics]
    if metrics:
        params["eval_metric"] = list(metrics)
    folds_data = _make_folds(dtrain, nfold, params, seed, stratified, folds, shuffle)
    cvpacks = []
    for dtr, dte in folds_data:
        p = params
        if fpreproc is not None:
            dtr, dte, p = fpreproc(dtr, dte, dict(params))
        cvpacks.append((Booster(p, cache=[dtr, dte]), dtr, dte))

    feval = custom_metric if custom_metric is not None else feval
    history: Dict[str, List[float]] = {}
    rounds_done = 0
    best_iteration = None
    es_state = {"best": None, "rounds": 0}

    results_per_round: List[Dict[str, Tuple[float, float]]] = []
    for i in range(num_boost_round):
        round_scores: Dict[str, List[float]] = {}
        for bst, dtr, dte in cvpacks:
            bst.update(dtr, i, fobj=obj)
            msg = bst.eval_set([(dtr, "train"), (dte, "test")], i, feval=feval)
            for tok in msg.split("\t")[1:]:
                nm, _, val = tok.rpartition(":")
                round_scores.setdefault(nm, []).append(float(val))
        agg = {k: (float(np.mean(v)), float(np.std(v))) for k, v in round_scores.items()}
        results_per_round.append(agg)
        rounds_done = i + 1
        for k, (m, s) in agg.items():
            history.setdefault(f"{k}-mean", []).append(m)
            history.setdefault(f"{k}-std", []).append(s)
        if verbose_eval:
            line = f"[{i}]\t" + "\t".join(
                f"{k}:{m:.5f}" + (f"+{s:.5f}" if show_stdv else "")
                for k, (m, s) in agg.items()
            )
            print(line, flush=True)
        if early_stopping_rounds is not None:
            test_keys = [k for k in agg if k.startswith("test-")]
            if test_keys:
                key = test_keys[-1]
                score = agg[key][0]
                base = key[len("test-"):].split("@")[0]
                is_max = (
                    maximize
                    if maximize is not None
                    else base in EarlyStopping._MAXIMIZE_METRICS
                )
                best = es_state["best"]
                improved = (
                    best is None
                    or (is_max and score > best)
                    or (not is_max and score < best)
                )
                if improved:
                    es_state["best"] = score
                    es_state["rounds"] = 0
                    best_iteration = i
                else:
                    es_state["rounds"] += 1
                    if es_state["rounds"] >= early_stopping_rounds:
                        break
    if early_stopping_rounds is not None and best_iteration is not None:
        for k in history:
            history[k] = history[k][: best_iteration + 1]
    if as_pandas:
        try:
            import pandas as pd

            return pd.DataFrame(history)
        except ImportError:
            pass
    return history
