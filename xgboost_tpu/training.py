"""train() / cv() loops (reference: ``python-package/xgboost/training.py`` —
train at :49, cv + folds at :189-459) plus the elastic multi-host driver
``elastic_train`` (detection -> quiesce -> resize -> checkpoint replay;
docs/distributed.md, "Elastic training")."""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .callback import (
    CallbackContainer,
    EarlyStopping,
    EvaluationMonitor,
    TrainingCallback,
)
from .data.dmatrix import DMatrix
from .learner import Booster

__all__ = ["train", "cv", "elastic_train", "elastic_exit"]


class _AtomicCheckpoint(TrainingCallback):
    """Per-round crash-safe checkpointing for ``train(resume_from=...)``:
    atomic tmp+fsync+rename writes with a checksum trailer
    (``resilience/checkpoint.py``), pruned to the 2 newest so a previous
    good snapshot always survives the one in flight. Since ISSUE 15 the
    serialization + fsync + rename run on the async writer thread by
    default (``XGBTPU_ASYNC_CKPT=0`` restores the synchronous path): the
    round loop captures the model snapshot at its sync point and blocks
    again only if the PREVIOUS write is still in flight at the next
    checkpoint boundary; ``after_training`` drains so the final round is
    durable before ``train`` returns."""

    def __init__(self, directory: str, interval: int = 1):
        self.directory = directory
        self.interval = max(1, int(interval))

    def _save(self, model, final: bool = False) -> None:
        from .resilience import checkpoint as _ckpt

        rounds = model.num_boosted_rounds()
        if rounds:
            if _ckpt.async_enabled():
                w = _ckpt.async_writer()
                # probe-before-write, async flavor: skip rounds whose
                # commit is in flight or provably on disk (covered() is
                # deletion-safe — a wiped directory re-commits)
                if not w.covered(self.directory, rounds) \
                        and _ckpt.read_checkpoint(_ckpt.checkpoint_path(
                            self.directory, rounds)) is None:
                    w.submit(self.directory, model, rounds)
                if final:
                    w.wait(self.directory)
            elif _ckpt.read_checkpoint(
                    _ckpt.checkpoint_path(self.directory, rounds)) is None:
                _ckpt.save_checkpoint(self.directory, model, rounds)

    def after_iteration(self, model, epoch, evals_log) -> bool:
        if (epoch + 1) % self.interval == 0:
            self._save(model)
        return False

    def after_training(self, model):
        self._save(model, final=True)  # the final round is always durable
        return model


def train(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    evals: Optional[Sequence[Tuple[DMatrix, str]]] = None,
    obj=None,
    feval=None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    evals_result: Optional[dict] = None,
    verbose_eval: Any = True,
    xgb_model: Optional[Booster] = None,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    custom_metric=None,
    resume_from: Optional[str] = None,
    checkpoint_interval: int = 1,
    checkpoint_shared: bool = False,
    resume_mode: str = "total",
) -> Booster:
    """``resume_from`` (ISSUE 5 tentpole): a directory of crash-safe
    checkpoints. When set, training (a) resumes from the newest VERIFIED
    checkpoint found there — rerunning the same command after a crash
    picks up at the last committed round and grows the same trees as an
    uninterrupted run — and (b) commits an atomic checkpoint every
    ``checkpoint_interval`` rounds. With the default
    ``resume_mode="total"``, ``num_boost_round`` stays the TOTAL round
    count: a run resumed at round r trains the remaining
    ``num_boost_round - r``. ``resume_mode="append"`` (ISSUE 12 —
    continuous training) instead trains ``num_boost_round`` MORE rounds
    on top of the checkpoint, on possibly FRESH ``dtrain`` data:
    boosting is naturally incremental, so periodic append-mode re-trains
    against the same directory plus the serving delivery controller form
    a real online-learning loop (docs/serving.md "Model delivery").
    ``train(N)`` then append-resume ``+M`` on the same data is
    bit-identical to ``train(N + M)`` straight through
    (tests/test_delivery.py). ``checkpoint_shared`` keeps multi-process
    checkpoints in ONE directory (the elastic layer's mode — payloads are
    rank-identical and tmp names pid-unique) instead of per-rank
    subdirectories."""
    if resume_mode not in ("total", "append"):
        raise ValueError(
            f"resume_mode must be 'total' or 'append', got {resume_mode!r}")
    callbacks = list(callbacks) if callbacks else []
    evals = list(evals) if evals else []
    feval = custom_metric if custom_metric is not None else feval
    # scan fast-path eligibility, decided on USER-supplied state before the
    # auto-added monitor/early-stop/checkpoint callbacks join the list
    _no_per_iter_consumer = (
        not evals and not callbacks and obj is None and feval is None
        and early_stopping_rounds is None and resume_from is None
    )

    ckpt_dir: Optional[str] = None
    if resume_from is not None:
        from .resilience import checkpoint as _ckpt

        ckpt_dir = _ckpt.process_dir(resume_from, shared=checkpoint_shared)
        loaded = _ckpt.load_latest(ckpt_dir)
        if loaded is not None and xgb_model is None:
            raw, done_rounds = loaded
            xgb_model = bytes(raw)
            if resume_mode == "total":
                # total-round semantics: an already-complete checkpoint
                # trains 0 further rounds (but still flows through the
                # normal path so caches/callbacks see the same state as a
                # live run)
                num_boost_round = max(0, num_boost_round - done_rounds)
            # append semantics: num_boost_round MORE rounds from here —
            # the continuous-training half of the delivery loop
        callbacks.append(_AtomicCheckpoint(ckpt_dir, checkpoint_interval))

    if verbose_eval:
        period = verbose_eval if isinstance(verbose_eval, int) and not isinstance(verbose_eval, bool) else 1
        callbacks.append(EvaluationMonitor(period=period))
    if early_stopping_rounds is not None:
        callbacks.append(EarlyStopping(rounds=early_stopping_rounds, maximize=maximize))

    if xgb_model is not None:
        from .learner import _PredCache

        bst = xgb_model.copy() if isinstance(xgb_model, Booster) else Booster(params, model_file=xgb_model)
        bst.set_param(params)
        for d, _ in [(dtrain, "train")] + evals:
            bst._caches.setdefault(id(d), _PredCache())
            bst._cache_refs.setdefault(id(d), d)
        start_round = bst.num_boosted_rounds()
    else:
        bst = Booster(params, cache=[dtrain] + [d for d, _ in evals])
        start_round = 0

    container = CallbackContainer(callbacks)
    bst = container.before_training(bst)

    import jax

    from .native import boundary as _boundary
    from .observability import flight as _flight
    from .observability import kernelprof as _kernelprof
    from .observability import trace as _trace
    from .pipeline import RoundPipeline, completion_probe
    from .resilience.policy import RetryPolicy as _RetryPolicy
    from .resilience.watchdog import watchdog as _watchdog

    def _commit_on_abort() -> None:
        """A watchdog abort mid-dispatch must not lose the committed
        rounds: flush the last consistent model state as a checkpoint
        (in-flight, uncommitted tree state is never serialized — save_raw
        walks only committed trees). The async writer is drained first so
        the abort-path synchronous write never races an in-flight commit
        of the same round."""
        if ckpt_dir is None:
            return
        try:
            from .resilience import checkpoint as _ckpt

            try:
                _ckpt.async_writer().wait(ckpt_dir)
            except Exception:
                pass  # a parked write failure must not mask THIS abort
            rounds = bst.num_boosted_rounds()
            if rounds:
                _ckpt.save_checkpoint(ckpt_dir, bst, rounds)
        except Exception:
            pass  # the abort itself must still surface

    try:
        if _no_per_iter_consumer and jax.default_backend() == "tpu":
            # no per-iteration consumer (no eval lines, early stopping,
            # checkpoints or custom callbacks): train whole chunks as single
            # scan dispatches (Booster.update_many; falls back per-round for
            # ineligible configs). TPU-only: the scan amortizes dispatch
            # latency, which is what accelerator backends pay; on CPU it only
            # multiplies XLA:CPU compile load (observed LLVM segfaults under
            # the full-suite compile volume), so the classic loop stays.
            with _trace.span("train", rounds=num_boost_round, path="scan"):
                with _watchdog("train_dispatch"):
                    bst.update_many(dtrain, start_round, num_boost_round)
                if bst._pipeline is not None:
                    # end-of-training sync point: the last chunks' async
                    # faults must surface HERE, attributed, not as an
                    # anonymous error at a later save/predict (direct
                    # update_many callers keep cross-call pipelining and
                    # drain at their own boundaries)
                    bst._pipeline.drain()
        else:
            # the async pipelined round loop (ISSUE 13): each round's
            # dispatch overlaps the previous rounds' device execution,
            # bounded to XGBTPU_PIPELINE_DEPTH rounds in flight. Host
            # synchronization happens ONLY at the blessed points — an
            # eval/early-stop/custom-callback boundary, a checkpoint
            # commit, or the end of training — so a consumer-free run
            # never blocks inside the loop (docs/perf.md).
            pipe = RoundPipeline()
            # per-round consumers force a drain every round; when the ONLY
            # consumer is the auto-added interval checkpoint, drain only on
            # the rounds it actually commits — a checkpoint_interval=k run
            # keeps the overlap window on the other k-1 rounds
            _other_consumers = (
                bool(evals) or obj is not None or feval is not None
                or early_stopping_rounds is not None
                or any(not isinstance(c, (EvaluationMonitor,
                                          _AtomicCheckpoint))
                       for c in callbacks))
            _ckpt_cb = ckpt_dir is not None

            def _round_consumer(i: int) -> bool:
                if _other_consumers:
                    return True
                return _ckpt_cb and (i + 1) % max(checkpoint_interval,
                                                  1) == 0

            # the native-boundary containment bracket (ISSUE 20): a fault
            # raised while a native train route is active degrades the
            # owning library (dispatch re-routes to the XLA/level impls)
            # and the ROUND retries on the fallback route. Rounds that
            # already committed into the model are never retried — a
            # post-commit fault re-raises as-is.
            _native_retry = _RetryPolicy(
                "native_dispatch", retries=2,
                retry_types=(_boundary.NativeFault,))

            def _contained_update(i: int) -> None:
                _committed = bst.num_boosted_rounds()
                try:
                    with _watchdog("round_dispatch"):
                        # ``native_dispatch`` chaos site: fires once per
                        # round while a native train route is active
                        _boundary.round_chaos()
                        bst.update(dtrain, i, fobj=obj)
                except Exception as _e:
                    if bst.num_boosted_rounds() != _committed:
                        raise
                    raise _boundary.contain(_e) from _e
            with _trace.span("train", rounds=num_boost_round,
                             path="per_round", pipeline_depth=pipe.depth):
                for i in range(start_round, start_round + num_boost_round):
                    if container.before_iteration(bst, i, dtrain, evals):
                        break
                    _flight.profile_tick(i)
                    _flight.RECORDER.begin_round(i)
                    # sampled rounds (XGBTPU_KERNEL_PROF; off by default)
                    # run the grow dispatch through the instrumented
                    # driver — per-depth × per-op attribution lands on
                    # the round record as grow_detail
                    _kp = (_kernelprof.arm(i)
                           if _kernelprof.should_sample(i) else None)
                    try:
                        with _trace.span("round", iteration=i):
                            # deadline around the per-round host dispatch
                            # (off unless XGBTPU_WATCHDOG names
                            # round_dispatch or *): a wedged relay aborts
                            # cleanly — raise + checkpoint — instead of
                            # hanging the run
                            _t0 = time.perf_counter()
                            _boundary.tick()
                            _native_retry.run(_contained_update, i)
                            # host-blocked dispatch time: the number the
                            # pipelined executor exists to shrink; waits
                            # land in the 'sync' stage instead
                            _flight.note("grow", time.perf_counter() - _t0)
                            _entry = bst._caches.get(id(dtrain))
                            pipe.admit(i, completion_probe(
                                _entry.margin if _entry is not None
                                else None))
                            if _round_consumer(i):
                                # sync point: the consumer must observe a
                                # finished round (and an async fault must
                                # surface HERE, attributed to its round)
                                pipe.drain()
                            stop = container.after_iteration(
                                bst, i, dtrain, evals, feval=feval)
                    finally:
                        if _kp is not None:
                            _gd = _kernelprof.disarm()
                            if _gd is not None:
                                _flight.RECORDER.annotate("grow_detail",
                                                          _gd)
                        _flight.RECORDER.end_round()
                    if stop:
                        break
                pipe.drain()  # end-of-training sync point
    except BaseException as e:
        # ANY abort mid-loop — watchdog expiry, a collective failing
        # because a peer died, an elastic guard raising WorkerLost —
        # flushes the last consistent rounds as a checkpoint before
        # surfacing: this is the quiesce half of the elastic contract
        # (the resize half replays from exactly this snapshot)
        _commit_on_abort()
        _flight.RECORDER.abort_dump(e)  # black box: ring + metrics
        raise
    finally:
        _flight.profile_stop()

    bst = container.after_training(bst)

    if evals_result is not None:
        for k, v in container.history.items():
            evals_result[k] = {mk: list(mv) for mk, mv in v.items()}
    return bst


# ---------------------------------------------------------------------------
# Elastic multi-host training: fault-tolerant membership + checkpoint replay
# ---------------------------------------------------------------------------


class _ElasticGuard(TrainingCallback):
    """Per-round elastic sentinel. At every round boundary it (a) fires
    the ``worker_kill`` chaos site — a scripted hit SIGKILLs this worker,
    the rabit-mock "die at (version, seqno)" analog; (b) exports the
    round into the heartbeat stream; (c) checks membership and raises
    :class:`~xgboost_tpu.parallel.membership.WorkerLost` on a dead peer
    (quiesce at the round boundary) or fences itself if tombstoned."""

    def __init__(self, membership):
        self.membership = membership

    def before_iteration(self, model, epoch, evals_log) -> bool:
        from .parallel.membership import WorkerLost
        from .resilience import chaos
        from .resilience.chaos import ChaosError

        try:
            chaos.hit("worker_kill")
        except ChaosError:
            import signal

            from .utils import console_logger

            console_logger.warning(
                f"chaos: worker_kill fired at round {epoch} — SIGKILLing "
                f"rank {self.membership.rank} (pid {os.getpid()})")
            os.kill(os.getpid(), signal.SIGKILL)
        self.membership.round = epoch
        dead = self.membership.scan()
        if self.membership.fenced:
            raise WorkerLost([self.membership.rank], epoch)
        if dead:
            raise WorkerLost(dead, epoch)
        return False


def _atomic_json(path: str, obj: dict) -> None:
    import json

    from .resilience.checkpoint import atomic_write_bytes

    atomic_write_bytes(path, json.dumps(obj).encode())


def _read_json(path: str) -> Optional[dict]:
    import json

    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _canonical_cuts(run_dir: str, data_fn, max_bin: int, rank: int,
                    members: List[int]):
    """Sharding-invariant binning for bit-exact elastic replay: the
    LOWEST member computes cuts ONCE from the full dataset
    (``data_fn(0, 1)`` — the load_row_split contract's world-1 view)
    through the plain local quantile path, persists them atomically, and
    every generation at every world size bins its shard against them.
    Without this, the distributed sketch's cuts depend on the shard
    count and a post-resize model could never be bit-identical to an
    uninterrupted run at the final world size."""
    import hashlib
    import json

    from .data.quantile import HistogramCuts
    from .resilience.watchdog import watchdog

    path = os.path.join(run_dir, "cuts.json")
    got = _read_json(path)
    if got is None and rank == min(members):
        full = data_fn(0, 1)
        bm = full.get_binned(max_bin)
        payload = {
            "max_bin": int(max_bin),
            "values": np.asarray(bm.cuts.values).tolist(),
            "min_vals": np.asarray(bm.cuts.min_vals).tolist(),
        }
        payload["sha256"] = hashlib.sha256(
            json.dumps(payload, sort_keys=True).encode()).hexdigest()
        _atomic_json(path, payload)
        got = payload
    if got is None:
        # non-writers wait for the writer (deadline-guarded: a dead
        # writer here must abort, not hang — the driver restarts us)
        import time

        with watchdog("elastic_cuts", seconds=300.0):
            while got is None:
                time.sleep(0.1)
                got = _read_json(path)
    check = dict(got)
    sha = check.pop("sha256", None)
    if sha != hashlib.sha256(
            json.dumps(check, sort_keys=True).encode()).hexdigest():
        raise RuntimeError(f"elastic cuts manifest {path} failed its "
                           "checksum; delete it to recompute")
    if int(got["max_bin"]) != int(max_bin):
        raise RuntimeError(
            f"elastic cuts manifest was built for max_bin="
            f"{got['max_bin']}, run requests {max_bin}")
    return HistogramCuts(
        values=np.asarray(got["values"], np.float32),
        min_vals=np.asarray(got["min_vals"], np.float32))


def _bin_with_cuts(d: DMatrix, cuts, max_bin: int) -> DMatrix:
    """Seed ``d``'s quantized-matrix cache with the canonical cuts (the
    ``QuantileDMatrix(ref=...)`` mechanism, applied in place)."""
    from .data.quantile import BinnedMatrix

    cat = d.categorical_features()
    if d._sparse is not None and d._data is None:
        bm = BinnedMatrix.from_sparse(
            d._sparse, max_bin=max_bin, cuts=cuts, categorical=cat)
    else:
        bm = BinnedMatrix.from_dense(
            d.data, max_bin=max_bin, cuts=cuts, categorical=cat)
    d._binned[max_bin] = bm
    return d


_GEN_ENV = "XGBTPU_ELASTIC_GEN"


def elastic_train(
    params: Dict[str, Any],
    data_fn: Callable[[int, int], DMatrix],
    num_boost_round: int,
    *,
    run_dir: str,
    world: int,
    rank: int,
    coordinator: Optional[str] = None,
    checkpoint_interval: int = 1,
    verbose_eval: Any = False,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
) -> Booster:
    """Fault-tolerant multi-host training: worker loss shrinks the world
    and replays from the newest verified checkpoint instead of aborting
    the job (ROADMAP item 1; the reference's rabit LoadCheckPoint story
    at the whole-cluster level). See docs/distributed.md, "Elastic
    training" for the state machine and its guarantees.

    ``data_fn(rank, world) -> DMatrix`` is the re-shardable ingestion
    hook — the ``load_row_split`` contract: called again at every world
    size, it returns that rank's row shard. For bit-exact replay, shards
    must be CONTIGUOUS BLOCKS of one fixed global row order (process-rank
    concatenation then preserves the global order across resizes).

    ``run_dir`` is a directory shared by all workers (local disk on one
    host, NFS on a pod) holding the membership heartbeats, the canonical
    cuts manifest, the generation state and the shared checkpoints.
    ``coordinator`` is ``host:basePort``; generation g rendezvouses on
    ``basePort + g`` (default: localhost, for single-host tests).

    The state machine per worker: TRAIN -> (peer death detected by
    heartbeat silence or a failed collective) -> QUIESCE at a round
    boundary (commit the last consistent rounds) -> RESIZE (tombstone the
    dead, agree on the survivor set, re-form the runtime at the new
    size — in-process when shrinking to one worker, by process restart
    when several survive or when the coordinator died) -> REPLAY (rebin
    against the canonical cuts, ``train(resume_from=...)`` from the
    newest verified checkpoint) -> TRAIN.
    """
    from .observability.metrics import REGISTRY
    from .observability import flight as _flight
    from .observability import trace as _trace
    from .parallel.membership import Membership, WorkerLost, hb_deadline
    from .parallel.mesh import mesh_context
    from .resilience import checkpoint as _ckpt, policy as _policy
    from .utils import console_logger

    os.makedirs(run_dir, exist_ok=True)
    # the fleet black box: per-round records + metrics + trace persist
    # under run_dir/obs/rank<base_rank>/ from here on (obs-report merges
    # them across ranks — docs/observability.md)
    _flight.configure(run_dir, rank=int(rank))
    ckpt_dir = os.path.join(run_dir, "checkpoints")
    member_dir = os.path.join(run_dir, "members")
    gen_path = os.path.join(run_dir, "generation.json")
    max_bin = int(params.get("max_bin", 256))
    base_rank = int(rank)
    host, _, base_port = (coordinator or "localhost:29950").rpartition(":")
    base_port = int(base_port)

    state = _read_json(gen_path) or {
        "generation": 0, "members": list(range(world)),
        "attempted_round": 0,
    }
    env_gen = int(os.environ.get(_GEN_ENV, state["generation"]))
    if env_gen > state["generation"]:
        # restarted ahead of the generation writer (the lowest survivor
        # commits generation.json just before its own restart): wait for
        # the membership agreement to land rather than racing it
        import time

        from .resilience.watchdog import watchdog as _wd_ctx

        with _wd_ctx("elastic_generation", seconds=300.0):
            while state["generation"] < env_gen:
                time.sleep(0.1)
                state = _read_json(gen_path) or state
    gen = max(env_gen, state["generation"])

    cuts = None
    while True:
        members = [m for m in state["members"]]
        if base_rank not in members:
            raise WorkerLost([base_rank])  # fenced before we even started
        world_g = len(members)
        rank_g = members.index(base_rank)
        _trace.instant("elastic_generation", generation=gen,
                       world=world_g, rank=rank_g)
        # stamp the generation on every round record from here on: the
        # fleet table keys (gen, round), so replayed rounds after a
        # resize land in their own entries instead of overwriting gen 0's
        _flight.RECORDER.set_generation(gen)
        mesh = None
        if world_g > 1:
            from .parallel.mesh import init_distributed

            mesh = init_distributed(
                coordinator_address=f"{host}:{base_port + gen}",
                num_processes=world_g, process_id=rank_g, elastic=True)
        # membership starts immediately after the rendezvous barrier (the
        # one moment all ranks are synchronized) — BEFORE the cuts/data
        # work, whose duration varies per rank and must not read as
        # heartbeat silence
        membership = Membership(member_dir, base_rank, members,
                                generation=gen).start()
        if cuts is None:
            cuts = _canonical_cuts(run_dir, data_fn, max_bin, rank_g,
                                   list(range(world_g)))
        dtrain = _bin_with_cuts(data_fn(rank_g, world_g), cuts, max_bin)

        # replay accounting: rounds the previous generation had reached
        # beyond what the checkpoint preserves get re-trained now (header
        # verification only — train() re-reads the payload anyway)
        resumed = 0
        for p in reversed(_ckpt.list_checkpoints(ckpt_dir)):
            ok, _, rounds = _ckpt.verify_checkpoint(p)
            if ok:
                resumed = rounds
                break
        replayed = max(0, int(state.get("attempted_round", 0)) - resumed)
        if gen > 0:
            REGISTRY.counter(
                "elastic_resume_rounds_replayed",
                "Rounds re-trained after elastic resizes").inc(replayed)
            _trace.instant("elastic_replay", generation=gen,
                           resumed=resumed, replayed=replayed)
            _flight.RECORDER.event("elastic_replay", generation=gen,
                                   resumed=resumed, replayed=replayed)

        try:
            import contextlib

            ctx = mesh_context(mesh) if mesh is not None \
                else contextlib.nullcontext()
            with ctx:
                bst = train(
                    params, dtrain, num_boost_round,
                    verbose_eval=verbose_eval,
                    callbacks=[_ElasticGuard(membership)]
                    + (list(callbacks) if callbacks else []),
                    resume_from=ckpt_dir,
                    checkpoint_interval=checkpoint_interval,
                    checkpoint_shared=True,
                )
            membership.stop()
            # elastic workers leave via elastic_exit (os._exit — no
            # atexit): flush the black box and trace NOW or lose them
            _flight.RECORDER.dump("elastic_complete")
            if _trace.enabled():
                _trace.flush()
            return bst
        except BaseException as e:
            # NOTE: the heartbeat agent keeps beating through this whole
            # block — we are alive, and stopping it before the resize
            # decision would make simultaneous survivors read each other
            # as silent and mutually fence (observed, not hypothetical)
            dead: List[int] = []
            # rounds attempted so far: a WorkerLost from the guard fires
            # BEFORE its round runs; a broken collective means the
            # guard's last round was in flight (and will be replayed)
            at_round = int(state.get("attempted_round", 0))
            if isinstance(e, WorkerLost):
                dead = e.ranks
                at_round = max(at_round, max(e.round, 0))
            else:
                suspects = [m for m in members if m != base_rank]
                if _policy.is_worker_loss(e):
                    # a broken collective: corroborate against the
                    # heartbeat stream before shrinking — a transient
                    # network fault must not cost a healthy worker its
                    # shard
                    dead = membership.wait_dead(
                        suspects, timeout=2 * hb_deadline())
                else:
                    # peer loss without a TCP reset (a wedged collective
                    # aborted by the watchdog, an opaque runtime error):
                    # the signature says nothing, but the heartbeat
                    # stream may already know — resize if membership has
                    # declared a peer dead, re-raise otherwise
                    dead = [r for r in membership.scan()
                            if r in suspects]
                if not dead:
                    membership.stop()
                    raise
                at_round = max(at_round, membership.round + 1)
            if base_rank in dead or membership.fenced:
                membership.stop()
                console_logger.warning(
                    f"elastic: rank {base_rank} fenced (tombstoned by a "
                    "peer); exiting rather than split-braining the run")
                raise WorkerLost([base_rank]) from e
            _policy.record_failure("elastic_resize", e)
            # QUIESCE committed its rounds in train()'s abort handler;
            # mark the transition on both the trace and the flight stream
            # (detection -> quiesce -> resize -> replay, obs-report's
            # instant sequence)
            _trace.instant("elastic_quiesce", generation=gen,
                           at_round=at_round, dead=repr(dead))
            _flight.RECORDER.event("elastic_quiesce", generation=gen,
                                   at_round=at_round, dead=repr(dead))
            _flight.RECORDER.dump("elastic_quiesce")
            for r in dead:
                membership.declare_dead(r)
            survivors = [m for m in members if m not in dead]
            # audit trail: preserve the exact snapshot this resize will
            # replay from (retention in the live dir prunes it later) —
            # run_dir/quiesce/gen<g>_ckpt_<rounds>.ckpt
            try:
                import shutil

                for p in reversed(_ckpt.list_checkpoints(ckpt_dir)):
                    if _ckpt.verify_checkpoint(p)[0]:
                        qdir = os.path.join(run_dir, "quiesce")
                        os.makedirs(qdir, exist_ok=True)
                        shutil.copy(p, os.path.join(
                            qdir, f"gen{gen}_{os.path.basename(p)}"))
                        break
            except OSError:
                pass  # the audit copy is best effort, never blocks resize
            gen += 1
            state = {"generation": gen, "members": survivors,
                     "attempted_round": at_round}
            if base_rank == min(survivors):
                _atomic_json(gen_path, state)
            REGISTRY.counter(
                "worker_restarts_total",
                "Training restarts caused by elastic resizes").inc()
            _trace.instant("elastic_resize", generation=gen,
                           dead=repr(dead), world=len(survivors))
            _flight.RECORDER.event("elastic_resize", generation=gen,
                                   dead=repr(dead), world=len(survivors))
            console_logger.warning(
                f"elastic: lost rank(s) {dead}; resizing world "
                f"{len(members)} -> {len(survivors)} (generation {gen}), "
                f"replaying from the newest verified checkpoint")
            membership.stop()
            if len(survivors) == 1:
                # shrink-to-one completes in-process: drop the mesh, keep
                # the (deaf) runtime alive, train locally on the full
                # re-shard — no new rendezvous needed
                continue
            # several survivors: the runtime cannot re-form a smaller
            # world in-process (coordination service lifecycle) — restart
            # this worker image in place; all state is in run_dir
            import sys

            os.environ[_GEN_ENV] = str(gen)
            console_logger.warning(
                f"elastic: re-executing worker for generation {gen} "
                f"(world {len(survivors)})")
            if _trace.enabled():  # execv skips atexit: flush the timeline
                _trace.flush()
            sys.stdout.flush()
            sys.stderr.flush()
            os.execv(sys.executable, [sys.executable] + sys.argv)


def elastic_exit(code: int = 0) -> None:
    """Exit an elastic worker process without tripping the distributed
    runtime's exit-time shutdown barrier (after a peer death the barrier
    can never complete; the stock runtime turns that into a process
    abort). Flushes stdio, then ``os._exit`` — call this LAST, after
    models/metrics are saved."""
    import sys

    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def _make_folds(
    dtrain: DMatrix,
    nfold: int,
    params: Dict[str, Any],
    seed: int,
    stratified: bool,
    folds,
    shuffle: bool = True,
):
    n = dtrain.num_row()
    rng = np.random.RandomState(seed)
    if folds is not None:
        splits = folds if not hasattr(folds, "split") else list(
            folds.split(X=np.zeros(n), y=dtrain.get_label())
        )
    else:
        idx = np.arange(n)
        if shuffle:
            rng.shuffle(idx)
        if stratified and dtrain.info.label is not None:
            label = dtrain.get_label()[idx]
            order = np.argsort(label, kind="stable")
            idx = idx[order]  # interleave classes across folds
            fold_of = np.arange(n) % nfold
        else:
            fold_of = np.repeat(np.arange(nfold), int(np.ceil(n / nfold)))[:n]
        splits = []
        for k in range(nfold):
            test = idx[fold_of == k]
            trainix = idx[fold_of != k]
            splits.append((trainix, test))
    out = []
    for trainix, testix in splits:
        dtr = dtrain.slice(np.asarray(trainix))
        dte = dtrain.slice(np.asarray(testix))
        out.append((dtr, dte))
    return out


def cv(
    params: Dict[str, Any],
    dtrain: DMatrix,
    num_boost_round: int = 10,
    nfold: int = 3,
    stratified: bool = False,
    folds=None,
    metrics: Sequence[str] = (),
    obj=None,
    feval=None,
    maximize: Optional[bool] = None,
    early_stopping_rounds: Optional[int] = None,
    fpreproc=None,
    as_pandas: bool = True,
    verbose_eval: Any = None,
    show_stdv: bool = True,
    seed: int = 0,
    callbacks: Optional[Sequence[TrainingCallback]] = None,
    shuffle: bool = True,
    custom_metric=None,
):
    """K-fold cross-validation (reference training.py:189-459)."""
    params = dict(params)
    if isinstance(metrics, str):
        metrics = [metrics]
    if metrics:
        params["eval_metric"] = list(metrics)
    folds_data = _make_folds(dtrain, nfold, params, seed, stratified, folds, shuffle)
    cvpacks = []
    for dtr, dte in folds_data:
        p = params
        if fpreproc is not None:
            dtr, dte, p = fpreproc(dtr, dte, dict(params))
        cvpacks.append((Booster(p, cache=[dtr, dte]), dtr, dte))

    feval = custom_metric if custom_metric is not None else feval
    history: Dict[str, List[float]] = {}
    rounds_done = 0
    best_iteration = None
    es_state = {"best": None, "rounds": 0}

    results_per_round: List[Dict[str, Tuple[float, float]]] = []
    for i in range(num_boost_round):
        round_scores: Dict[str, List[float]] = {}
        for bst, dtr, dte in cvpacks:
            bst.update(dtr, i, fobj=obj)
            msg = bst.eval_set([(dtr, "train"), (dte, "test")], i, feval=feval)
            for tok in msg.split("\t")[1:]:
                nm, _, val = tok.rpartition(":")
                round_scores.setdefault(nm, []).append(float(val))
        agg = {k: (float(np.mean(v)), float(np.std(v))) for k, v in round_scores.items()}
        results_per_round.append(agg)
        rounds_done = i + 1
        for k, (m, s) in agg.items():
            history.setdefault(f"{k}-mean", []).append(m)
            history.setdefault(f"{k}-std", []).append(s)
        if verbose_eval:
            line = f"[{i}]\t" + "\t".join(
                f"{k}:{m:.5f}" + (f"+{s:.5f}" if show_stdv else "")
                for k, (m, s) in agg.items()
            )
            print(line, flush=True)
        if early_stopping_rounds is not None:
            test_keys = [k for k in agg if k.startswith("test-")]
            if test_keys:
                key = test_keys[-1]
                score = agg[key][0]
                base = key[len("test-"):].split("@")[0]
                is_max = (
                    maximize
                    if maximize is not None
                    else base in EarlyStopping._MAXIMIZE_METRICS
                )
                best = es_state["best"]
                improved = (
                    best is None
                    or (is_max and score > best)
                    or (not is_max and score < best)
                )
                if improved:
                    es_state["best"] = score
                    es_state["rounds"] = 0
                    best_iteration = i
                else:
                    es_state["rounds"] += 1
                    if es_state["rounds"] >= early_stopping_rounds:
                        break
    if early_stopping_rounds is not None and best_iteration is not None:
        for k in history:
            history[k] = history[k][: best_iteration + 1]
    if as_pandas:
        try:
            import pandas as pd

            return pd.DataFrame(history)
        except ImportError:
            pass
    return history
