from . import fault  # noqa: F401
from .log import Logger, console_logger  # noqa: F401
from .timer import Monitor  # noqa: F401
