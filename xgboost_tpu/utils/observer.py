"""TrainingObserver: numeric debugging dumps.

Reference: ``src/common/observer.h:38`` — compile-gated dumps of
gradients/predictions/trees designed to be diff-able across
implementations (USE_DEBUG_OUTPUT). Here it is runtime-gated by the
``XGBTPU_OBSERVER`` env var (set to a directory path) or
``set_config(observer_dir=...)`` would be overkill — env is enough for a
debugging tool. Each observed tensor lands as
``<dir>/<iteration>_<name>.npy`` plus a one-line summary on stderr, so two
implementations (or two code versions) can be diffed array by array.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Optional

import numpy as np

__all__ = ["observe", "enabled"]


def _dir() -> Optional[str]:
    return os.environ.get("XGBTPU_OBSERVER") or None


def enabled() -> bool:
    return _dir() is not None


def observe(name: str, value: Any, iteration: int = 0) -> None:
    """No-op unless XGBTPU_OBSERVER points at a directory."""
    d = _dir()
    if d is None:
        return
    os.makedirs(d, exist_ok=True)
    arr = np.asarray(value)
    path = os.path.join(d, f"{iteration:05d}_{name}.npy")
    np.save(path, arr)
    with np.errstate(all="ignore"):
        print(
            f"[observer] it={iteration} {name}: shape={arr.shape} "
            f"sum={float(arr.astype(np.float64).sum()):.9g} "
            f"min={float(arr.min()) if arr.size else 0:.6g} "
            f"max={float(arr.max()) if arr.size else 0:.6g} -> {path}",
            file=sys.stderr,
            flush=True,
        )
