"""Per-label accumulating timers — a thin adapter over the telemetry layer.

Analog of ``common::Monitor`` (``src/common/timer.h:16,47``): label ->
accumulated wall time + call count per component, printed at verbosity>=3.
Since ISSUE 1 the Monitor is an adapter over ``observability``: every
``stop`` ALSO feeds the ``monitor_seconds{monitor=,section=}`` histogram in
the process-wide metrics registry and emits a span on the active trace
(``XGBTPU_TRACE``), so existing call sites (``learner.py``'s
GetGradient/GetBinned/BoostOneRound sections) appear in Perfetto timelines
and Prometheus dumps with zero changes. The local ``stats`` dict and
``report()`` format are preserved for the verbosity>=3 stderr path.

On TPU the heavyweight device profiling story remains ``jax.profiler``
(``profiler_context`` below); the Monitor is the cheap always-on host-side
accumulator the reference keeps around every phase (learner.cc:1061-1085).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Tuple

from ..config import get_config
from ..observability import metrics as _metrics, trace as _trace

_MONITOR_HELP = "Host-side wall time per Monitor section"


class Monitor:
    def __init__(self, label: str):
        self.label = label
        self.stats: Dict[str, Tuple[float, int]] = {}
        self._open: Dict[str, int] = {}

    def start(self, name: str) -> None:
        self._open[name] = time.perf_counter_ns()

    def stop(self, name: str) -> None:
        t0 = self._open.pop(name, None)
        if t0 is None:
            return
        t1 = time.perf_counter_ns()
        dt = (t1 - t0) * 1e-9
        acc, n = self.stats.get(name, (0.0, 0))
        self.stats[name] = (acc + dt, n + 1)
        _metrics.REGISTRY.histogram("monitor_seconds", _MONITOR_HELP).labels(
            monitor=self.label, section=name).observe(dt)
        _trace.emit(name, t0, t1, monitor=self.label)

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        self.start(name)
        try:
            yield
        finally:
            self.stop(name)

    def report(self) -> str:
        lines = [f"======== Monitor: {self.label} ========"]
        for name, (acc, n) in sorted(self.stats.items()):
            lines.append(f"{name}: {acc * 1e3:.3f}ms, {n} calls")
        return "\n".join(lines)

    def maybe_print(self) -> None:
        if get_config()["verbosity"] >= 3 and self.stats:
            import sys

            print(self.report(), file=sys.stderr, flush=True)


@contextlib.contextmanager
def profiler_context(log_dir: str) -> Iterator[None]:
    """Capture a device profile of everything inside the context — the
    heavyweight tracing story (reference analog: NVTX ranges gated by
    USE_NVTX, ``src/common/timer.h:52``; on TPU the native tool is
    ``jax.profiler``, viewable in TensorBoard/XProf). Composes with the
    always-on Monitor accumulators::

        with xgboost_tpu.profiler_context("/tmp/prof"):
            xgb.train(params, dtrain, 50)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
