"""Per-label accumulating timers.

Analog of ``common::Monitor`` (``src/common/timer.h:16,47``): label ->
accumulated wall time + call count per component, printed at verbosity>=3.
On TPU the heavyweight profiling story is ``jax.profiler``; this is the
cheap always-on host-side accumulator the reference keeps around every
phase (learner.cc:1061-1085).
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, Tuple

from ..config import get_config


class Monitor:
    def __init__(self, label: str):
        self.label = label
        self.stats: Dict[str, Tuple[float, int]] = {}
        self._open: Dict[str, float] = {}

    def start(self, name: str) -> None:
        self._open[name] = time.perf_counter()

    def stop(self, name: str) -> None:
        t0 = self._open.pop(name, None)
        if t0 is None:
            return
        acc, n = self.stats.get(name, (0.0, 0))
        self.stats[name] = (acc + time.perf_counter() - t0, n + 1)

    @contextlib.contextmanager
    def section(self, name: str) -> Iterator[None]:
        self.start(name)
        try:
            yield
        finally:
            self.stop(name)

    def report(self) -> str:
        lines = [f"======== Monitor: {self.label} ========"]
        for name, (acc, n) in sorted(self.stats.items()):
            lines.append(f"{name}: {acc * 1e3:.3f}ms, {n} calls")
        return "\n".join(lines)

    def maybe_print(self) -> None:
        if get_config()["verbosity"] >= 3 and self.stats:
            import sys

            print(self.report(), file=sys.stderr, flush=True)


@contextlib.contextmanager
def profiler_context(log_dir: str) -> Iterator[None]:
    """Capture a device profile of everything inside the context — the
    heavyweight tracing story (reference analog: NVTX ranges gated by
    USE_NVTX, ``src/common/timer.h:52``; on TPU the native tool is
    ``jax.profiler``, viewable in TensorBoard/XProf). Composes with the
    always-on Monitor accumulators::

        with xgboost_tpu.profiler_context("/tmp/prof"):
            xgb.train(params, dtrain, 50)
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
