"""Verbosity-gated console logging.

Analog of the reference's ``ConsoleLogger`` with 4 verbosity levels
(``include/xgboost/logging.h:39``): 0=silent, 1=warning, 2=info, 3=debug.
"""

from __future__ import annotations

import sys
import time
from typing import Any

from ..config import get_config


class Logger:
    def _emit(self, level: int, tag: str, *args: Any) -> None:
        if get_config()["verbosity"] >= level:
            msg = " ".join(str(a) for a in args)
            print(f"[{time.strftime('%H:%M:%S')}] {tag}: {msg}", file=sys.stderr, flush=True)

    def warning(self, *args: Any) -> None:
        self._emit(1, "WARNING", *args)

    def info(self, *args: Any) -> None:
        self._emit(2, "INFO", *args)

    def debug(self, *args: Any) -> None:
        self._emit(3, "DEBUG", *args)


console_logger = Logger()
