"""Scripted fault injection for recovery testing.

Analog of rabit's mock engine (reference ``rabit/src/allreduce_mock.h:20-50``,
built with ``RABIT_MOCK`` — ``CMakeLists.txt:47``): the mock kills a worker
when a scripted ``(rank, version, seqno, ntrial)`` tuple matches the current
collective call, and the fault-tolerance tests assert training recovers from
the last checkpoint.

Single-controller JAX has no per-worker process to kill — worker death is
process death, and the recovery story (matching the reference's production
behavior) is restart-from-checkpoint. The structural equivalents of the
mock's interception points are the host-side dispatch boundaries of each
round: ``version`` is the boosting round (rabit's model version), ``seqno``
counts injection sites hit within the round (rabit's collective sequence
number), and ``ntrial`` is how many times the fault fires before the
trigger is exhausted (rabit kills a restarted worker again until ntrial
runs out).

Usage (see ``tests/test_components.py``)::

    with fault_injection({(5, 1): 2}):          # version 5, seqno 1, twice
        for attempt in range(max_restarts):
            try:
                bst = train(..., xgb_model=last_checkpoint)
                break
            except InjectedFault:
                continue                         # restart from checkpoint
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict, Iterator, Tuple

__all__ = ["InjectedFault", "fault_injection", "inject", "begin_version"]

_state = threading.local()


class InjectedFault(RuntimeError):
    """The scripted fault: the moral equivalent of the mock engine's
    ``exit(-2)`` at a matching (version, seqno) — except recoverable
    in-process so tests can exercise the restart loop."""

    def __init__(self, site: str, version: int, seqno: int, trial: int):
        super().__init__(
            f"injected fault at site={site!r} version={version} "
            f"seqno={seqno} (trial {trial})"
        )
        self.site = site
        self.version = version
        self.seqno = seqno
        self.trial = trial


class _FaultSpec:
    def __init__(self, triggers: Dict[Tuple[int, int], int]):
        # {(version, seqno): remaining_trials}
        self.triggers = dict(triggers)
        self.version = -1
        self.seqno = 0
        self.fired = []  # [(site, version, seqno)] audit log


@contextlib.contextmanager
def fault_injection(triggers: Dict[Tuple[int, int], int]) -> Iterator[_FaultSpec]:
    """Arm scripted faults: ``{(version, seqno): ntrial}``. The spec object
    is yielded so tests can inspect ``spec.fired``."""
    prev = getattr(_state, "spec", None)
    spec = _FaultSpec(triggers)
    _state.spec = spec
    try:
        yield spec
    finally:
        _state.spec = prev


def begin_version(version: int) -> None:
    """Round boundary: resets the seqno counter (rabit's version bump at
    CheckPoint, ``allreduce_base.h:155``). Called by ``Booster.update``."""
    spec = getattr(_state, "spec", None)
    if spec is not None:
        spec.version = version
        spec.seqno = 0


def inject(site: str) -> None:
    """Injection site: no-op unless a spec is armed and the current
    (version, seqno) has remaining trials. Sites are the per-round host
    dispatch boundaries (gradient/grow/eval) — the places the reference
    mock intercepts collectives. These boundaries double as chaos sites of
    the same names: ``resilience/chaos.py`` generalizes this scripted
    (version, seqno) mock into named-site schedules, and bridging here
    means ``XGBTPU_CHAOS="grow:transient:3"`` can kill round dispatch
    without arming a fault spec."""
    from ..resilience import chaos

    chaos.hit(site)
    spec = getattr(_state, "spec", None)
    if spec is None:
        return
    key = (spec.version, spec.seqno)
    spec.seqno += 1
    remaining = spec.triggers.get(key, 0)
    if remaining > 0:
        spec.triggers[key] = remaining - 1
        trial = remaining
        spec.fired.append((site, key[0], key[1]))
        raise InjectedFault(site, key[0], key[1], trial)
